"""Detached entries under asyncio: guard N concurrent downstream calls from
one coroutine, completing out of order.

reference: ``AsyncEntryDemo.java`` (SphU.asyncEntry).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


import asyncio
import random

from sentinel_tpu.local import BlockException
from sentinel_tpu.local.chain import get_cluster_node
from sentinel_tpu.local.flow import FlowRule, FlowRuleManager
from sentinel_tpu.local.sph import async_entry


async def downstream_call(i: int) -> str:
    try:
        e = async_entry("asyncRpc")
    except BlockException:
        return f"call {i}: blocked"
    try:
        await asyncio.sleep(random.uniform(0.01, 0.05))
        return f"call {i}: ok"
    except Exception as err:  # pragma: no cover - demo
        e.trace(err)
        raise
    finally:
        e.exit()


async def run() -> None:
    FlowRuleManager.load_rules([FlowRule(resource="asyncRpc", count=5)])
    results = await asyncio.gather(*(downstream_call(i) for i in range(8)))
    for line in results:
        print(line)
    node = get_cluster_node("asyncRpc")
    print(f"live concurrency after completion: {node.cur_thread_num}")
    print(f"avg rt over real call durations: {node.avg_rt():.1f}ms")
    FlowRuleManager.reset_for_tests()


if __name__ == "__main__":
    asyncio.run(run())
