"""Cluster pacing: SHOULD_WAIT + wait-ms instead of blocks, and the
client's opt-in sleep-and-admit.

reference: ``PaceFlowDemo.java`` (``RuleConstant.CONTROL_BEHAVIOR_RATE_
LIMITER``) — but the leaky bucket lives cluster-side as a per-flow
``latest_passed_time`` tensor column (docs/SHAPING.md): a burst against
the token server comes back as OK for the first request and SHOULD_WAIT
with an assigned wait for the rest, spaced 1000/count ms apart. The wire
protocol already carries ``wait_ms``, and ``TokenClient(wait_and_admit=
True)`` turns those verdicts into delayed OKs by sleeping out the assigned
wait client-side — the whole burst passes, paced, with zero rejects.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.cluster.server import TokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.engine import ClusterFlowRule, EngineConfig, TokenStatus
from sentinel_tpu.engine.rules import ControlBehavior, ThresholdMode

FLOW = 302
NAMES = {
    int(TokenStatus.OK): "OK",
    int(TokenStatus.SHOULD_WAIT): "SHOULD_WAIT",
    int(TokenStatus.BLOCKED): "BLOCKED",
}


def main() -> None:
    svc = DefaultTokenService(
        EngineConfig(max_flows=16, max_namespaces=4, batch_size=64)
    )
    # count=10 → one pass every 100ms; queue caps at 600ms of waits
    svc.load_rules([
        ClusterFlowRule(
            FLOW, 10.0, ThresholdMode.GLOBAL,
            control_behavior=ControlBehavior.RATE_LIMITER,
            max_queueing_time_ms=600,
        )
    ])
    server = TokenServer(svc, port=0, metrics_port=0)
    server.start()
    print(f"token server on :{server.port} — flow {FLOW} paced at 10/s "
          f"(100ms spacing, 600ms max queue)")

    raw = TokenClient("127.0.0.1", server.port, timeout_ms=2000)
    pacer = TokenClient("127.0.0.1", server.port, timeout_ms=2000,
                        wait_and_admit=True)
    try:
        print("\nburst of 5 without wait_and_admit (the raw verdicts):")
        for i in range(5):
            r = raw.request_token(FLOW)
            print(f"  req {i}: {NAMES.get(r.status, r.status)}"
                  + (f" wait={r.wait_ms}ms" if r.wait_ms else ""))

        time.sleep(1.0)  # let the first burst's schedule drain

        print("\nburst of 5 with wait_and_admit=True (sleep out the "
              "assigned wait, then admit):")
        t0 = time.monotonic()
        for i in range(5):
            r = pacer.request_token(FLOW)
            dt = (time.monotonic() - t0) * 1000.0
            print(f"  req {i}: {NAMES.get(r.status, r.status)} "
                  f"at t={dt:5.0f}ms"
                  + (f" (slept {r.wait_ms}ms)" if r.wait_ms else ""))
        total = (time.monotonic() - t0) * 1000.0
        print(f"whole burst admitted, paced over ~{total:.0f}ms "
              f"(≈ 4 × 100ms spacing) — zero rejects")
    finally:
        raw.close()
        pacer.close()
        server.stop()


if __name__ == "__main__":
    main()
