"""Slow-call-ratio circuit breaker: CLOSED → OPEN → HALF_OPEN → CLOSED.

reference: ``ResponseTimeCircuitBreaker.java:34`` + state machine in
``AbstractCircuitBreaker.java:33-155``. Manual clock makes the recovery
timeout instantaneous.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.core import clock as clock_mod
from sentinel_tpu.core.clock import ManualClock
from sentinel_tpu.local import BlockException
from sentinel_tpu.local.degrade import (
    DegradeGrade,
    DegradeRule,
    DegradeRuleManager,
    register_state_change_observer,
    clear_state_change_observers,
)
from sentinel_tpu.local.sph import entry


def main() -> None:
    clock = ManualClock()
    prev = clock_mod.set_clock(clock)
    register_state_change_observer(
        lambda res, frm, to, rule: print(f"  [observer] {res}: {frm.name} -> {to.name}")
    )
    try:
        DegradeRuleManager.load_rules([
            DegradeRule(
                resource="api",
                grade=DegradeGrade.SLOW_REQUEST_RATIO,
                count=50,  # calls slower than 50ms are "slow"
                slow_ratio_threshold=0.5,
                min_request_amount=5,
                stat_interval_ms=1000,
                time_window_sec=2,  # recovery timeout
            )
        ])
        clock.set_ms(10_000)

        def call(duration_ms: int) -> str:
            try:
                with entry("api"):
                    clock.sleep(duration_ms)
                return "ok"
            except BlockException:
                return "CUT"

        print("6 slow calls (120ms each):", [call(120) for _ in range(6)])
        print("while OPEN:", [call(1) for _ in range(3)])
        clock.sleep(2_100)  # recovery window elapses
        print("probe after recovery (fast):", call(1), "— breaker closes")
        print("normal traffic:", [call(1) for _ in range(3)])
    finally:
        DegradeRuleManager.reset_for_tests()
        clear_state_change_observers()
        clock_mod.set_clock(prev)


if __name__ == "__main__":
    main()
