"""Class-level guard binding: ``@sentinel_intercept`` on a service class.

The CDI-interceptor deployment shape
(``sentinel-annotation-cdi-interceptor/.../SentinelResourceInterceptor.java:35-70``):
bind once at the class, and every public business method becomes a guarded
resource — with a method-level ``@sentinel_resource`` override keeping its
own name and handlers, exactly as the CDI interceptor consults the method
annotation first.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from sentinel_tpu.adapters import sentinel_intercept, sentinel_resource
from sentinel_tpu.local import BlockException
from sentinel_tpu.local.flow import FlowRule, FlowRuleManager


def degraded_quote(*args, ex=None, **kwargs):
    return {"price": None, "degraded": True}


@sentinel_intercept(fallback=degraded_quote)
class PricingService:
    """Every public method below is a resource: PricingService.quote,
    PricingService.refresh — guarded with the binding-level fallback."""

    def quote(self, sku: str):
        return {"price": 42.0, "sku": sku}

    def refresh(self):
        raise RuntimeError("upstream catalog down")  # traced, then fallback

    @sentinel_resource("pricing:vip-quote")  # method-level binding wins
    def vip_quote(self, sku: str):
        return {"price": 13.37, "sku": sku}


def main() -> None:
    FlowRuleManager.load_rules([
        FlowRule(resource="PricingService.quote", count=2.0),
        FlowRule(resource="pricing:vip-quote", count=1.0),
    ])
    svc = PricingService()

    print("two quotes pass:", svc.quote("a"), svc.quote("b"))
    print("third is shed to the binding fallback:", svc.quote("c"))

    print("vip passes once:", svc.vip_quote("v"))
    try:
        svc.vip_quote("v2")
    except BlockException as e:
        print("vip blocked under its OWN name (no class fallback):",
              type(e).__name__)

    print("business error degrades:", svc.refresh())


if __name__ == "__main__":
    main()
