"""Dynamic rules from a watched file: edit the JSON, limits change live.

reference: ``sentinel-demo-dynamic-file-rule`` /
``FileRefreshableDataSource.java:39``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


import json
import tempfile
import time

from sentinel_tpu.datasource.converters import flow_rules_from_json
from sentinel_tpu.datasource.file import FileRefreshableDataSource
from sentinel_tpu.local import BlockException
from sentinel_tpu.local.flow import FlowRuleManager
from sentinel_tpu.local.sph import entry


def admitted(n: int = 50) -> int:
    ok = 0
    for _ in range(n):
        try:
            with entry("res"):
                ok += 1
        except BlockException:
            pass
    return ok


def main() -> None:
    path = os.path.join(tempfile.mkdtemp(), "flow_rules.json")
    with open(path, "w") as f:
        json.dump([{"resource": "res", "count": 5}], f)

    ds = FileRefreshableDataSource(
        path, converter=flow_rules_from_json, refresh_interval_s=0.2
    )
    FlowRuleManager.register_property(ds.property)
    ds.start()
    try:
        print(f"rule file {path} says count=5  → admitted {admitted()}/50")
        with open(path, "w") as f:
            json.dump([{"resource": "res", "count": 30}], f)
        time.sleep(1.2)  # datasource polls and pushes the new rule
        print(f"edited file to count=30        → admitted {admitted()}/50")
    finally:
        ds.close()
        FlowRuleManager.reset_for_tests()


if __name__ == "__main__":
    main()
