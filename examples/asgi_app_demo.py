"""A guarded ASGI app with the command center mounted in the same server —
the control plane rides the app's own event loop.

reference: the servlet ``CommonFilter`` + ``sentinel-transport-netty-http``
(command handlers on the app's netty loop). Here: SentinelAsgiMiddleware
guards the app, ``command_asgi_app()`` serves the command surface from the
same process with no extra thread server, and a rule pushed through that
surface takes effect immediately.
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


import json

from sentinel_tpu.adapters.asgi import SentinelAsgiMiddleware
from sentinel_tpu.core import clock as clock_mod
from sentinel_tpu.core.clock import ManualClock
from sentinel_tpu.local.flow import FlowRuleManager
from sentinel_tpu.transport.command_asgi import command_asgi_app


async def hello_app(scope, receive, send):
    await send({"type": "http.response.start", "status": 200, "headers": []})
    await send({"type": "http.response.body", "body": b"ok"})


async def call(app, path, method="GET", body=b"", query=""):
    sent = []
    scope = {"type": "http", "method": method, "path": path,
             "query_string": query.encode(), "client": ("127.0.0.1", 1)}
    chunks = [{"type": "http.request", "body": body}]

    async def receive():
        return chunks.pop(0)

    async def send(msg):
        sent.append(msg)

    await app(scope, receive, send)
    status = next(m["status"] for m in sent
                  if m["type"] == "http.response.start")
    data = b"".join(m.get("body", b"") for m in sent
                    if m["type"] == "http.response.body")
    return status, data


async def main() -> None:
    # manual clock: the exact 2-pass/3-block assertion must not depend on
    # wall-clock window rolls (FAST_EXAMPLES determinism contract)
    prev = clock_mod.set_clock(ManualClock())
    app = SentinelAsgiMiddleware(hello_app)      # the guarded business app
    control = command_asgi_app()                 # the embedded control plane

    # push a QPS=2 rule through the control surface (what the dashboard does)
    rules = json.dumps([{"resource": "GET:/pay", "count": 2}]).encode()
    status, body = await call(control, "/setRules", "POST", rules,
                              query="type=flow")
    assert status == 200 and b"success" in body

    outcomes = [await call(app, "/pay") for _ in range(5)]
    codes = [s for s, _ in outcomes]
    print("statuses after pushing QPS=2 through the ASGI control plane:",
          codes)
    assert codes.count(200) == 2 and codes.count(429) == 3

    status, body = await call(control, "/getRules", query="type=flow")
    print("control plane sees:", json.loads(body))
    FlowRuleManager.load_rules([])
    clock_mod.set_clock(prev)


if __name__ == "__main__":
    asyncio.run(main())
