"""Hot-parameter flow control: per-value token buckets with a per-item
override for a VIP value.

reference: ``sentinel-demo-parameter-flow-control`` /
``ParamFlowChecker.java:46-190``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.core import clock as clock_mod
from sentinel_tpu.core.clock import ManualClock
from sentinel_tpu.local import BlockException
from sentinel_tpu.local.param import (
    ParamFlowItem,
    ParamFlowRule,
    ParamFlowRuleManager,
)
from sentinel_tpu.local.sph import entry


def main() -> None:
    clock = ManualClock()
    prev = clock_mod.set_clock(clock)
    try:
        ParamFlowRuleManager.load_rules([
            ParamFlowRule(
                resource="getUser",
                param_idx=0,
                count=2,  # 2 QPS per distinct user id
                items=[ParamFlowItem(object_value="vip", count=10)],
            )
        ])
        clock.set_ms(10_000)
        counts = {}
        for user in ("alice", "bob", "vip") * 12:
            try:
                with entry("getUser", args=(user,)):
                    counts[user] = counts.get(user, 0) + 1
            except BlockException:
                pass
        print(f"admitted this second: {counts}")
        print("(ordinary users capped at 2, the vip item override allows 10)")
    finally:
        ParamFlowRuleManager.reset_for_tests()
        clock_mod.set_clock(prev)


if __name__ == "__main__":
    main()
