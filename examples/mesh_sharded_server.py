"""One token-server pod whose chips decide TOGETHER: tier-1 (ICI) sharding.

The flow axis of the engine state and rule table shards across the pod's
devices (here: an 8-device virtual CPU mesh standing in for a v5e-8);
``shard_map`` + psums stitch each batch's verdicts across shards inside one
jitted step (``parallel/sharding.py``), and the TCP front door serves that
sharded step exactly like a single-chip one — clients cannot tell.

This demo exercises the REAL serving path, not a demo fork of it: the
mesh-backed service runs the same donating sharded step, greedy fusion
ladder (oversized pulls fold into one ``lax.scan``-of-``shard_map`` device
dispatch), prep cache, and staging freelists as production serving — the
mesh only changes the step function (``docs/PERF.md`` "Pod serving"). The
same layout snapshots and delta-replicates to standbys of any mesh shape
(``docs/CLUSTER_HA.md``).

reference shape: one embedded token server owning its namespace's flows
(``DefaultTokenService.java:36-97`` + ``NettyTransportServer.java:73-101``);
the intra-pod flow-axis sharding is the TPU-build extension (SURVEY.md §7.5,
tier 1 — tier 2, namespace partitioning ACROSS pods, is
``namespace_partition_demo.py``).

Run: ``python examples/mesh_sharded_server.py`` (pure CPU, ~20 s).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 8 virtual devices must be requested before the first CPU-backend creation;
# the platform pin must go through jax.config (the axon preload resolves
# JAX_PLATFORMS at backend init, which can block on a down tunnel)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from sentinel_tpu.cluster.client import TokenClient  # noqa: E402
from sentinel_tpu.cluster.server import TokenServer  # noqa: E402
from sentinel_tpu.cluster.token_service import DefaultTokenService  # noqa: E402
from sentinel_tpu.engine import ClusterFlowRule, EngineConfig  # noqa: E402
from sentinel_tpu.engine.rules import ThresholdMode  # noqa: E402
from sentinel_tpu.parallel import make_flow_mesh  # noqa: E402


def main() -> None:
    mesh = make_flow_mesh()
    print(f"pod mesh: {len(mesh.devices.flat)} devices, axes {mesh.axis_names}")

    # 64 flow slots shard 8 per device; batch verdicts are psum-stitched
    config = EngineConfig(max_flows=64, max_namespaces=4, batch_size=64)
    service = DefaultTokenService(config, mesh=mesh, serve_buckets=(64,))
    service.load_rules(
        [
            ClusterFlowRule(flow_id=i, count=3.0, mode=ThresholdMode.GLOBAL)
            for i in range(16)
        ]
    )
    service.warmup()  # compile the sharded step outside the serving window

    n_dev = len(mesh.devices.flat)
    print(
        f"flow window tensor: {n_dev} shards of "
        f"{config.max_flows // n_dev} flow slots each (flow axis over ICI)"
    )

    server = TokenServer(service, host="127.0.0.1", port=0, max_batch=64)
    server.start()
    client = TokenClient("127.0.0.1", server.port, timeout_ms=5000)
    try:
        # 5 requests for flow 1 (budget 3/s) through the real front door:
        # the owning shard admits exactly 3, psums carry the verdicts back
        res = client.request_batch_arrays(np.full(5, 1, np.int64))
        assert res is not None, "no response from the pod"
        statuses = res[0]
        ok = int((statuses == 0).sum())
        blocked = int((statuses == 1).sum())
        print(f"flow 1 (budget 3/s): {ok} OK, {blocked} BLOCKED over TCP")
        assert (ok, blocked) == (3, 2), statuses
    finally:
        client.close()
        server.stop()
        service.close()
    print("mesh-sharded pod served and enforced over the wire — OK")


if __name__ == "__main__":
    main()
