"""Prometheus scrape endpoint over live guarded traffic.

reference: ``sentinel-metric-exporter`` (JMX MBeans per resource) — the
Python-ecosystem analog is a pull-based scrape endpoint rendering straight
off the live ClusterNode windows. Besides the per-resource QPS gauges shown
here, the same body carries cumulative ``sentinel_pass_total`` /
``sentinel_block_total`` counters and the ``sentinel_server_*`` token-server
pipeline series — the full reference is ``docs/OBSERVABILITY.md``.
"""

import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.local import BlockException
from sentinel_tpu.local.flow import FlowRule, FlowRuleManager
from sentinel_tpu.local.sph import entry
from sentinel_tpu.metrics.exporter import PrometheusExporter


def main() -> None:
    FlowRuleManager.load_rules([FlowRule(resource="GET:/orders", count=5)])
    exporter = PrometheusExporter(host="127.0.0.1", port=0).start()
    try:
        passed = blocked = 0
        for _ in range(9):
            try:
                with entry("GET:/orders"):
                    passed += 1
            except BlockException:
                blocked += 1
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/metrics", timeout=5
        ) as rsp:
            text = rsp.read().decode()
        wanted = [
            line for line in text.splitlines()
            if "GET:/orders" in line and (
                "pass_qps" in line or "block_qps" in line
                or "pass_total" in line or "block_total" in line
            )
        ]
        print(f"served {passed} / blocked {blocked}; scrape says:")
        for line in wanted:
            print(" ", line)
        assert any("sentinel_pass_qps" in w for w in wanted)
        assert any("sentinel_block_qps" in w for w in wanted)
        # cumulative counters ride the same scrape (rate() these in PromQL
        # instead of trusting the instantaneous QPS gauges)
        assert any("sentinel_pass_total" in w for w in wanted)
    finally:
        exporter.stop()
        FlowRuleManager.load_rules([])


if __name__ == "__main__":
    main()
