"""Datasource-wired cluster: files drive mode, assignment, and rules.

The ``DemoClusterInitFunc.java:48-70`` idiom without a dashboard in the
loop: one watched file holds the cluster map (who is the token server), one
holds the cluster flow rules. Editing the rule file re-budgets the fleet
live; the mode/assignment properties come from the same datasource layer
the Nacos/etcd/… backends feed in production.

Wiring (all property-driven, no HTTP commands):

- ``cluster_map.json``  → ``register_cluster_mode_property``  (this process
  promotes itself to an embedded token server, ``ClusterStateManager``)
- ``cluster_map.json``  → ``register_client_assign_property`` (a client
  re-points at the mapped server, ``ClusterClientConfigManager``)
- ``flow_rules.json``   → ``DefaultTokenService.load_namespace_rules``
  (the ``registerClusterRuleSupplier`` analog: rules per namespace follow
  the datasource)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


import json
import socket
import tempfile
import time

from sentinel_tpu.cluster import assign as cluster_assign
from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.datasource.converters import cluster_flow_rules_from_json
from sentinel_tpu.datasource.file import FileRefreshableDataSource
from sentinel_tpu.transport import handlers as H

FLOW_ID = 7001


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _admitted(client: TokenClient, n: int) -> int:
    return sum(client.request_token(FLOW_ID).ok for _ in range(n))


def main() -> None:
    workdir = tempfile.mkdtemp(prefix="sentinel-cluster-ds-")
    map_path = os.path.join(workdir, "cluster_map.json")
    rules_path = os.path.join(workdir, "flow_rules.json")
    port = _free_port()

    # the "cluster map" a config service would hold: one entry saying who
    # serves tokens (ClusterGroupEntity shape, trimmed)
    with open(map_path, "w") as f:
        json.dump({"mode": 1, "tokenPort": port}, f)
    with open(rules_path, "w") as f:
        json.dump([{"flowId": FLOW_ID, "count": 10, "thresholdType": 1}], f)

    # mode follows the map file → this process promotes itself to server
    mode_ds = FileRefreshableDataSource(
        map_path, converter=json.loads, refresh_interval_s=0.2
    ).start()
    cluster_assign.register_cluster_mode_property(mode_ds.property)
    for _ in range(50):
        if H._EMBEDDED_SERVER["server"] is not None:
            break
        time.sleep(0.1)
    server = H._EMBEDDED_SERVER["server"]
    assert server is not None, "mode datasource did not promote the server"
    print(f"promoted to embedded token server on :{server.port} (from file)")

    # rules follow the rule file → the server's namespace rule supplier
    rules_ds = FileRefreshableDataSource(
        rules_path, converter=cluster_flow_rules_from_json,
        refresh_interval_s=0.2,
    ).start()
    rules_ds.property.listen(
        lambda rules: server.service.load_namespace_rules(
            "default", rules or []
        )
    )

    client = TokenClient("127.0.0.1", server.port, timeout_ms=2000)
    try:
        got = _admitted(client, 20)
        print(f"budget 10/s: {got}/20 admitted")
        assert got == 10, got

        # a config push: edit the rule file, fleet re-budgets itself
        with open(rules_path, "w") as f:
            json.dump([{"flowId": FLOW_ID, "count": 3, "thresholdType": 1}], f)
        time.sleep(0.6)  # refresh interval + settle
        time.sleep(1.1)  # let the 1s metric window roll past the old grants
        got = _admitted(client, 20)
        print(f"budget  3/s: {got}/20 admitted after editing flow_rules.json")
        assert got == 3, got
    finally:
        client.close()
        rules_ds.close()
        mode_ds.close()
        H.apply_cluster_mode(-1)
    print("datasource-driven cluster demo OK")


if __name__ == "__main__":
    main()
