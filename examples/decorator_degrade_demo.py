"""@sentinel_resource + circuit breaker: annotation-style degradation.

The ``sentinel-demo-annotation-spring-aop`` × ``sentinel-demo-degrade``
combination (``SentinelResourceAspect.java:36-68`` dispatching to
``fallback``/``blockHandler``, ``ExceptionCircuitBreaker.java:35`` doing the
failure detection): a flaky downstream call is guarded by the decorator;
its error ratio trips the breaker; while OPEN, calls short-circuit into the
fallback without touching the downstream; after the recovery window one
probe call closes the breaker again.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.adapters import sentinel_resource
from sentinel_tpu.core import clock as clock_mod
from sentinel_tpu.core.clock import ManualClock
from sentinel_tpu.local.degrade import (
    DegradeGrade,
    DegradeRule,
    DegradeRuleManager,
    clear_state_change_observers,
    register_state_change_observer,
)

DOWNSTREAM_CALLS = {"n": 0}
HEALTHY = {"ok": False}


def quote_fallback(symbol, ex=None):
    return f"{symbol}: cached quote (fallback, {type(ex).__name__})"


@sentinel_resource("quote_service", fallback=quote_fallback)
def get_quote(symbol):
    DOWNSTREAM_CALLS["n"] += 1
    if not HEALTHY["ok"]:
        raise ConnectionError("downstream quote service down")
    return f"{symbol}: 42.00"


def main() -> None:
    clock = ManualClock()
    prev = clock_mod.set_clock(clock)
    register_state_change_observer(
        lambda res, frm, to, rule: print(f"  [observer] {res}: {frm.name} -> {to.name}")
    )
    try:
        DegradeRuleManager.load_rules([
            DegradeRule(
                resource="quote_service",
                grade=DegradeGrade.ERROR_RATIO,
                count=0.5,  # open at 50% errors
                min_request_amount=5,
                stat_interval_ms=1000,
                time_window_sec=2,  # recovery timeout
            )
        ])
        clock.set_ms(10_000)

        print("downstream down — errors fall through to the fallback:")
        for _ in range(6):
            print(" ", get_quote("TPU"))
            clock.advance(10)

        print("breaker is OPEN — calls short-circuit (downstream untouched):")
        before = DOWNSTREAM_CALLS["n"]
        for _ in range(3):
            print(" ", get_quote("TPU"))
            clock.advance(10)
        assert DOWNSTREAM_CALLS["n"] == before, "OPEN must not touch downstream"

        print("downstream recovers; after the 2s window one probe closes it:")
        HEALTHY["ok"] = True
        clock.advance(2_100)
        print(" ", get_quote("TPU"))  # HALF_OPEN probe succeeds -> CLOSED
        print(" ", get_quote("TPU"))  # normal traffic again
        assert DOWNSTREAM_CALLS["n"] == before + 2
    finally:
        clear_state_change_observers()
        DegradeRuleManager.load_rules([])
        clock_mod.set_clock(prev)
    print("decorator + degrade demo OK")


if __name__ == "__main__":
    main()
