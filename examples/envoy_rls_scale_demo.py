"""Envoy RLS at scale: 10k descriptors on one token service.

BASELINE.json's ``sentinel-cluster-server-envoy-rls`` config: 10k RLS
descriptors behind an Envoy gateway. Each descriptor hashes to a cluster
flow id (``EnvoySentinelRuleConverter.generateKey`` → flow id); the device
table holds all 10k budgets in one [flows × buckets × events] tensor, so a
``shouldRateLimit`` burst over ANY mix of descriptors is one micro-batched
device step — rule count does not touch per-request cost.

Runs the gRPC transport when ``grpcio`` is importable, else drives
``RlsService`` directly (same decision path minus the socket).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.cluster.envoy_rls import (
    EnvoyRlsRule,
    EnvoyRlsRuleManager,
    RlsDescriptor,
    RlsService,
)
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.engine import EngineConfig

N_DESCRIPTORS = 10_000


def main() -> None:
    svc = DefaultTokenService(
        EngineConfig(max_flows=16_384, max_namespaces=4, batch_size=1024)
    )
    manager = EnvoyRlsRuleManager(svc)
    t0 = time.perf_counter()
    manager.load_rules(
        [
            EnvoyRlsRule(
                domain="gw",
                descriptors=tuple(
                    RlsDescriptor(
                        entries=(("path", f"/api/route{i}"),),
                        count=100.0,
                    )
                    for i in range(start, min(start + 2000, N_DESCRIPTORS))
                ),
            )
            for start in range(0, N_DESCRIPTORS, 2000)
        ]
    )
    print(f"loaded {N_DESCRIPTORS} RLS descriptors in "
          f"{time.perf_counter() - t0:.2f}s (one device rule table)")

    rls = RlsService(svc, manager)
    svc.warmup()

    # a burst across 512 random routes: one should_rate_limit per request,
    # the hot path the Envoy filter drives
    t0 = time.perf_counter()
    n = 512
    over = 0
    for i in range(n):
        verdict = rls.should_rate_limit(
            "gw", [[("path", f"/api/route{(i * 37) % N_DESCRIPTORS}")]]
        )
        over += verdict.overall_code != 1  # CODE_OK
    dt = time.perf_counter() - t0
    print(f"{n} shouldRateLimit calls across 10k descriptors: "
          f"{dt * 1e3 / n:.2f} ms/call, {over} over-limit")

    # exhaust one descriptor's budget to show enforcement at scale
    hot = [[("path", "/api/route7")]]
    ok = sum(
        rls.should_rate_limit("gw", hot).overall_code == 1
        for _ in range(150)
    )
    print(f"hot descriptor /api/route7: {ok} of 150 allowed "
          f"(budget 100/s) — the other 9,999 budgets unaffected")
    unaffected = rls.should_rate_limit("gw", [[("path", "/api/route8")]])
    print(f"neighbor /api/route8 verdict: "
          f"{'OK' if unaffected.overall_code == 1 else 'OVER_LIMIT'}")
    svc.close()


if __name__ == "__main__":
    main()
