"""Cross-service authority over HTTP: the caller's identity travels in the
``X-Sentinel-Origin`` header and authority rules enforce it on the callee.

reference: the dubbo adapter's origin propagation
(``SentinelDubboConsumerFilter``/``SentinelDubboProviderFilter`` attachment
pair) and the servlet ``CommonFilter``'s origin header — here as a real WSGI
service guarded by ``SentinelWsgiMiddleware`` plus an outbound header
injected by ``adapters.origin``.

billing-svc is whitelisted for ``GET:/admin``; report-svc is not.
"""

import os
import sys
import threading
import urllib.error
import urllib.request
from wsgiref.simple_server import WSGIServer, make_server

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.adapters.origin import ORIGIN_HEADER
from sentinel_tpu.adapters.wsgi import SentinelWsgiMiddleware
from sentinel_tpu.local.authority import (
    AuthorityRule,
    AuthorityRuleManager,
    AuthorityStrategy,
)


def app(environ, start_response):
    start_response("200 OK", [("Content-Type", "text/plain")])
    return [b"admin ok"]


def call(port: int, origin: str) -> int:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/admin", headers={ORIGIN_HEADER: origin}
    )
    try:
        with urllib.request.urlopen(req, timeout=5) as rsp:
            return rsp.status
    except urllib.error.HTTPError as e:
        return e.code


def main() -> None:
    AuthorityRuleManager.load_rules([
        AuthorityRule(
            resource="GET:/admin",
            limit_app="billing-svc",
            strategy=AuthorityStrategy.WHITE,
        )
    ])
    guarded = SentinelWsgiMiddleware(app)

    class QuietServer(WSGIServer):
        def handle_error(self, request, client_address):  # demo: no tracebacks
            pass

    server = make_server("127.0.0.1", 0, guarded, server_class=QuietServer)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        allowed = call(port, "billing-svc")
        denied = call(port, "report-svc")
        print(f"billing-svc -> {allowed} (whitelisted)")
        print(f"report-svc  -> {denied} (blocked by authority rule)")
        assert allowed == 200 and denied == 429, (allowed, denied)
    finally:
        server.shutdown()
        AuthorityRuleManager.load_rules([])


if __name__ == "__main__":
    main()
