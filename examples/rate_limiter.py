"""Leaky-bucket pacing: bursts are smoothed into uniform gaps instead of
rejected.

reference: ``PaceFlowDemo.java`` / ``RateLimiterController.java:46-91``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


import time

from sentinel_tpu.local import BlockException
from sentinel_tpu.local.flow import ControlBehavior, FlowRule, FlowRuleManager
from sentinel_tpu.local.sph import entry


def main() -> None:
    FlowRuleManager.load_rules([
        FlowRule(
            resource="paced",
            count=10,  # one pass every ~100ms
            control_behavior=ControlBehavior.RATE_LIMITER,
            max_queueing_time_ms=2_000,
        )
    ])
    t0 = time.time()
    stamps = []
    for i in range(10):  # a burst of 10 arrives at once
        try:
            with entry("paced"):
                stamps.append(time.time() - t0)
        except BlockException:
            print(f"request {i}: queue full, rejected")
    gaps = [round(b - a, 3) for a, b in zip(stamps, stamps[1:])]
    print(f"pass times: {[round(s, 3) for s in stamps]}")
    print(f"gaps: {gaps} (~0.1s each — the burst was paced, not dropped)")
    FlowRuleManager.reset_for_tests()


if __name__ == "__main__":
    main()
