"""Outcome feedback: entry → complete(rt, exception) → the metric surface
moving, with zero extra RPCs.

The cluster grants tokens; this demo closes the loop with what the
admitted work actually *did*. A client records each entry's completion
locally (``record_outcome(flow_id, rt_ms, exception=)``), and the
buffered rows ride the NEXT request frame as piggy-backed wire-rev-6
``OUTCOME_REPORT`` frames — fire-and-forget, no response, no extra round
trip. The server scatters them into per-flow device state columns
(windowed rt_sum / complete / exception counts plus a log2 RT histogram
for a device-side p99), and the whole metric surface moves:
``sentinel_flow_rt_avg_ms`` climbs as the simulated dependency slows,
``sentinel_flow_exception_qps`` lights up under an error burst, and the
drop counter accounts for a deliberately bogus report. See
docs/OBSERVABILITY.md "Outcome-feedback series".
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.cluster.server import TokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.engine import ClusterFlowRule, EngineConfig, TokenStatus
from sentinel_tpu.metrics.server import server_metrics

FLOW = 707


def flow_gauge(name: str) -> float:
    """Read one per-flow gauge for FLOW off the live Prometheus body."""
    needle = f'{name}{{flow_id="{FLOW}"}} '
    for line in server_metrics().render().splitlines():
        if line.startswith(needle):
            return float(line.split()[-1])
    return 0.0


def main() -> None:
    svc = DefaultTokenService(EngineConfig(max_flows=16, max_namespaces=4))
    svc.load_rules([ClusterFlowRule(FLOW, 1000.0, namespace="checkout")])
    server = TokenServer(svc, port=0)
    server.start()
    # generous timeout: the first device step compiles, and a timed-out
    # request would silently skip that iteration's completion record
    client = TokenClient("127.0.0.1", server.port, timeout_ms=2000)
    print(f"token server on :{server.port} — flow {FLOW} (ns 'checkout')")

    try:
        # phase 1: healthy dependency, ~5ms completions
        for _ in range(20):
            if client.request_token(FLOW).status == TokenStatus.OK:
                client.record_outcome(FLOW, 5.0)
        client.request_token(FLOW)  # outcomes piggyback on this frame
        time.sleep(0.3)             # fire-and-forget: let the server land it
        healthy = flow_gauge("sentinel_flow_rt_avg_ms")
        print(f"healthy:  sentinel_flow_rt_avg_ms = {healthy:.1f}")

        # phase 2: the dependency slows 10x and starts throwing
        for i in range(20):
            if client.request_token(FLOW).status == TokenStatus.OK:
                client.record_outcome(FLOW, 50.0 + i, exception=(i % 4 == 0))
        client.record_outcome(FLOW, -12.0)  # bogus report: validated away
        client.request_token(FLOW)
        time.sleep(0.3)
        slow = flow_gauge("sentinel_flow_rt_avg_ms")
        exc = flow_gauge("sentinel_flow_exception_qps")
        p99 = flow_gauge("sentinel_flow_rt_p99_ms")
        print(f"degraded: sentinel_flow_rt_avg_ms = {slow:.1f} "
              f"(p99 {p99:.0f}ms), sentinel_flow_exception_qps = {exc:g}")

        stats = svc.outcome_stats()
        print(f"server accepted {stats['reported']} outcomes "
              f"({stats['exceptions']} exceptions), dropped "
              f"{dict(stats['dropped'])}")
        print(f"client piggybacked {client.outcome_stats()['frames']} "
              f"outcome frames onto request sends — extra RPCs: 0")
        if slow > healthy and exc > 0:
            print("the RT average moved with the dependency: "
                  "outcome loop closed")
    finally:
        client.close()
        server.stop()
        svc.close()


if __name__ == "__main__":
    main()
