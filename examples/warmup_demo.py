"""Cluster warm-up: the device engine's cold-start admission curve.

reference: ``WarmUpFlowDemo.java`` — but enforced CLUSTER-side: the warmup
token bucket lives as per-flow tensor columns inside the batched decide
kernel (see docs/SHAPING.md), so every connected client shares ONE
cold-start ramp instead of each warming up privately.

Part 1 drives a cold service and shows the count/coldFactor cap. Part 2
prints the admissible-QPS slope curve straight from the compiled rule
columns — the same numbers the kernel's ``warning_qps`` branch evaluates
as the bucket drains from maxToken down to the warning line.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


import numpy as np  # noqa: E402

from sentinel_tpu.core import clock as clock_mod
from sentinel_tpu.core.clock import ManualClock
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.engine import ClusterFlowRule, EngineConfig
from sentinel_tpu.engine.rules import ControlBehavior, ThresholdMode

FLOW = 301


def main() -> None:
    clock = ManualClock()
    prev_clock = clock_mod.set_clock(clock)
    try:
        svc = DefaultTokenService(
            EngineConfig(max_flows=16, max_namespaces=4, batch_size=64)
        )
        svc.load_rules([
            ClusterFlowRule(
                FLOW, 100.0, ThresholdMode.GLOBAL,
                control_behavior=ControlBehavior.WARM_UP,
                warm_up_period_sec=10, cold_factor=3,
            )
        ])
        clock.set_ms(10_000)

        # --- part 1: a cold cluster admits count/coldFactor ---------------
        admitted = 0
        for _ in range(200):
            if svc.request_token(FLOW).ok:
                admitted += 1
            clock.sleep(5)
        print(f"cold cluster, offered 200/s: admitted {admitted} "
              f"(≈ count/coldFactor = 100/3)")

        # --- part 2: the slope curve the kernel walks as tokens drain -----
        table = svc._table
        slot = svc._index.slot_of[FLOW]
        cnt = float(np.asarray(table.count)[slot])
        warn = float(np.asarray(table.warning_token)[slot])
        max_tok = float(np.asarray(table.max_token)[slot])
        slope = float(np.asarray(table.slope)[slot])
        print(f"\nrule columns: warningToken={warn:.0f} maxToken={max_tok:.0f}"
              f" slope={slope:.6f}")
        print("admissible QPS as the stored-token bucket drains:")
        for tok in np.linspace(max_tok, warn, 6):
            qps = 1.0 / ((tok - warn) * slope + 1.0 / cnt)
            print(f"  tokens={tok:6.0f}  admissible={qps:5.1f}/s")
        print(f"below the warning line the full count applies: {cnt:.0f}/s")
    finally:
        clock_mod.set_clock(prev_clock)


if __name__ == "__main__":
    main()
