"""Cluster flow control: a TPU-backed token server enforcing one global
budget across several TCP clients.

reference: ``sentinel-demo-cluster`` (embedded mode) — the server here is
``DefaultTokenService`` (micro-batched device kernel) behind the asyncio
transport; clients speak the 5-type binary protocol.
"""

import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.cluster.server import TokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.engine import ClusterFlowRule, EngineConfig
from sentinel_tpu.engine.rules import ThresholdMode


def main() -> None:
    svc = DefaultTokenService(EngineConfig(max_flows=64, max_namespaces=4,
                                           batch_size=128))
    svc.load_rules([
        ClusterFlowRule(flow_id=101, count=30.0, mode=ThresholdMode.GLOBAL)
    ])
    server = TokenServer(svc, port=0, metrics_port=0)
    server.start()
    print(f"token server on :{server.port} — flow 101 global budget 30/s "
          f"(metrics on :{server.metrics_port})")
    clients = [
        TokenClient("127.0.0.1", server.port, timeout_ms=2000) for _ in range(3)
    ]
    try:
        t0 = time.time()
        granted = [0, 0, 0]
        asked = 90  # round-robin across the clients, well over budget
        for i in range(asked):
            c = clients[i % 3]
            if c.request_token(101).ok:
                granted[i % 3] += 1
        elapsed = time.time() - t0
        windows = int(elapsed) + 1  # 1s sliding windows touched
        print(f"{asked} asks round-robin in {elapsed:.2f}s; granted per "
              f"client: {granted}")
        print(f"total granted {sum(granted)} ≤ {30 * windows} "
              f"(30/s GLOBAL budget × {windows} window(s)) — the three "
              f"clients share ONE budget")
        # the embedded Prometheus surface saw every verdict go by — see
        # docs/OBSERVABILITY.md for the full series reference
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.metrics_port}/metrics", timeout=5
        ) as rsp:
            scrape = rsp.read().decode()
        print("pipeline metrics scrape says:")
        for line in scrape.splitlines():
            name = line.split("{")[0].split(" ")[0]
            if name == "sentinel_server_verdicts_total" or (
                name.startswith("sentinel_server_") and name.endswith("_count")
            ):
                print(" ", line)
    finally:
        for c in clients:
            c.close()
        server.stop()


if __name__ == "__main__":
    main()
