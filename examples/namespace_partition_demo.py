"""Two-pod namespace partitioning: tier-2 (DCN) scale-out.

One TPU pod serves one namespace partition (tier 1: the pod's chips shard
the flow axis over ICI — see ``parallel/sharding.py``); namespaces partition
ACROSS pods host-side (tier 2), so the fleet scales beyond a single pod
without any cross-pod coordination on the hot path. This demo runs two
"pods" as two token servers in one process, routes by namespace through
``RoutingTokenClient``, then MOVES a namespace between pods live — in-flight
traffic keeps flowing, budgets stay enforced by the new owner.

reference shape: assignment config of ``sentinel-cluster`` (one token server
per namespace group); the partitioning itself is a TPU-build extension
(SURVEY.md §7.5).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.cluster.namespaces import NamespaceAssignment, partition_rules
from sentinel_tpu.cluster.routing import RoutingTokenClient
from sentinel_tpu.cluster.server import TokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.engine import ClusterFlowRule, EngineConfig
from sentinel_tpu.engine.rules import ThresholdMode


def main() -> None:
    # flows 1xx live in namespace "payments", flows 2xx in "search"
    rules = [
        ClusterFlowRule(flow_id=101, count=20.0, mode=ThresholdMode.GLOBAL,
                        namespace="payments"),
        ClusterFlowRule(flow_id=201, count=40.0, mode=ThresholdMode.GLOBAL,
                        namespace="search"),
    ]
    assignment = NamespaceAssignment({"payments": "pod0", "search": "pod1"})

    # one token server per pod, each loading ONLY its partition's rules
    by_pod = partition_rules(rules, assignment)
    pods = {}
    cfg = EngineConfig(max_flows=64, max_namespaces=4, batch_size=128)
    for pod_id in ("pod0", "pod1"):
        svc = DefaultTokenService(cfg)
        svc.load_rules(by_pod.get(pod_id, []))
        server = TokenServer(svc, port=0)
        server.start()
        pods[pod_id] = server
        print(f"{pod_id}: token server on :{server.port} serving "
              f"{assignment.namespaces_of(pod_id)}")

    namespace_of = {r.flow_id: r.namespace for r in rules}
    router = RoutingTokenClient(
        timeout_ms=2000,
        namespace_of=namespace_of,
        pod_of=assignment.snapshot(),
        endpoints={p: ("127.0.0.1", s.port) for p, s in pods.items()},
    )
    try:
        granted = {101: 0, 201: 0}
        for _ in range(60):
            for fid in (101, 201):
                if router.request_token(fid).ok:
                    granted[fid] += 1
        print(f"60 asks each: payments flow 101 granted {granted[101]} "
              f"(budget 20), search flow 201 granted {granted[201]} "
              f"(budget 40) — different pods, independent budgets")

        # live re-partition: move "search" onto pod0 (e.g. pod1 drains for
        # maintenance). The new owner loads the namespace's rules; the
        # router re-points; counters start fresh on the new owner (counters
        # are ephemeral — same stance as the reference on server failover).
        assignment.assign("search", "pod0")
        pods["pod0"].service.load_namespace_rules(
            "search", [r for r in rules if r.namespace == "search"]
        )
        router.update(pod_of=assignment.snapshot())
        moved = sum(router.request_token(201).ok for _ in range(60))
        print(f"after moving 'search' to pod0: granted {moved} of 60 "
              f"(fresh 40-budget on the new owner) — traffic never stopped")
    finally:
        router.close()
        for server in pods.values():
            server.stop()


if __name__ == "__main__":
    main()
