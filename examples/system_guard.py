"""System-adaptive (BBR-style) inbound protection.

reference: ``SystemGuardDemo.java`` / ``SystemRuleManager.java:290-340`` —
a global qps ceiling over ALL inbound traffic, independent of per-resource
rules.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.core import clock as clock_mod
from sentinel_tpu.core.clock import ManualClock
from sentinel_tpu.local import BlockException, EntryType
from sentinel_tpu.local.sph import entry
from sentinel_tpu.local.system_adaptive import SystemRule, SystemRuleManager


def main() -> None:
    clock = ManualClock()
    prev = clock_mod.set_clock(clock)
    try:
        SystemRuleManager.load_rules([SystemRule(qps=50)])
        clock.set_ms(10_000)
        passed = blocked = 0
        for _ in range(120):
            try:
                with entry("anyInboundApi", EntryType.IN):
                    passed += 1
            except BlockException:
                blocked += 1
        print(f"offered 120 inbound this second: pass={passed} block={blocked}")
        print("(global system qps=50 guards every IN entry)")
    finally:
        SystemRuleManager.reset_for_tests()
        clock_mod.set_clock(prev)


if __name__ == "__main__":
    main()
