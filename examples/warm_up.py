"""Warm-up (cold start) traffic shaping.

reference: ``WarmUpFlowDemo.java`` / ``WarmUpController.java:64-170``.

Part 1 guards real traffic: a cold system admits only count/coldFactor.
Part 2 drives the controller with sustained warning-rate readings (the
reference's own ``WarmUpControllerTest`` pattern — under single-threaded
deterministic load the drain never triggers, in the reference too, because
admissions cluster into one bucket per second) and prints the admissible-QPS
curve as the token bucket drains from cold to warm.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.core import clock as clock_mod
from sentinel_tpu.core.clock import ManualClock
from sentinel_tpu.local import BlockException
from sentinel_tpu.local.flow import (
    ControlBehavior,
    FlowRule,
    FlowRuleManager,
    WarmUpController,
)
from sentinel_tpu.local.sph import entry


class _Node:
    """Minimal stat stub for driving the controller directly."""

    def __init__(self):
        self.cur_pass = 0.0
        self.prev = 0.0

    def pass_qps(self, now=None):
        return self.cur_pass

    def previous_pass_qps(self, now=None):
        return self.prev


def main() -> None:
    clock = ManualClock()
    prev_clock = clock_mod.set_clock(clock)
    try:
        # --- part 1: cold cap on real entries (count=100, coldFactor=3) ---
        FlowRuleManager.load_rules([
            FlowRule(
                resource="warm",
                count=100,
                control_behavior=ControlBehavior.WARM_UP,
                warm_up_period_sec=5,
            )
        ])
        clock.set_ms(10_000)
        passed = 0
        for _ in range(200):
            try:
                with entry("warm"):
                    passed += 1
            except BlockException:
                pass
            clock.sleep(5)
        print(f"cold system, offered 200/s: admitted {passed} "
              f"(≈ count/coldFactor = 100/3)")

        # --- part 2: the warm-up curve under sustained warning-rate load ---
        ctl = WarmUpController(count=100, warm_up_period_sec=5)
        node = _Node()
        clock.set_ms(100_000)
        print("\nsustained load at the admissible rate (tokens drain):")
        for second in range(9):
            # measure this second's admissible rate, then feed it back as the
            # measured pass qps of the next sync (sustained saturation)
            node.cur_pass = 0.0
            admissible = 0
            for _ in range(150):
                if ctl.can_pass(node, 1):
                    node.cur_pass += 1
                    admissible += 1
            print(f"  t={second}s admissible={admissible}/s "
                  f"stored_tokens={ctl._stored_tokens:.0f}")
            node.prev = float(admissible + 1)  # concurrency jitter: ≥ warning
            clock.sleep(1_000)
        print("tokens fell below the warning line → full rate (count=100)")
    finally:
        FlowRuleManager.reset_for_tests()
        clock_mod.set_clock(prev_clock)


if __name__ == "__main__":
    main()
