"""Full control plane in one process: a guarded app with a command center +
heartbeat, and a dashboard that discovers it, pulls metrics, and pushes a
rule to it.

reference: ``sentinel-dashboard`` + ``sentinel-transport`` wiring.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


import json
import time
import urllib.request

from sentinel_tpu.dashboard.server import DashboardServer
from sentinel_tpu.local import BlockException
from sentinel_tpu.local.flow import FlowRuleManager
from sentinel_tpu.local.sph import entry
from sentinel_tpu.metrics.log import MetricTimer
from sentinel_tpu.transport.command import CommandCenter
from sentinel_tpu.transport.heartbeat import HeartbeatSender


def main() -> None:
    dash = DashboardServer(port=0).start()
    cc = CommandCenter(port=0).start()
    hb = HeartbeatSender(
        dashboard_addrs=[f"127.0.0.1:{dash.port}"],
        command_port=cc.port,
        interval_ms=500,
        client_ip="127.0.0.1",
    ).start()
    mt = MetricTimer(interval_s=0.5).start()
    try:
        print(f"dashboard :{dash.port}  command center :{cc.port}")
        # drive some traffic (unguarded by rules yet)
        for _ in range(60):
            try:
                with entry("demoApi"):
                    pass
            except BlockException:
                pass
        time.sleep(2.5)  # heartbeat registers; metric log flushes; fetch runs

        apps = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{dash.port}/apps", timeout=3))
        print("dashboard discovered:",
              [(a["name"], len(a["machines"])) for a in apps])

        # push a flow rule through the dashboard to the app
        app_name = apps[0]["name"]
        req = urllib.request.Request(
            f"http://127.0.0.1:{dash.port}/rules?app={app_name}&type=flow",
            data=json.dumps([{"resource": "demoApi", "count": 3}]).encode(),
            headers={"Content-Type": "application/json"},
        )
        print("rule push:", json.load(urllib.request.urlopen(req, timeout=3)))
        ok = 0
        for _ in range(10):
            try:
                with entry("demoApi"):
                    ok += 1
            except BlockException:
                pass
        print(f"after pushed rule count=3: admitted {ok}/10")
    finally:
        mt.stop()
        hb.stop()
        cc.stop()
        dash.stop()
        FlowRuleManager.reset_for_tests()


if __name__ == "__main__":
    main()
