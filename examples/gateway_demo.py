"""API-gateway flow control: route rules, a custom API group, and per-client
parameter limiting.

reference: ``sentinel-demo-api-gateway`` (zuul/spring-cloud-gateway demos) —
a route rule paces the whole route, a ``GatewayParamFlowItem`` keys the
budget per client IP, and an ``ApiDefinition`` groups paths under one shared
budget (``GatewayApiMatcherManager`` pick).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


from sentinel_tpu.core import clock as clock_mod
from sentinel_tpu.core.clock import ManualClock
from sentinel_tpu.local import BlockException
from sentinel_tpu.adapters.gateway import (
    DictRequestAdapter,
    GatewayFlowRule,
    GatewayGuard,
    GatewayParamFlowItem,
    GatewayRuleManager,
    ParseStrategy,
    ResourceMode,
)
from sentinel_tpu.adapters.gateway_api import (
    ApiDefinition,
    ApiPathPredicateItem,
    GatewayApiDefinitionManager,
    UrlMatchStrategy,
)


def serve(route: str, path: str, ip: str) -> bool:
    request = DictRequestAdapter(ip=ip)
    try:
        with GatewayGuard(route, request, path=path):
            return True
    except BlockException:
        return False


def main() -> None:
    clock = ManualClock()
    prev = clock_mod.set_clock(clock)
    try:
        clock.set_ms(10_000)
        # every /product/* path shares ONE "product-api" budget
        GatewayApiDefinitionManager.load_api_definitions([
            ApiDefinition(
                "product-api",
                (ApiPathPredicateItem("/product/",
                                      UrlMatchStrategy.PREFIX),),
            )
        ])
        GatewayRuleManager.load_rules([
            # per-client budget on the route: 3 QPS per distinct IP
            GatewayFlowRule(
                resource="shop-route", count=3,
                param_item=GatewayParamFlowItem(
                    parse_strategy=ParseStrategy.CLIENT_IP
                ),
            ),
            # the API group caps all /product/* paths together at 5 QPS
            GatewayFlowRule(
                resource="product-api",
                resource_mode=ResourceMode.CUSTOM_API_NAME, count=5,
            ),
        ])

        per_ip = {}
        for ip in ("10.0.0.1", "10.0.0.2"):
            per_ip[ip] = sum(
                serve("shop-route", "/cart", ip) for _ in range(6)
            )
        print(f"route per-IP budgets: {per_ip} (3 QPS each)")
        assert per_ip == {"10.0.0.1": 3, "10.0.0.2": 3}, per_ip

        clock.advance(1000)
        passed = sum(
            serve("shop-route", f"/product/{i}", f"10.0.1.{i}")
            for i in range(8)
        )
        print(f"product-api group: {passed}/8 passed (5 QPS shared across "
              "paths and IPs)")
        # the route's per-IP budget (3/ip) never binds here — 8 distinct
        # IPs, one request each — so the shared API-group cap is what limits
        assert passed == 5, passed
    finally:
        GatewayRuleManager.reset_for_tests()
        GatewayApiDefinitionManager.reset_for_tests()
        clock_mod.set_clock(prev)


if __name__ == "__main__":
    main()
