"""The demo-basic slice: one QPS=20 flow rule on "HelloWorld".

reference: ``sentinel-demo-basic/.../flow/FlowQpsDemo.java`` — expect ~20
passes per second, the rest blocked.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


import time

from sentinel_tpu.local import BlockException
from sentinel_tpu.local.flow import FlowRule, FlowRuleManager
from sentinel_tpu.local.sph import entry


def main(seconds: float = 2.0) -> None:
    FlowRuleManager.load_rules([FlowRule(resource="HelloWorld", count=20)])
    deadline = time.time() + seconds
    second = int(time.time())
    passed = blocked = 0
    while time.time() < deadline:
        try:
            with entry("HelloWorld"):
                passed += 1
        except BlockException:
            blocked += 1
        if int(time.time()) != second:
            print(f"second {second}: pass={passed} block={blocked}")
            second, passed, blocked = int(time.time()), 0, 0
        time.sleep(0.001)
    print(f"second {second}: pass={passed} block={blocked}")


if __name__ == "__main__":
    main()
