"""Two token-server pods in separate processes, one routing client.

reference: the multi-server deployment of ``sentinel-cluster`` — each
namespace's flows are owned by one token server and clients are pointed at
their server via assignment config. Here the DCN-tier pieces run live:
two OS processes each serve one namespace over real TCP, and a
``RoutingTokenClient`` routes ``flow_id → namespace → pod`` so the caller
never thinks about the partitioning (``cluster/routing.py``,
``cluster/namespaces.py``).

Each flow has a 3-QPS budget; six requests through the routing client show
exactly 3 admitted by the owning pod, and pods never see the other
namespace's flows.
"""

import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Route platform selection through jax.config: the axon environment resolves
# JAX_PLATFORMS at backend-init inside its register hook, which can block on
# a down tunnel; an explicit config.update pins the platform up front.
import jax  # noqa: E402

_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p.split(",")[0])


FLOWS = {"ns-payments": (1, 2), "ns-search": (11, 12)}


def pod_main(namespace: str, port_file: str) -> None:
    from sentinel_tpu.cluster.server import TokenServer
    from sentinel_tpu.cluster.token_service import DefaultTokenService
    from sentinel_tpu.core import clock as clock_mod
    from sentinel_tpu.core.clock import ManualClock
    from sentinel_tpu.engine import ClusterFlowRule, EngineConfig
    from sentinel_tpu.engine.rules import ThresholdMode

    # frozen per-pod clock: the 3-of-6 admission assertion must not depend
    # on a wall-clock window roll mid-demo (FAST_EXAMPLES determinism)
    clock_mod.set_clock(ManualClock())
    service = DefaultTokenService(
        EngineConfig(max_flows=64, max_namespaces=4, batch_size=64),
        serve_buckets=(64,),
    )
    service.load_rules([
        ClusterFlowRule(flow_id=f, count=3.0, mode=ThresholdMode.GLOBAL,
                        namespace=namespace)
        for f in FLOWS[namespace]
    ])
    server = TokenServer(service, port=0)
    server.start()
    # atomic publication: the parent must never parse a half-written port
    tmp_path = port_file + ".tmp"
    with open(tmp_path, "w") as f:
        f.write(str(server.port))
    os.rename(tmp_path, port_file)
    # exit when the parent does: stdin is a pipe from the parent, so EOF
    # means it died (no orphan pods holding ports on a killed harness)
    sys.stdin.read()


def main() -> None:
    from sentinel_tpu.cluster.routing import RoutingTokenClient
    from sentinel_tpu.engine import TokenStatus

    tmp = tempfile.mkdtemp()
    pods = {}
    try:
        for ns in FLOWS:
            port_file = os.path.join(tmp, f"{ns}.port")
            proc = subprocess.Popen(
                [sys.executable, __file__, "--pod", ns, port_file],
                stdin=subprocess.PIPE,
            )
            pods[ns] = [proc, port_file, None]
        for ns, entry in pods.items():
            deadline = time.time() + 60
            while time.time() < deadline:
                rc = entry[0].poll()
                assert rc is None, f"pod {ns} died at startup (rc={rc})"
                try:
                    with open(entry[1]) as f:
                        entry[2] = int(f.read())
                    break
                except (OSError, ValueError):
                    time.sleep(0.1)
            assert entry[2], f"pod {ns} never published its port"

        router = RoutingTokenClient(
            timeout_ms=5000,
            namespace_of={f: ns for ns, fs in FLOWS.items() for f in fs},
            pod_of={"ns-payments": "podA", "ns-search": "podB"},
            endpoints={"podA": ("127.0.0.1", pods["ns-payments"][2]),
                       "podB": ("127.0.0.1", pods["ns-search"][2])},
        )
        for ns, fs in FLOWS.items():
            flow = fs[0]
            results = router.request_batch([(flow, 1, False)] * 6)
            ok = sum(r.status == TokenStatus.OK for r in results)
            blocked = sum(r.status == TokenStatus.BLOCKED for r in results)
            print(f"{ns}: flow {flow} -> {ok} OK / {blocked} BLOCKED "
                  f"(3-QPS budget enforced by its owning pod)")
            assert (ok, blocked) == (3, 3), (ns, ok, blocked)
        # a flow the routing tables don't know degrades cleanly, no pod hit
        r = router.request_token(999)
        print(f"unrouted flow 999 -> {r.status.name}")
        assert r.status == TokenStatus.NO_RULE_EXISTS
        router.close()
    finally:
        for proc, _, _ in pods.values():
            proc.terminate()
        for proc, _, _ in pods.values():
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--pod":
        pod_main(sys.argv[2], sys.argv[3])
    else:
        main()
