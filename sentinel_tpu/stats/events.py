"""Metric event channels.

Analog of ``sentinel-core/.../slots/statistic/MetricEvent.java:21-38``
({PASS, BLOCK, EXCEPTION, SUCCESS, RT, OCCUPIED_PASS}). RT is stored in a
separate float32 tensor (sums of milliseconds overflow int32 at high QPS),
so the integer channel list here has five entries.
"""

from __future__ import annotations

import enum


class Event(enum.IntEnum):
    PASS = 0
    BLOCK = 1
    EXCEPTION = 2
    SUCCESS = 3
    OCCUPIED_PASS = 4


N_EVENTS = len(Event)
