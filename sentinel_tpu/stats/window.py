"""Sliding-window counters as ring-indexed device tensors.

This is the TPU-native re-design of the reference's ``LeapArray<T>``
(``sentinel-core/.../slots/statistic/base/LeapArray.java:41``): a circular array
of time buckets where ``idx = (now // bucket_ms) % n_buckets`` and a bucket is
*deprecated* (excluded from reads) once its recorded window start falls outside
``(now - interval, now]``.

Key differences from the JVM design, driven by XLA semantics:

- **One global clock per step.** The reference resets buckets lazily per
  resource with a CAS loop (``LeapArray.java:116-160``) because each thread
  carries its own ``now``. A batched kernel applies a single ``now_ms`` to the
  whole step, so bucket occupancy is *uniform across resources*: the window
  start of ring slot ``b`` is one shared ``starts[b]`` vector, not per-resource
  state. Reset becomes "zero the counts column whose slot is being re-occupied"
  — a masked elementwise op, no CAS.

- **Mask-on-read instead of reset-on-read.** Buckets that went stale during an
  idle gap keep old counts but are excluded by the validity mask
  (``starts[b] in (now - interval, now]``); they are zeroed when their slot is
  next written. Matches ``LeapArray.isWindowDeprecated`` + ``values()`` read
  semantics (``LeapArray.java:257-266``).

- **Engine-relative int32 time.** Timestamps are milliseconds since an
  engine-chosen epoch so they fit int32 without enabling jax x64 (which would
  change dtype defaults for embedding applications). int32 ms wraps after
  ~24.8 days; hosts re-base the epoch with :func:`rebase` well before that
  (a single subtraction over ``starts``).

All functions are pure, jit-compatible, and take ``now`` explicitly (the test
lesson from the reference's PowerMock clock fixture, SURVEY.md §4).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

# Sentinel value for "slot never occupied": far in the past relative to any
# engine-relative timestamp (engine time starts near 0).
NEVER = jnp.int32(-(2**30))


class WindowSpec(NamedTuple):
    """Static geometry of a sliding window.

    reference: ``LeapArray(sampleCount, intervalInMs)`` with
    ``windowLengthInMs = intervalInMs / sampleCount`` (``LeapArray.java:61-72``).
    """

    bucket_ms: int
    n_buckets: int

    @property
    def interval_ms(self) -> int:
        return self.bucket_ms * self.n_buckets


class WindowState(NamedTuple):
    """Dynamic window state (a pytree of device arrays).

    ``starts``: ``[n_buckets] int32`` — engine-ms window start currently
    occupying each ring slot (shared across resources; see module docstring).
    ``counts``: ``[n_resources, n_buckets, n_channels]`` int32 (or float32 for
    RT-style accumulators) — per-resource, per-bucket event counters.
    """

    starts: jax.Array
    counts: jax.Array


def make_window(
    spec: WindowSpec, n_resources: int, n_channels: int, dtype=jnp.int32
) -> WindowState:
    return WindowState(
        starts=jnp.full((spec.n_buckets,), NEVER, dtype=jnp.int32),
        counts=jnp.zeros((n_resources, spec.n_buckets, n_channels), dtype=dtype),
    )


def bucket_index(spec: WindowSpec, now: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """``(ring slot, window start)`` for time ``now``.

    reference: ``LeapArray.calculateTimeIdx`` / ``calculateWindowStart``
    (``LeapArray.java:100-108``).
    """
    now = jnp.asarray(now, jnp.int32)
    idx = (now // spec.bucket_ms) % spec.n_buckets
    start = now - now % spec.bucket_ms
    return idx, start


def roll(spec: WindowSpec, ws: WindowState, now: jax.Array) -> WindowState:
    """Ensure the ring slot for ``now`` holds the current window (zero if stale).

    Analog of the reset arm of ``LeapArray.currentWindow`` (``LeapArray.java:
    132-160``) — but a data-parallel masked zero instead of a CAS race.
    """
    idx, cur_start = bucket_index(spec, now)
    stale = ws.starts[idx] != cur_start
    # scatter-multiply of ONE bucket column ([R, E]) instead of rewriting the
    # whole [R, B, E] tensor — keeps the roll O(R·E) per step
    keep = jnp.where(stale, 0, 1).astype(ws.counts.dtype)
    counts = ws.counts.at[:, idx, :].multiply(keep)
    starts = ws.starts.at[idx].set(cur_start)
    return WindowState(starts=starts, counts=counts)


def add_events(
    spec: WindowSpec,
    ws: WindowState,
    now: jax.Array,
    resource_ids: jax.Array,
    channel_ids: jax.Array,
    values: jax.Array,
    valid: Optional[jax.Array] = None,
) -> WindowState:
    """Batched scatter-add of ``values`` into the current bucket.

    Replaces the reference's per-request ``bucket.addPass(n)`` LongAdder
    increments (``MetricBucket.java``) with one ``scatter-add``; duplicate
    ``(resource, channel)`` pairs within the batch accumulate correctly.
    """
    ws = roll(spec, ws, now)
    idx, _ = bucket_index(spec, now)
    if valid is not None:
        values = jnp.where(valid, values, 0)
    counts = ws.counts.at[resource_ids, idx, channel_ids].add(
        values.astype(ws.counts.dtype), mode="drop"
    )
    return WindowState(starts=ws.starts, counts=counts)


def add_event_rows(
    spec: WindowSpec,
    ws: WindowState,
    now: jax.Array,
    resource_ids: jax.Array,
    row_updates: jax.Array,
    channels: Optional[Tuple[int, ...]] = None,
) -> WindowState:
    """Scatter-add ``row_updates[i, j]`` ([K, len(channels)]) into channel
    ``channels[j]`` of the current bucket of resource ``resource_ids[i]``.

    One scatter per *static* channel: measured on v5e, a scatter whose only
    traced index dimension is the resource row costs ~70ns/row, while adding
    the channel as a second traced index dimension (the 5N-concatenation
    form) or as a scatter update window is 4–10× slower. This is the
    decision kernel's write path. Rows intended as no-ops must carry zero
    updates (or an out-of-range id to drop the row entirely).
    """
    ws = roll(spec, ws, now)
    idx, _ = bucket_index(spec, now)
    counts = ws.counts
    chans = range(row_updates.shape[1]) if channels is None else channels
    for j, ch in enumerate(chans):
        counts = counts.at[resource_ids, idx, int(ch)].add(
            row_updates[:, j].astype(counts.dtype), mode="drop"
        )
    return WindowState(starts=ws.starts, counts=counts)


def add_column(
    spec: WindowSpec,
    ws: WindowState,
    now: jax.Array,
    deltas: jax.Array,
    channel: int = 0,
) -> WindowState:
    """Add a dense per-resource delta vector ([n_resources]) to one channel of
    the current bucket — for small resource axes (the namespace guard) where
    the deltas are cheaper to materialize densely (one-hot matvec) than to
    scatter row-by-row."""
    ws = roll(spec, ws, now)
    idx, _ = bucket_index(spec, now)
    counts = ws.counts.at[:, idx, channel].add(deltas.astype(ws.counts.dtype))
    return WindowState(starts=ws.starts, counts=counts)


def valid_mask(spec: WindowSpec, ws: WindowState, now: jax.Array) -> jax.Array:
    """``[n_buckets] bool`` — slots whose window is inside ``(now - interval, now]``.

    reference: ``!isWindowDeprecated(time, w)`` i.e.
    ``time - windowStart < intervalInMs`` (``LeapArray.java:250-266``).
    """
    now = jnp.asarray(now, jnp.int32)
    age = now - ws.starts
    return (age >= 0) & (age < spec.interval_ms)


def window_sum(
    spec: WindowSpec, ws: WindowState, now: jax.Array, channel: int
) -> jax.Array:
    """``[n_resources]`` sum of one channel over valid buckets
    (``ArrayMetric.pass_()/block()…`` read path)."""
    mask = valid_mask(spec, ws, now)
    return jnp.sum(
        ws.counts[:, :, channel] * mask[None, :].astype(ws.counts.dtype), axis=1
    )


def window_sum_at(
    spec: WindowSpec,
    ws: WindowState,
    now: jax.Array,
    channel: int,
    ids: jax.Array,
) -> jax.Array:
    """``[K]`` valid-bucket sums of one channel at resource rows ``ids``.

    Gather-first: reads ``O(K · n_buckets)`` instead of reducing the whole
    ``[n_resources, n_buckets]`` plane — the read path stays independent of
    the table size (matters at 10^5–10^6 rule slots)."""
    mask = valid_mask(spec, ws, now)
    rows = ws.counts[ids, :, channel]  # [K, B]
    return jnp.sum(rows * mask[None, :].astype(rows.dtype), axis=1)


def window_sum_all(spec: WindowSpec, ws: WindowState, now: jax.Array) -> jax.Array:
    """``[n_resources, n_channels]`` sums over valid buckets."""
    mask = valid_mask(spec, ws, now)
    return jnp.sum(
        ws.counts * mask[None, :, None].astype(ws.counts.dtype), axis=1
    )


def avg_qps(spec: WindowSpec, total: jax.Array) -> jax.Array:
    """Per-second rate from a window sum (``StatisticNode.passQps`` divides by
    ``IntervalProperty.INTERVAL/1000``)."""
    return total.astype(jnp.float32) * (1000.0 / spec.interval_ms)


def rebase(ws: WindowState, delta_ms: int) -> WindowState:
    """Shift the engine epoch forward by ``delta_ms`` (host maintenance op, run
    well before int32 engine-ms wraps at ~24.8 days)."""
    starts = jnp.where(ws.starts == NEVER, ws.starts, ws.starts - jnp.int32(delta_ms))
    return WindowState(starts=starts, counts=ws.counts)


# ---------------------------------------------------------------------------
# Future (occupy/borrow) windows — analog of FutureBucketLeapArray
# (``slots/statistic/metric/occupy/FutureBucketLeapArray.java``): same ring, but
# a slot is valid when its window lies strictly in the future within the next
# interval. Used by prioritized requests to "borrow" capacity from upcoming
# windows (``OccupiableBucketLeapArray.java:29-73``, ``StatisticNode.tryOccupyNext``).
# ---------------------------------------------------------------------------


def future_valid_mask(spec: WindowSpec, ws: WindowState, now: jax.Array) -> jax.Array:
    now = jnp.asarray(now, jnp.int32)
    ahead = ws.starts - now
    return (ahead > 0) & (ahead <= spec.interval_ms)


def future_sum(
    spec: WindowSpec, ws: WindowState, now: jax.Array, channel: int
) -> jax.Array:
    """``[n_resources]`` occupied counts waiting in future windows
    (``OccupiableBucketLeapArray.currentWaiting``)."""
    mask = future_valid_mask(spec, ws, now)
    return jnp.sum(
        ws.counts[:, :, channel] * mask[None, :].astype(ws.counts.dtype), axis=1
    )


def future_sum_at(
    spec: WindowSpec,
    ws: WindowState,
    now: jax.Array,
    channel: int,
    ids: jax.Array,
) -> jax.Array:
    """``[K]`` future-window sums at resource rows ``ids`` (gather-first
    counterpart of :func:`future_sum`)."""
    mask = future_valid_mask(spec, ws, now)
    rows = ws.counts[ids, :, channel]
    return jnp.sum(rows * mask[None, :].astype(rows.dtype), axis=1)


def add_future(
    spec: WindowSpec,
    ws: WindowState,
    now: jax.Array,
    wait_ms: jax.Array,
    resource_ids: jax.Array,
    channel_ids: jax.Array,
    values: jax.Array,
    valid: Optional[jax.Array] = None,
    combine_desired=None,
) -> WindowState:
    """Scatter-add into the bucket ``wait_ms`` ahead of ``now`` (per request).

    reference: ``OccupiableBucketLeapArray.addWaiting(futureTime, n)``. Each
    request may target a different future slot, so the roll (stale-slot zeroing)
    is computed for the union of targeted slots first, then one scatter-add.

    A ring of ``B`` slots can hold the current window plus at most ``B - 1``
    future windows, so the target window offset is clamped to
    ``[1, B-1]`` buckets ahead — a row can never collide with the current
    bucket's slot or wrap the ring. Rows with ``wait_ms <= 0`` or
    ``valid=False`` are fully masked (they contribute neither counts nor slot
    resets).
    """
    now = jnp.asarray(now, jnp.int32)
    wait_ms = jnp.asarray(wait_ms, jnp.int32)
    row_ok = wait_ms > 0
    if valid is not None:
        row_ok = row_ok & valid
    values = jnp.where(row_ok, values, 0)

    _, cur_start = bucket_index(spec, now)
    future_time = now + wait_ms
    k = (future_time - cur_start) // spec.bucket_ms
    k = jnp.clip(k, 1, spec.n_buckets - 1)
    start = cur_start + k * spec.bucket_ms
    idx = (start // spec.bucket_ms) % spec.n_buckets
    # Masked rows must not drive the slot-reset union below.
    start = jnp.where(row_ok, start, NEVER)

    # Zero any targeted slot whose recorded start differs from the target start.
    # (Duplicate valid targets agree on `start`: after clamping, slot index k
    # uniquely determines the start within one ring period.)
    # `combine_desired` (e.g. a pmax over a mesh axis) lets sharded callers
    # agree on the reset union so the replicated `starts` vector cannot
    # diverge across devices when only the owner shard sees a borrow.
    desired = jnp.full_like(ws.starts, NEVER).at[idx].max(start, mode="drop")
    if combine_desired is not None:
        desired = combine_desired(desired)
    needs_reset = (desired != NEVER) & (desired != ws.starts)
    # A reset only happens the first time a future bucket is targeted (once
    # per bucket_ms at most); lax.cond skips the full-tensor rewrite on the
    # hot no-reset path.
    keep = (~needs_reset).astype(ws.counts.dtype)
    counts = jax.lax.cond(
        jnp.any(needs_reset),
        lambda c: c * keep[None, :, None],
        lambda c: c,
        ws.counts,
    )
    starts = jnp.where(needs_reset, desired, ws.starts)
    counts = counts.at[resource_ids, idx, channel_ids].add(
        values.astype(counts.dtype), mode="drop"
    )
    return WindowState(starts=starts, counts=counts)
