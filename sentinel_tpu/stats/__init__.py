"""Statistics engine: sliding-window counters as device tensors.

Analog of reference L1 (``sentinel-core/.../slots/statistic/{base,data,metric}``),
re-designed for XLA: no CAS, no LongAdder — one ``[resources, buckets, events]``
tensor per window resolution, lazily reset by masking against a shared
window-start vector, updated by batched scatter-adds.
"""

from sentinel_tpu.stats.window import (
    WindowSpec,
    WindowState,
    make_window,
    roll,
    add_events,
    window_sum,
    window_sum_all,
    bucket_index,
)
from sentinel_tpu.stats.events import Event

__all__ = [
    "WindowSpec",
    "WindowState",
    "make_window",
    "roll",
    "add_events",
    "window_sum",
    "window_sum_all",
    "bucket_index",
    "Event",
]
