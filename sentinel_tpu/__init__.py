"""sentinel_tpu — a TPU-native flow-control / traffic-shaping / circuit-breaking framework.

A ground-up re-design of the capabilities of Alibaba Sentinel (reference:
longkaimao/Sentinel, a fork of Sentinel 1.8.4) for JAX/XLA on TPU:

- **Local engine** (``sentinel_tpu.local``): in-process resource guarding —
  ``entry()/exit()`` API, context + invocation tree, slot chain, sliding-window
  statistics, flow rules (4 traffic-shaping behaviors), circuit breakers,
  system-adaptive (BBR) protection, authority rules, hot-param limiting.
  Analog of ``sentinel-core`` (reference ``sentinel-core/src/main/java``).

- **Batched engine** (``sentinel_tpu.engine``): the TPU data plane — all
  counters live in device-resident ``[resources, buckets, events]`` tensors,
  rules are padded tensor tables, and admission is one jitted pure function
  ``decide(state, rules, requests, now_ms) -> (state, verdicts)`` with
  in-batch prefix-sum admission (strictly stronger than the reference's
  per-thread TOCTOU).

- **Cluster** (``sentinel_tpu.cluster``): the token client/server (analog of
  ``sentinel-cluster``) — binary wire protocol, micro-batched front door, and
  a ``TokenService`` whose decision path runs on TPU, sharded over a
  ``jax.sharding.Mesh`` along the resource axis with ``psum`` for global
  limits.

The behavioral contract (rule semantics, verdict statuses, fallback modes)
matches the reference; the architecture does not — see SURVEY.md.
"""

__version__ = "0.1.0"

from sentinel_tpu.core.clock import Clock, ManualClock, SystemClock, now_ms
from sentinel_tpu.core.config import SentinelConfig

__all__ = [
    "Clock",
    "ManualClock",
    "SystemClock",
    "now_ms",
    "SentinelConfig",
    "__version__",
]
