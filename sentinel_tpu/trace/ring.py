"""Always-on flight recorder: per-thread fixed-size struct rings.

Every hop of the serving pipeline — both front doors, the batcher, the
device step boundary, the reply lanes, and the lease/hierarchy/MOVE control
paths — drops a tiny ``(t_ns, stage, xid, shard, aux)`` event into a ring
owned by the recording thread. The discipline mirrors ``chaos/``:

- **Disarmed (the default)** the entire subsystem is ONE module-attribute
  read and branch per hop (``if _TR.ARMED: ...``) — no lock, no call, no
  allocation. This is what keeps the trace-off overhead inside the ≤2%
  serve_smoke gate.
- **Armed** each hop appends one 24-byte row to a thread-local numpy struct
  ring (no lock: one writer per ring) and the write head wraps, so memory
  is fixed no matter how long the recorder runs. Data-plane events are
  further gated by an xid-hash sample (``sample_xid``), so arming at a low
  rate on a production server records a representative slice, not the
  firehose.

Rings are registered process-wide so :mod:`sentinel_tpu.trace.spans` can
assemble per-xid spans across threads and :mod:`sentinel_tpu.trace.blackbox`
can dump the last N seconds post-mortem. A ring whose thread died mid-write
is still readable — readers treat rows as advisory (torn tails drop out in
span assembly), never as a consistency contract.

Env arming (mirrors ``SENTINEL_CHAOS``): ``SENTINEL_TRACE=1`` arms at
import, ``SENTINEL_TRACE_SAMPLE=0.01`` sets the xid sample fraction.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

# -- stage codes (aux meaning in parens) --------------------------------------
CLIENT_IN = 1    # frame decoded / pulled off a door (aux = rows)
ENQUEUE = 2      # frame handed to the batching queue (aux = queue depth)
DISPATCH = 3     # frame's batch entered the device dispatch (aux = batch rows)
DEVICE_IN = 4    # device step submitted (aggregate, xid=0; aux = rows)
DEVICE_OUT = 5   # device step materialized (aggregate, xid=0; aux = rows)
REPLY_OUT = 6    # frame's reply encoded + submitted to its door (aux = rows)
SHED = 7         # frame/rows refused (aux = shed-reason index)
FUSE = 8         # fusion ladder stacked frames (aggregate; aux = depth)
LEASE = 9        # lease grant/renew/return on the server (aux = tokens)
LEASE_LOCAL = 10  # client-local admission against a held lease (aux = n)
HIER = 11        # hierarchy share op (demand/grant/renew/return)
MOVE = 12        # MOVE begin/commit/abort (aux = phase: 0/1/2)
PROMOTE = 13     # standby promoted to primary
BROWNOUT = 14    # admission ladder escalated (aux = level)
SHM_POLL = 15    # shm ring door poll/doorbell activity (aux = frames)
OUTCOME = 16     # batched completion report ingested (aux = rows accepted)

STAGE_NAMES: Dict[int, str] = {
    CLIENT_IN: "client_in",
    ENQUEUE: "enqueue",
    DISPATCH: "dispatch",
    DEVICE_IN: "device_in",
    DEVICE_OUT: "device_out",
    REPLY_OUT: "reply_out",
    SHED: "shed",
    FUSE: "fuse",
    LEASE: "lease",
    LEASE_LOCAL: "lease_local",
    HIER: "hier",
    MOVE: "move",
    PROMOTE: "promote",
    BROWNOUT: "brownout",
    SHM_POLL: "shm_poll",
    OUTCOME: "outcome",
}

# one ring row: 24 bytes, fixed
_EVENT_DTYPE = np.dtype(
    [("t_ns", "<i8"), ("xid", "<i8"), ("stage", "<i2"), ("shard", "<i2"),
     ("aux", "<i4")]
)

DEFAULT_RING_EVENTS = 8192  # per thread; power of two (mask-wrapped)

# -- the armed flag: the ONLY thing hot paths read when tracing is off --------
ARMED: bool = False

# xid sampling: a data-plane xid is recorded iff hash(xid) < _SAMPLE_LIMIT.
# Fibonacci-hash the xid so adjacent xids (every client counts up) spread
# uniformly over the 32-bit range; limit = fraction × 2^32.
_HASH_MULT = 2654435761
_SAMPLE_LIMIT = 1 << 32  # sample everything by default
_SAMPLE_FRACTION = 1.0

_REG_LOCK = threading.Lock()
_RINGS: List["_ThreadRing"] = []
_TLS = threading.local()
_ARMED_AT_NS: Optional[int] = None


class _ThreadRing:
    """One thread's event ring. Single-writer; readers are advisory."""

    __slots__ = ("buf", "idx", "mask", "thread_name")

    def __init__(self, capacity: int, thread_name: str):
        self.buf = np.zeros(capacity, dtype=_EVENT_DTYPE)
        self.idx = 0  # monotonically increasing write head
        self.mask = capacity - 1
        self.thread_name = thread_name

    def write(self, t_ns: int, stage: int, xid: int, shard: int,
              aux: int) -> None:
        i = self.idx & self.mask
        row = self.buf[i]
        row["t_ns"] = t_ns
        row["xid"] = xid
        row["stage"] = stage
        row["shard"] = shard
        row["aux"] = aux
        self.idx += 1

    def rows(self) -> np.ndarray:
        """Valid rows, oldest→newest write order (advisory under a live
        writer; a torn tail shows as a t_ns=0 or stale row and is filtered
        by readers)."""
        n = min(self.idx, self.mask + 1)
        if n == 0:
            return self.buf[:0]
        if self.idx <= self.mask + 1:
            return self.buf[:n]
        head = self.idx & self.mask
        return np.concatenate([self.buf[head:], self.buf[:head]])


def _ring() -> _ThreadRing:
    r = getattr(_TLS, "ring", None)
    if r is None:
        r = _ThreadRing(DEFAULT_RING_EVENTS, threading.current_thread().name)
        _TLS.ring = r
        with _REG_LOCK:
            _RINGS.append(r)
    return r


# -- recording (call sites guard with `if ring.ARMED:`) -----------------------
def sample_xid(xid: int) -> bool:
    """True when this xid is inside the sampled slice."""
    return ((xid * _HASH_MULT) & 0xFFFFFFFF) < _SAMPLE_LIMIT


def record(stage: int, xid: int = 0, shard: int = 0, aux: int = 0) -> None:
    """Append one event. Data-plane events (xid != 0) honor the sample;
    control-plane events (xid == 0) always record while armed."""
    if xid and ((xid * _HASH_MULT) & 0xFFFFFFFF) >= _SAMPLE_LIMIT:
        return
    _ring().write(time.monotonic_ns(), stage, xid, shard, aux)


def record_many(stage: int, xids, shard: int = 0, aux: int = 0) -> None:
    """One event per sampled xid in ``xids`` (a batch hop touching many
    frames). Python-loop cost is paid only while armed and only for
    sampled xids."""
    r = _ring()
    t = time.monotonic_ns()
    lim = _SAMPLE_LIMIT
    for x in xids:
        x = int(x)
        if ((x * _HASH_MULT) & 0xFFFFFFFF) < lim:
            r.write(t, stage, x, shard, aux)


# -- arming -------------------------------------------------------------------
def arm(sample: float = 1.0) -> None:
    """Arm the recorder; ``sample`` is the fraction of xids recorded."""
    global ARMED, _SAMPLE_LIMIT, _SAMPLE_FRACTION, _ARMED_AT_NS
    sample = min(1.0, max(0.0, float(sample)))
    _SAMPLE_FRACTION = sample
    _SAMPLE_LIMIT = int(sample * (1 << 32))
    _ARMED_AT_NS = time.monotonic_ns()
    ARMED = True


def disarm() -> None:
    global ARMED
    ARMED = False


def status() -> dict:
    with _REG_LOCK:
        threads = [
            {"thread": r.thread_name,
             "events": int(min(r.idx, r.mask + 1)),
             "dropped": int(max(0, r.idx - (r.mask + 1)))}
            for r in _RINGS
        ]
    return {
        "armed": ARMED,
        "sample": _SAMPLE_FRACTION,
        "ringEvents": DEFAULT_RING_EVENTS,
        "threads": threads,
        "totalEvents": sum(t["events"] for t in threads),
    }


def reset_for_tests() -> None:
    """Disarm and drop every registered ring (tests/benches only — live
    threads re-register their ring on the next armed record)."""
    global _SAMPLE_LIMIT, _SAMPLE_FRACTION, _ARMED_AT_NS
    disarm()
    _SAMPLE_LIMIT = 1 << 32
    _SAMPLE_FRACTION = 1.0
    _ARMED_AT_NS = None
    with _REG_LOCK:
        _RINGS.clear()
    if getattr(_TLS, "ring", None) is not None:
        _TLS.ring = None


# -- reading ------------------------------------------------------------------
def events(
    xid: Optional[int] = None,
    since_ns: Optional[int] = None,
    stages: Optional[set] = None,
) -> List[dict]:
    """Snapshot matching events from EVERY ring (live or torn), sorted by
    time. Rows with t_ns == 0 (never written / torn tail) are dropped."""
    with _REG_LOCK:
        rings = list(_RINGS)
    out: List[dict] = []
    for r in rings:
        rows = r.rows()
        if rows.shape[0] == 0:
            continue
        keep = rows["t_ns"] > 0
        if since_ns is not None:
            keep &= rows["t_ns"] >= since_ns
        if xid is not None:
            keep &= rows["xid"] == xid
        for row in rows[keep]:
            st = int(row["stage"])
            if stages is not None and st not in stages:
                continue
            out.append({
                "t_ns": int(row["t_ns"]),
                "stage": STAGE_NAMES.get(st, str(st)),
                "xid": int(row["xid"]),
                "shard": int(row["shard"]),
                "aux": int(row["aux"]),
                "thread": r.thread_name,
            })
    out.sort(key=lambda e: e["t_ns"])
    return out


def sampled_xids(limit: int = 256) -> List[int]:
    """Distinct data-plane xids seen at client_in, newest first."""
    seen: Dict[int, int] = {}
    for e in events(stages={CLIENT_IN}):
        if e["xid"]:
            seen[e["xid"]] = e["t_ns"]
    ordered = sorted(seen, key=seen.get, reverse=True)
    return ordered[:limit]


def _env_arm() -> None:
    if os.environ.get("SENTINEL_TRACE", "") not in ("", "0"):
        try:
            frac = float(os.environ.get("SENTINEL_TRACE_SAMPLE", "1.0"))
        except ValueError:
            frac = 1.0
        arm(sample=frac)


_env_arm()
