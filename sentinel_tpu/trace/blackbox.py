"""Black-box post-mortem dumps.

When something goes wrong on a serving node — the brownout ladder
escalates, a standby promotes itself, a MOVE aborts, or an operator asks —
the last N seconds of flight-recorder rings plus a full metrics snapshot
and the config fingerprint are dumped atomically to
``blackbox-<ts>.json``. The point is the flight-data-recorder property:
the evidence of WHY is captured at the moment of the event, not
reconstructed later from whatever the dashboards happened to retain.

Auto-dumps are opt-in (``configure(dir)`` or ``SENTINEL_BLACKBOX_DIR``)
and rate-limited so a flapping trigger can't fill a disk; ``dump()`` is
the unconditional operator path. Every trigger call is wrapped so a dump
failure can never take down the path that tripped it — a post-mortem
recorder that crashes the patient is worse than none.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Optional

from sentinel_tpu.core.log import record_log
from sentinel_tpu.trace import ring as _R

_LOCK = threading.Lock()
_DIR: Optional[str] = os.environ.get("SENTINEL_BLACKBOX_DIR") or None
_WINDOW_S: float = 30.0
_MIN_INTERVAL_S: float = 5.0
_last_dump: float = 0.0
dumps_written: int = 0
last_path: Optional[str] = None


def configure(
    directory: Optional[str],
    window_s: float = 30.0,
    min_interval_s: float = 5.0,
) -> None:
    """Enable (or disable with None) automatic trigger dumps."""
    global _DIR, _WINDOW_S, _MIN_INTERVAL_S
    _DIR = directory
    _WINDOW_S = float(window_s)
    _MIN_INTERVAL_S = float(min_interval_s)


def enabled() -> bool:
    return _DIR is not None


def config_fingerprint() -> str:
    """Stable hash of the effective config layers (defaults + file +
    explicit sets) — two dumps with the same fingerprint ran the same
    knobs."""
    from sentinel_tpu.core.config import SentinelConfig, _DEFAULTS

    with SentinelConfig._lock:
        merged = dict(_DEFAULTS)
        merged.update(SentinelConfig._file_props)
        merged.update(SentinelConfig._props)
    blob = json.dumps(merged, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _document(reason: str, window_s: Optional[float]) -> dict:
    from sentinel_tpu.metrics.exporter import build_info
    from sentinel_tpu.metrics.server import server_metrics
    from sentinel_tpu.trace.slo import slo_plane

    win = _WINDOW_S if window_s is None else float(window_s)
    since = time.monotonic_ns() - int(win * 1e9)
    return {
        "schema": "sentinel-blackbox/1",
        "reason": reason,
        "wallTime": time.time(),
        "build": build_info(),
        "configFingerprint": config_fingerprint(),
        "windowSeconds": win,
        "trace": _R.status(),
        "events": _R.events(since_ns=since),
        "metrics": server_metrics().snapshot(),
        "slo": slo_plane().snapshot(),
    }


def dump(
    reason: str,
    directory: Optional[str] = None,
    window_s: Optional[float] = None,
) -> str:
    """Write one dump unconditionally; returns the path. Atomic: readers
    never see a half-written file (tmp + rename in the same dir)."""
    global dumps_written, last_path
    target = directory or _DIR
    if not target:
        raise ValueError("no black-box directory configured")
    os.makedirs(target, exist_ok=True)
    doc = _document(reason, window_s)
    ts = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    path = os.path.join(
        target, f"blackbox-{ts}-{os.getpid()}-{dumps_written}.json"
    )
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)
    with _LOCK:
        dumps_written += 1
        last_path = path
    record_log.warning("black-box dump (%s) → %s", reason, path)
    return path


def maybe_dump(reason: str) -> Optional[str]:
    """The trigger path (brownout escalation, promotion, MOVE abort):
    no-op unless configured, rate-limited, and NEVER raises into the
    caller — the serving path that tripped the trigger must not pay for a
    broken recorder."""
    global _last_dump
    if _DIR is None:
        return None
    now = time.monotonic()
    with _LOCK:
        if now - _last_dump < _MIN_INTERVAL_S:
            return None
        _last_dump = now
    try:
        return dump(reason)
    except Exception:
        record_log.exception("black-box dump (%s) failed", reason)
        return None


def reset_for_tests() -> None:
    global _DIR, _WINDOW_S, _MIN_INTERVAL_S, _last_dump, dumps_written
    global last_path
    _DIR = os.environ.get("SENTINEL_BLACKBOX_DIR") or None
    _WINDOW_S = 30.0
    _MIN_INTERVAL_S = 5.0
    _last_dump = 0.0
    dumps_written = 0
    last_path = None
