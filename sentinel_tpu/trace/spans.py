"""Sampled end-to-end spans assembled on demand from the flight-recorder
rings.

No wire change: the xid already rides every frame of every transport, so a
span is just "every ring event carrying this xid, time-ordered". Assembly
is a read-side join across ALL thread rings — intake shard, batcher,
device lane, reply lane each recorded their hop into their own ring, and
the xid stitches them back into one request timeline.

Spans are advisory by construction: a wrapped ring has already evicted the
oldest hops, and a thread that died mid-record leaves a torn tail. Both
show up as an *incomplete* span (``complete=False`` with the covered
stages listed), never as an exception — the completeness check is the
consumer's gate, not the assembler's.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional

from sentinel_tpu.trace import ring as _R

# the client→reply contract: a complete span enters at the door and leaves
# through a reply (or an explicit shed refusal, which IS the reply)
_ENTRY_STAGE = "client_in"
_EXIT_STAGES = ("reply_out", "shed")


def assemble(xid: int) -> Optional[dict]:
    """Span for one xid, or None when no ring holds any event for it
    (unsampled xid, or the ring wrapped past it)."""
    evs = _R.events(xid=xid)
    if not evs:
        return None
    stages = [e["stage"] for e in evs]
    t0, t1 = evs[0]["t_ns"], evs[-1]["t_ns"]
    complete = _ENTRY_STAGE in stages and any(
        s in stages for s in _EXIT_STAGES
    )
    return {
        "xid": xid,
        "startNs": t0,
        "durationUs": round((t1 - t0) / 1_000.0, 3),
        "stages": stages,
        "complete": complete,
        "events": evs,
    }


def assemble_recent(limit: int = 64) -> List[dict]:
    """Spans for the most recently sampled xids (newest first)."""
    out = []
    for xid in _R.sampled_xids(limit=limit):
        sp = assemble(xid)
        if sp is not None:
            out.append(sp)
    return out


def completeness(spans: List[dict]) -> dict:
    """The trace-smoke gate: fraction of assembled spans covering
    client-in → reply-out."""
    total = len(spans)
    complete = sum(1 for s in spans if s["complete"])
    return {
        "spans": total,
        "complete": complete,
        "fraction": (complete / total) if total else None,
    }


def write_artifact(path: str, limit: int = 256) -> str:
    """Dump recent spans + completeness to a JSON artifact (the profiler
    hook's stop() product). Returns the written path."""
    from sentinel_tpu.metrics.exporter import build_info

    spans = assemble_recent(limit=limit)
    doc = {
        "schema": "sentinel-trace-spans/1",
        "wallTime": time.time(),
        "build": build_info(),
        "trace": _R.status(),
        "completeness": completeness(spans),
        "spans": spans,
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)
    return path
