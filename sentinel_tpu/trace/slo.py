"""Per-tenant SLO plane: latency histograms, burn rates, shed attribution.

The north star is an SLO (p99 < 2ms), but aggregate stage histograms can't
say WHICH tenant ate the budget. This plane keys everything by namespace:

- a :class:`~sentinel_tpu.metrics.histogram.LatencyHistogram` of decision
  latency (enqueue → verdict materialized) per namespace,
- rolling **multi-window burn rate** against the configured p99 objective
  (``sentinel.tpu.slo.p99.ms``, default 2.0): the objective allows 1% of
  requests over the latency bound, so ``burn = over_fraction / 0.01`` —
  burn 1.0 spends the error budget exactly at the sustainable rate, burn
  14 on the 1m window is the classic page-now signal. Two windows (1m/1h)
  distinguish a transient spike from a sustained bleed,
- per-tenant **shed/over-admission attribution**: refusals (OVERLOAD,
  brownout sheds, too_many_request) counted per namespace, so "who got
  shed" and "who caused the shedding" are answerable separately,
- per-tenant **completion outcomes** (wire-rev-6 OUTCOME_REPORT): reported
  response times feed a second histogram + burn-rate pair against the RT
  objective (``sentinel.tpu.slo.rt.p99.ms``, default 100.0) — the
  latency-burn SLO window over what the protected dependency actually
  served, not just how fast the verdict was — plus exception counts.

Surfaced through the Prometheus exporter (``sentinel_slo_*``),
``clusterServerStats`` (``slo`` block), black-box dumps, and
:func:`merge_fleet` — the fleet view summed across pods on the same pull
path ``aggregate_snapshots`` already uses.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional

from sentinel_tpu.metrics.histogram import LatencyHistogram

KEY_OBJECTIVE_MS = "sentinel.tpu.slo.p99.ms"
# completion-RT objective: the p99 bound on what protected calls REPORT
# back (OUTCOME_REPORT rt_ms), as opposed to the decision-latency objective
# above which bounds the admission verdict itself
KEY_RT_OBJECTIVE_MS = "sentinel.tpu.slo.rt.p99.ms"
# the p99 objective tolerates 1% of requests over the bound — that 1% IS
# the error budget the burn rate is measured against
BUDGET_FRACTION = 0.01

_WINDOWS = (("1m", 60), ("1h", 3600))


class _BurnWindow:
    """Per-second (total, over) buckets covering the last ``seconds``;
    stale buckets are lazily reused, so recording is O(1) and reading is
    one pass over at most ``seconds`` small ints."""

    __slots__ = ("seconds", "_stamp", "_total", "_over")

    def __init__(self, seconds: int):
        self.seconds = seconds
        self._stamp = [0] * seconds
        self._total = [0] * seconds
        self._over = [0] * seconds

    def record(self, total: int, over: int, now_s: Optional[int] = None):
        t = int(now_s if now_s is not None else time.time())
        i = t % self.seconds
        if self._stamp[i] != t:
            self._stamp[i] = t
            self._total[i] = 0
            self._over[i] = 0
        self._total[i] += total
        self._over[i] += over

    def totals(self, now_s: Optional[int] = None):
        t = int(now_s if now_s is not None else time.time())
        lo = t - self.seconds
        total = over = 0
        for i in range(self.seconds):
            if lo < self._stamp[i] <= t:
                total += self._total[i]
                over += self._over[i]
        return total, over


class _Tenant:
    __slots__ = ("hist", "windows", "shed", "waited",
                 "rt_hist", "rt_windows", "completed", "exceptions")

    def __init__(self):
        # decision latency in ms; log buckets fine enough to resolve a
        # 2ms objective (0.01ms..10s, 5/decade)
        self.hist = LatencyHistogram(lo=0.01, hi=10_000.0, per_decade=5)
        self.windows = {name: _BurnWindow(s) for name, s in _WINDOWS}
        self.shed: Dict[str, int] = {}
        # SHOULD_WAIT verdicts: served-with-delay (pacing / priority
        # occupy) — counted separately from sheds because the request WAS
        # admitted; a paced tenant is shaped, not failing
        self.waited = 0
        # reported completion RT (OUTCOME_REPORT): wider range than the
        # decision histogram — a protected dependency can take seconds
        self.rt_hist = LatencyHistogram(lo=0.1, hi=100_000.0, per_decade=5)
        self.rt_windows = {name: _BurnWindow(s) for name, s in _WINDOWS}
        self.completed = 0
        self.exceptions = 0


class SloPlane:
    """Process-wide per-namespace SLO accounting. Thread-safe; the
    recording path is one dict lookup + histogram record + two window
    adds per (namespace, batch)."""

    def __init__(self, objective_ms: Optional[float] = None,
                 rt_objective_ms: Optional[float] = None):
        from sentinel_tpu.core.config import SentinelConfig

        if objective_ms is None:
            objective_ms = SentinelConfig.get_float(KEY_OBJECTIVE_MS, 2.0)
        if rt_objective_ms is None:
            rt_objective_ms = SentinelConfig.get_float(
                KEY_RT_OBJECTIVE_MS, 100.0
            )
        self.objective_ms = float(objective_ms)
        self.rt_objective_ms = float(rt_objective_ms)
        self._lock = threading.Lock()
        self._tenants: Dict[str, _Tenant] = {}

    def _tenant(self, ns: str) -> _Tenant:
        t = self._tenants.get(ns)
        if t is None:
            with self._lock:
                t = self._tenants.setdefault(ns, _Tenant())
        return t

    # -- recording ----------------------------------------------------------
    def record(self, namespace: str, latency_ms: float, n: int = 1,
               now_s: Optional[int] = None) -> None:
        """n requests for this tenant observed ``latency_ms`` (a batch
        shares one decision latency — every row waited for the same
        device step)."""
        if n <= 0:
            return
        t = self._tenant(namespace)
        t.hist.record(latency_ms, n)
        over = n if latency_ms > self.objective_ms else 0
        for w in t.windows.values():
            w.record(n, over, now_s)

    def record_waited(self, namespace: str, n: int = 1) -> None:
        """n rows admitted with an assigned wait (SHOULD_WAIT). Latency /
        burn accounting already happened via :meth:`record` — this only
        keeps the per-tenant attribution the stats command and exporter
        surface as ``sentinel_slo_waited_total``."""
        if n <= 0:
            return
        t = self._tenant(namespace)
        with self._lock:
            t.waited += n

    def record_completion(self, namespace: str, rts, n_exception: int = 0,
                          now_s: Optional[int] = None) -> None:
        """A batch of reported completions for this tenant: ``rts`` is an
        array-like of response times in ms (already validated/clamped at
        the wire boundary). Feeds the RT histogram and the latency-burn
        windows against ``rt_objective_ms``; exceptions are counted but do
        NOT burn the RT budget twice (an exception's RT is still a real
        observation of the dependency)."""
        import numpy as np

        r = np.asarray(rts, dtype=np.float64)
        n = int(r.shape[0])
        if n == 0 and n_exception <= 0:
            return
        t = self._tenant(namespace)
        if n:
            # batches repeat few distinct RTs (whole ms); record grouped
            for v, c in zip(*np.unique(r, return_counts=True)):
                t.rt_hist.record(float(v), int(c))
            over = int((r > self.rt_objective_ms).sum())
            for w in t.rt_windows.values():
                w.record(n, over, now_s)
        with self._lock:
            t.completed += n
            t.exceptions += max(0, int(n_exception))

    def record_shed(self, namespace: str, reason: str, n: int = 1) -> None:
        """n rows refused for this tenant (OVERLOAD verdicts, brownout
        sheds, namespace guards). A shed burns the whole budget for those
        requests: counted as over-objective in the burn windows too.
        Every shed path in the process funnels through here (door-level
        ``record_shed_indexed`` and the verdict counter's refusal
        statuses alike), so this is also the single feed point for the
        metric timeline's ``shed`` column — each refused row lands there
        exactly once."""
        if n <= 0:
            return
        t = self._tenant(namespace)
        with self._lock:
            t.shed[reason] = t.shed.get(reason, 0) + n
        for w in t.windows.values():
            w.record(n, n)
        from sentinel_tpu.metrics.timeline import timeline

        timeline().record(namespace, n_shed=n)

    def record_shed_indexed(self, ns_idx, ns_names, reason: str) -> None:
        """Vectorized shed attribution off a ``(ns_idx, ns_names)`` pair
        (the ``TokenService.namespace_index`` shape the front doors use
        for rows that never reach the device)."""
        import numpy as np

        ns_idx = np.asarray(ns_idx)
        if ns_idx.shape[0] == 0:
            return
        counts = np.bincount(ns_idx + 1, minlength=len(ns_names) + 1)
        if counts[0]:
            self.record_shed("(no-rule)", reason, int(counts[0]))
        for j in np.nonzero(counts[1:])[0]:
            self.record_shed(ns_names[int(j)], reason, int(counts[1 + j]))

    # -- reading ------------------------------------------------------------
    def burn_rates(self, namespace: str) -> Dict[str, Optional[float]]:
        t = self._tenants.get(namespace)
        out: Dict[str, Optional[float]] = {}
        for name, _s in _WINDOWS:
            if t is None:
                out[name] = None
                continue
            total, over = t.windows[name].totals()
            out[name] = (
                (over / total) / BUDGET_FRACTION if total else None
            )
        return out

    def snapshot(self) -> dict:
        """The ``clusterServerStats``/black-box shape (and
        :func:`merge_fleet` input)."""
        with self._lock:
            names = list(self._tenants)
        tenants = {}
        for ns in names:
            t = self._tenants[ns]
            h = t.hist.snapshot()
            rates = {}
            windows = {}
            for name, _s in _WINDOWS:
                total, over = t.windows[name].totals()
                windows[name] = {"total": total, "over": over}
                rates[name] = (
                    round((over / total) / BUDGET_FRACTION, 4)
                    if total else None
                )
            rh = t.rt_hist.snapshot()
            rt_rates = {}
            rt_windows = {}
            for name, _s in _WINDOWS:
                total, over = t.rt_windows[name].totals()
                rt_windows[name] = {"total": total, "over": over}
                rt_rates[name] = (
                    round((over / total) / BUDGET_FRACTION, 4)
                    if total else None
                )
            tenants[ns] = {
                "count": h["count"],
                "p50Ms": h["p50"],
                "p99Ms": h["p99"],
                "maxMs": h["max"],
                "burnRate": rates,
                "windows": windows,
                "shed": dict(t.shed),
                "waited": int(t.waited),
                "completed": int(t.completed),
                "exceptions": int(t.exceptions),
                "rtP50Ms": rh["p50"],
                "rtP99Ms": rh["p99"],
                "rtMaxMs": rh["max"],
                "rtBurnRate": rt_rates,
                "rtWindows": rt_windows,
            }
        return {
            "objectiveMs": self.objective_ms,
            "rtObjectiveMs": self.rt_objective_ms,
            "tenants": tenants,
        }

    def render(self) -> str:
        """Prometheus 0.0.4 exposition of the whole plane."""
        lines = [
            "# HELP sentinel_slo_objective_ms Configured per-tenant p99 "
            "latency objective.",
            "# TYPE sentinel_slo_objective_ms gauge",
            f"sentinel_slo_objective_ms {self.objective_ms:g}",
            "# HELP sentinel_slo_rt_objective_ms Configured per-tenant p99 "
            "objective on reported completion RT.",
            "# TYPE sentinel_slo_rt_objective_ms gauge",
            f"sentinel_slo_rt_objective_ms {self.rt_objective_ms:g}",
        ]
        with self._lock:
            names = sorted(self._tenants)
        for i, ns in enumerate(names):
            t = self._tenants[ns]
            lines.append(t.hist.render_prometheus(
                "sentinel_slo_latency_ms",
                "Per-tenant decision latency (enqueue to verdict).",
                labels=f'namespace="{_escape(ns)}"',
                header=(i == 0),  # one HELP/TYPE per family, not per tenant
            ))
        first = True
        for ns in names:
            t = self._tenants[ns]
            if t.rt_hist.count:
                lines.append(t.rt_hist.render_prometheus(
                    "sentinel_slo_rt_ms",
                    "Per-tenant reported completion RT (OUTCOME_REPORT).",
                    labels=f'namespace="{_escape(ns)}"',
                    header=first,
                ))
                first = False
        burn_lines: List[str] = []
        rt_burn_lines: List[str] = []
        shed_lines: List[str] = []
        waited_lines: List[str] = []
        exc_lines: List[str] = []
        for ns in names:
            t = self._tenants[ns]
            for name, _s in _WINDOWS:
                total, over = t.windows[name].totals()
                if total:
                    rate = (over / total) / BUDGET_FRACTION
                    burn_lines.append(
                        f'sentinel_slo_burn_rate{{namespace="{_escape(ns)}"'
                        f',window="{name}"}} {rate:g}'
                    )
                total, over = t.rt_windows[name].totals()
                if total:
                    rate = (over / total) / BUDGET_FRACTION
                    rt_burn_lines.append(
                        f'sentinel_slo_rt_burn_rate'
                        f'{{namespace="{_escape(ns)}"'
                        f',window="{name}"}} {rate:g}'
                    )
            for reason, n in sorted(t.shed.items()):
                shed_lines.append(
                    f'sentinel_slo_shed_total{{namespace="{_escape(ns)}"'
                    f',reason="{reason}"}} {n}'
                )
            if t.waited:
                waited_lines.append(
                    f'sentinel_slo_waited_total{{namespace="{_escape(ns)}"'
                    f'}} {t.waited}'
                )
            if t.exceptions:
                exc_lines.append(
                    f'sentinel_slo_exceptions_total'
                    f'{{namespace="{_escape(ns)}"}} {t.exceptions}'
                )
        if burn_lines:
            lines.append(
                "# HELP sentinel_slo_burn_rate Error-budget burn vs the "
                "p99 objective (1.0 = sustainable)."
            )
            lines.append("# TYPE sentinel_slo_burn_rate gauge")
            lines.extend(burn_lines)
        if rt_burn_lines:
            lines.append(
                "# HELP sentinel_slo_rt_burn_rate Error-budget burn of "
                "reported completion RT vs the RT objective "
                "(1.0 = sustainable)."
            )
            lines.append("# TYPE sentinel_slo_rt_burn_rate gauge")
            lines.extend(rt_burn_lines)
        if shed_lines:
            lines.append(
                "# HELP sentinel_slo_shed_total Refused rows attributed "
                "per tenant."
            )
            lines.append("# TYPE sentinel_slo_shed_total counter")
            lines.extend(shed_lines)
        if waited_lines:
            lines.append(
                "# HELP sentinel_slo_waited_total SHOULD_WAIT verdicts "
                "(delayed admission: pacing / priority occupy) per tenant."
            )
            lines.append("# TYPE sentinel_slo_waited_total counter")
            lines.extend(waited_lines)
        if exc_lines:
            lines.append(
                "# HELP sentinel_slo_exceptions_total Reported completion "
                "exceptions per tenant (OUTCOME_REPORT exc flag)."
            )
            lines.append("# TYPE sentinel_slo_exceptions_total counter")
            lines.extend(exc_lines)
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._tenants.clear()


def _escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


# -- fleet merge --------------------------------------------------------------
def merge_fleet(snapshots: Iterable[dict]) -> dict:
    """Sum per-tenant SLO snapshots from every pod into the fleet view —
    the SLO-plane analog of ``cluster.namespaces.aggregate_snapshots``
    (and consumed on the same stats-pull path). Window totals and shed
    counts add; burn rates are recomputed from the summed windows (a mean
    of ratios would weight an idle pod equal to a loaded one); p99 keeps
    the worst pod's value (histograms don't merge across the wire — the
    conservative bound is the honest one). Malformed pod payloads
    contribute nothing, mirroring aggregate_snapshots' fault contract."""
    objective = None
    rt_objective = None
    tenants: Dict[str, dict] = {}
    for snap in snapshots:
        try:
            if callable(snap):
                snap = snap()
            if objective is None:
                objective = snap.get("objectiveMs")
            if rt_objective is None:
                rt_objective = snap.get("rtObjectiveMs")
            for ns, t in snap.get("tenants", {}).items():
                agg = tenants.setdefault(ns, {
                    "count": 0, "p99Ms": None, "windows": {
                        name: {"total": 0, "over": 0} for name, _s in _WINDOWS
                    }, "shed": {}, "waited": 0,
                    "completed": 0, "exceptions": 0, "rtP99Ms": None,
                    "rtWindows": {
                        name: {"total": 0, "over": 0} for name, _s in _WINDOWS
                    },
                })
                agg["count"] += int(t.get("count", 0))
                agg["waited"] += int(t.get("waited", 0))
                agg["completed"] += int(t.get("completed", 0))
                agg["exceptions"] += int(t.get("exceptions", 0))
                for key in ("p99Ms", "rtP99Ms"):
                    v = t.get(key)
                    if v is not None and (
                        agg[key] is None or v > agg[key]
                    ):
                        agg[key] = v
                for wkey in ("windows", "rtWindows"):
                    for name, _s in _WINDOWS:
                        w = t.get(wkey, {}).get(name, {})
                        agg[wkey][name]["total"] += int(w.get("total", 0))
                        agg[wkey][name]["over"] += int(w.get("over", 0))
                for reason, n in t.get("shed", {}).items():
                    agg["shed"][reason] = agg["shed"].get(reason, 0) + int(n)
        except Exception:
            from sentinel_tpu.core.log import record_log

            record_log.exception("fleet SLO merge: pod snapshot dropped")
    for agg in tenants.values():
        for wkey, rkey in (("windows", "burnRate"),
                           ("rtWindows", "rtBurnRate")):
            rates = {}
            for name, _s in _WINDOWS:
                w = agg[wkey][name]
                rates[name] = (
                    round((w["over"] / w["total"]) / BUDGET_FRACTION, 4)
                    if w["total"] else None
                )
            agg[rkey] = rates
    return {
        "objectiveMs": objective,
        "rtObjectiveMs": rt_objective,
        "tenants": tenants,
    }


# -- singleton ----------------------------------------------------------------
_PLANE: Optional[SloPlane] = None
_PLANE_LOCK = threading.Lock()


def slo_plane() -> SloPlane:
    global _PLANE
    if _PLANE is None:
        with _PLANE_LOCK:
            if _PLANE is None:
                _PLANE = SloPlane()
    return _PLANE


def reset_slo_plane_for_tests() -> None:
    global _PLANE
    with _PLANE_LOCK:
        _PLANE = None
