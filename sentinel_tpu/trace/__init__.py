"""Flight-recorder tracing, black-box dumps, and the per-tenant SLO plane.

Four pieces, one discipline (the ``chaos/`` ARMED pattern — a disarmed
subsystem costs one branch per hop):

- :mod:`~sentinel_tpu.trace.ring` — per-thread fixed-size struct rings of
  ``(t_ns, stage, xid, shard, aux)`` events, fed by every hop of both
  front doors, the device step boundary, and the control paths.
- :mod:`~sentinel_tpu.trace.spans` — xid-hash-sampled end-to-end spans
  assembled on demand across rings (``cluster/server/trace`` command).
- :mod:`~sentinel_tpu.trace.blackbox` — atomic post-mortem dumps (rings +
  metrics + config fingerprint) on brownout escalation, promotion, MOVE
  abort, or operator command.
- :mod:`~sentinel_tpu.trace.slo` — per-namespace latency histograms,
  1m/1h burn rates vs the p99 objective, and per-tenant shed attribution,
  merged fleet-wide by :func:`~sentinel_tpu.trace.slo.merge_fleet`.
"""

from sentinel_tpu.trace import blackbox, ring, slo, spans
from sentinel_tpu.trace.ring import arm, disarm, record, sample_xid
from sentinel_tpu.trace.slo import merge_fleet, slo_plane

__all__ = [
    "ring",
    "spans",
    "blackbox",
    "slo",
    "arm",
    "disarm",
    "record",
    "sample_xid",
    "slo_plane",
    "merge_fleet",
]
