"""Per-endpoint health: exponential backoff + jitter, half-open breaker.

The failover client needs one judgment per endpoint: "may I send the next
request here?" This module answers it with a three-state circuit breaker:

- **CLOSED** (healthy): requests flow; failures accumulate.
- **OPEN** (down): after ``failure_threshold`` consecutive failures the
  endpoint is evicted; re-probe no earlier than an exponentially growing,
  jittered backoff (``base_ms · 2^(k-1)``, capped at ``max_ms``).
- **HALF_OPEN**: the backoff elapsed; exactly ONE probe request is let
  through. Success closes the breaker, failure re-opens it with a longer
  backoff. Because only ``record_success``/``record_failure`` leave this
  state, callers must treat an admitted probe as a commitment: consult
  ``allows_request()`` immediately before dispatching to the endpoint,
  never speculatively for endpoints that might not be tried. As a backstop
  against a prober that dies without reporting, a probe that hasn't been
  answered within a backoff-length grace window forfeits its slot and the
  next ``allows_request()`` admits a fresh probe.

Time comes from the injectable ``core.clock`` so tests drive the state
machine with a ``ManualClock``; jitter comes from an injectable uniform
source for the same reason. Thread-safe: the failover client calls this from
whatever thread carries the request.
"""

from __future__ import annotations

import enum
import random
import threading
from dataclasses import dataclass

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.config import SentinelConfig

# SentinelConfig keys (defaults registered in core.config._DEFAULTS)
KEY_FAILURE_THRESHOLD = "sentinel.tpu.ha.failure.threshold"
KEY_BACKOFF_BASE_MS = "sentinel.tpu.ha.backoff.base.ms"
KEY_BACKOFF_MAX_MS = "sentinel.tpu.ha.backoff.max.ms"
KEY_BACKOFF_JITTER = "sentinel.tpu.ha.backoff.jitter"


@dataclass(frozen=True)
class Endpoint:
    """One token-server address in the ordered endpoint list."""

    host: str
    port: int

    def __str__(self) -> str:
        return f"{self.host}:{self.port}"


class HealthState(enum.IntEnum):
    CLOSED = 0  # healthy
    OPEN = 1  # evicted, waiting out the backoff
    HALF_OPEN = 2  # backoff elapsed; one probe in flight


class EndpointHealth:
    """Circuit-breaker state for one endpoint."""

    def __init__(
        self,
        failure_threshold: int = None,
        backoff_base_ms: float = None,
        backoff_max_ms: float = None,
        jitter: float = None,
        rand=random.random,
    ):
        self.failure_threshold = max(1, int(
            failure_threshold
            if failure_threshold is not None
            else SentinelConfig.get_int(KEY_FAILURE_THRESHOLD, 3)
        ))
        self.backoff_base_ms = float(
            backoff_base_ms
            if backoff_base_ms is not None
            else SentinelConfig.get_float(KEY_BACKOFF_BASE_MS, 100.0)
        )
        self.backoff_max_ms = float(
            backoff_max_ms
            if backoff_max_ms is not None
            else SentinelConfig.get_float(KEY_BACKOFF_MAX_MS, 10_000.0)
        )
        self.jitter = float(
            jitter
            if jitter is not None
            else SentinelConfig.get_float(KEY_BACKOFF_JITTER, 0.2)
        )
        self._rand = rand
        self._lock = threading.Lock()
        self.state = HealthState.CLOSED
        self.consecutive_failures = 0
        self.retry_at_ms = 0
        self._opened = 0  # open cycles since last success → backoff exponent
        self._probe_deadline_ms = 0.0  # HALF_OPEN: when the probe forfeits

    # -- queries ------------------------------------------------------------
    def allows_request(self) -> bool:
        """May the next request go to this endpoint? An OPEN breaker whose
        backoff elapsed transitions to HALF_OPEN and admits exactly one
        probe (subsequent calls are refused until that probe reports).

        A ``True`` answer in non-CLOSED states hands out the probe slot, so
        call this only when the request WILL be dispatched to the endpoint —
        an admitted-but-never-sent probe would otherwise pin the breaker in
        HALF_OPEN until the grace window below reclaims it."""
        now = _clock.now_ms()
        with self._lock:
            if self.state == HealthState.CLOSED:
                return True
            if self.state == HealthState.OPEN:
                if now >= self.retry_at_ms:
                    self.state = HealthState.HALF_OPEN
                    self._probe_deadline_ms = now + self.backoff_ms()
                    return True
                return False
            # HALF_OPEN: one probe in flight — unless it was admitted a full
            # backoff ago and never reported (the dispatcher died before
            # calling record_*); then it forfeits and a fresh probe goes out
            if now >= self._probe_deadline_ms:
                self._probe_deadline_ms = now + self.backoff_ms()
                return True
            return False

    @property
    def healthy(self) -> bool:
        return self.state == HealthState.CLOSED

    def backoff_ms(self) -> float:
        """Jittered delay for the current open cycle (exponent capped so the
        doubling can't overflow long before max_ms clamps it)."""
        k = min(max(self._opened, 1), 32)
        raw = min(self.backoff_base_ms * (2 ** (k - 1)), self.backoff_max_ms)
        return raw * (1.0 + self.jitter * self._rand())

    # -- transitions ---------------------------------------------------------
    def record_success(self) -> None:
        with self._lock:
            self.state = HealthState.CLOSED
            self.consecutive_failures = 0
            self.retry_at_ms = 0
            self._opened = 0

    def record_failure(self) -> None:
        now = _clock.now_ms()
        with self._lock:
            self.consecutive_failures += 1
            if self.state == HealthState.HALF_OPEN:
                # failed probe: straight back to OPEN with a longer backoff
                self._opened += 1
                self.state = HealthState.OPEN
                self.retry_at_ms = now + self.backoff_ms()
            elif (
                self.state == HealthState.CLOSED
                and self.consecutive_failures >= self.failure_threshold
            ):
                self._opened += 1
                self.state = HealthState.OPEN
                self.retry_at_ms = now + self.backoff_ms()

    # -- introspection -------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state.name,
                "consecutiveFailures": self.consecutive_failures,
                "retryAtMs": int(self.retry_at_ms),
            }
