"""Runtime cluster-mode transitions (``ClusterStateManager.java`` analog).

The reference flips a node between CLIENT and SERVER at runtime
(``ClusterStateManager.applyState``) and the slot chain picks the new
service on the very next request because ``FlowRuleChecker`` consults the
global state per call. This build works the same way — ``cluster.api``'s
``_pick_service()`` reads module globals on every cluster check — so a
transition here rewires the slot chain live, with no restart and no
re-registration of rules on the local side.

What this class adds over raw ``transport.handlers.apply_cluster_mode``:

- **to_client** installs a :class:`~sentinel_tpu.ha.failover.FailoverTokenClient`
  (ordered endpoint list + local fallback) instead of a single-host client;
- **to_server** optionally restores the newest state snapshot into the
  embedded service before it takes traffic — the warm-standby promotion
  path (a demoted primary's artifact, or one fetched over the
  ``cluster/server/snapshot`` transport command);
- every transition closes what the previous mode held (client socket,
  server port) instead of leaking it.
"""

from __future__ import annotations

from typing import Optional, Sequence

from sentinel_tpu.core.log import record_log
from sentinel_tpu.ha.failover import FailoverTokenClient
from sentinel_tpu.ha.fallback import LocalFallbackPolicy


class ClusterStateManager:
    """Client/server/off transitions for this node."""

    def to_client(
        self,
        endpoints: Sequence,
        timeout_ms: int = 20,
        namespace: str = "default",
        fallback: Optional[LocalFallbackPolicy] = None,
        **failover_kwargs,
    ) -> FailoverTokenClient:
        """Run as a cluster client against the ordered endpoint list.

        A running embedded server is stopped first (its port frees for
        whoever is promoted in our place). Returns the installed client."""
        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.transport.handlers import apply_cluster_mode

        if cluster_api.get_mode() == cluster_api.ClusterMode.SERVER:
            apply_cluster_mode(int(cluster_api.ClusterMode.NOT_STARTED))
        client = FailoverTokenClient(
            endpoints,
            timeout_ms=timeout_ms,
            namespace=namespace,
            fallback=fallback,
            **failover_kwargs,
        )
        cluster_api.set_client(client)  # sets CLIENT mode, closes the old one
        record_log.info(
            "cluster mode → CLIENT (%d endpoint(s), namespace=%s)",
            len(client.health_snapshot()), namespace,
        )
        return client

    def to_server(
        self,
        token_port: int = 18730,
        snapshot_dir: Optional[str] = None,
        restore: bool = True,
    ):
        """Promote this node to an embedded token server. With
        ``snapshot_dir`` and ``restore``, a cold service (no rules loaded
        yet) restores the newest snapshot artifact before taking traffic —
        the warm-standby path. Returns the embedded service."""
        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.transport.handlers import apply_cluster_mode

        cluster_api.clear_client()
        apply_cluster_mode(int(cluster_api.ClusterMode.SERVER), token_port)
        service = cluster_api.get_embedded_server()
        if restore and snapshot_dir and not service.current_rules():
            from sentinel_tpu.ha.snapshot import restore_latest

            if restore_latest(service, snapshot_dir):
                record_log.info(
                    "cluster mode → SERVER (port %d, state restored from %s)",
                    token_port, snapshot_dir,
                )
                return service
        record_log.info("cluster mode → SERVER (port %d)", token_port)
        return service

    def to_off(self) -> None:
        """Back to NOT_STARTED: stop the embedded server if running, drop
        the client if installed. Local (non-cluster) rules keep working."""
        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.transport.handlers import apply_cluster_mode

        apply_cluster_mode(int(cluster_api.ClusterMode.NOT_STARTED))
        cluster_api.clear_client()
        record_log.info("cluster mode → off")

    # -- introspection -------------------------------------------------------
    def current_mode(self):
        from sentinel_tpu.cluster import api as cluster_api

        return cluster_api.get_mode()

    def status(self) -> dict:
        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.transport.handlers import (
            _EMBEDDED_LOCK,
            _EMBEDDED_SERVER,
        )

        out = {"mode": self.current_mode().name}
        with _EMBEDDED_LOCK:
            server = _EMBEDDED_SERVER["server"]
        if server is not None:
            out["serverPort"] = server.port
        client = cluster_api._client
        health = getattr(client, "health_snapshot", None)
        if health is not None:
            out["endpoints"] = health()
        return out
