"""Cluster high availability: failover client, local fallback, snapshots.

The reference Sentinel treats cluster flow control as degradable-by-design —
``ClusterStateManager`` flips nodes between CLIENT/SERVER at runtime and a
client falls back to local rules when the token server is unreachable
(``FlowRuleChecker.fallbackToLocalOrPass``). This package carries those
semantics to the TPU build and adds the piece the device-resident state
makes necessary: a versioned snapshot of the token server's window/CMS
tensors so a warm standby (or a restarted primary) resumes counting instead
of forgetting every in-window verdict.

- :mod:`~sentinel_tpu.ha.endpoints` — per-endpoint health with exponential
  backoff + jitter and a half-open circuit breaker.
- :mod:`~sentinel_tpu.ha.failover` — :class:`FailoverTokenClient`, an
  ordered-endpoint-list ``TokenService`` that evicts dead primaries.
- :mod:`~sentinel_tpu.ha.fallback` — per-rule local degradation (pass /
  block / local-window throttle) riding ``local.flow`` controllers.
- :mod:`~sentinel_tpu.ha.snapshot` — device→host state snapshot/restore and
  the periodic :class:`SnapshotManager`.
- :mod:`~sentinel_tpu.ha.manager` — :class:`ClusterStateManager`, runtime
  client/server/off transitions that rewire the slot chain live.
- :mod:`~sentinel_tpu.ha.replication` — warm-standby delta streaming:
  :class:`ReplicationSender` ships dirty counter rows every tick over wire
  rev 3; :class:`StandbyApplier` applies them behind a closed front door
  until promotion, bounding failover loss at one ship interval instead of
  one snapshot period.
"""

from sentinel_tpu.ha.endpoints import Endpoint, EndpointHealth, HealthState
from sentinel_tpu.ha.failover import FailoverTokenClient
from sentinel_tpu.ha.fallback import (
    FallbackAction,
    FallbackRule,
    LocalFallbackPolicy,
)
from sentinel_tpu.ha.manager import ClusterStateManager
from sentinel_tpu.ha.replication import ReplicationSender, StandbyApplier
from sentinel_tpu.ha.snapshot import (
    SNAPSHOT_VERSION,
    SnapshotManager,
    decode_snapshot,
    encode_snapshot,
    load_latest,
    restore_from_doc,
    restore_latest,
    save_snapshot,
    snapshot_to_doc,
)

__all__ = [
    "Endpoint",
    "EndpointHealth",
    "HealthState",
    "FailoverTokenClient",
    "FallbackAction",
    "FallbackRule",
    "LocalFallbackPolicy",
    "ClusterStateManager",
    "ReplicationSender",
    "StandbyApplier",
    "SNAPSHOT_VERSION",
    "SnapshotManager",
    "encode_snapshot",
    "decode_snapshot",
    "snapshot_to_doc",
    "restore_from_doc",
    "save_snapshot",
    "load_latest",
    "restore_latest",
]
