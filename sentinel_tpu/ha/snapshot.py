"""Versioned token-server state snapshot/restore.

The reference server loses nothing on restart worth keeping — its LeapArray
windows are seconds wide and JVM-heap cheap. Here the window/CMS tensors
live on device and back *cluster-wide* admission: a restarted (or standby)
token server that forgets them over-admits a full window of traffic across
every client at once. So the server periodically captures device state to a
host-side artifact and restores it on startup:

- artifact = one JSON document: ``version``, ``saved_at_ms``, rule sources,
  slot maps, and each window/sketch tensor as
  ``{dtype, shape, data=base64(zlib(raw))}`` — self-describing, greppable
  metadata, compact arrays (the counters are mostly zeros; zlib typically
  shrinks the tensor payload >100×).
- restore goes through ``DefaultTokenService.import_state``: rules reload
  through the normal path and counter rows remap per flow_id, so the
  artifact is valid for a warm standby whose slot assignment differs.
- engine time continues from the snapshot epoch — counters older than one
  window expire on the first masked read instead of resurrecting stale
  quota; a snapshot is never *more* permissive than the truth, only up to
  one window less.

``SnapshotManager`` is the periodic writer (daemon thread, injectable
period); ``save_snapshot``/``restore_latest`` are the one-shot forms the
transport command and server startup use.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import zlib
from typing import Dict, Optional

import numpy as np

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.core.log import record_log
from sentinel_tpu.cluster.token_service import ClusterParamFlowRule
from sentinel_tpu.engine.rules import (
    decode_degrade_rule,
    decode_rule,
    encode_degrade_rule,
    encode_rule,
)
from sentinel_tpu.metrics.ha import ha_metrics

SNAPSHOT_VERSION = 1
KEY_SNAPSHOT_PERIOD_S = "sentinel.tpu.ha.snapshot.period.s"

_PREFIX = "sentinel-snapshot-"
_SUFFIX = ".json"


# -- array codec -------------------------------------------------------------
def _enc_array(arr: np.ndarray) -> Dict[str, object]:
    arr = np.ascontiguousarray(arr)
    return {
        "dtype": str(arr.dtype),
        "shape": list(arr.shape),
        "data": base64.b64encode(zlib.compress(arr.tobytes())).decode(
            "ascii"
        ),
    }


def _dec_array(doc: Dict[str, object]) -> np.ndarray:
    raw = zlib.decompress(base64.b64decode(doc["data"]))
    return np.frombuffer(raw, dtype=np.dtype(doc["dtype"])).reshape(
        doc["shape"]
    ).copy()


def _enc_win(win: Dict[str, np.ndarray]) -> Dict[str, object]:
    return {k: _enc_array(v) for k, v in win.items()}


def _dec_win(doc: Dict[str, object]) -> Dict[str, np.ndarray]:
    return {k: _dec_array(v) for k, v in doc.items()}


# -- document codec ----------------------------------------------------------
def encode_snapshot(state: Dict[str, object]) -> Dict[str, object]:
    """``DefaultTokenService.export_state()`` capture → JSON-safe document."""
    return {
        "version": SNAPSHOT_VERSION,
        "saved_at_ms": int(_clock.now_ms()),
        "engine_now": state["engine_now"],
        "epoch_ms": state["epoch_ms"],
        "wall_ms": state["wall_ms"],
        "ns_max_qps": state["ns_max_qps"],
        "connected": state["connected"],
        "namespace_set": state["namespace_set"],
        "rules": [encode_rule(r) for r in state["rules"]],
        "param_rules": [
            {
                "flow_id": r.flow_id,
                "count": r.count,
                "item_thresholds": [
                    [int(h), float(c)] for h, c in (r.item_thresholds or ())
                ],
                "namespace": r.namespace,
            }
            for r in state["param_rules"]
        ],
        "slot_of": {str(k): int(v) for k, v in state["slot_of"].items()},
        "ns_of": dict(state["ns_of"]),
        "param_slot_of": {
            str(k): int(v) for k, v in state["param_slot_of"].items()
        },
        "flow": _enc_win(state["flow"]),
        "occupy": _enc_win(state["occupy"]),
        "ns": _enc_win(state["ns"]),
        "param": _enc_win(state["param"]),
        # per-flow shaper clocks (absent in pre-shaping snapshots; the
        # importer then starts those slots cold)
        **(
            {"shaping": _enc_win(state["shaping"])}
            if "shaping" in state else {}
        ),
        # per-flow completion-outcome columns (absent in pre-outcome
        # snapshots; the importer then starts those columns cold)
        **(
            {"outcome": _enc_win(state["outcome"])}
            if "outcome" in state else {}
        ),
        # circuit-breaker rules + state columns (absent in pre-breaker
        # snapshots; the importer then restores every breaker CLOSED)
        **(
            {
                "degrade_rules": [
                    encode_degrade_rule(d) for d in state["degrade_rules"]
                ],
            }
            if "degrade_rules" in state else {}
        ),
        **(
            {"breaker": _enc_win(state["breaker"])}
            if "breaker" in state else {}
        ),
        # hierarchy-coordinator ledger piggyback (already JSON-safe; absent
        # when no coordinator is co-located with this pod)
        **({"hier": state["hier"]} if "hier" in state else {}),
    }


def decode_snapshot(doc: Dict[str, object]) -> Dict[str, object]:
    """JSON document → the dict shape ``import_state`` consumes. Raises
    ``ValueError`` on an unknown version."""
    version = doc.get("version")
    if version != SNAPSHOT_VERSION:
        raise ValueError(
            f"unsupported snapshot version {version!r} "
            f"(this build reads {SNAPSHOT_VERSION})"
        )
    return {
        "engine_now": int(doc["engine_now"]),
        "epoch_ms": int(doc["epoch_ms"]),
        "wall_ms": int(doc["wall_ms"]),
        "ns_max_qps": float(doc["ns_max_qps"]),
        "connected": {str(k): int(v) for k, v in doc["connected"].items()},
        "namespace_set": list(doc["namespace_set"]),
        "rules": [decode_rule(r) for r in doc["rules"]],
        "param_rules": [
            ClusterParamFlowRule(
                int(r["flow_id"]), float(r["count"]),
                tuple((int(h), float(c)) for h, c in r["item_thresholds"])
                or None,
                str(r["namespace"]),
            )
            for r in doc["param_rules"]
        ],
        "slot_of": {int(k): int(v) for k, v in doc["slot_of"].items()},
        "ns_of": {str(k): int(v) for k, v in doc["ns_of"].items()},
        "param_slot_of": {
            int(k): int(v) for k, v in doc["param_slot_of"].items()
        },
        "flow": _dec_win(doc["flow"]),
        "occupy": _dec_win(doc["occupy"]),
        "ns": _dec_win(doc["ns"]),
        "param": _dec_win(doc["param"]),
        **(
            {"shaping": _dec_win(doc["shaping"])}
            if "shaping" in doc else {}
        ),
        **(
            {"outcome": _dec_win(doc["outcome"])}
            if "outcome" in doc else {}
        ),
        **(
            {
                "degrade_rules": [
                    decode_degrade_rule(d) for d in doc["degrade_rules"]
                ],
            }
            if "degrade_rules" in doc else {}
        ),
        **(
            {"breaker": _dec_win(doc["breaker"])}
            if "breaker" in doc else {}
        ),
        **({"hier": doc["hier"]} if "hier" in doc else {}),
    }


def snapshot_to_doc(service) -> Dict[str, object]:
    """One device→host capture, already encoded (the transport command's
    fetch action returns this inline for a warm standby to restore)."""
    return encode_snapshot(service.export_state())


def restore_from_doc(service, doc: Dict[str, object]) -> None:
    service.import_state(decode_snapshot(doc))
    ha_metrics().count_snapshot("restore")


# -- directory artifacts -----------------------------------------------------
def _fsync_dir(directory: str) -> None:
    """Flush a rename to the directory inode (no-op where directories can't
    be opened, e.g. Windows)."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save_snapshot(service, directory: str, retain: int = 3) -> str:
    """Write one snapshot artifact; atomic (tmp + rename), prunes to the
    newest ``retain`` files. Returns the artifact path."""
    doc = snapshot_to_doc(service)
    os.makedirs(directory, exist_ok=True)
    name = f"{_PREFIX}{doc['saved_at_ms']}{_SUFFIX}"
    path = os.path.join(directory, name)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, separators=(",", ":"))
        # crash safety: the rename below is only atomic for data already on
        # disk — an unsynced tmp can survive a crash as a torn artifact
        # under the FINAL name
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(directory)  # persist the rename itself
    ha_metrics().count_snapshot("save")
    for stale in _artifacts(directory)[:-max(1, int(retain))]:
        try:
            os.remove(os.path.join(directory, stale))
        except OSError:
            pass
    return path


def _artifact_key(name: str):
    """Numeric value of the embedded save timestamp. Lexical order would
    misplace artifacts across a digit rollover (999 vs 1000 — real under an
    injected ManualClock); names whose timestamp doesn't parse sort oldest
    so they are pruned first and restored last."""
    try:
        return (0, int(name[len(_PREFIX):-len(_SUFFIX)]), name)
    except ValueError:
        return (-1, 0, name)


def _artifacts(directory: str) -> list:
    """Snapshot filenames in the directory, oldest → newest (ordered by the
    embedded save timestamp, numerically; same-ms ties break lexically)."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(
        (n for n in names
         if n.startswith(_PREFIX) and n.endswith(_SUFFIX)),
        key=_artifact_key,
    )


def load_latest(directory: str) -> Optional[Dict[str, object]]:
    """Newest readable artifact in the directory, or None. A torn or
    corrupt newest file falls back to the next-newest (the writer is
    atomic, but the disk under it doesn't have to be)."""
    for name in reversed(_artifacts(directory)):
        path = os.path.join(directory, name)
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            record_log.warning("skipping unreadable snapshot %s", path)
    return None


def restore_latest(service, directory: str) -> bool:
    """Restore the newest artifact into ``service``; False when the
    directory has none (fresh node) or the artifact doesn't fit this
    service's geometry (config changed — start cold rather than corrupt)."""
    doc = load_latest(directory)
    if doc is None:
        return False
    try:
        restore_from_doc(service, doc)
    except ValueError as e:
        record_log.warning("snapshot restore skipped: %s", e)
        return False
    return True


class SnapshotManager:
    """Periodic snapshot writer for a live token service.

    A daemon thread saves every ``period_s`` (default from
    ``sentinel.tpu.ha.snapshot.period.s``); ``save_now()`` forces one
    between ticks (the transport command and server shutdown use it). A
    failed save is logged and retried next tick — snapshotting must never
    take the serving path down with it."""

    def __init__(
        self,
        service,
        directory: str,
        period_s: Optional[float] = None,
        retain: int = 3,
    ):
        self.service = service
        self.directory = directory
        self.period_s = float(
            period_s
            if period_s is not None
            else SentinelConfig.get_float(KEY_SNAPSHOT_PERIOD_S, 30.0)
        )
        self.retain = retain
        self.last_path: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SnapshotManager":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="sentinel-snapshot", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, final_save: bool = True) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
        if final_save:
            self.save_now()

    def save_now(self) -> Optional[str]:
        try:
            self.last_path = save_snapshot(
                self.service, self.directory, self.retain
            )
            return self.last_path
        except Exception:
            record_log.exception(
                "snapshot save failed (dir=%s)", self.directory
            )
            return None

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            self.save_now()
