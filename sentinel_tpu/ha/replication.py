"""Warm-standby state replication: delta streaming over wire rev 3.

PR 2's snapshot/restore bounds failover loss at snapshot granularity
(default 30s) — a SIGKILLed primary forgets everything since the last
artifact, and the promoted standby over-admits up to a full window per
flow. This module shrinks that loss to ONE DELTA-SHIP INTERVAL: the
primary keeps shipping only the counter rows that changed (the SF-sketch
slim-twin shape, arXiv:1701.04148 — a fat local structure keeps a compact
remote twin fresh for cheap), and the standby applies them behind its
closed front door until promotion.

Topology and protocol::

    primary                                      standby
    ──────────                                   ──────────
    ReplicationSender ── REPL_HELLO ──────────▶  front door ─▶ StandbyApplier
        │              ◀─ REPL_ACK(OK|NEED_SNAPSHOT) ─┘
        ├── REPL_SNAPSHOT chunks ─────────────▶  import_state (bootstrap /
        │              ◀─ REPL_ACK ──────────┘   generation resync)
        └── REPL_DELTA chunks (every tick) ───▶  apply_replication_delta
                       ◀─ REPL_ACK ──────────┘

- The sender speaks to the standby's ORDINARY front door (both
  ``TokenServer`` and ``NativeTokenServer`` route rev-3 type bytes to the
  applier), so replication needs no extra port and inherits the door's
  chaos instrumentation.
- Deltas are generation-fenced: every rule reload bumps the token
  service's ``state_generation`` and invalidates slot-keyed rows, so the
  sender re-bootstraps the standby with a full snapshot on any gen change,
  NEED_SNAPSHOT ack, or reconnect. Delivery is therefore idempotent-safe:
  a delta the standby missed is covered by the next snapshot resync, and a
  delta applied twice sets the same absolute rows (ship state, not
  increments — the SALSA-style merge, arXiv:2102.12531, stays available
  for multi-primary later).
- The repl channel must survive chaos: ``conn_reset`` / ``lane_delay``
  probes fire in the sender's ship path when armed, and every failure mode
  funnels into "reconnect + snapshot resync", never a crashed thread.
- An un-promoted standby answers data-plane traffic with
  ``TokenStatus.STANDBY`` (redirect-style refusal); promotion is explicit
  (``cluster/server/promote`` transport command → ``promote()``) or
  automatic when the repl channel has been silent for
  ``promote_after_ms`` (primary-death detection).

Metrics land on :mod:`sentinel_tpu.metrics.ha`:
``sentinel_repl_deltas_total{event=}``, ``sentinel_repl_bytes_total``,
and the ``sentinel_repl_lag_ms`` gauge (capture → ACK age of the last
acked document).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from sentinel_tpu import chaos as _chaos
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.core.log import record_log
from sentinel_tpu.ha.snapshot import (
    _dec_array,
    _enc_array,
    decode_snapshot,
    encode_snapshot,
)
from sentinel_tpu.metrics.ha import ha_metrics

DELTA_VERSION = 1
KEY_REPL_INTERVAL_MS = "sentinel.tpu.ha.repl.interval.ms"
KEY_PROMOTE_AFTER_MS = "sentinel.tpu.ha.repl.promote.after.ms"

# export_delta keys holding numpy arrays (everything else is JSON-native)
_ARRAY_KEYS = frozenset(
    {
        "flow_starts", "occupy_starts", "ns_starts", "param_starts",
        "flow_counts", "occupy_counts", "ns_counts", "param_counts",
        "param_slim",  # SF slim-twin rows: the param payload when slim is on
        # shaper clocks (raw engine-ms, same dirty-row keying as flow_counts)
        "shaping_lpt", "shaping_warm_tokens", "shaping_warm_filled",
        # completion-outcome columns (own dirty set: reporting cadence is
        # decoupled from the admission windows')
        "outcome_starts", "outcome_counts",
        # circuit-breaker columns (own dirty set: transitions happen only
        # on batched/reported rows, so touched∩breaker is exact)
        "breaker_state", "breaker_opened", "breaker_probe",
    }
)


# -- blob codecs --------------------------------------------------------------
def encode_delta_blob(delta: Dict[str, object]) -> bytes:
    """``export_delta()`` document → compressed wire blob."""
    doc: Dict[str, object] = {"version": DELTA_VERSION}
    for k, v in delta.items():
        doc[k] = _enc_array(v) if k in _ARRAY_KEYS else v
    return zlib.compress(json.dumps(doc, separators=(",", ":")).encode())


def decode_delta_blob(blob: bytes) -> Dict[str, object]:
    """Wire blob → the dict ``apply_replication_delta`` consumes. Raises
    ``ValueError`` on any malformed input (fuzz-safe: corrupt bytes must
    never kill the applier)."""
    try:
        doc = json.loads(zlib.decompress(blob).decode())
        if doc.pop("version", None) != DELTA_VERSION:
            raise ValueError("unsupported delta version")
        return {
            k: (_dec_array(v) if k in _ARRAY_KEYS else v)
            for k, v in doc.items()
        }
    except ValueError:
        raise
    except Exception as e:  # zlib.error, UnicodeDecodeError, KeyError, ...
        raise ValueError(f"malformed delta blob: {e}") from None


def encode_snapshot_blob(state: Dict[str, object]) -> bytes:
    """``export_state()`` capture → compressed full-sync wire blob."""
    return zlib.compress(
        json.dumps(encode_snapshot(state), separators=(",", ":")).encode()
    )


def decode_snapshot_blob(blob: bytes) -> Dict[str, object]:
    """Wire blob → the dict ``import_state`` consumes (fuzz-safe)."""
    try:
        return decode_snapshot(json.loads(zlib.decompress(blob).decode()))
    except ValueError:
        raise
    except Exception as e:
        raise ValueError(f"malformed snapshot blob: {e}") from None


# -- primary side -------------------------------------------------------------
class _Link:
    """One standby's connection state. ``gen=-1`` + ``needs_snapshot`` make
    the first ship a full bootstrap; every failure path resets to that."""

    __slots__ = ("host", "port", "sock", "gen", "needs_snapshot", "promoted",
                 "buf")

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = int(port)
        self.sock: Optional[socket.socket] = None
        self.gen = -1
        self.needs_snapshot = True
        self.promoted = False  # standby answered NOT_STANDBY; stop shipping
        self.buf = b""

    def close(self) -> None:
        sock, self.sock = self.sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self.buf = b""
        self.needs_snapshot = True
        self.gen = -1

    def __str__(self) -> str:
        return f"{self.host}:{self.port}"


class ReplicationSender:
    """Primary-side delta shipper: one daemon thread collects ONE delta per
    tick (``export_delta`` is destructive — collect once, ship to all) and
    streams it to every standby link, falling back to a full snapshot for
    any link that is fresh, acked NEED_SNAPSHOT, reconnected, or whose last
    shipped generation is stale. An idle tick still ships the starts-only
    heartbeat delta, which doubles as the standby's liveness signal (the
    applier's promotion watchdog resets on it)."""

    def __init__(
        self,
        service,
        standbys: Sequence,
        interval_ms: Optional[float] = None,
        sender_id: str = "",
        ack_timeout_s: float = 2.0,
    ):
        self.service = service
        self.interval_ms = float(
            interval_ms
            if interval_ms is not None
            else SentinelConfig.get_float(KEY_REPL_INTERVAL_MS, 250.0)
        )
        self.sender_id = sender_id
        self.ack_timeout_s = float(ack_timeout_s)
        self._links: List[_Link] = []
        for sb in standbys:
            if isinstance(sb, _Link):
                self._links.append(sb)
            elif isinstance(sb, str):
                host, _, port = sb.rpartition(":")
                self._links.append(_Link(host, int(port)))
            else:
                self._links.append(_Link(str(sb[0]), int(sb[1])))
        if not self._links:
            raise ValueError("at least one standby required")
        self._seq = 0
        self._xid = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_ship_ms: Optional[int] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ReplicationSender":
        if self._thread is None:
            self.service.replication_enable()
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="sentinel-repl-sender", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
        for link in self._links:
            link.close()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_ms / 1000.0):
            try:
                self.ship_once()
            except Exception:
                # the tick must never kill the thread: any per-link failure
                # is already handled per link; this catches collect-side
                # surprises (e.g. a concurrent close)
                record_log.exception("replication tick failed")
                ha_metrics().count_repl("error")

    # -- one tick ------------------------------------------------------------
    def ship_once(self) -> int:
        """Collect one delta and ship to every live link. Returns the number
        of links that acked a document this tick (test/drill hook)."""
        delta = self.service.export_delta()
        delta_blob: Optional[bytes] = None
        snap_blob: Optional[bytes] = None
        snap_wall = 0
        acked = 0
        for link in self._links:
            if link.promoted:
                continue
            try:
                self._ensure_connected(link)
                if link.needs_snapshot or link.gen != delta["gen"]:
                    if snap_blob is None:
                        state = self.service.export_state()
                        snap_wall = int(state["wall_ms"])
                        snap_blob = encode_snapshot_blob(state)
                    self._ship(
                        link, P.MsgType.REPL_SNAPSHOT, int(delta["gen"]),
                        snap_blob,
                    )
                    # the snapshot captured at/after the delta, so it covers
                    # the delta's rows too — the delta is subsumed
                    link.gen = int(delta["gen"])
                    link.needs_snapshot = False
                    ha_metrics().count_repl("snapshot")
                    ha_metrics().set_repl_lag(
                        max(0, _clock.now_ms() - snap_wall)
                    )
                else:
                    if delta_blob is None:
                        delta_blob = encode_delta_blob(delta)
                    self._ship(
                        link, P.MsgType.REPL_DELTA, int(delta["gen"]),
                        delta_blob,
                    )
                    ha_metrics().count_repl("shipped")
                    ha_metrics().set_repl_lag(
                        max(0, _clock.now_ms() - int(delta["wall_ms"]))
                    )
                acked += 1
            except Exception as e:
                if link.sock is not None or not isinstance(e, OSError):
                    record_log.warning(
                        "replication to %s failed (%s); will reconnect",
                        link, e,
                    )
                link.close()
                ha_metrics().count_repl("reconnect")
        self.last_ship_ms = _clock.now_ms()
        return acked

    # -- link plumbing -------------------------------------------------------
    def _ensure_connected(self, link: _Link) -> None:
        if link.sock is not None:
            return
        sock = socket.create_connection(
            (link.host, link.port), timeout=self.ack_timeout_s
        )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        link.sock = sock
        link.buf = b""
        # HELLO → the standby tells us whether it can extend our timeline
        self._xid += 1
        gen = self.service.state_generation()
        epoch = getattr(self.service, "_epoch_ms", None) or 0
        sock.sendall(
            P.encode_repl_hello(
                self._xid, gen, int(epoch), self._seq, self.sender_id
            )
        )
        code, _g, _s = self._read_ack(link)
        if code == P.ReplAck.NOT_STANDBY:
            link.promoted = True
            record_log.warning("standby %s reports promoted; link idle", link)
            return
        link.needs_snapshot = code != P.ReplAck.OK
        link.gen = gen if code == P.ReplAck.OK else -1

    def _ship(self, link: _Link, mtype: int, gen: int, blob: bytes) -> None:
        self._seq += 1
        self._xid += 1
        seq = self._seq
        frames = P.encode_repl_blob(self._xid, mtype, gen, seq, blob)
        for frame in frames:
            if _chaos.ARMED:
                _chaos.maybe_sleep("lane_delay")
                if _chaos.should("conn_reset"):
                    raise ConnectionResetError("chaos: repl conn_reset")
            link.sock.sendall(frame)
        ha_metrics().add_repl_bytes(sum(len(f) for f in frames))
        code, _ack_gen, ack_seq = self._read_ack(link)
        if code == P.ReplAck.NOT_STANDBY:
            # carries seq=-1 (it answers any frame, not a document), so it
            # must be recognized before the seq-match check
            link.promoted = True
            record_log.warning("standby %s reports promoted; link idle", link)
            return
        if ack_seq != seq:
            raise ConnectionError(
                f"repl ack out of step (sent seq {seq}, acked {ack_seq})"
            )
        if code == P.ReplAck.OK:
            return
        if code == P.ReplAck.NEED_SNAPSHOT:
            link.needs_snapshot = True
            ha_metrics().count_repl("need_snapshot")
            return
        raise ConnectionError(f"standby {link} acked ERROR")

    def _read_ack(self, link: _Link) -> Tuple[int, int, int]:
        """Block for the next REPL_ACK frame on this link's socket. Frames
        of any other type on the repl channel are protocol violations and
        tear the link (handled by the caller's except path)."""
        while True:
            while len(link.buf) < 2:
                link.buf += self._recv(link)
            (length,) = struct.unpack_from(">H", link.buf, 0)
            while len(link.buf) < 2 + length:
                link.buf += self._recv(link)
            payload = link.buf[2 : 2 + length]
            link.buf = link.buf[2 + length :]
            if len(payload) < 5 or P.peek_type(payload) != P.MsgType.REPL_ACK:
                raise ConnectionError("non-ack frame on repl channel")
            _xid, code, gen, seq = P.decode_repl_ack(payload)
            return code, gen, seq

    def _recv(self, link: _Link) -> bytes:
        link.sock.settimeout(self.ack_timeout_s)
        data = link.sock.recv(65536)
        if not data:
            raise ConnectionError("repl link closed by standby")
        return data

    # -- introspection -------------------------------------------------------
    def status(self) -> Dict[str, object]:
        return {
            "intervalMs": self.interval_ms,
            "lastShipMs": self.last_ship_ms,
            "seq": self._seq,
            "links": [
                {
                    "standby": str(link),
                    "connected": link.sock is not None,
                    "gen": link.gen,
                    "needsSnapshot": link.needs_snapshot,
                    "promoted": link.promoted,
                }
                for link in self._links
            ],
        }


# -- standby side -------------------------------------------------------------
class StandbyApplier:
    """Applies replication documents into a standby's token service and
    owns the promotion decision.

    The front doors hand every rev-3 frame to a per-connection session
    (:meth:`connection`); the session reassembles chunked blobs and calls
    back into this shared applier, which serializes applies (the doors run
    on different threads/loops) and acks. Until :meth:`promote` flips the
    flag the doors refuse data-plane traffic with ``TokenStatus.STANDBY``;
    after it they serve, and any late repl frame is acked NOT_STANDBY so
    the old primary stops shipping.

    ``promote_after_ms > 0`` arms the primary-death watchdog: a daemon
    thread promotes automatically when no repl traffic (hello, delta, or
    snapshot chunk) has arrived for that long — counted from the LAST
    contact, and only once the primary has connected at least once. Death
    can't be detected for a primary never seen alive: a standby brought up
    ahead of its (slow-booting) primary must keep its door closed, not
    promote into a split brain the moment the boot outlasts the timer.
    A standby whose primary truly never appears stays refusing until an
    operator promotes it explicitly (``cluster/server/promote``)."""

    def __init__(
        self,
        service,
        promote_after_ms: Optional[float] = None,
        on_promote: Optional[Callable[[str], None]] = None,
    ):
        self.service = service
        self.promote_after_ms = float(
            promote_after_ms
            if promote_after_ms is not None
            else SentinelConfig.get_float(KEY_PROMOTE_AFTER_MS, 0.0)
        )
        self.on_promote = on_promote
        self._promoted = threading.Event()
        self._lock = threading.Lock()  # serializes applies across doors
        self._last_contact_ms: Optional[int] = None
        self._started_ms: Optional[int] = None
        self._applied = 0
        self._snapshots = 0
        self._lag_ms = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "StandbyApplier":
        self._started_ms = _clock.now_ms()
        if self.promote_after_ms > 0 and self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watchdog, name="sentinel-standby-watchdog",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)

    def _watchdog(self) -> None:
        poll_s = max(0.01, self.promote_after_ms / 4000.0)
        while not self._stop.wait(poll_s):
            if self._promoted.is_set():
                return
            with self._lock:
                base = self._last_contact_ms
            if base is None:  # primary never connected: nothing to detect
                continue
            if _clock.now_ms() - base >= self.promote_after_ms:
                self.promote(reason="primary_silent")
                return

    # -- promotion -----------------------------------------------------------
    @property
    def promoted(self) -> bool:
        return self._promoted.is_set()

    def promote(self, reason: str = "manual") -> bool:
        """Open the front door. Returns False when already promoted."""
        if self._promoted.is_set():
            return False
        self._promoted.set()
        ha_metrics().count_repl("promoted")
        record_log.warning(
            "standby promoted to primary (reason=%s, lag=%.0fms)",
            reason, self._lag_ms,
        )
        from sentinel_tpu.trace import blackbox as _blackbox
        from sentinel_tpu.trace import ring as _TR

        if _TR.ARMED:
            _TR.record(_TR.PROMOTE)
        # a promotion means the primary just died (or an operator thinks
        # it did) — freeze the evidence before the new primary's traffic
        # overwrites the rings
        _blackbox.maybe_dump(f"standby_promote:{reason}")
        if self.on_promote is not None:
            try:
                self.on_promote(reason)
            except Exception:
                record_log.exception("on_promote callback failed")
        return True

    # -- frame handling ------------------------------------------------------
    def connection(self) -> "ReplSession":
        """Per-connection session (chunk reassembly is per TCP stream)."""
        return ReplSession(self)

    def _touch(self) -> None:
        with self._lock:
            self._last_contact_ms = _clock.now_ms()

    def _apply(self, mtype: int, blob: bytes) -> int:
        """Decode + apply one reassembled document; returns the ack code.
        ``ValueError`` (malformed blob, epoch/rule mismatch) asks for a
        snapshot resync; anything else is ERROR (the sender tears the
        link and starts over — state is never half-applied: the service
        validates before mutating)."""
        try:
            if mtype == P.MsgType.REPL_SNAPSHOT:
                state = decode_snapshot_blob(blob)
                wall = int(state["wall_ms"])
                with self._lock:
                    self.service.import_state(state)
                    self._snapshots += 1
                    self._lag_ms = max(0, _clock.now_ms() - wall)
                ha_metrics().count_repl("snapshot")
            else:
                delta = decode_delta_blob(blob)
                wall = int(delta["wall_ms"])
                with self._lock:
                    self.service.apply_replication_delta(delta)
                    self._applied += 1
                    self._lag_ms = max(0, _clock.now_ms() - wall)
                ha_metrics().count_repl("applied")
            ha_metrics().set_repl_lag(self._lag_ms)
            return int(P.ReplAck.OK)
        except ValueError as e:
            record_log.warning("replication document refused: %s", e)
            ha_metrics().count_repl("need_snapshot")
            return int(P.ReplAck.NEED_SNAPSHOT)
        except Exception:
            record_log.exception("replication apply failed")
            ha_metrics().count_repl("error")
            return int(P.ReplAck.ERROR)

    # -- introspection -------------------------------------------------------
    def status(self) -> Dict[str, object]:
        with self._lock:
            return {
                "promoted": self.promoted,
                "promoteAfterMs": self.promote_after_ms,
                "lastContactMs": self._last_contact_ms,
                "deltasApplied": self._applied,
                "snapshotsApplied": self._snapshots,
                "lagMs": self._lag_ms,
            }


class ReplSession:
    """One repl connection's state behind a front door: the chunk
    reassembler plus the ack plumbing. ``handle(payload, send)`` consumes
    one rev-3 frame and writes any ack through ``send`` (the door-specific
    raw-bytes writer). Raises ``ValueError`` on a torn or malformed chunk
    stream so the door can drop the connection (same contract as
    ``decode_request``)."""

    def __init__(self, applier: StandbyApplier):
        self.applier = applier
        self._asm = P.ReplBlobAssembler()

    def handle(self, payload: bytes, send: Callable[[bytes], None]) -> None:
        mtype = P.peek_type(payload)
        if self.applier.promoted:
            # late frame from the deposed primary: tell it to stop
            send(P.encode_repl_ack(P.peek_xid(payload),
                                   P.ReplAck.NOT_STANDBY, -1, -1))
            return
        if mtype == P.MsgType.REPL_HELLO:
            xid, _gen, epoch, _seq, sender = P.decode_repl_hello(payload)
            self.applier._touch()
            local_epoch = getattr(self.applier.service, "_epoch_ms", None)
            code = (
                P.ReplAck.OK
                if local_epoch is not None and int(epoch) == int(local_epoch)
                else P.ReplAck.NEED_SNAPSHOT
            )
            send(P.encode_repl_ack(xid, code, -1, -1))
            return
        if mtype == P.MsgType.REPL_ACK:
            return  # acks flow standby → primary only; ignore strays
        # chunked blob frame (REPL_DELTA / REPL_SNAPSHOT)
        self.applier._touch()
        done = self._asm.feed(mtype, payload)
        if done is None:
            return
        dtype, gen, seq, blob = done
        xid = P.peek_xid(payload)
        code = self.applier._apply(dtype, blob)
        send(P.encode_repl_ack(xid, code, gen, seq))
