"""Per-rule local degradation when no token server is reachable.

Mirrors the reference's fail-to-local semantics
(``FlowRuleChecker.fallbackToLocalOrPass``) at the *client* layer: when the
failover client exhausts its endpoint list, every request still resolves —
never an exception, never an indefinite FAIL — according to a per-flow-id
policy:

- **PASS**: admit (the reference's pass-through when
  ``fallback_to_local_when_fail`` is off).
- **BLOCK**: reject (fail-closed for rules that must not over-admit).
- **THROTTLE**: run a *local* sliding-window check against a degraded
  threshold via the existing ``local.flow`` controllers — the fail-to-local
  path proper, sized for one node's fair share of the cluster budget.

Throttle state is per flow_id (a host ``StatisticNode`` + a controller from
:func:`sentinel_tpu.local.flow.fallback_controller`) and is created lazily —
fallback is the degraded path, its setup cost must not precede the failure
it handles.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

from sentinel_tpu.cluster.token_service import TokenResult
from sentinel_tpu.core import clock as _clock
from sentinel_tpu.engine import TokenStatus
from sentinel_tpu.local.base import PriorityWaitException
from sentinel_tpu.local.flow import fallback_controller
from sentinel_tpu.local.stat import StatisticNode
from sentinel_tpu.metrics.ha import ha_metrics


class FallbackAction(enum.IntEnum):
    PASS = 0
    BLOCK = 1
    THROTTLE = 2


@dataclass(frozen=True)
class FallbackRule:
    """Fallback policy for one cluster flow id.

    ``count`` is the *local* degraded QPS budget for THROTTLE (typically the
    cluster threshold divided by the expected client count — the AVG_LOCAL
    share); ``max_queueing_time_ms > 0`` paces instead of rejecting."""

    flow_id: int
    action: FallbackAction = FallbackAction.THROTTLE
    count: float = 0.0
    max_queueing_time_ms: int = 0


class _Throttle:
    """Lazy per-flow local window + controller."""

    __slots__ = ("node", "controller")

    def __init__(self, rule: FallbackRule):
        self.node = StatisticNode()
        self.controller = fallback_controller(
            rule.count, rule.max_queueing_time_ms
        )


class LocalFallbackPolicy:
    """flow_id → FallbackRule table with a default action for unlisted ids.

    A THROTTLE default throttles unlisted ids against ``default_count`` /
    ``default_max_queueing_time_ms`` (the zero default admits nothing —
    still a resolved BLOCKED verdict, never an exception).

    Thread-safe; shared by every request the failover client degrades."""

    def __init__(
        self,
        rules: Iterable[FallbackRule] = (),
        default_action: FallbackAction = FallbackAction.PASS,
        default_count: float = 0.0,
        default_max_queueing_time_ms: int = 0,
    ):
        self.default_action = FallbackAction(default_action)
        self.default_count = float(default_count)
        self.default_max_queueing_time_ms = int(default_max_queueing_time_ms)
        self._lock = threading.Lock()
        self._rules: Dict[int, FallbackRule] = {}
        self._throttles: Dict[int, _Throttle] = {}
        self._passed = 0
        self._blocked = 0
        self.load_rules(rules)

    def load_rules(self, rules: Iterable[FallbackRule]) -> None:
        table = {int(r.flow_id): r for r in rules}
        with self._lock:
            self._rules = table
            # reloads reset throttle state, matching local.flow's
            # re-instantiated controllers on rule reload
            self._throttles = {}

    def rule_for(self, flow_id: int) -> Optional[FallbackRule]:
        with self._lock:
            return self._rules.get(int(flow_id))

    # -- decision path -------------------------------------------------------
    def decide(self, flow_id: int, acquire: int = 1,
               prioritized: bool = False) -> TokenResult:
        """One degraded verdict. Counts into ``sentinel_fallback_total``."""
        rule = self.rule_for(flow_id)
        action = rule.action if rule is not None else self.default_action
        if action == FallbackAction.PASS:
            self._count("pass", passed=True)
            return TokenResult(TokenStatus.OK)
        if action == FallbackAction.BLOCK:
            self._count("block", passed=False)
            return TokenResult(TokenStatus.BLOCKED)
        if rule is None:
            # unlisted id under a THROTTLE default: synthesize a rule so the
            # degraded hot path still resolves (against the default budget)
            # instead of dereferencing None
            rule = FallbackRule(
                int(flow_id),
                FallbackAction.THROTTLE,
                count=self.default_count,
                max_queueing_time_ms=self.default_max_queueing_time_ms,
            )
        throttle = self._throttle_for(rule)
        now = _clock.now_ms()
        try:
            ok = bool(throttle.controller.can_pass(throttle.node, acquire,
                                                   prioritized))
        except PriorityWaitException:
            # the controller already waited the occupied window in; admitted
            ok = True
        if ok:
            throttle.node.add_pass(acquire, _clock.now_ms())
            self._count("throttle_pass", passed=True)
            return TokenResult(TokenStatus.OK)
        throttle.node.add_block(acquire, now)
        self._count("throttle_block", passed=False)
        return TokenResult(TokenStatus.BLOCKED)

    def decide_batch_arrays(
        self, flow_ids, acquires=None, prios=None
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Array-shaped degraded verdicts matching the
        ``TokenService.request_batch_arrays`` contract."""
        flow_ids = np.asarray(flow_ids, np.int64)
        n = flow_ids.shape[0]
        status = np.empty(n, np.int8)
        remaining = np.zeros(n, np.int32)
        wait = np.zeros(n, np.int32)
        for i in range(n):
            r = self.decide(
                int(flow_ids[i]),
                1 if acquires is None else int(acquires[i]),
                False if prios is None else bool(prios[i]),
            )
            status[i] = int(r.status)
            remaining[i] = r.remaining
            wait[i] = r.wait_ms
        return status, remaining, wait

    # -- internals -----------------------------------------------------------
    def _throttle_for(self, rule: FallbackRule) -> _Throttle:
        with self._lock:
            throttle = self._throttles.get(rule.flow_id)
            if throttle is None:
                throttle = self._throttles[rule.flow_id] = _Throttle(rule)
            return throttle

    def _count(self, action: str, passed: bool) -> None:
        ha_metrics().count_fallback(action)
        with self._lock:
            if passed:
                self._passed += 1
            else:
                self._blocked += 1

    def stats(self) -> dict:
        """Pass/block totals since construction (bench artifact shape)."""
        with self._lock:
            total = self._passed + self._blocked
            return {
                "passed": self._passed,
                "blocked": self._blocked,
                "blocked_rate": (self._blocked / total) if total else 0.0,
            }
