"""FailoverTokenClient: ordered endpoint list with eviction + fallback.

A drop-in ``TokenService`` for the client side of cluster flow control:
instead of pinning one host (``cluster.client.TokenClient``), it walks an
ordered endpoint list — primary first, standbys after — and serves each
request from the first endpoint whose circuit breaker admits it. A FAIL
verdict (the client-side degraded status for send failure / timeout /
connection loss) records a failure against that endpoint; after
``failure_threshold`` consecutive failures the endpoint is evicted (breaker
OPEN) and the next request goes straight to the standby — so a SIGKILLed
primary costs at most ``threshold × request_timeout`` of unhealthy verdicts
before the standby serves, well inside the configured failover deadline.

When NO endpoint is available (all breakers open, or the per-request
failover deadline is spent), the request resolves through the
:class:`~sentinel_tpu.ha.fallback.LocalFallbackPolicy` — pass, block, or
local-window throttle per rule — and never raises.

Wire-level behavior (timeouts, pipelined BATCH_FLOW chunks, reconnect
backoff) stays in the wrapped per-endpoint ``TokenClient``s; this class only
decides *where* a request goes and *what* happens when nowhere is healthy.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

from sentinel_tpu.cluster.client import TokenClient
from sentinel_tpu.cluster.token_service import TokenResult, TokenService
from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.core.log import record_log
from sentinel_tpu.engine import TokenStatus
from sentinel_tpu.ha.endpoints import Endpoint, EndpointHealth
from sentinel_tpu.ha.fallback import LocalFallbackPolicy
from sentinel_tpu.metrics.ha import ha_metrics

KEY_FAILOVER_DEADLINE_MS = "sentinel.tpu.ha.failover.deadline.ms"


class _Member:
    __slots__ = ("endpoint", "health", "client")

    def __init__(self, endpoint: Endpoint, health: EndpointHealth, client):
        self.endpoint = endpoint
        self.health = health
        self.client = client


class FailoverTokenClient(TokenService):
    def __init__(
        self,
        endpoints: Sequence,
        timeout_ms: int = 20,
        namespace: str = "default",
        fallback: Optional[LocalFallbackPolicy] = None,
        failure_threshold: Optional[int] = None,
        backoff_base_ms: Optional[float] = None,
        backoff_max_ms: Optional[float] = None,
        deadline_ms: Optional[float] = None,
        client_factory: Callable = TokenClient,
        lease: bool = False,
        lease_want: int = 256,
    ):
        if not endpoints:
            raise ValueError("at least one endpoint required")
        self.namespace = namespace
        self.timeout_ms = timeout_ms
        # lease kwargs are forwarded only when enabled so stub factories
        # that predate wire rev 5 keep working unchanged
        extra = {"lease": True, "lease_want": lease_want} if lease else {}
        # overall per-request budget for walking the endpoint list; once
        # spent, the request degrades to fallback instead of trying further
        # standbys (the configured failover deadline)
        self.deadline_ms = float(
            deadline_ms
            if deadline_ms is not None
            else SentinelConfig.get_float(KEY_FAILOVER_DEADLINE_MS, 500.0)
        )
        self.fallback = fallback if fallback is not None else (
            LocalFallbackPolicy()
        )
        self._members: List[_Member] = []
        for ep in endpoints:
            if not isinstance(ep, Endpoint):
                ep = Endpoint(str(ep[0]), int(ep[1]))
            self._members.append(
                _Member(
                    ep,
                    EndpointHealth(
                        failure_threshold=failure_threshold,
                        backoff_base_ms=backoff_base_ms,
                        backoff_max_ms=backoff_max_ms,
                    ),
                    client_factory(
                        ep.host, ep.port, timeout_ms=timeout_ms,
                        namespace=namespace, **extra,
                    ),
                )
            )
        self._lock = threading.Lock()
        self._active = 0  # index of the member that served last (telemetry)
        # rev-7 brownout advisories: per-member wall-clock until which the
        # endpoint has ADVERTISED it is shedding. An advisory only reorders
        # the walk (standbys first) — it never removes the endpoint, so a
        # fleet-wide brownout still gets served by the least-bad member.
        self._brownout_until: List[float] = [0.0] * len(self._members)
        for i, member in enumerate(self._members):
            self._arm_push(i, member)

    # -- rev-7 push interest -------------------------------------------------
    def _arm_push(self, index: int, member: _Member) -> None:
        """(Re-)subscribe to a member client's brownout pushes. The callback
        lives on the client object and survives its internal reconnects;
        re-arming after a walk lands elsewhere (``_note_served``) keeps the
        subscription alive even if a wrapper swapped the callback out."""
        client = member.client
        if not hasattr(client, "on_brownout"):
            return

        def _advise(level, retry_after_ms, _i=index):
            if int(level) <= 0:
                self._brownout_until[_i] = 0.0
                return
            hold = float(retry_after_ms) if retry_after_ms > 0 else 100.0
            self._brownout_until[_i] = _clock.now_ms() + hold

        client.on_brownout = _advise

    def _walk_order(self) -> List[int]:
        """Endpoint indices in walk order: members without a live brownout
        advisory first, advertised-browned members demoted to the tail (the
        early-walk hint — we reach the standby BEFORE burning a round trip
        on an endpoint that told us it is shedding). All browned, or none:
        the configured order stands."""
        now = _clock.now_ms()
        until = self._brownout_until
        n = len(self._members)
        browned = [i for i in range(n) if until[i] > now]
        if not browned or len(browned) == n:
            return list(range(n))
        ha_metrics().count_fallback("brownout_hint")
        return [i for i in range(n) if until[i] <= now] + browned

    # -- endpoint walk -------------------------------------------------------
    def _note_served(self, index: int) -> None:
        with self._lock:
            if index != self._active:
                prev = self._members[self._active].endpoint
                ha_metrics().count_failover(
                    str(prev), str(self._members[index].endpoint),
                    now_ms=_clock.now_ms(),
                )
                record_log.warning(
                    "token client failed over: %s -> %s", prev,
                    self._members[index].endpoint,
                )
                self._active = index
                # the walk landed on a different endpoint: re-register push
                # interest there so revocations/advisories keep flowing
                self._arm_push(index, self._members[index])

    def _note_exhausted(self) -> None:
        """Every endpoint refused or failed → this request degrades."""
        with self._lock:
            prev = self._members[self._active].endpoint
        ha_metrics().count_failover(str(prev), "", now_ms=_clock.now_ms())

    @staticmethod
    def _overloaded(result) -> bool:
        """An explicit server-side admission refusal (OVERLOAD). A batch
        counts only when EVERY row was refused — a partially-admitted batch
        is an answer and returns to the caller as-is."""
        if isinstance(result, TokenResult):
            return result.status == TokenStatus.OVERLOAD
        if isinstance(result, tuple) and len(result) == 3:
            status = np.asarray(result[0])
            return status.size > 0 and bool(
                (status == int(TokenStatus.OVERLOAD)).all()
            )
        return False

    @staticmethod
    def _moved_redirect(result) -> bool:
        """A live-rebalance redirect (MOVED): the namespace is being (or has
        been) handed to another server. Same whole-batch rule as OVERLOAD."""
        if isinstance(result, TokenResult):
            return result.status == TokenStatus.MOVED
        if isinstance(result, tuple) and len(result) == 3:
            status = np.asarray(result[0])
            return status.size > 0 and bool(
                (status == int(TokenStatus.MOVED)).all()
            )
        return False

    @staticmethod
    def _standby_refusal(result) -> bool:
        """An unpromoted warm standby's closed-door refusal (STANDBY). Same
        whole-batch rule as OVERLOAD: every row refused, or it's an
        answer."""
        if isinstance(result, TokenResult):
            return result.status == TokenStatus.STANDBY
        if isinstance(result, tuple) and len(result) == 3:
            status = np.asarray(result[0])
            return status.size > 0 and bool(
                (status == int(TokenStatus.STANDBY)).all()
            )
        return False

    @staticmethod
    def _degraded(result) -> bool:
        """A server-side circuit-breaker refusal (DEGRADED): the resource's
        breaker is OPEN and the server is answering honestly with a
        retry-after hint. Same whole-batch rule as OVERLOAD."""
        if isinstance(result, TokenResult):
            return result.status == TokenStatus.DEGRADED
        if isinstance(result, tuple) and len(result) == 3:
            status = np.asarray(result[0])
            return status.size > 0 and bool(
                (status == int(TokenStatus.DEGRADED)).all()
            )
        return False

    @staticmethod
    def _lease_refusal(result) -> bool:
        """A lease-protocol refusal (NOT_LEASABLE: flow not leasable, lease
        revoked, or no headroom to delegate). The wrapped per-endpoint
        client already degrades its own lease refusals to per-request RPCs,
        so this only fires for custom clients that surface the status — but
        when it does, the server is alive and answering honestly. Same
        whole-batch rule as OVERLOAD."""
        if isinstance(result, TokenResult):
            return result.status == TokenStatus.NOT_LEASABLE
        if isinstance(result, tuple) and len(result) == 3:
            status = np.asarray(result[0])
            return status.size > 0 and bool(
                (status == int(TokenStatus.NOT_LEASABLE)).all()
            )
        return False

    def _call(self, op: Callable, failed=None):
        """Walk available endpoints inside the deadline; ``op(member)``
        returns the raw result and ``failed(result)`` judges it. Returns the
        first healthy result or None when the list is exhausted.

        OVERLOAD replies are proof of life, not failure: the server is up
        and explicitly refusing admission, so the breaker records SUCCESS
        (evicting an overloaded-but-alive server would dogpile the
        standbys) and the walk tries the next endpoint. When every
        reachable endpoint is overloaded the first OVERLOAD reply — with
        its retry hint — is returned rather than degrading to fallback.

        STANDBY replies are likewise proof of life: an unpromoted warm
        standby keeps its door closed so clients walk on to the primary.
        Unlike OVERLOAD, a STANDBY reply carries no verdict at all, so it
        is never returned — if nothing else answers, the local fallback
        decides (without counting the cluster as exhausted: the standby is
        alive and about to promote).

        MOVED replies (live shard rebalancing) are proof of life too: the
        server is up and telling us the namespace now lives elsewhere. This
        client has no shard map to follow the redirect with (that is
        RoutingTokenClient's job), so it records SUCCESS — evicting a
        healthy server for answering honestly would be wrong — and walks on
        to the next endpoint, which may be the move's destination.

        DEGRADED replies (server-side circuit breaking) are proof of life
        as well: the resource's breaker is OPEN, which says the PROTECTED
        DEPENDENCY is unhealthy, not the token server. The breaker records
        SUCCESS and the walk tries the next endpoint (a standby whose
        replicated breaker lags may still admit); when nothing answers
        better the first DEGRADED verdict — with its retry-after hint in
        ``remaining`` — is returned rather than degrading to fallback,
        which would defeat the breaker's whole purpose."""
        if failed is None:
            failed = lambda r: (
                r is None
                or (isinstance(r, TokenResult)
                    and r.status == TokenStatus.FAIL)
            )
        deadline = _clock.now_ms() + self.deadline_ms
        overload_result = None
        degraded_result = None
        saw_standby = False
        for i in self._walk_order():
            member = self._members[i]
            # health is consulted immediately before dispatch, never up
            # front for the whole list: allows_request() may flip an OPEN
            # breaker to HALF_OPEN and hand this call its one probe slot,
            # which MUST be followed by record_success/record_failure below
            # — a member the walk never reaches (earlier endpoint served,
            # deadline broke the loop) must not be flipped speculatively
            if not member.health.allows_request():
                continue
            try:
                result = op(member)
            except Exception:
                record_log.exception(
                    "token endpoint %s raised; treating as failure",
                    member.endpoint,
                )
                result = None
            if failed(result):
                member.health.record_failure()
                if _clock.now_ms() >= deadline:
                    break
                continue
            member.health.record_success()
            if self._standby_refusal(result):
                saw_standby = True
                ha_metrics().count_fallback("standby_redirect")
                if _clock.now_ms() >= deadline:
                    break
                continue
            if self._moved_redirect(result):
                saw_standby = True  # alive, not exhausted — same as STANDBY
                ha_metrics().count_fallback("moved_redirect")
                if _clock.now_ms() >= deadline:
                    break
                continue
            if self._lease_refusal(result):
                # proof of life, never eviction: a server refusing to
                # delegate its window still decides per-request RPCs fine.
                # The refusal carries no admission verdict, so walk on; the
                # member's own client falls back to wire on the next call.
                saw_standby = True
                ha_metrics().count_fallback("lease_refused")
                if _clock.now_ms() >= deadline:
                    break
                continue
            if self._degraded(result):
                ha_metrics().count_fallback("degraded")
                if degraded_result is None:
                    degraded_result = result
                if _clock.now_ms() >= deadline:
                    break
                continue
            if self._overloaded(result):
                ha_metrics().count_fallback("overload_backoff")
                if overload_result is None:
                    overload_result = result
                if _clock.now_ms() >= deadline:
                    break
                continue
            self._note_served(i)
            return result
        if degraded_result is not None:
            # the breaker verdict is authoritative cluster state (the same
            # OPEN row replicates everywhere) — prefer it over OVERLOAD
            return degraded_result
        if overload_result is not None:
            return overload_result
        if not saw_standby:
            self._note_exhausted()
        return None

    # -- TokenService --------------------------------------------------------
    def request_token(self, flow_id, acquire=1, prioritized=False):
        result = self._call(
            lambda m: m.client.request_token(flow_id, acquire, prioritized)
        )
        if result is not None:
            return result
        return self.fallback.decide(flow_id, acquire, prioritized)

    def request_params_token(self, flow_id, acquire, param_hashes):
        result = self._call(
            lambda m: m.client.request_params_token(
                flow_id, acquire, param_hashes
            )
        )
        if result is not None:
            return result
        return self.fallback.decide(flow_id, acquire)

    def request_concurrent_token(self, flow_id, acquire=1, prioritized=False):
        result = self._call(
            lambda m: m.client.request_concurrent_token(
                flow_id, acquire, prioritized
            )
        )
        if result is not None:
            return result
        return self.fallback.decide(flow_id, acquire, prioritized)

    def release_concurrent_token(self, token_id):
        result = self._call(
            lambda m: m.client.release_concurrent_token(token_id)
        )
        if result is not None:
            return result
        # a release that can reach no server is lost either way; report OK so
        # callers don't retry forever against a dead cluster (the server-side
        # TTL sweep reclaims the permit)
        ha_metrics().count_fallback("release_dropped")
        return TokenResult(TokenStatus.RELEASE_OK)

    # -- hierarchy tier (share agent → coordinator) --------------------------
    def _hier_call(self, op: Callable):
        """Endpoint walk for hierarchy control ops (share grant/renew/
        return, demand report). STANDBY replies walk on as usual;
        NOT_LEASABLE is ambiguous here — the true coordinator refusing
        headroom, or a door with no coordinator attached — so it walks on
        too but is REMEMBERED and returned when no endpoint answers
        better (the agent treats it as an authoritative zero-share)."""
        deadline = _clock.now_ms() + self.deadline_ms
        refusal = None
        for i in self._walk_order():
            member = self._members[i]
            if not member.health.allows_request():
                continue
            try:
                result = op(member)
            except Exception:
                record_log.exception(
                    "hier endpoint %s raised; treating as failure",
                    member.endpoint,
                )
                result = None
            if result is None:
                member.health.record_failure()
                if _clock.now_ms() >= deadline:
                    break
                continue
            member.health.record_success()
            if int(result.status) == int(TokenStatus.STANDBY):
                ha_metrics().count_fallback("standby_redirect")
                if _clock.now_ms() >= deadline:
                    break
                continue
            if int(result.status) == int(TokenStatus.NOT_LEASABLE):
                if refusal is None:
                    refusal = result
                if _clock.now_ms() >= deadline:
                    break
                continue
            self._note_served(i)
            return result
        return refusal

    def share_op(self, msg_type, flow_id, want=0, share_id=0, used=0):
        """Walk endpoints for a SHARE_* op; returns ``P.LeaseResponse``
        or None when nothing answered."""
        return self._hier_call(
            lambda m: m.client.share_op(
                msg_type, flow_id, want, share_id=share_id, used=used
            )
        )

    def demand_report(self, pod_id, entries):
        """Walk endpoints for a DEMAND_REPORT; returns the ack
        ``P.LeaseResponse`` or None."""
        return self._hier_call(
            lambda m: m.client.demand_report(pod_id, entries)
        )

    def request_batch_arrays(self, flow_ids, acquires=None, prios=None,
                             timeout_ms: Optional[int] = None):
        def op(member):
            return member.client.request_batch_arrays(
                flow_ids, acquires, prios, timeout_ms=timeout_ms
            )

        result = self._call(op, failed=lambda r: r is None)
        if result is not None:
            # degraded verdicts inside an otherwise-delivered batch (FAIL
            # statuses) stay as-is: the server answered, per-row FAIL means
            # the server's own step degraded, not the transport
            return result
        return self.fallback.decide_batch_arrays(flow_ids, acquires, prios)

    def request_batch(self, requests):
        if not requests:
            return []
        n = len(requests)
        status, remaining, wait = self.request_batch_arrays(
            np.fromiter((f for f, _, _ in requests), np.int64, n),
            np.fromiter((a for _, a, _ in requests), np.int32, n),
            np.fromiter((p for _, _, p in requests), bool, n),
        )
        return [
            TokenResult(TokenStatus(int(status[i])), int(remaining[i]),
                        int(wait[i]))
            for i in range(n)
        ]

    def ping(self, namespace: Optional[str] = None) -> bool:
        """True when some endpoint's server answers the ping affirmatively.

        Only transport-level failure — no reply at all, or a raised
        exception — charges an endpoint's breaker. A live server that
        answers the ping negatively (e.g. an unknown namespace) is
        reachable, and repeated health pings must not evict it from
        rotation; its answer closes the breaker and is returned as-is."""
        answered_no = False
        deadline = _clock.now_ms() + self.deadline_ms
        for i in self._walk_order():
            member = self._members[i]
            if not member.health.allows_request():
                continue
            try:
                ping_ex = getattr(member.client, "ping_ex", None)
                if ping_ex is not None:
                    # None = transport failure, bool = the server's answer
                    reply = ping_ex(namespace)
                else:
                    # bool-only ping (TokenClient-compatible stubs): False
                    # means no response arrived at all
                    reply = True if member.client.ping(namespace) else None
            except Exception:
                record_log.exception(
                    "token endpoint %s raised on ping; treating as failure",
                    member.endpoint,
                )
                reply = None
            if reply is None:
                member.health.record_failure()
                if _clock.now_ms() >= deadline:
                    break
                continue
            member.health.record_success()  # reachable: the probe is answered
            if reply:
                self._note_served(i)
                return True
            answered_no = True
        if not answered_no:
            self._note_exhausted()
        return False

    # -- lifecycle / introspection ------------------------------------------
    def close(self) -> None:
        for member in self._members:
            try:
                member.client.close()
            except Exception:
                pass

    @property
    def active_endpoint(self) -> Endpoint:
        with self._lock:
            return self._members[self._active].endpoint

    def lease_stats(self) -> dict:
        """Merged lease counters across member clients (zeros when the
        members don't lease)."""
        merged: dict = {}
        for member in self._members:
            stats_fn = getattr(member.client, "lease_stats", None)
            if stats_fn is None:
                continue
            try:
                for key, value in stats_fn().items():
                    merged[key] = merged.get(key, 0) + int(value)
            except Exception:
                continue
        return merged

    def health_snapshot(self) -> List[dict]:
        out = []
        with self._lock:
            active = self._active
        now = _clock.now_ms()
        for i, member in enumerate(self._members):
            entry = {"endpoint": str(member.endpoint), "active": i == active}
            entry["brownoutMs"] = max(0, int(self._brownout_until[i] - now))
            entry.update(member.health.snapshot())
            consecutive = getattr(
                member.client, "consecutive_failures", None
            )
            if consecutive is not None:
                entry["connectFailures"] = int(consecutive)
            out.append(entry)
        return out
