"""Static engine geometry. Everything here is baked into the jit trace;
changing it forces a recompile (rule *contents* are dynamic, sizes are not).
"""

from __future__ import annotations

from typing import NamedTuple


class EngineConfig(NamedTuple):
    """Sizes for the device tensors.

    Defaults mirror the reference cluster server: 1s interval / 10 buckets
    (``ServerFlowConfig.java:29-30``), 30k default namespace guard
    (``ServerFlowConfig.java:31``).
    """

    max_flows: int = 4096  # rule slots (F)
    max_namespaces: int = 64  # NS
    batch_size: int = 1024  # N — requests per device step
    bucket_ms: int = 100
    n_buckets: int = 10
    max_occupy_ratio: float = 1.0  # ServerFlowConfig.maxOccupyRatio
    exceed_count: float = 1.0  # ServerFlowConfig.exceedCount
    # in-batch prefix refinement passes — MUST be odd (odd counts guarantee
    # the admission mask is a subset of the sequential-greedy set; decide()
    # rejects even values)
    admission_refine_iters: int = 3
    # segment-prefix implementation for the flow axis: "matmul" ([N,N]
    # masked matmuls — cheap on the MXU for small N), "sort" (one argsort
    # per batch + blocked-matmul cumsums, wins beyond ~2k), or "auto"
    # (matmul ≤ 2048, sort above). Grouped host batches bypass this and use
    # the sort-free "grouped" impl (see decide()'s grouped flag).
    prefix_impl: str = "auto"
    # decision-step backend: "xla" (the `_decide_core` pipeline — one XLA
    # pass per subsystem), "pallas" (the one-HBM-traversal megakernel in
    # ops/decide_pallas.py: window reads, roll, admission math and the
    # event scatters fused into a single kernel over the flow plane), or
    # "auto" (SENTINEL_DECIDE_IMPL env var wins; off-TPU picks "xla"
    # outright — interpret-mode pallas is orders of magnitude slower; on
    # TPU both are micro-probed once per process and the faster wins).
    # The pallas step requires grouped batches; non-grouped callers fall
    # back to "xla" regardless of this setting.
    decide_impl: str = "auto"

    @property
    def interval_ms(self) -> int:
        return self.bucket_ms * self.n_buckets
