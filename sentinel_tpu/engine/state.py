"""Engine state: all mutable counters as one pytree of device arrays.

The analog of the reference's ``ClusterMetricStatistics`` registry of
per-flowId ``ClusterMetric`` LeapArrays (``metric/ClusterMetric.java:28-79``)
— flattened into ``[max_flows, n_buckets, events]`` tensors plus a
``[max_namespaces, n_buckets, 1]`` tensor for the namespace guard
(``GlobalRequestLimiter``).
"""

from __future__ import annotations

import enum
from typing import NamedTuple

import jax
import jax.numpy as jnp

from sentinel_tpu.engine.config import EngineConfig
from sentinel_tpu.stats.window import NEVER, WindowSpec, WindowState, make_window


class ClusterEvent(enum.IntEnum):
    """``ClusterFlowEvent`` (``ClusterMetricBucket``): PASS counts tokens,
    PASS_REQUEST counts RPCs (a request may acquire N tokens).

    ``LEASED`` (wire rev 5, no reference analog) counts tokens delegated to
    clients as short-TTL local-admission leases. A grant charges the full
    slice into the current bucket at grant time — the delegated tokens are
    *pre-paid*, so client-local admissions never touch the server and the
    device admission read (PASS + LEASED + matured borrows vs threshold)
    keeps the global limit without seeing them individually. Unused tokens
    are credited back (a negative fold) on renew/return when the charge
    bucket is provably still inside the live window; otherwise they simply
    expire with the window — the conservative direction. Because LEASED is
    an ordinary event column it rides psum'd mesh limits, snapshots,
    replication deltas, and MOVE window-sum handoffs unchanged."""

    PASS = 0
    PASS_REQUEST = 1
    BLOCK = 2
    BLOCK_REQUEST = 3
    OCCUPIED_PASS = 4
    LEASED = 5


N_CLUSTER_EVENTS = len(ClusterEvent)


class OutcomeChannel(enum.IntEnum):
    """Completion-outcome channels of the per-flow outcome window.

    The reference's ``MetricBucket`` records four event classes per bucket
    (pass/block/success/RT + exception, ``MetricBucket.java``); the admission
    half lives in :class:`ClusterEvent`, and these columns are the completion
    half, fed by the batched OUTCOME_REPORT wire op. ``RT_SUM`` accumulates
    milliseconds (int32 — reports are clamp-validated at the wire boundary so
    a bucket cannot overflow), ``COMPLETE`` / ``EXCEPTION`` count completions.
    Channels ``RT_HIST0 .. RT_HIST0 + N_RT_BUCKETS - 1`` are a coarse
    log2-bucketed RT histogram (SALSA-style compact cells, arXiv:2102.12531):
    a completion with RT ``r`` ms lands in bucket
    ``clip(floor(log2(r + 1)), 0, N_RT_BUCKETS - 1)``, so bucket ``j`` spans
    ``[2^j - 1, 2^(j+1) - 1)`` ms and the last bucket absorbs the tail. That
    is enough resolution for a device-side p99 read without per-flow sketch
    state."""

    RT_SUM = 0
    COMPLETE = 1
    EXCEPTION = 2
    # completions whose RT exceeded the flow's DegradeRule slow_rt_ms —
    # the SLOW_REQUEST_RATIO breaker numerator. Counted exactly at report
    # time (the per-flow cutoff is a rule column), not reconstructed from
    # the coarse log2 histogram, so the breaker ratio matches the
    # reference's per-request `rt > maxAllowedRt` test bit-for-bit.
    SLOW = 3
    RT_HIST0 = 4


# log2 RT histogram cells; bucket 11 spans [2047, inf) ms. Upper edges are
# 2^(j+1) - 1 ms (see OutcomeChannel docstring).
N_RT_BUCKETS = 12
N_OUTCOME_CHANNELS = int(OutcomeChannel.RT_HIST0) + N_RT_BUCKETS

# Upper edge (ms, inclusive-exclusive) of each RT histogram bucket; the last
# bucket is open-ended. Host-side p99 reads walk this table.
RT_BUCKET_UPPER_MS = tuple(
    (1 << (j + 1)) - 1 for j in range(N_RT_BUCKETS - 1)
) + (float("inf"),)


class ShapingState(NamedTuple):
    """Per-flow traffic-shaper clocks (the mutable halves of the reference's
    ``RateLimiterController.latestPassedTime`` and ``WarmUpController``'s
    ``storedTokens``/``lastFilledTime`` atomics, flattened to ``[max_flows]``
    columns). ``NEVER`` marks a slot whose shaper has not run yet: pacing
    starts unconstrained, warmup's first lazy sync sees a huge idle gap and
    fills the bucket to ``max_token`` — the cold state."""

    lpt: jax.Array  # int32 [F] — latest passed time (pacing), engine ms
    warm_tokens: jax.Array  # float32 [F] — warmup stored tokens
    warm_filled: jax.Array  # int32 [F] — last warmup sync second, engine ms


# circuit-breaker states (AbstractCircuitBreaker.State); plain ints so the
# kernel compares i8 columns without enum machinery
BR_CLOSED = 0
BR_OPEN = 1
BR_HALF_OPEN = 2


class BreakerState(NamedTuple):
    """Per-flow circuit-breaker columns (``AbstractCircuitBreaker``'s
    ``currentState`` + ``nextRetryTimestamp`` atomics, flattened to
    ``[max_flows]`` device columns so transitions run batch-vectorized
    inside the decide kernel).

    ``opened_ms`` doubles as the stats fence: every transition stamps it
    ``now``, and the breaker evaluation only reads outcome buckets whose
    start is >= ``max(now - stat_interval, opened_ms)`` — the device analog
    of the reference's ``resetStat()`` on close, without destroying the
    shared telemetry window. ``probe_ms`` is the HALF_OPEN probe ticket:
    the engine clock at which the current probe was elected (``NEVER``
    when no probe is in flight); a probe whose completion report never
    arrives re-arms after ``recovery_timeout_ms``."""

    state: jax.Array  # int8 [F] — BR_CLOSED / BR_OPEN / BR_HALF_OPEN
    opened_ms: jax.Array  # int32 [F] — last transition clock (stats fence)
    probe_ms: jax.Array  # int32 [F] — HALF_OPEN probe election clock


class EngineState(NamedTuple):
    flow: WindowState  # [F, B, E] current windows
    occupy: WindowState  # [F, B, 1] future (borrowed) windows
    ns: WindowState  # [NS, B, 1] namespace request qps guard
    shaping: ShapingState  # [F] per-flow shaper clocks
    outcome: WindowState  # [F, B, N_OUTCOME_CHANNELS] completion outcomes
    breaker: BreakerState  # [F] per-flow circuit-breaker columns


def flow_spec(config: EngineConfig) -> WindowSpec:
    return WindowSpec(bucket_ms=config.bucket_ms, n_buckets=config.n_buckets)


def make_shaping(n_flows: int) -> ShapingState:
    return ShapingState(
        lpt=jnp.full((n_flows,), NEVER, dtype=jnp.int32),
        warm_tokens=jnp.zeros((n_flows,), dtype=jnp.float32),
        warm_filled=jnp.full((n_flows,), NEVER, dtype=jnp.int32),
    )


def make_breaker(n_flows: int) -> BreakerState:
    return BreakerState(
        state=jnp.zeros((n_flows,), dtype=jnp.int8),  # BR_CLOSED
        opened_ms=jnp.full((n_flows,), NEVER, dtype=jnp.int32),
        probe_ms=jnp.full((n_flows,), NEVER, dtype=jnp.int32),
    )


def make_state(config: EngineConfig) -> EngineState:
    spec = flow_spec(config)
    return EngineState(
        flow=make_window(spec, config.max_flows, N_CLUSTER_EVENTS),
        occupy=make_window(spec, config.max_flows, 1),
        ns=make_window(spec, config.max_namespaces, 1),
        shaping=make_shaping(config.max_flows),
        outcome=make_window(spec, config.max_flows, N_OUTCOME_CHANNELS),
        breaker=make_breaker(config.max_flows),
    )
