"""Device-resident circuit breaking: the breaker gate shared by both
decide backends.

The reference's ``DegradeSlot`` (``AbstractCircuitBreaker`` +
``ResponseTimeCircuitBreaker`` / ``ExceptionCircuitBreaker``) keeps one
CLOSED/OPEN/HALF_OPEN state machine per resource, fed by completion stats.
Here the whole machine is three ``[max_flows]`` state columns
(:class:`~sentinel_tpu.engine.state.BreakerState`) plus six rule columns
(``RuleTable.br_*``), and every transition is computed batch-vectorized
inside the decide step from the PR-16 outcome window — outcomes in,
breaker verdicts out, zero host round-trips.

Semantics, mapped to the reference:

- **CLOSED → OPEN** (``tryPass`` + the strategy's ``onRequestComplete``
  threshold test, evaluated lazily at decide time): over the fenced stat
  window, ``metric > threshold`` with ``total >= min_request_amount``,
  where metric is slow-ratio / error-ratio / error-count by strategy.
  Strict ``>`` like the reference.
- **OPEN → HALF_OPEN** (``retryTimeoutArrived`` + ``fromOpenToHalfOpen``):
  after ``recovery_timeout_ms``, the first in-range request of the flow in
  batch order wins the probe ticket (same-flow prefix rank 0 — batch-safe
  under fusion and shard_map, because the election happens in the one
  place that sees the whole batch in order) and proceeds through normal
  admission; every other row keeps answering DEGRADED.
- **HALF_OPEN → CLOSED / OPEN** (``fromHalfOpenToClose`` / the error
  rollback): decided by the probe's completion report inside the outcome
  step (:mod:`sentinel_tpu.engine.outcome`), not here — the decide path
  only re-arms a probe whose report never came (client died mid-probe)
  after another ``recovery_timeout_ms``.

The stats fence: ``opened_ms`` is stamped ``now`` on every transition and
the evaluation only reads outcome buckets whose start is at or after
``max(now - stat_interval_ms, opened_ms)`` — the device analog of the
reference's ``resetStat()`` on close, at bucket granularity, without
destroying the shared telemetry window.

The no-breaker cost is tiered. A table built with no degrade rules at
all carries ``None`` br_* columns — a structurally different jit pytree,
so that compile never traces the breaker arm and pays exactly zero. A
table WITH breakers gates everything behind one mesh-uniform ``lax.cond``
(any breaker row in this batch, psum-stitched OUTSIDE the cond), so
batches that touch no guarded flow pay one [N] gather + one psum and
nothing else — the ≤2% serve-path overhead contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sentinel_tpu.engine.config import EngineConfig
from sentinel_tpu.engine.rules import DegradeStrategy, RuleTable
from sentinel_tpu.engine.state import (
    BR_CLOSED,
    BR_HALF_OPEN,
    BR_OPEN,
    BreakerState,
    EngineState,
    OutcomeChannel,
)
from sentinel_tpu.stats.window import NEVER


def breaker_gate(
    config: EngineConfig,
    spec,
    state: EngineState,
    rules: RuleTable,
    now: jax.Array,  # int32 scalar
    safe_slot: jax.Array,  # int32 [N] clamped local slots
    active: jax.Array,  # bool [N] — ns-admitted owned rows
    flow_prefix,  # same-flow exclusive prefix closure over batch order
    psum,  # mesh reduction (identity single-shard)
) -> tuple:
    """Evaluate breaker transitions for one batch; returns
    ``(degraded, retry_ms, breaker')``.

    ``degraded`` rows must be stripped from ``active`` before admission
    (they write NO flow-window events, like namespace-guard refusals) and
    answer ``TokenStatus.DEGRADED`` with ``retry_ms`` in ``remaining``.
    All three outputs are local to the owner shard; the verdict psum
    stitches them exactly like the other owner-emitted statuses.
    """
    n = safe_slot.shape[0]
    if rules.br_strategy is None:
        # no degrade rules in this table: the None columns are part of the
        # jit pytree structure, so this compile carries no breaker arm at
        # all — the ≤2% overhead contract costs literally zero here
        return (
            jnp.zeros((n,), bool),
            jnp.zeros((n,), jnp.int32),
            state.breaker,
        )
    f_local = rules.valid.shape[0]
    strat = rules.br_strategy[safe_slot].astype(jnp.int32)
    br_rows = active & (strat >= 0)
    # mesh-uniform predicate: the psum lives OUTSIDE the cond
    any_br = jnp.any(psum(br_rows.astype(jnp.int32)) > 0)

    def gate_off(_):
        return (
            jnp.zeros((n,), bool),
            jnp.zeros((n,), jnp.int32),
            state.breaker,
        )

    def gate_on(_):
        br = state.breaker
        st = br.state[safe_slot].astype(jnp.int32)
        opened = br.opened_ms[safe_slot]
        probe = br.probe_ms[safe_slot]
        thr = rules.br_threshold[safe_slot]
        minreq = rules.br_min_request[safe_slot]
        stat_ms = rules.br_stat_ms[safe_slot]
        rec_ms = rules.br_recovery_ms[safe_slot]

        # fenced stat window: buckets alive in the sliding window AND not
        # older than the stat interval or the last transition (opened_ms
        # doubles as the resetStat() fence; NEVER fences nothing)
        lo = jnp.maximum(now - stat_ms, opened)  # [N]
        starts = state.outcome.starts  # [B]
        age = now - starts
        bvalid = (age >= 0) & (age < spec.interval_ms)  # [B]
        inc = (bvalid[None, :] & (starts[None, :] >= lo[:, None])).astype(
            jnp.float32
        )  # [N, B]
        counts = state.outcome.counts[safe_slot]  # [N, B, C]
        total_i = jnp.sum(
            counts[:, :, int(OutcomeChannel.COMPLETE)]
            * inc.astype(counts.dtype),
            axis=1,
        )
        errs = jnp.sum(
            counts[:, :, int(OutcomeChannel.EXCEPTION)]
            * inc.astype(counts.dtype),
            axis=1,
        ).astype(jnp.float32)
        slows = jnp.sum(
            counts[:, :, int(OutcomeChannel.SLOW)]
            * inc.astype(counts.dtype),
            axis=1,
        ).astype(jnp.float32)
        denom = jnp.maximum(total_i.astype(jnp.float32), 1.0)
        metric = jnp.where(
            strat == int(DegradeStrategy.SLOW_REQUEST_RATIO),
            slows / denom,
            jnp.where(
                strat == int(DegradeStrategy.ERROR_RATIO),
                errs / denom,
                errs,
            ),
        )
        # strict > like the reference; gated on minRequestAmount
        crossing = (total_i >= minreq) & (metric > thr)

        is_closed = st == BR_CLOSED
        is_open = st == BR_OPEN
        is_half = st == BR_HALF_OPEN
        just_open = br_rows & is_closed & crossing
        open_elapsed = is_open & (now - opened >= rec_ms)
        probe_stale = is_half & (now - probe >= rec_ms)
        electable = br_rows & (open_elapsed | probe_stale)
        # HALF_OPEN probe election: first electable row of the flow in
        # batch order wins the ticket and proceeds through admission
        rank = flow_prefix(electable.astype(jnp.float32))
        is_probe = electable & (rank == 0.0)

        degraded = br_rows & (
            just_open
            | (is_open & ~open_elapsed)
            | (is_half & ~probe_stale)
            | (electable & ~is_probe)
        )
        retry = jnp.where(
            just_open | (electable & ~is_probe),
            rec_ms,
            jnp.where(
                is_open & ~open_elapsed,
                opened + rec_ms - now,
                probe + rec_ms - now,  # HALF_OPEN with a live probe
            ),
        )
        retry_ms = jnp.where(
            degraded, jnp.maximum(retry, 0), 0
        ).astype(jnp.int32)

        # transition scatters: values are flow-uniform (pure functions of
        # per-flow state + now), so duplicate same-flow rows write
        # identical values and .set stays deterministic; non-transition
        # rows route to row F which mode="drop" discards
        scat_open = jnp.where(just_open, safe_slot, f_local)
        scat_half = jnp.where(electable, safe_slot, f_local)
        br_state = (
            br.state.at[scat_open].set(jnp.int8(BR_OPEN), mode="drop")
            .at[scat_half].set(jnp.int8(BR_HALF_OPEN), mode="drop")
        )
        br_opened = br.opened_ms.at[scat_open].set(now, mode="drop")
        br_probe = (
            br.probe_ms.at[scat_open].set(jnp.int32(NEVER), mode="drop")
            .at[scat_half].set(now, mode="drop")
        )
        return degraded, retry_ms, BreakerState(
            state=br_state, opened_ms=br_opened, probe_ms=br_probe
        )

    return jax.lax.cond(any_br, gate_on, gate_off, None)
