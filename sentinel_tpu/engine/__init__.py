"""Batched decision engine: the TPU data plane.

This is where the reference's per-request JVM hot path
(``DefaultTokenService.requestToken`` → ``ClusterFlowChecker.acquireClusterToken``,
``ClusterFlowChecker.java:36-120``) becomes one jitted pure function over
micro-batches::

    decide(state, rules, batch, now) -> (state', verdicts)

Counters live in device-resident ``[flows, buckets, events]`` tensors; rules
are padded tensor tables (reloadable without retrace); admission inside a
batch uses masked prefix sums so a batch can never collectively overshoot a
threshold — strictly stronger than the reference's cross-thread TOCTOU.
"""

from sentinel_tpu.engine.config import EngineConfig
from sentinel_tpu.engine.rules import (
    RuleTable,
    ClusterFlowRule,
    DegradeRule,
    DegradeStrategy,
    build_rule_table,
    drain_pending_clear,
)
from sentinel_tpu.engine.state import EngineState, make_state
from sentinel_tpu.engine.decide import (
    RequestBatch,
    VerdictBatch,
    TokenStatus,
    alloc_fused_batch,
    decide,
    make_batch,
    make_batch_into,
)

__all__ = [
    "alloc_fused_batch",
    "make_batch_into",
    "EngineConfig",
    "RuleTable",
    "ClusterFlowRule",
    "DegradeRule",
    "DegradeStrategy",
    "build_rule_table",
    "drain_pending_clear",
    "EngineState",
    "make_state",
    "RequestBatch",
    "VerdictBatch",
    "TokenStatus",
    "decide",
    "make_batch",
]
