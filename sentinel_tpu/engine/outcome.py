"""Fused completion-outcome scatter: the device half of the outcome plane.

Clients report ``(flow, rt_ms, exception)`` completions in batches (the
OUTCOME_REPORT wire op, piggy-backed on request frames); the token service
funnels every decoded batch through the donated step built here. One step
performs a single window roll plus four scatter-adds into the per-flow
``state.outcome`` window ([F, B, N_OUTCOME_CHANNELS]):

- ``RT_SUM``     += rt_ms          (windowed RT accumulator, "Give Me Some
                                    Slack"-style sliding measurement)
- ``COMPLETE``   += 1
- ``EXCEPTION``  += exception
- ``RT_HIST0+b`` += 1 where ``b = clip(floor(log2(rt+1)), 0, NB-1)`` — the
  SALSA-style coarse log2 histogram cell for device-side p99.

The step is deliberately DECOUPLED from the admission kernel: completions
arrive on their own cadence (whenever a client's next frame carries a
piggy-backed report), and fusing them into ``decide`` would put a
data-dependent extra scatter on the serve path's critical step. Instead the
outcome step donates the full EngineState exactly like ``decide_donating`` —
the admission windows alias straight through, only ``outcome`` is rewritten —
so the serve path pays nothing while reporting is idle and the outcome path
reuses the same buffer-donation discipline.

Rows are pre-validated on the host (see ``TokenService.report_outcomes``:
negative / non-finite / oversized RTs are dropped and counted before they
reach the device); the kernel additionally masks ``valid=False`` rows by
routing them to an out-of-range resource id, which ``mode="drop"`` scatters
discard — padding rows cost nothing and can never poison a live slot.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from sentinel_tpu.engine.config import EngineConfig
from sentinel_tpu.engine.prefix import segment_prefix_builder
from sentinel_tpu.engine.rules import DegradeStrategy
from sentinel_tpu.engine.state import (
    BR_CLOSED,
    BR_HALF_OPEN,
    BR_OPEN,
    BreakerState,
    EngineState,
    N_RT_BUCKETS,
    OutcomeChannel,
    flow_spec,
)
from sentinel_tpu.stats import window as W
from sentinel_tpu.stats.window import NEVER


def rt_bucket(rt_ms: jax.Array) -> jax.Array:
    """Log2 histogram cell for an RT in ms: ``clip(floor(log2(rt+1)), 0,
    NB-1)`` — computed with integer bit-length semantics (no float log), so
    the device and the scalar reference in tests agree bit-exactly."""
    r = jnp.maximum(jnp.asarray(rt_ms, jnp.int32), 0) + 1  # >= 1
    # floor(log2(r)) == (bit length of r) - 1; 31 - clz(r) without a clz
    # primitive: compare against the 31 powers of two reachable by int32.
    powers = jnp.asarray([1 << k for k in range(1, 31)], jnp.int32)
    blog = jnp.sum(r[:, None] >= powers[None, :], axis=1).astype(jnp.int32)
    return jnp.clip(blog, 0, N_RT_BUCKETS - 1)


def _resolve_probes(
    br: BreakerState,
    br_strategy: jax.Array,  # int8 [F] rule columns
    br_slow_rt_ms: jax.Array,  # int32 [F]
    gslot: jax.Array,  # int32 [K] clamped in-range slots
    in_rng: jax.Array,  # bool [K] valid & slot in range
    rt_ms: jax.Array,
    exc: jax.Array,
    now: jax.Array,
) -> BreakerState:
    """HALF_OPEN probe resolution — ``fromHalfOpenToClose`` / the error
    rollback: the FIRST report of each flow whose breaker sits HALF_OPEN
    with a live probe ticket decides the flow's fate. Success (fast for
    SLOW_REQUEST_RATIO, non-exception otherwise) → CLOSED with
    ``opened_ms = now`` (the stats fence excludes pre-recovery buckets,
    the device resetStat()); failure → straight back to OPEN with a fresh
    recovery clock. Any in-flight completion for the flow can resolve the
    probe, like the reference's ``onRequestComplete`` — the probe request
    is merely the only one the breaker ADMITTED."""
    f = br.state.shape[0]
    st = br.state[gslot].astype(jnp.int32)
    probe = br.probe_ms[gslot]
    live = in_rng & (st == BR_HALF_OPEN) & (probe != NEVER)

    def off(_):
        return br

    def on(_):
        # first live report per flow in batch order wins the resolution
        rank = segment_prefix_builder(gslot, "auto")(
            live.astype(jnp.float32)
        )
        elected = live & (rank == 0.0)
        strat = br_strategy[gslot].astype(jnp.int32)
        fail = jnp.where(
            strat == int(DegradeStrategy.SLOW_REQUEST_RATIO),
            jnp.asarray(rt_ms, jnp.int32) > br_slow_rt_ms[gslot],
            jnp.asarray(exc, jnp.int32) > 0,
        )
        new_st = jnp.where(fail, BR_OPEN, BR_CLOSED).astype(jnp.int8)
        scat = jnp.where(elected, gslot, f)
        return BreakerState(
            state=br.state.at[scat].set(new_st, mode="drop"),
            opened_ms=br.opened_ms.at[scat].set(now, mode="drop"),
            probe_ms=br.probe_ms.at[scat].set(jnp.int32(NEVER), mode="drop"),
        )

    return jax.lax.cond(jnp.any(live), on, off, None)


def _outcome_core(
    config: EngineConfig,
    state: EngineState,
    slots: jax.Array,  # int32 [K] rule-slot ids (out-of-range = dropped)
    rt_ms: jax.Array,  # int32 [K] clamped response times
    exc: jax.Array,  # int32 [K] 1 = exception, 0 = success
    valid: jax.Array,  # bool [K]
    now: jax.Array,  # int32 engine ms
    br_strategy=None,  # int8 [F] rule column, or None (no breakers loaded)
    br_slow_rt_ms=None,  # int32 [F] rule column, or None
) -> EngineState:
    spec = flow_spec(config)
    k = slots.shape[0]
    # invalid rows scatter to row F, which mode="drop" discards entirely
    safe_slot = jnp.where(valid, slots, jnp.int32(config.max_flows))
    ones = jnp.ones((k,), jnp.int32)
    row_cols = [
        jnp.asarray(rt_ms, jnp.int32),
        ones,
        jnp.asarray(exc, jnp.int32),
    ]
    channels = (
        int(OutcomeChannel.RT_SUM),
        int(OutcomeChannel.COMPLETE),
        int(OutcomeChannel.EXCEPTION),
    )
    if br_strategy is not None:
        # SLOW channel: counted exactly at report time against the flow's
        # DegradeRule cutoff (rules without a breaker carry NO_SLOW_RT_MS,
        # so their rows never count) — the SLOW_REQUEST_RATIO numerator
        gslot = jnp.where(valid, slots, 0).astype(jnp.int32)
        in_rng = valid & (slots >= 0) & (slots < br_strategy.shape[0])
        is_slow = (
            jnp.asarray(rt_ms, jnp.int32) > br_slow_rt_ms[gslot]
        ).astype(jnp.int32)
        row_cols.append(is_slow)
        channels = channels + (int(OutcomeChannel.SLOW),)
    rows = jnp.stack(row_cols, axis=1)
    ws = W.add_event_rows(
        spec, state.outcome, now, safe_slot, rows, channels=channels
    )
    # histogram cell: one extra scatter with a traced channel id (the roll
    # inside add_events is a no-op — the slot was refreshed just above)
    ws = W.add_events(
        spec, ws, now,
        resource_ids=safe_slot,
        channel_ids=int(OutcomeChannel.RT_HIST0) + rt_bucket(rt_ms),
        values=ones,
    )
    breaker = state.breaker
    if br_strategy is not None:
        breaker = _resolve_probes(
            state.breaker, br_strategy, br_slow_rt_ms, gslot, in_rng,
            rt_ms, exc, now,
        )
    return state._replace(outcome=ws, breaker=breaker)


def outcome_step_donating(config: EngineConfig):
    """Build the jitted donated step ``(state, slots, rt, exc, valid, now)
    -> state'``. The full EngineState is donated (the admission windows
    alias through untouched), mirroring ``decide_donating``'s contract:
    the caller's lock must make the passed state the only live reference.

    When breakers are loaded the caller additionally passes the
    ``br_strategy``/``br_slow_rt_ms`` rule columns, which turns on the
    SLOW-channel scatter and HALF_OPEN probe resolution (a separate jit
    trace; the 6-arg form stays bit-identical to the pre-breaker step)."""
    return jax.jit(partial(_outcome_core, config), donate_argnums=(0,))
