"""Rules as padded device tensors.

The reference token server holds ``flowId → FlowRule`` maps
(``ClusterFlowRuleManager.java:46-235``); reloading rules must not retrace the
jitted step, so the device sees only fixed-shape arrays. The host keeps the
``flow_id → slot`` assignment (slots are stable across reloads for unchanged
rules, so sliding-window history survives a rule update — the reference gets
this by keeping ``ClusterMetric`` objects keyed by flowId).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.engine.config import EngineConfig


class ThresholdMode(enum.IntEnum):
    # ClusterFlowConfig.thresholdType (ClusterRuleConstant): AVG_LOCAL
    # multiplies the per-client count by the connected-client count
    # (ClusterFlowChecker.java:43-47); GLOBAL uses count as-is.
    AVG_LOCAL = 0
    GLOBAL = 1


class ControlBehavior(enum.IntEnum):
    # RuleConstant.CONTROL_BEHAVIOR_*: which TrafficShapingController serves
    # the rule. DEFAULT rejects on threshold; WARM_UP admits along the
    # stored-token slope curve (WarmUpController); RATE_LIMITER paces
    # admissions and answers waitInMs (RateLimiterController); the combined
    # mode paces at the warmup curve's current rate.
    DEFAULT = 0
    WARM_UP = 1
    RATE_LIMITER = 2
    WARM_UP_RATE_LIMITER = 3


@dataclass(frozen=True)
class ClusterFlowRule:
    """Host-side cluster rule (``FlowRule`` + ``ClusterFlowConfig`` subset).

    ``mode`` defaults to AVG_LOCAL like the reference's
    ``ClusterFlowConfig.thresholdType`` — a rule set ported from Sentinel with
    the field omitted keeps its count × connected-clients semantics.

    The shaping fields mirror ``FlowRule``'s traffic-shaping knobs and only
    matter when ``control_behavior`` is non-DEFAULT; defaults match the
    reference (``RuleConstant``: 10s warmup, cold factor 3, 500ms max queue).
    """

    flow_id: int
    count: float
    mode: ThresholdMode = ThresholdMode.AVG_LOCAL
    namespace: str = "default"
    control_behavior: int = 0
    warm_up_period_sec: int = 10
    cold_factor: int = 3
    max_queueing_time_ms: int = 500


class RuleTable(NamedTuple):
    """Device tensors, all shaped ``[max_flows]`` (+ ``[max_namespaces]``).

    The shaping columns are precomputed host-side from the rule's warmup
    knobs (the reference computes them once in ``WarmUpController``'s
    constructor, ``WarmUpController.java:94-117``) so the kernel's per-row
    work is pure gathers + elementwise math. Rows with ``behavior == 0``
    carry zeros — the ``jnp.where`` branch selection never reads them.
    """

    valid: jax.Array  # bool — slot holds an active rule
    count: jax.Array  # float32 — rule threshold (per-client for AVG_LOCAL)
    mode: jax.Array  # int8 — ThresholdMode
    namespace_id: jax.Array  # int32
    ns_max_qps: jax.Array  # float32 [NS] — GlobalRequestLimiter threshold
    ns_connected: jax.Array  # int32 [NS] — connected client count (AVG_LOCAL)
    behavior: jax.Array  # int8 — ControlBehavior
    warning_token: jax.Array  # float32 — warmup warning line (stored tokens)
    max_token: jax.Array  # float32 — warmup bucket capacity
    slope: jax.Array  # float32 — warmup admission slope above the line
    cold_count: jax.Array  # float32 — floor(count / cold_factor) refill gate
    max_queue_ms: jax.Array  # int32 — pacing queue bound (ring-clamped)


class RuleIndex:
    """Host-side flow_id → slot assignment (stable across reloads)."""

    def __init__(self, config: EngineConfig):
        self.config = config
        self._lock = threading.RLock()
        self.slot_of: Dict[int, int] = {}
        self.ns_of: Dict[str, int] = {}
        self._free = list(range(config.max_flows - 1, -1, -1))
        # Slots freed by a reload still hold the removed flow's window history
        # (and possibly pending future borrows); they MUST be zeroed in the
        # engine state before reuse — callers drain this via
        # ``drain_pending_clear(index, state)`` after every build.
        self.pending_clear: List[int] = []

    def namespace_slot(self, namespace: str) -> int:
        with self._lock:
            ns = self.ns_of.get(namespace)
            if ns is None:
                if len(self.ns_of) >= self.config.max_namespaces:
                    raise ValueError("namespace capacity exceeded")
                ns = self.ns_of[namespace] = len(self.ns_of)
            return ns

    def assign(self, flow_id: int) -> int:
        with self._lock:
            slot = self.slot_of.get(flow_id)
            if slot is None:
                if not self._free:
                    raise ValueError("flow rule capacity exceeded")
                slot = self._free.pop()
                self.slot_of[flow_id] = slot
            return slot

    def release_missing(self, live_flow_ids) -> List[int]:
        """Free slots whose flow_id is no longer present; returns freed slots."""
        live = set(live_flow_ids)
        freed = []
        with self._lock:
            for fid in list(self.slot_of):
                if fid not in live:
                    slot = self.slot_of.pop(fid)
                    self._free.append(slot)
                    freed.append(slot)
            self.pending_clear.extend(freed)
        return freed

    def lookup(self, flow_id: int) -> int:
        """Slot for a flow_id, or -1 (→ NO_RULE verdict)."""
        return self.slot_of.get(flow_id, -1)


def build_rule_table(
    config: EngineConfig,
    rules: List[ClusterFlowRule],
    index: Optional[RuleIndex] = None,
    ns_max_qps: float = 30_000.0,
    connected: Optional[Dict[str, int]] = None,
) -> tuple:
    """Build/refresh the device rule table. Returns ``(table, index)``.

    ``ns_max_qps`` defaults to the reference's namespace self-protection cap
    (``ServerFlowConfig.java:31``).

    After a rebuild, call ``drain_pending_clear(index, state)`` so slots freed
    by removed rules are zeroed before a new flow_id reuses them — otherwise
    the new flow inherits the removed flow's live window history.
    """
    index = index or RuleIndex(config)
    index.release_missing(r.flow_id for r in rules)

    valid = np.zeros(config.max_flows, dtype=bool)
    count = np.zeros(config.max_flows, dtype=np.float32)
    mode = np.zeros(config.max_flows, dtype=np.int8)
    namespace_id = np.zeros(config.max_flows, dtype=np.int32)
    ns_max = np.full(config.max_namespaces, float(ns_max_qps), dtype=np.float32)
    ns_conn = np.ones(config.max_namespaces, dtype=np.int32)
    behavior = np.zeros(config.max_flows, dtype=np.int8)
    warning_token = np.zeros(config.max_flows, dtype=np.float32)
    max_token = np.zeros(config.max_flows, dtype=np.float32)
    slope = np.zeros(config.max_flows, dtype=np.float32)
    cold_count = np.zeros(config.max_flows, dtype=np.float32)
    max_queue_ms = np.zeros(config.max_flows, dtype=np.int32)
    # add_future can park a borrow at most n_buckets-1 windows ahead, so a
    # pacing queue longer than that would assign waits the cross-batch
    # charge cannot cover — clamp at build time and let docs/SHAPING.md
    # carry the math
    queue_cap_ms = (config.n_buckets - 1) * config.bucket_ms
    for rule in rules:
        slot = index.assign(rule.flow_id)
        ns = index.namespace_slot(rule.namespace)
        valid[slot] = True
        count[slot] = rule.count
        mode[slot] = int(rule.mode)
        namespace_id[slot] = ns
        beh = int(rule.control_behavior)
        behavior[slot] = beh
        if beh in (int(ControlBehavior.WARM_UP),
                   int(ControlBehavior.WARM_UP_RATE_LIMITER)):
            # WarmUpController.construct(): warningToken, maxToken, slope
            c = max(float(rule.count), 1e-6)
            cold = max(2, int(rule.cold_factor))
            period = max(1, int(rule.warm_up_period_sec))
            warn = int(period * c / (cold - 1))
            warning_token[slot] = warn
            max_token[slot] = int(warn + 2.0 * period * c / (1.0 + cold))
            slope[slot] = (cold - 1.0) / c / max(1, max_token[slot] - warn)
            cold_count[slot] = int(c) // cold
        if beh in (int(ControlBehavior.RATE_LIMITER),
                   int(ControlBehavior.WARM_UP_RATE_LIMITER)):
            max_queue_ms[slot] = min(
                int(rule.max_queueing_time_ms), queue_cap_ms
            )
    for ns_name, n in (connected or {}).items():
        ns_conn[index.namespace_slot(ns_name)] = max(1, int(n))
    table = RuleTable(
        valid=jnp.asarray(valid),
        count=jnp.asarray(count),
        mode=jnp.asarray(mode),
        namespace_id=jnp.asarray(namespace_id),
        ns_max_qps=jnp.asarray(ns_max),
        ns_connected=jnp.asarray(ns_conn),
        behavior=jnp.asarray(behavior),
        warning_token=jnp.asarray(warning_token),
        max_token=jnp.asarray(max_token),
        slope=jnp.asarray(slope),
        cold_count=jnp.asarray(cold_count),
        max_queue_ms=jnp.asarray(max_queue_ms),
    )
    return table, index


def encode_rule(rule: ClusterFlowRule) -> dict:
    """The wire/blob dict shape shared by snapshots and MOVE blobs. Shaping
    keys are emitted only when non-default, so pre-shaping payloads stay
    byte-identical for plain rules (and old decoders keep working)."""
    d = {
        "flow_id": int(rule.flow_id),
        "count": float(rule.count),
        "mode": int(rule.mode),
        "namespace": rule.namespace,
    }
    if int(rule.control_behavior) != 0:
        d["behavior"] = int(rule.control_behavior)
        d["warmupSec"] = int(rule.warm_up_period_sec)
        d["coldFactor"] = int(rule.cold_factor)
        d["maxQueueMs"] = int(rule.max_queueing_time_ms)
    return d


def decode_rule(d: dict) -> ClusterFlowRule:
    """Inverse of :func:`encode_rule`; tolerant of payloads written before
    the shaping fields existed."""
    return ClusterFlowRule(
        flow_id=int(d["flow_id"]),
        count=float(d["count"]),
        mode=ThresholdMode(int(d["mode"])),
        namespace=str(d["namespace"]),
        control_behavior=int(d.get("behavior", 0)),
        warm_up_period_sec=int(d.get("warmupSec", 10)),
        cold_factor=int(d.get("coldFactor", 3)),
        max_queueing_time_ms=int(d.get("maxQueueMs", 500)),
    )


def drain_pending_clear(index: RuleIndex, state) -> "object":
    """Zero the window history of slots freed by rule reloads; returns the
    updated EngineState. Idempotent; call after every ``build_rule_table``."""
    with index._lock:
        slots, index.pending_clear = index.pending_clear, []
    if not slots:
        return state
    import jax.numpy as _jnp

    from sentinel_tpu.engine.state import EngineState
    from sentinel_tpu.stats.window import WindowState

    from sentinel_tpu.stats.window import NEVER

    idx = _jnp.asarray(np.asarray(slots, dtype=np.int32))
    flow_counts = state.flow.counts.at[idx].set(0)
    occupy_counts = state.occupy.counts.at[idx].set(0)
    shaping = state.shaping
    # a freed slot also holds the removed flow's shaper clock — a reused
    # slot must start cold (pacing unset, warmup bucket full on first sync)
    shaping = shaping._replace(
        lpt=shaping.lpt.at[idx].set(NEVER),
        warm_tokens=shaping.warm_tokens.at[idx].set(0.0),
        warm_filled=shaping.warm_filled.at[idx].set(NEVER),
    )
    # a reused slot must not inherit the removed flow's completion history
    outcome_counts = state.outcome.counts.at[idx].set(0)
    return EngineState(
        flow=WindowState(starts=state.flow.starts, counts=flow_counts),
        occupy=WindowState(starts=state.occupy.starts, counts=occupy_counts),
        ns=state.ns,
        shaping=shaping,
        outcome=WindowState(starts=state.outcome.starts, counts=outcome_counts),
    )
