"""Rules as padded device tensors.

The reference token server holds ``flowId → FlowRule`` maps
(``ClusterFlowRuleManager.java:46-235``); reloading rules must not retrace the
jitted step, so the device sees only fixed-shape arrays. The host keeps the
``flow_id → slot`` assignment (slots are stable across reloads for unchanged
rules, so sliding-window history survives a rule update — the reference gets
this by keeping ``ClusterMetric`` objects keyed by flowId).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.engine.config import EngineConfig


class ThresholdMode(enum.IntEnum):
    # ClusterFlowConfig.thresholdType (ClusterRuleConstant): AVG_LOCAL
    # multiplies the per-client count by the connected-client count
    # (ClusterFlowChecker.java:43-47); GLOBAL uses count as-is.
    AVG_LOCAL = 0
    GLOBAL = 1


@dataclass(frozen=True)
class ClusterFlowRule:
    """Host-side cluster rule (``FlowRule`` + ``ClusterFlowConfig`` subset).

    ``mode`` defaults to AVG_LOCAL like the reference's
    ``ClusterFlowConfig.thresholdType`` — a rule set ported from Sentinel with
    the field omitted keeps its count × connected-clients semantics.
    """

    flow_id: int
    count: float
    mode: ThresholdMode = ThresholdMode.AVG_LOCAL
    namespace: str = "default"


class RuleTable(NamedTuple):
    """Device tensors, all shaped ``[max_flows]`` (+ ``[max_namespaces]``)."""

    valid: jax.Array  # bool — slot holds an active rule
    count: jax.Array  # float32 — rule threshold (per-client for AVG_LOCAL)
    mode: jax.Array  # int8 — ThresholdMode
    namespace_id: jax.Array  # int32
    ns_max_qps: jax.Array  # float32 [NS] — GlobalRequestLimiter threshold
    ns_connected: jax.Array  # int32 [NS] — connected client count (AVG_LOCAL)


class RuleIndex:
    """Host-side flow_id → slot assignment (stable across reloads)."""

    def __init__(self, config: EngineConfig):
        self.config = config
        self._lock = threading.RLock()
        self.slot_of: Dict[int, int] = {}
        self.ns_of: Dict[str, int] = {}
        self._free = list(range(config.max_flows - 1, -1, -1))
        # Slots freed by a reload still hold the removed flow's window history
        # (and possibly pending future borrows); they MUST be zeroed in the
        # engine state before reuse — callers drain this via
        # ``drain_pending_clear(index, state)`` after every build.
        self.pending_clear: List[int] = []

    def namespace_slot(self, namespace: str) -> int:
        with self._lock:
            ns = self.ns_of.get(namespace)
            if ns is None:
                if len(self.ns_of) >= self.config.max_namespaces:
                    raise ValueError("namespace capacity exceeded")
                ns = self.ns_of[namespace] = len(self.ns_of)
            return ns

    def assign(self, flow_id: int) -> int:
        with self._lock:
            slot = self.slot_of.get(flow_id)
            if slot is None:
                if not self._free:
                    raise ValueError("flow rule capacity exceeded")
                slot = self._free.pop()
                self.slot_of[flow_id] = slot
            return slot

    def release_missing(self, live_flow_ids) -> List[int]:
        """Free slots whose flow_id is no longer present; returns freed slots."""
        live = set(live_flow_ids)
        freed = []
        with self._lock:
            for fid in list(self.slot_of):
                if fid not in live:
                    slot = self.slot_of.pop(fid)
                    self._free.append(slot)
                    freed.append(slot)
            self.pending_clear.extend(freed)
        return freed

    def lookup(self, flow_id: int) -> int:
        """Slot for a flow_id, or -1 (→ NO_RULE verdict)."""
        return self.slot_of.get(flow_id, -1)


def build_rule_table(
    config: EngineConfig,
    rules: List[ClusterFlowRule],
    index: Optional[RuleIndex] = None,
    ns_max_qps: float = 30_000.0,
    connected: Optional[Dict[str, int]] = None,
) -> tuple:
    """Build/refresh the device rule table. Returns ``(table, index)``.

    ``ns_max_qps`` defaults to the reference's namespace self-protection cap
    (``ServerFlowConfig.java:31``).

    After a rebuild, call ``drain_pending_clear(index, state)`` so slots freed
    by removed rules are zeroed before a new flow_id reuses them — otherwise
    the new flow inherits the removed flow's live window history.
    """
    index = index or RuleIndex(config)
    index.release_missing(r.flow_id for r in rules)

    valid = np.zeros(config.max_flows, dtype=bool)
    count = np.zeros(config.max_flows, dtype=np.float32)
    mode = np.zeros(config.max_flows, dtype=np.int8)
    namespace_id = np.zeros(config.max_flows, dtype=np.int32)
    ns_max = np.full(config.max_namespaces, float(ns_max_qps), dtype=np.float32)
    ns_conn = np.ones(config.max_namespaces, dtype=np.int32)
    for rule in rules:
        slot = index.assign(rule.flow_id)
        ns = index.namespace_slot(rule.namespace)
        valid[slot] = True
        count[slot] = rule.count
        mode[slot] = int(rule.mode)
        namespace_id[slot] = ns
    for ns_name, n in (connected or {}).items():
        ns_conn[index.namespace_slot(ns_name)] = max(1, int(n))
    table = RuleTable(
        valid=jnp.asarray(valid),
        count=jnp.asarray(count),
        mode=jnp.asarray(mode),
        namespace_id=jnp.asarray(namespace_id),
        ns_max_qps=jnp.asarray(ns_max),
        ns_connected=jnp.asarray(ns_conn),
    )
    return table, index


def drain_pending_clear(index: RuleIndex, state) -> "object":
    """Zero the window history of slots freed by rule reloads; returns the
    updated EngineState. Idempotent; call after every ``build_rule_table``."""
    with index._lock:
        slots, index.pending_clear = index.pending_clear, []
    if not slots:
        return state
    import jax.numpy as _jnp

    from sentinel_tpu.engine.state import EngineState
    from sentinel_tpu.stats.window import WindowState

    idx = _jnp.asarray(np.asarray(slots, dtype=np.int32))
    flow_counts = state.flow.counts.at[idx].set(0)
    occupy_counts = state.occupy.counts.at[idx].set(0)
    return EngineState(
        flow=WindowState(starts=state.flow.starts, counts=flow_counts),
        occupy=WindowState(starts=state.occupy.starts, counts=occupy_counts),
        ns=state.ns,
    )
