"""Rules as padded device tensors.

The reference token server holds ``flowId → FlowRule`` maps
(``ClusterFlowRuleManager.java:46-235``); reloading rules must not retrace the
jitted step, so the device sees only fixed-shape arrays. The host keeps the
``flow_id → slot`` assignment (slots are stable across reloads for unchanged
rules, so sliding-window history survives a rule update — the reference gets
this by keeping ``ClusterMetric`` objects keyed by flowId).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.engine.config import EngineConfig


class ThresholdMode(enum.IntEnum):
    # ClusterFlowConfig.thresholdType (ClusterRuleConstant): AVG_LOCAL
    # multiplies the per-client count by the connected-client count
    # (ClusterFlowChecker.java:43-47); GLOBAL uses count as-is.
    AVG_LOCAL = 0
    GLOBAL = 1


class DegradeStrategy(enum.IntEnum):
    # RuleConstant.DEGRADE_GRADE_*: which metric trips the breaker.
    # SLOW_REQUEST_RATIO compares (completions with RT > slow_rt_ms) / total
    # against `threshold` in [0, 1]; ERROR_RATIO compares exceptions / total;
    # ERROR_COUNT compares the raw exception count against `threshold`.
    SLOW_REQUEST_RATIO = 0
    ERROR_RATIO = 1
    ERROR_COUNT = 2


class ControlBehavior(enum.IntEnum):
    # RuleConstant.CONTROL_BEHAVIOR_*: which TrafficShapingController serves
    # the rule. DEFAULT rejects on threshold; WARM_UP admits along the
    # stored-token slope curve (WarmUpController); RATE_LIMITER paces
    # admissions and answers waitInMs (RateLimiterController); the combined
    # mode paces at the warmup curve's current rate.
    DEFAULT = 0
    WARM_UP = 1
    RATE_LIMITER = 2
    WARM_UP_RATE_LIMITER = 3


@dataclass(frozen=True)
class ClusterFlowRule:
    """Host-side cluster rule (``FlowRule`` + ``ClusterFlowConfig`` subset).

    ``mode`` defaults to AVG_LOCAL like the reference's
    ``ClusterFlowConfig.thresholdType`` — a rule set ported from Sentinel with
    the field omitted keeps its count × connected-clients semantics.

    The shaping fields mirror ``FlowRule``'s traffic-shaping knobs and only
    matter when ``control_behavior`` is non-DEFAULT; defaults match the
    reference (``RuleConstant``: 10s warmup, cold factor 3, 500ms max queue).
    """

    flow_id: int
    count: float
    mode: ThresholdMode = ThresholdMode.AVG_LOCAL
    namespace: str = "default"
    control_behavior: int = 0
    warm_up_period_sec: int = 10
    cold_factor: int = 3
    max_queueing_time_ms: int = 500


@dataclass(frozen=True)
class DegradeRule:
    """Host-side circuit-breaker rule (``DegradeRule.java`` subset).

    ``threshold`` is a ratio in [0, 1] for the two ratio strategies and a
    raw count for ERROR_COUNT (the reference overloads ``count`` the same
    way). ``stat_interval_ms`` is clamped at build time to the engine's
    outcome-window interval — the sliding window holds no older history.
    A flow may carry a DegradeRule with or without a ClusterFlowRule; a
    breaker-only flow gets a slot with an effectively-unlimited admission
    threshold, so CLOSED answers OK and only the breaker gates it."""

    flow_id: int
    strategy: DegradeStrategy = DegradeStrategy.SLOW_REQUEST_RATIO
    threshold: float = 1.0
    slow_rt_ms: int = 1000
    min_request_amount: int = 5
    stat_interval_ms: int = 1000
    recovery_timeout_ms: int = 5000
    namespace: str = "default"


# br_slow_rt_ms default for slots without a breaker rule: no real RT can
# exceed it, so the SLOW outcome channel stays zero for those slots
NO_SLOW_RT_MS = 2**30 - 1

# admission threshold for breaker-only slots (no ClusterFlowRule): large
# enough that the window can never fill, small enough that
# threshold * exceed_count * interval stays well inside f32 exactness
UNLIMITED_COUNT = 1e9


class RuleTable(NamedTuple):
    """Device tensors, all shaped ``[max_flows]`` (+ ``[max_namespaces]``).

    The shaping columns are precomputed host-side from the rule's warmup
    knobs (the reference computes them once in ``WarmUpController``'s
    constructor, ``WarmUpController.java:94-117``) so the kernel's per-row
    work is pure gathers + elementwise math. Rows with ``behavior == 0``
    carry zeros — the ``jnp.where`` branch selection never reads them.
    """

    valid: jax.Array  # bool — slot holds an active rule
    count: jax.Array  # float32 — rule threshold (per-client for AVG_LOCAL)
    mode: jax.Array  # int8 — ThresholdMode
    namespace_id: jax.Array  # int32
    ns_max_qps: jax.Array  # float32 [NS] — GlobalRequestLimiter threshold
    ns_connected: jax.Array  # int32 [NS] — connected client count (AVG_LOCAL)
    behavior: jax.Array  # int8 — ControlBehavior
    warning_token: jax.Array  # float32 — warmup warning line (stored tokens)
    max_token: jax.Array  # float32 — warmup bucket capacity
    slope: jax.Array  # float32 — warmup admission slope above the line
    cold_count: jax.Array  # float32 — floor(count / cold_factor) refill gate
    max_queue_ms: jax.Array  # int32 — pacing queue bound (ring-clamped)
    # circuit-breaker columns (DegradeRule); br_strategy == -1 marks a slot
    # with no breaker rule, which the breaker gate skips entirely. All six
    # are None when the table carries no degrade rules at all — None is
    # part of the jit pytree structure, so breaker-free tables compile the
    # decide step without tracing the breaker arm
    br_strategy: Optional[jax.Array]  # int8 — DegradeStrategy, -1 = none
    br_threshold: Optional[jax.Array]  # float32 — ratio (0/1) or count (2)
    br_slow_rt_ms: Optional[jax.Array]  # int32 — slow-call RT cutoff
    br_min_request: Optional[jax.Array]  # int32 — minRequestAmount gate
    br_stat_ms: Optional[jax.Array]  # int32 — stat interval (ring-clamped)
    br_recovery_ms: Optional[jax.Array]  # int32 — OPEN → HALF_OPEN timeout


class RuleIndex:
    """Host-side flow_id → slot assignment (stable across reloads)."""

    def __init__(self, config: EngineConfig):
        self.config = config
        self._lock = threading.RLock()
        self.slot_of: Dict[int, int] = {}
        self.ns_of: Dict[str, int] = {}
        self._free = list(range(config.max_flows - 1, -1, -1))
        # Slots freed by a reload still hold the removed flow's window history
        # (and possibly pending future borrows); they MUST be zeroed in the
        # engine state before reuse — callers drain this via
        # ``drain_pending_clear(index, state)`` after every build.
        self.pending_clear: List[int] = []

    def namespace_slot(self, namespace: str) -> int:
        with self._lock:
            ns = self.ns_of.get(namespace)
            if ns is None:
                if len(self.ns_of) >= self.config.max_namespaces:
                    raise ValueError("namespace capacity exceeded")
                ns = self.ns_of[namespace] = len(self.ns_of)
            return ns

    def assign(self, flow_id: int) -> int:
        with self._lock:
            slot = self.slot_of.get(flow_id)
            if slot is None:
                if not self._free:
                    raise ValueError("flow rule capacity exceeded")
                slot = self._free.pop()
                self.slot_of[flow_id] = slot
            return slot

    def release_missing(self, live_flow_ids) -> List[int]:
        """Free slots whose flow_id is no longer present; returns freed slots."""
        live = set(live_flow_ids)
        freed = []
        with self._lock:
            for fid in list(self.slot_of):
                if fid not in live:
                    slot = self.slot_of.pop(fid)
                    self._free.append(slot)
                    freed.append(slot)
            self.pending_clear.extend(freed)
        return freed

    def lookup(self, flow_id: int) -> int:
        """Slot for a flow_id, or -1 (→ NO_RULE verdict)."""
        return self.slot_of.get(flow_id, -1)


def build_rule_table(
    config: EngineConfig,
    rules: List[ClusterFlowRule],
    index: Optional[RuleIndex] = None,
    ns_max_qps: float = 30_000.0,
    connected: Optional[Dict[str, int]] = None,
    degrade_rules: Optional[List[DegradeRule]] = None,
) -> tuple:
    """Build/refresh the device rule table. Returns ``(table, index)``.

    ``ns_max_qps`` defaults to the reference's namespace self-protection cap
    (``ServerFlowConfig.java:31``).

    ``degrade_rules`` attach circuit breakers to flows by flow_id; a flow
    with only a DegradeRule still gets a live slot (admission effectively
    unlimited) so the breaker alone gates it.

    After a rebuild, call ``drain_pending_clear(index, state)`` so slots freed
    by removed rules are zeroed before a new flow_id reuses them — otherwise
    the new flow inherits the removed flow's live window history.
    """
    degrade_rules = degrade_rules or []
    index = index or RuleIndex(config)
    index.release_missing(
        {r.flow_id for r in rules} | {d.flow_id for d in degrade_rules}
    )

    valid = np.zeros(config.max_flows, dtype=bool)
    count = np.zeros(config.max_flows, dtype=np.float32)
    mode = np.zeros(config.max_flows, dtype=np.int8)
    namespace_id = np.zeros(config.max_flows, dtype=np.int32)
    ns_max = np.full(config.max_namespaces, float(ns_max_qps), dtype=np.float32)
    ns_conn = np.ones(config.max_namespaces, dtype=np.int32)
    behavior = np.zeros(config.max_flows, dtype=np.int8)
    warning_token = np.zeros(config.max_flows, dtype=np.float32)
    max_token = np.zeros(config.max_flows, dtype=np.float32)
    slope = np.zeros(config.max_flows, dtype=np.float32)
    cold_count = np.zeros(config.max_flows, dtype=np.float32)
    max_queue_ms = np.zeros(config.max_flows, dtype=np.int32)
    br_strategy = np.full(config.max_flows, -1, dtype=np.int8)
    br_threshold = np.zeros(config.max_flows, dtype=np.float32)
    br_slow_rt = np.full(config.max_flows, NO_SLOW_RT_MS, dtype=np.int32)
    br_min_request = np.zeros(config.max_flows, dtype=np.int32)
    br_stat_ms = np.zeros(config.max_flows, dtype=np.int32)
    br_recovery_ms = np.zeros(config.max_flows, dtype=np.int32)
    # add_future can park a borrow at most n_buckets-1 windows ahead, so a
    # pacing queue longer than that would assign waits the cross-batch
    # charge cannot cover — clamp at build time and let docs/SHAPING.md
    # carry the math
    queue_cap_ms = (config.n_buckets - 1) * config.bucket_ms
    for rule in rules:
        slot = index.assign(rule.flow_id)
        ns = index.namespace_slot(rule.namespace)
        valid[slot] = True
        count[slot] = rule.count
        mode[slot] = int(rule.mode)
        namespace_id[slot] = ns
        beh = int(rule.control_behavior)
        behavior[slot] = beh
        if beh in (int(ControlBehavior.WARM_UP),
                   int(ControlBehavior.WARM_UP_RATE_LIMITER)):
            # WarmUpController.construct(): warningToken, maxToken, slope
            c = max(float(rule.count), 1e-6)
            cold = max(2, int(rule.cold_factor))
            period = max(1, int(rule.warm_up_period_sec))
            warn = int(period * c / (cold - 1))
            warning_token[slot] = warn
            max_token[slot] = int(warn + 2.0 * period * c / (1.0 + cold))
            slope[slot] = (cold - 1.0) / c / max(1, max_token[slot] - warn)
            cold_count[slot] = int(c) // cold
        if beh in (int(ControlBehavior.RATE_LIMITER),
                   int(ControlBehavior.WARM_UP_RATE_LIMITER)):
            max_queue_ms[slot] = min(
                int(rule.max_queueing_time_ms), queue_cap_ms
            )
    interval_ms = config.n_buckets * config.bucket_ms
    for d in degrade_rules:
        slot = index.assign(d.flow_id)
        if not valid[slot]:
            # breaker-only flow: a live slot whose admission threshold the
            # window can never reach — only the breaker gates it
            valid[slot] = True
            count[slot] = UNLIMITED_COUNT
            mode[slot] = int(ThresholdMode.GLOBAL)
            namespace_id[slot] = index.namespace_slot(d.namespace)
        br_strategy[slot] = int(d.strategy)
        br_threshold[slot] = float(d.threshold)
        if int(d.strategy) == int(DegradeStrategy.SLOW_REQUEST_RATIO):
            br_slow_rt[slot] = max(0, int(d.slow_rt_ms))
        br_min_request[slot] = max(1, int(d.min_request_amount))
        # the outcome ring holds exactly one interval of history; a stat
        # interval past that would silently read a shorter window anyway
        br_stat_ms[slot] = int(
            np.clip(int(d.stat_interval_ms), config.bucket_ms, interval_ms)
        )
        br_recovery_ms[slot] = max(1, int(d.recovery_timeout_ms))
    for ns_name, n in (connected or {}).items():
        ns_conn[index.namespace_slot(ns_name)] = max(1, int(n))
    table = RuleTable(
        valid=jnp.asarray(valid),
        count=jnp.asarray(count),
        mode=jnp.asarray(mode),
        namespace_id=jnp.asarray(namespace_id),
        ns_max_qps=jnp.asarray(ns_max),
        ns_connected=jnp.asarray(ns_conn),
        behavior=jnp.asarray(behavior),
        warning_token=jnp.asarray(warning_token),
        max_token=jnp.asarray(max_token),
        slope=jnp.asarray(slope),
        cold_count=jnp.asarray(cold_count),
        max_queue_ms=jnp.asarray(max_queue_ms),
        # no degrade rules → None columns: a structurally different pytree,
        # so jit specializes the decide step with NO breaker arm traced in
        br_strategy=jnp.asarray(br_strategy) if degrade_rules else None,
        br_threshold=jnp.asarray(br_threshold) if degrade_rules else None,
        br_slow_rt_ms=jnp.asarray(br_slow_rt) if degrade_rules else None,
        br_min_request=jnp.asarray(br_min_request) if degrade_rules else None,
        br_stat_ms=jnp.asarray(br_stat_ms) if degrade_rules else None,
        br_recovery_ms=jnp.asarray(br_recovery_ms) if degrade_rules else None,
    )
    return table, index


def encode_rule(rule: ClusterFlowRule) -> dict:
    """The wire/blob dict shape shared by snapshots and MOVE blobs. Shaping
    keys are emitted only when non-default, so pre-shaping payloads stay
    byte-identical for plain rules (and old decoders keep working)."""
    d = {
        "flow_id": int(rule.flow_id),
        "count": float(rule.count),
        "mode": int(rule.mode),
        "namespace": rule.namespace,
    }
    if int(rule.control_behavior) != 0:
        d["behavior"] = int(rule.control_behavior)
        d["warmupSec"] = int(rule.warm_up_period_sec)
        d["coldFactor"] = int(rule.cold_factor)
        d["maxQueueMs"] = int(rule.max_queueing_time_ms)
    return d


def decode_rule(d: dict) -> ClusterFlowRule:
    """Inverse of :func:`encode_rule`; tolerant of payloads written before
    the shaping fields existed."""
    return ClusterFlowRule(
        flow_id=int(d["flow_id"]),
        count=float(d["count"]),
        mode=ThresholdMode(int(d["mode"])),
        namespace=str(d["namespace"]),
        control_behavior=int(d.get("behavior", 0)),
        warm_up_period_sec=int(d.get("warmupSec", 10)),
        cold_factor=int(d.get("coldFactor", 3)),
        max_queueing_time_ms=int(d.get("maxQueueMs", 500)),
    )


def encode_degrade_rule(rule: DegradeRule) -> dict:
    """Wire/blob dict for a DegradeRule — shared by snapshots and MOVE
    blobs, same emit-only-non-default discipline as :func:`encode_rule`."""
    d = {
        "flow_id": int(rule.flow_id),
        "strategy": int(rule.strategy),
        "threshold": float(rule.threshold),
        "minRequest": int(rule.min_request_amount),
        "statMs": int(rule.stat_interval_ms),
        "recoveryMs": int(rule.recovery_timeout_ms),
        "namespace": rule.namespace,
    }
    if int(rule.strategy) == int(DegradeStrategy.SLOW_REQUEST_RATIO):
        d["slowRtMs"] = int(rule.slow_rt_ms)
    return d


def decode_degrade_rule(d: dict) -> DegradeRule:
    return DegradeRule(
        flow_id=int(d["flow_id"]),
        strategy=DegradeStrategy(int(d["strategy"])),
        threshold=float(d["threshold"]),
        slow_rt_ms=int(d.get("slowRtMs", 1000)),
        min_request_amount=int(d.get("minRequest", 5)),
        stat_interval_ms=int(d.get("statMs", 1000)),
        recovery_timeout_ms=int(d.get("recoveryMs", 5000)),
        namespace=str(d.get("namespace", "default")),
    )


def drain_pending_clear(index: RuleIndex, state) -> "object":
    """Zero the window history of slots freed by rule reloads; returns the
    updated EngineState. Idempotent; call after every ``build_rule_table``."""
    with index._lock:
        slots, index.pending_clear = index.pending_clear, []
    if not slots:
        return state
    import jax.numpy as _jnp

    from sentinel_tpu.engine.state import EngineState
    from sentinel_tpu.stats.window import WindowState

    from sentinel_tpu.stats.window import NEVER

    idx = _jnp.asarray(np.asarray(slots, dtype=np.int32))
    flow_counts = state.flow.counts.at[idx].set(0)
    occupy_counts = state.occupy.counts.at[idx].set(0)
    shaping = state.shaping
    # a freed slot also holds the removed flow's shaper clock — a reused
    # slot must start cold (pacing unset, warmup bucket full on first sync)
    shaping = shaping._replace(
        lpt=shaping.lpt.at[idx].set(NEVER),
        warm_tokens=shaping.warm_tokens.at[idx].set(0.0),
        warm_filled=shaping.warm_filled.at[idx].set(NEVER),
    )
    # a reused slot must not inherit the removed flow's completion history
    outcome_counts = state.outcome.counts.at[idx].set(0)
    # nor the removed flow's breaker: a reused slot starts CLOSED/cold
    breaker = state.breaker
    breaker = breaker._replace(
        state=breaker.state.at[idx].set(jnp.int8(0)),
        opened_ms=breaker.opened_ms.at[idx].set(NEVER),
        probe_ms=breaker.probe_ms.at[idx].set(NEVER),
    )
    return EngineState(
        flow=WindowState(starts=state.flow.starts, counts=flow_counts),
        occupy=WindowState(starts=state.occupy.starts, counts=occupy_counts),
        ns=state.ns,
        shaping=shaping,
        outcome=WindowState(starts=state.outcome.starts, counts=outcome_counts),
        breaker=breaker,
    )
