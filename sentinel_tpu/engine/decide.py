"""The batched token-verdict kernel.

One jitted pure function replaces the reference's per-request server hot loop
(``DefaultTokenService.requestToken`` → ``ClusterFlowChecker.acquireClusterToken``,
``ClusterFlowChecker.java:36-120``):

1. **Namespace guard** — ``GlobalRequestLimiter.tryPass`` (30k-QPS default
   self-protection, ``GlobalRequestLimiter.java:46-55``) as a windowed
   request counter per namespace.
2. **Threshold** — ``count × (GLOBAL ? 1 : connectedCount) × exceedCount``
   (``ClusterFlowChecker.java:38-48``).
3. **Admission** — window PASS sum + *in-batch prefix sums*: request *i*
   passes iff already-passed + tokens of earlier admitted same-flow requests
   + its own acquire fits the threshold. The prefix refinement iterates an
   odd number of times, which provably yields a subset of the exact
   sequential (greedy) admission set — a batch can *never* collectively
   overshoot a threshold, unlike the reference's benign cross-thread TOCTOU.
   Equal-acquire batches (the common case) are exact after one iteration.
4. **Priority occupy** — blocked prioritized requests borrow the next window
   if it has headroom (``ClusterFlowChecker.canOccupy`` + ``tryOccupyNext``),
   yielding SHOULD_WAIT + wait-ms. Borrowed tokens live in a future-window
   tensor; they fold into the PASS read automatically once their window
   arrives (no transfer step — the validity masks do it).

The in-batch prefix sums are [N, N] masked matmuls — MXU-friendly by
construction (N = batch_size ≤ ~2k ⇒ ≤ 4M MACs, noise for the systolic
array).
"""

from __future__ import annotations

import enum
from functools import partial
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.engine.config import EngineConfig
from sentinel_tpu.engine.rules import RuleTable, ThresholdMode
from sentinel_tpu.engine.state import (
    ClusterEvent,
    EngineState,
    ShapingState,
    flow_spec,
)
from sentinel_tpu.stats import window as W


class TokenStatus(enum.IntEnum):
    """Verdict statuses (``TokenResultStatus.java`` names)."""

    OK = 0
    BLOCKED = 1
    SHOULD_WAIT = 2
    NO_RULE_EXISTS = 3
    TOO_MANY_REQUEST = 4
    FAIL = 5
    # concurrent (cluster-semaphore) mode only:
    RELEASE_OK = 6
    ALREADY_RELEASE = 7
    # server-side admission refusal (no reference analog): the token server
    # answered instead of deciding — queue full, deadline blown, or brownout
    # shed. Distinct from FAIL (broken) and BLOCKED (a rule's verdict): the
    # server is alive and asks the caller to back off (wait_ms carries a
    # retry hint). Never produced by the device kernels.
    OVERLOAD = 8
    # warm-standby refusal: the server answered instead of deciding because
    # it is replicating from a primary and has not been promoted — clients
    # should walk on to the (still-alive) primary. Like OVERLOAD, never
    # produced by the device kernels.
    STANDBY = 9
    # live-rebalance redirect: the namespace owning this flow is moving (or
    # has moved) to another token server; ``remaining`` carries the shard-map
    # epoch and, on the single-request wire path, the frame carries the new
    # owner's endpoint. Routing clients re-resolve and retry once; the
    # failover client treats it as proof of life. Like OVERLOAD/STANDBY,
    # never produced by the device kernels.
    MOVED = 10
    # wire rev 5 lease refusal: the flow is not leasable right now (no
    # headroom to delegate, leasing disabled, or the named lease was
    # revoked). The server is alive and still answers per-request RPCs —
    # clients back off leasing for this flow and fall back to the RPC
    # path; the failover client treats it as proof of life. Never produced
    # by the device kernels.
    NOT_LEASABLE = 11
    # circuit-breaker refusal (DegradeSlot / DegradeException): the flow's
    # breaker is OPEN (or HALF_OPEN with its single probe already in
    # flight), so the request is shed without touching the flow window.
    # ``remaining`` carries retry-after-ms — the time until the breaker
    # will admit a recovery probe. Unlike OVERLOAD..NOT_LEASABLE this IS
    # produced by the device kernels: the breaker state machine runs
    # batch-vectorized inside the decide step (engine/degrade.py).
    DEGRADED = 12


class RequestBatch(NamedTuple):
    flow_slot: jax.Array  # int32 [N]; -1 → NO_RULE
    acquire: jax.Array  # int32 [N]
    prioritized: jax.Array  # bool [N]
    valid: jax.Array  # bool [N] — padding mask


class VerdictBatch(NamedTuple):
    status: jax.Array  # int8 [N]
    wait_ms: jax.Array  # int32 [N]
    remaining: jax.Array  # int32 [N]


def make_batch(
    config: EngineConfig,
    flow_slots: Sequence[int],
    acquires: Optional[Sequence[int]] = None,
    prioritized: Optional[Sequence[bool]] = None,
) -> RequestBatch:
    """Pad host request lists to the static batch size."""
    n = len(flow_slots)
    N = config.batch_size
    if n > N:
        raise ValueError(f"batch of {n} exceeds configured size {N}")
    slot = np.full(N, -1, dtype=np.int32)
    acq = np.zeros(N, dtype=np.int32)
    prio = np.zeros(N, dtype=bool)
    valid = np.zeros(N, dtype=bool)
    slot[:n] = np.asarray(flow_slots, dtype=np.int32)
    acq[:n] = np.asarray(acquires, dtype=np.int32) if acquires is not None else 1
    if prioritized is not None:
        prio[:n] = np.asarray(prioritized, dtype=bool)
    valid[:n] = True
    # numpy leaves on purpose: jit dispatch converts them on its C++ fast
    # path, which is ~4× cheaper than eager per-array jnp.asarray here —
    # this is the serving hot path (one make_batch per micro-batch)
    return RequestBatch(
        flow_slot=slot, acquire=acq, prioritized=prio, valid=valid
    )


def alloc_fused_batch(config: EngineConfig, depth: int) -> RequestBatch:
    """One ``[depth, batch_size]`` stacked-frame staging block — the numpy
    leaves :func:`decide_fused_donating` consumes. Freelist-recycled by the
    fused dispatcher (`cluster.protocol.StagingPool`): jit copies numpy
    arguments to device buffers during the call, so a block is safe to
    recycle the moment the dispatch returns."""
    N = config.batch_size
    return RequestBatch(
        flow_slot=np.empty((depth, N), np.int32),
        acquire=np.empty((depth, N), np.int32),
        prioritized=np.empty((depth, N), bool),
        valid=np.empty((depth, N), bool),
    )


def make_batch_into(
    out: RequestBatch,
    row: int,
    flow_slots,
    acquires=None,
    prioritized=None,
) -> None:
    """:func:`make_batch` writing into row ``row`` of a stacked staging
    block (see :func:`alloc_fused_batch`) instead of allocating fresh
    leaves — identical padding semantics (slot −1 / acquire 0 / prio False /
    valid False beyond n; acquire defaults to 1 for live rows),
    property-tested bit-identical against :func:`make_batch`."""
    N = out.flow_slot.shape[-1]
    n = len(flow_slots)
    if n > N:
        raise ValueError(f"batch of {n} exceeds configured size {N}")
    slot, acq, prio, valid = (
        out.flow_slot[row], out.acquire[row], out.prioritized[row],
        out.valid[row],
    )
    slot[:n] = flow_slots
    slot[n:] = -1
    acq[:n] = 1 if acquires is None else acquires
    acq[n:] = 0
    prio[:n] = False if prioritized is None else prioritized
    prio[n:] = False
    valid[:n] = True
    valid[n:] = False


from sentinel_tpu.engine.degrade import breaker_gate as _breaker_gate
from sentinel_tpu.engine.prefix import segment_prefix_builder as _segment_prefix_builder
from sentinel_tpu.ops.scan_mm import blocked_cumsum as _blocked_cumsum


def _warmup_curve(
    spec,
    now,
    passed,
    cnt,
    cnt_safe,
    warn,
    max_token,
    slope,
    cold_count,
    filled,
    tokens,
    warm_rows,
):
    """WARM_UP lazy token sync + slope curve on gathered ``[N]`` columns.

    Shared verbatim by the XLA pipeline (``_decide_core``'s ``warm_on``
    branch) and the Pallas megakernel (``ops/decide_pallas.py``) so the two
    backends stay *bitwise* equal: the op sequence here IS the parity
    contract. Returns ``(qps, tokens_new, do_sync, cur_sec)``; rows outside
    ``warm_rows`` come back with ``qps = cnt`` and ``do_sync = False`` (the
    cond-off values), which is what makes computing this unconditionally in
    the kernel equivalent to the XLA path's ``lax.cond`` gating.
    """
    # lazy once-per-second token sync (WarmUpController.syncToken):
    # refill below the warning line (or above it while pass qps stays
    # under count/coldFactor), clamp to maxToken, then drain one
    # second's worth of passes. The reference syncs with the previous
    # second's pass QPS; here the sliding-window pass rate stands in —
    # the scalar port in tests/test_shaping.py mirrors exactly this.
    # A NEVER fill stamp makes the first sync see a huge idle gap and
    # clamp to maxToken: the cold state, for free.
    pass_qps = passed * (1000.0 / spec.interval_ms)
    cur_sec = now - now % 1000
    can_refill = (tokens < warn) | ((tokens > warn) & (pass_qps < cold_count))
    elapsed = (cur_sec - filled).astype(jnp.float32)
    cooled = jnp.minimum(
        tokens + jnp.where(can_refill, elapsed * cnt_safe / 1000.0, 0.0),
        max_token,
    )
    synced = jnp.maximum(cooled - pass_qps, 0.0)
    do_sync = warm_rows & (cur_sec > filled)
    tokens_new = jnp.where(do_sync, synced, tokens)
    # above the warning line the system is still cold and the allowed
    # rate follows the slope curve (WarmUpController.canPass)
    above = jnp.maximum(tokens_new - warn, 0.0)
    warning_qps = 1.0 / (above * slope + 1.0 / cnt_safe)
    qps = jnp.where(warm_rows & (tokens_new >= warn), warning_qps, cnt)
    return qps, tokens_new, do_sync, cur_sec


def _occupy_feasible(
    config,
    try_occupy,
    passed,
    expiring,
    admitted_prefix,
    waiting,
    occ_prefix,
    acquire_f,
    threshold,
):
    """The priority-occupy headroom check (``ClusterFlowChecker.canOccupy``)
    on gathered ``[N]`` columns — shared by both decide backends (see
    :func:`_warmup_curve` for why)."""
    # admitted_prefix: tokens admitted earlier in THIS batch land in the
    # current bucket, which is still valid at the next window — without
    # this term a borrow could overcommit the window the batch just filled
    return try_occupy & (
        passed - expiring + admitted_prefix + waiting + occ_prefix + acquire_f
        <= config.max_occupy_ratio * threshold
    )


def _ns_guard(config, spec, ns_state, rules, now, psum, owned, safe_slot, live):
    """Namespace guard (request-count qps, ``GlobalRequestLimiter.java:46``)
    — computed identically on every device from global inputs. Shared by
    both decide backends (it is [N]/[NS]-sized prologue math; the Pallas
    megakernel never touches the tiny replicated namespace window).

    Returns ``(ns_id, ns_ok, seg_ns_sum)`` where ``seg_ns_sum`` is the
    per-namespace segment-sum closure reused for the guard-counter update.
    """
    ns_id = psum(jnp.where(owned, rules.namespace_id[safe_slot], 0))
    live_f = live.astype(jnp.float32)
    # per-namespace totals: on TPU a one-hot matvec (the MXU eats it, a
    # 64-wide scatter serializes); off-TPU the scatter-add wins ~4× and
    # skips materializing the [N, NS] one-hot on the fast path entirely
    on_tpu = jax.default_backend() == "tpu"

    def _ns_one_hot():
        return (
            ns_id[:, None] == jnp.arange(config.max_namespaces)[None, :]
        ).astype(jnp.float32)

    def seg_ns_sum(vals):
        if on_tpu:
            # XLA CSE dedupes the identical one-hot across call sites
            return jnp.einsum(
                "nk,n->k", _ns_one_hot(), vals,
                precision=jax.lax.Precision.HIGHEST,  # exact int counts
            )
        return jnp.zeros(
            (config.max_namespaces,), jnp.float32
        ).at[ns_id].add(vals)
    # Dense per-namespace view ([NS], cheap): a request's verdict needs the
    # per-request in-batch prefix ONLY when a namespace's budget boundary
    # falls inside this batch. With already = valid-window count and
    # total = live requests of that namespace in the batch:
    #   fits-all:   already + total <= budget  → every request passes
    #   none-pass:  already + 1     >  budget  → every request blocks
    # and both reduce to ok = (already + 1 <= budget) applied per
    # namespace. Only a boundary-crossing namespace (already+total >
    # budget AND already+1 <= budget) needs the [N, NS] cumsum — rare in
    # steady state, so it lives behind a cond. All inputs here are global
    # (ns window replicated, ns_id/live psum-stitched), making the
    # predicate mesh-uniform and the cond safe under shard_map.
    ns_live_tot = seg_ns_sum(live_f)
    ns_ids_dense = jnp.arange(config.max_namespaces, dtype=jnp.int32)
    ns_already_dense = W.window_sum_at(
        spec, ns_state, now, 0, ns_ids_dense
    ).astype(jnp.float32)
    ns_budget_dense = rules.ns_max_qps * (spec.interval_ms / 1000.0)
    crossing = (
        (ns_live_tot > 0)
        & (ns_already_dense + ns_live_tot > ns_budget_dense)
        & (ns_already_dense + 1.0 <= ns_budget_dense)
    )

    def ns_ok_precise(_):
        ns_incl = _blocked_cumsum(_ns_one_hot() * live_f[:, None])
        ns_prefix = (
            jnp.take_along_axis(ns_incl, ns_id[:, None], axis=1)[:, 0]
            - live_f
        )
        ns_already = ns_already_dense[ns_id]
        ns_budget = ns_budget_dense[ns_id]
        return (ns_already + ns_prefix + 1.0) <= ns_budget

    def ns_ok_fast(_):
        ok_ns = (ns_already_dense + 1.0) <= ns_budget_dense
        return ok_ns[ns_id]

    ns_ok = jax.lax.cond(
        jnp.any(crossing), ns_ok_precise, ns_ok_fast, None
    )
    return ns_id, ns_ok, seg_ns_sum


def _decide_core(
    config: EngineConfig,
    state: EngineState,
    rules: RuleTable,
    batch: RequestBatch,
    now: jax.Array,
    axis_name: Optional[str] = None,
    grouped: bool = False,
    uniform: bool = False,
) -> tuple:
    """The decision pipeline, single-shard or mesh-sharded.

    With ``axis_name`` set (inside ``shard_map`` over a mesh axis that shards
    the flow dimension of ``state.flow``/``state.occupy`` and the per-flow
    rule arrays), each device evaluates the requests whose flow slot it owns
    and three ``psum``\\ s stitch the global picture together: rule ownership,
    namespace ids, and the final verdicts. The namespace window is replicated
    and updated identically on every device (its inputs are all global), so
    no collective is needed for its state. These are tiny ``[N]``-sized
    collectives riding ICI — the flow tensors themselves never move.

    Serving fast-path flags (static — the host batcher picks the compiled
    variant per batch):

    - ``grouped``: the batcher placed same-flow requests contiguously (e.g.
      sorted by slot; padding rows at the end are fine). Skips the device
      argsort in the segment-prefix builder.
    - ``uniform``: all live requests acquire the same token count (the
      overwhelmingly common acquire=1 traffic). Greedy admission then has
      the closed form ``admit = rank < floor((threshold - passed)/acquire)``
      — ONE prefix pass, exact (the iterative refinement is only needed for
      mixed acquire sizes, where greedy admission is not associative).
    """
    spec = flow_spec(config)
    now = jnp.asarray(now, jnp.int32)
    N = config.batch_size
    f_local = rules.valid.shape[0]

    if axis_name is not None:
        offset = jax.lax.axis_index(axis_name).astype(jnp.int32) * f_local
        psum = partial(jax.lax.psum, axis_name=axis_name)
        pmax = partial(jax.lax.pmax, axis_name=axis_name)
    else:
        offset = jnp.int32(0)
        psum = lambda x: x  # noqa: E731
        pmax = lambda x: x  # noqa: E731

    local_slot = batch.flow_slot - offset
    in_range = (batch.flow_slot >= 0) & (local_slot >= 0) & (local_slot < f_local)
    safe_slot = jnp.where(in_range, local_slot, 0)
    owned = in_range & rules.valid[safe_slot]
    has_rule = psum(owned.astype(jnp.int32)) > 0
    live = batch.valid & has_rule
    no_rule = batch.valid & ~has_rule

    acquire_f = batch.acquire.astype(jnp.float32)

    ns_id, ns_ok, seg_ns_sum = _ns_guard(
        config, spec, state.ns, rules, now, psum, owned, safe_slot, live
    )
    too_many = live & ~ns_ok
    ns_admitted = live & ns_ok  # global mask — identical on every device
    active = ns_admitted & owned  # flow evaluation happens on the owner

    if config.prefix_impl == "grouped":
        # "grouped" is only sound when the host batcher sorted the batch —
        # that guarantee arrives via decide()'s grouped flag, never via
        # config (on an interleaved batch it would silently drop earlier
        # same-flow contributions and break the no-overshoot guarantee)
        raise ValueError(
            "prefix_impl='grouped' is not a config value; pass grouped=True "
            "to decide() from a batcher that groups same-flow requests"
        )
    flow_prefix = _segment_prefix_builder(
        safe_slot, "grouped" if grouped else config.prefix_impl
    )

    # ------------------------------------------------------------------
    # 1b. circuit breakers (DegradeSlot): OPEN/HALF_OPEN rows shed here —
    #     they write NO flow-window events (like the namespace-guard
    #     refusals above) and answer DEGRADED with retry-after-ms. The
    #     HALF_OPEN probe winner stays in `active` and runs the normal
    #     admission below. Skipped at trace time when the table carries
    #     no degrade rules (None br_* columns); otherwise cond-gated
    #     inside breaker_gate on a mesh-uniform "any breaker row"
    #     predicate.
    # ------------------------------------------------------------------
    degraded, br_retry, breaker_ws = _breaker_gate(
        config, spec, state, rules, now, safe_slot, active, flow_prefix, psum
    )
    active = active & ~degraded

    # ------------------------------------------------------------------
    # 2. per-request threshold (ClusterFlowChecker.java:38-48)
    # ------------------------------------------------------------------
    conn = rules.ns_connected[ns_id].astype(jnp.float32)
    factor = jnp.where(
        rules.mode[safe_slot] == int(ThresholdMode.AVG_LOCAL), conn, 1.0
    )

    passed = (
        W.window_sum_at(spec, state.flow, now, ClusterEvent.PASS, safe_slot)
        + W.window_sum_at(spec, state.occupy, now, 0, safe_slot)  # matured borrows
        # wire rev 5: tokens delegated to clients as local-admission leases
        # are pre-paid — charged at grant time — so they occupy the window
        # exactly like passed tokens until they expire or are credited back
        + W.window_sum_at(spec, state.flow, now, ClusterEvent.LEASED, safe_slot)
    ).astype(jnp.float32)

    # ------------------------------------------------------------------
    # 2b. traffic shaping (FlowRule.controlBehavior): WARM_UP modulates the
    #     admission rate along the stored-token slope curve; RATE_LIMITER
    #     rows skip window admission entirely and are paced below. Both
    #     blocks are cond-gated on mesh-uniform "any shaped row in this
    #     batch" predicates, so a reject-only batch pays two [N] psums and
    #     nothing else.
    # ------------------------------------------------------------------
    beh = rules.behavior[safe_slot].astype(jnp.int32)
    is_warm = (beh == 1) | (beh == 3)
    is_pace = (beh == 2) | (beh == 3)
    warm_rows = active & is_warm
    pace_try = active & is_pace
    active_window = active & ~is_pace
    any_warm = jnp.any(psum(warm_rows.astype(jnp.int32)) > 0)
    any_pace = jnp.any(psum(pace_try.astype(jnp.int32)) > 0)

    cnt = rules.count[safe_slot]
    cnt_safe = jnp.maximum(cnt, 1e-6)

    def warm_on(_):
        qps_, tokens_new, do_sync, cur_sec = _warmup_curve(
            spec, now, passed, cnt, cnt_safe,
            rules.warning_token[safe_slot],
            rules.max_token[safe_slot],
            rules.slope[safe_slot],
            rules.cold_count[safe_slot],
            state.shaping.warm_filled[safe_slot],
            state.shaping.warm_tokens[safe_slot],
            warm_rows,
        )
        # duplicate same-flow rows scatter identical values (pure function
        # of state + now), so .set stays deterministic
        scat = jnp.where(do_sync, safe_slot, f_local)
        wt = state.shaping.warm_tokens.at[scat].set(tokens_new, mode="drop")
        wf = state.shaping.warm_filled.at[scat].set(cur_sec, mode="drop")
        return qps_, wt, wf

    def warm_off(_):
        return cnt, state.shaping.warm_tokens, state.shaping.warm_filled

    qps, warm_tokens_ws, warm_filled_ws = jax.lax.cond(
        any_warm, warm_on, warm_off, None
    )

    # rule count is per-second (ClusterMetric.getAvg divides by interval
    # seconds before comparing); the window budget scales by interval length
    rate_qps = qps * factor * config.exceed_count
    threshold = rate_qps * (spec.interval_ms / 1000.0)

    # ------------------------------------------------------------------
    # 3. prefix-sum admission (odd refinement count ⇒ ⊆ sequential-exact)
    # ------------------------------------------------------------------
    if uniform:
        # closed-form greedy admission: with one acquire size `a` per batch,
        # the admitted set of each flow is exactly its first
        # floor((threshold - passed)/a) active requests
        a = jnp.max(jnp.where(live, batch.acquire, 0)).astype(jnp.float32)
        a_safe = jnp.maximum(a, 1.0)
        rank = flow_prefix(active_window.astype(jnp.float32))
        admit = active_window & (passed + rank * a + a <= threshold)
        quota = jnp.floor(jnp.maximum(threshold - passed, 0.0) / a_safe)
        admitted_prefix = jnp.minimum(rank, quota) * a
    else:
        admit = active_window
        iters = config.admission_refine_iters
        if iters % 2 == 0:
            raise ValueError(
                "admission_refine_iters must be odd: an odd iteration count "
                "makes the final admission mask a subset of the "
                "sequential-greedy set (no-overshoot guarantee)"
            )
        for _ in range(iters):
            contrib = jnp.where(admit, acquire_f, 0.0)
            prefix = flow_prefix(contrib)  # earlier admitted same-flow tokens
            admit = active_window & (passed + prefix + acquire_f <= threshold)
        admitted_prefix = flow_prefix(jnp.where(admit, acquire_f, 0.0))

    # ------------------------------------------------------------------
    # 3b. pacing (RateLimiterController.canPass as a batch closed form):
    #     within one flow only the FIRST admitted row can pull
    #     latestPassedTime up to now, so under the all-admit assumption
    #     L_j = max(L0, now - cost_first) + inclusive-cost-prefix_j holds
    #     exactly; a row rejects when its wait exceeds maxQueueingTimeMs.
    #     With uniform costs the waits are monotone within a flow, rejects
    #     form a suffix, and one pass is exact. Mixed-acquire batches
    #     refine like the window-admission loop plus a final tightening
    #     recompute — the accepted set stays a subset of the
    #     sequential-exact one, so pacing can never over-admit. All the
    #     arithmetic is done relative to `now` so f32 stays exact (engine
    #     ms exceeds the f32 integer range after ~4.6h; waits never do).
    # ------------------------------------------------------------------
    def pace_on(_):
        cost_f = jnp.round(1000.0 * acquire_f / jnp.maximum(rate_qps, 1e-6))
        rel0 = jnp.maximum(
            state.shaping.lpt[safe_slot] - now, jnp.int32(-(2**20))
        ).astype(jnp.float32)
        maxq = rules.max_queue_ms[safe_slot].astype(jnp.float32)

        def pace_pass(accept):
            contrib = jnp.where(accept, cost_f, 0.0)
            # a row's own cost always counts toward its hypothetical
            # schedule (contrib only carries it into LATER rows' prefixes) —
            # otherwise a rejected row sheds its own cost and oscillates
            # back into the accepted set on the next refinement pass
            incl = flow_prefix(contrib) + cost_f
            rank_p = flow_prefix(accept.astype(jnp.float32))
            first = accept & (rank_p == 0.0)
            scat_first = jnp.where(first, safe_slot, f_local)
            c_first = jnp.zeros((f_local,), jnp.float32).at[scat_first].set(
                cost_f, mode="drop"
            )[safe_slot]
            # L_row - now, directly: base_rel = max(L0 - now, -cost_first)
            l_rel = jnp.maximum(rel0, -c_first) + incl
            return l_rel

        accept = pace_try
        l_rel = pace_pass(accept)
        for _i in range(0 if uniform else config.admission_refine_iters):
            accept = pace_try & (l_rel <= maxq)
            l_rel = pace_pass(accept)
        accept = pace_try & (l_rel <= maxq)
        wait_i = jnp.maximum(l_rel, 0.0).astype(jnp.int32)
        # scatter-max: the last accepted row's schedule is the flow's new
        # latestPassedTime; non-accepted rows leave it untouched
        scat = jnp.where(accept, safe_slot, f_local)
        lpt_ = state.shaping.lpt.at[scat].max(
            now + jnp.round(l_rel).astype(jnp.int32), mode="drop"
        )
        return accept, wait_i, lpt_

    def pace_off(_):
        return (
            jnp.zeros((N,), bool),
            jnp.zeros((N,), jnp.int32),
            state.shaping.lpt,
        )

    pace_admit, pace_wait, lpt_ws = jax.lax.cond(
        any_pace, pace_on, pace_off, None
    )
    pace_now = pace_admit & (pace_wait == 0)
    pace_later = pace_admit & (pace_wait > 0)
    pace_reject = pace_try & ~pace_admit

    # ------------------------------------------------------------------
    # 4. priority occupy of the next window (ClusterFlowChecker.java:84-97)
    #    — the whole occupy path (reads, prefix, future-window write) is
    #    gated on "any prioritized request in the batch", which is a global
    #    property of the replicated batch and therefore a mesh-uniform
    #    predicate (safe around the pmax inside add_future)
    # ------------------------------------------------------------------
    blocked = active_window & ~admit
    wait_next = spec.bucket_ms - (now % spec.bucket_ms)
    any_prio = jnp.any(batch.prioritized & batch.valid)
    # occupy borrowing stays a DEFAULT-behavior feature: a shaped rule's
    # admission curve is the whole point, and the reference's shapers have
    # no occupy interplay either
    try_occupy = blocked & batch.prioritized & (beh == 0)

    def occupy_check(_):
        next_start = now + wait_next
        # currently-valid PASS tokens that will have expired by the next window
        horizon = next_start - spec.interval_ms
        cur_valid = W.valid_mask(spec, state.flow, now)
        expiring_mask = cur_valid & (state.flow.starts <= horizon)
        pass_rows = state.flow.counts[safe_slot, :, ClusterEvent.PASS]  # [N, B]
        expiring = jnp.sum(
            pass_rows * expiring_mask[None, :].astype(pass_rows.dtype), axis=1
        ).astype(jnp.float32)
        waiting = W.future_sum_at(spec, state.occupy, now, 0, safe_slot).astype(
            jnp.float32
        )
        occ_contrib = jnp.where(try_occupy, acquire_f, 0.0)
        occ_prefix = flow_prefix(occ_contrib)  # conservative: all triers count
        return _occupy_feasible(
            config, try_occupy, passed, expiring, admitted_prefix, waiting,
            occ_prefix, acquire_f, threshold,
        )

    can_occupy = jax.lax.cond(
        any_prio, occupy_check, lambda _: jnp.zeros((N,), bool), None
    )
    hard_block = blocked & ~can_occupy

    # ------------------------------------------------------------------
    # 5. window updates: one scatter per static event channel (the layout
    #    measured fastest on v5e — see add_event_rows), with the rare
    #    OCCUPIED_PASS channel cond-gated. Rows whose masks are false
    #    contribute zeros (scatter targets stay in range, so no drops
    #    needed).
    # ------------------------------------------------------------------
    # paced rows with wait 0 pass NOW and count as ordinary PASS traffic;
    # paced rows with a wait charge the future window below (like occupy
    # borrows — they fold into the PASS read when their window matures, so
    # they are never double-counted); paced rejects count as BLOCK
    admit_i = (admit | pace_now).astype(jnp.int32)
    hard_i = (hard_block | pace_reject).astype(jnp.int32)
    ev = ClusterEvent
    row_updates = jnp.stack(
        [
            batch.acquire * admit_i,  # PASS
            admit_i,  # PASS_REQUEST
            batch.acquire * hard_i,  # BLOCK
            hard_i,  # BLOCK_REQUEST
        ],
        axis=1,
    )
    flow_ws = W.add_event_rows(
        spec, state.flow, now, safe_slot, row_updates,
        channels=(ev.PASS, ev.PASS_REQUEST, ev.BLOCK, ev.BLOCK_REQUEST),
    )
    # OCCUPIED_PASS marks prioritized requests admitted normally (the
    # reference's OK branch adds OCCUPIED_PASS when prioritized; the occupy
    # path records only the future-window WAITING, which is `occupy_ws`
    # below). Prioritized traffic is rare, so this scatter is cond-gated on
    # the same mesh-uniform predicate as the occupy path.
    idx_cur, _ = W.bucket_index(spec, now)
    flow_counts = jax.lax.cond(
        any_prio,
        lambda c: c.at[safe_slot, idx_cur, int(ev.OCCUPIED_PASS)].add(
            batch.acquire * (admit & batch.prioritized).astype(jnp.int32),
            mode="drop",
        ),
        lambda c: c,
        flow_ws.counts,
    )
    flow_ws = flow_ws._replace(counts=flow_counts)
    # pmax over the mesh axis keeps the replicated occupy.starts identical on
    # every device even when only the owner shard sees a borrow (each shard
    # then also zeroes its own stale counts column for the reset slot).
    # Paced SHOULD_WAIT admissions charge the same future-window tensor at
    # their assigned wait — the cross-batch borrow that makes open-loop
    # bursts unable to over-admit: the tokens are pre-paid into the window
    # where the waiter is scheduled to pass.
    charge_wait = jnp.where(
        can_occupy, jnp.full((N,), wait_next, jnp.int32), pace_wait
    )
    charge_valid = can_occupy | pace_later
    occupy_ws = jax.lax.cond(
        any_prio | any_pace,
        lambda occ: W.add_future(
            spec, occ, now,
            wait_ms=charge_wait,
            resource_ids=safe_slot,
            channel_ids=jnp.zeros((N,), jnp.int32),
            values=batch.acquire,
            valid=charge_valid,
            combine_desired=pmax,
        ),
        lambda occ: occ,
        state.occupy,
    )
    # namespace guard counts every ns-admitted request (the guard counts
    # arrivals, not flow verdicts — GlobalRequestLimiter adds on tryPass);
    # the mask is global, so the replicated ns window stays consistent. The
    # per-namespace deltas ride seg_ns_sum (MXU matvec on TPU, scatter-add
    # elsewhere).
    ns_deltas = seg_ns_sum(ns_admitted.astype(jnp.float32))
    ns_ws = W.add_column(spec, state.ns, now, ns_deltas)

    # ------------------------------------------------------------------
    # 6. verdicts — owner emits status+1, psum stitches shards together
    # ------------------------------------------------------------------
    local_status = jnp.where(
        degraded,
        int(TokenStatus.DEGRADED) + 1,
        jnp.where(
            admit | pace_now,
            int(TokenStatus.OK) + 1,
            jnp.where(
                can_occupy | pace_later,
                int(TokenStatus.SHOULD_WAIT) + 1,
                jnp.where(
                    hard_block | pace_reject, int(TokenStatus.BLOCKED) + 1, 0
                ),
            ),
        ),
    ).astype(jnp.int32)
    combined = psum(local_status)
    status = jnp.where(
        ~batch.valid,
        int(TokenStatus.FAIL),
        jnp.where(
            no_rule,
            int(TokenStatus.NO_RULE_EXISTS),
            jnp.where(
                too_many,
                int(TokenStatus.TOO_MANY_REQUEST),
                jnp.where(combined > 0, combined - 1, int(TokenStatus.FAIL)),
            ),
        ),
    ).astype(jnp.int8)

    wait_ms = psum(
        jnp.where(
            can_occupy, wait_next, jnp.where(pace_later, pace_wait, 0)
        ).astype(jnp.int32)
    )
    remaining_local = jnp.clip(
        threshold - passed - admitted_prefix - jnp.where(admit, acquire_f, 0.0),
        0.0,
        2**30,
    ).astype(jnp.int32)
    # blockedResult() in the reference always carries remaining=0 — and so
    # do paced admissions (RateLimiterController has no token count to
    # report); DEGRADED rows carry retry-after-ms instead
    remaining = psum(
        jnp.where(admit, remaining_local, jnp.where(degraded, br_retry, 0))
    )

    new_state = EngineState(
        flow=flow_ws, occupy=occupy_ws, ns=ns_ws,
        shaping=ShapingState(
            lpt=lpt_ws, warm_tokens=warm_tokens_ws, warm_filled=warm_filled_ws
        ),
        # completion outcomes are written by the decoupled outcome step
        # (engine/outcome.py), never by the admission kernel — the serve
        # path's donated buffers just flow through
        outcome=state.outcome,
        breaker=breaker_ws,
    )
    verdicts = VerdictBatch(status=status, wait_ms=wait_ms, remaining=remaining)
    return new_state, verdicts


_AUTO_DECIDE_IMPL: dict = {}  # backend platform → probed choice (per process)


def resolve_decide_impl(impl: str) -> str:
    """Resolve ``EngineConfig.decide_impl`` to a concrete step backend
    ("xla" | "pallas") — same selection discipline as
    ``engine.param.resolve_param_impl``.

    "auto" picks per platform: the ``SENTINEL_DECIDE_IMPL`` env var wins if
    set; off-TPU the XLA pipeline is chosen outright (interpret-mode pallas
    exists for parity testing, not serving); on TPU both steps are
    micro-probed once per process and the faster one is cached. A megakernel
    that fails to compile (Mosaic version skew) simply loses the probe.
    """
    if impl in ("xla", "pallas"):
        return impl
    if impl != "auto":
        raise ValueError(
            f"unknown decide impl {impl!r}; use 'auto'|'xla'|'pallas'"
        )
    import os

    env = os.environ.get("SENTINEL_DECIDE_IMPL", "").strip().lower()
    if env in ("xla", "pallas"):
        return env
    platform = jax.default_backend()
    choice = _AUTO_DECIDE_IMPL.get(platform)
    if choice is None:
        choice = "xla" if platform != "tpu" else _probe_decide_impl()
        _AUTO_DECIDE_IMPL[platform] = choice
    return choice


def _probe_decide_impl() -> str:
    """Time one warm grouped step of each backend on the live backend (small
    probe shapes — the comparison is kernel-vs-kernel, not absolute)."""
    import time as _time

    from sentinel_tpu.engine.rules import build_rule_table
    from sentinel_tpu.engine.state import make_state

    best_dt = None
    choice = "xla"
    for name in ("xla", "pallas"):
        cfg = EngineConfig(
            max_flows=256, batch_size=64, decide_impl=name
        )
        try:
            core = _core_for(cfg, grouped=True)
            step = jax.jit(
                partial(core, cfg, axis_name=None, grouped=True,
                        uniform=False)
            )
            state = make_state(cfg)
            rules, _ = build_rule_table(cfg, [])
            batch = make_batch(cfg, [0, 1, 2])
            _, v = step(state, rules, batch, jnp.int32(1000))  # compile+warm
            jax.block_until_ready(v.status)
            t0 = _time.perf_counter()
            for _ in range(3):
                _, v = step(state, rules, batch, jnp.int32(1000))
            jax.block_until_ready(v.status)
            dt = _time.perf_counter() - t0
        except Exception:
            continue  # backend unusable here: the other wins
        if best_dt is None or dt < best_dt:
            best_dt, choice = dt, name
    return choice


def _core_for(config: EngineConfig, grouped: bool):
    """The decide-core callable for this config's resolved backend.

    The Pallas megakernel depends on the grouped-batch contract (same-flow
    rows contiguous — its segment-tail read-modify-write scatter is only
    race-free then), so non-grouped callers always get the XLA pipeline.
    Batches above the kernel's VMEM cap also fall back inside the pallas
    core itself (see ``ops/decide_pallas.py``).
    """
    if grouped and resolve_decide_impl(config.decide_impl) == "pallas":
        from sentinel_tpu.ops.decide_pallas import decide_core_pallas

        return decide_core_pallas
    return _decide_core


@partial(jax.jit, static_argnames=("config", "grouped", "uniform"))
def decide(
    config: EngineConfig,
    state: EngineState,
    rules: RuleTable,
    batch: RequestBatch,
    now: jax.Array,
    grouped: bool = False,
    uniform: bool = False,
) -> tuple:
    """``(state, rules, batch, now) -> (state', verdicts)`` — single shard.

    ``grouped``/``uniform`` are the serving fast-path flags (see
    :func:`_decide_core`); the host batcher sets them per batch when its
    layout guarantees hold, selecting one of four compiled variants.
    """
    return _core_for(config, grouped)(
        config, state, rules, batch, now, axis_name=None,
        grouped=grouped, uniform=uniform,
    )


def decide_donating(config: EngineConfig, grouped: bool = False,
                    uniform: bool = False):
    """A single-shard step like :func:`decide` that DONATES the state
    buffers: every step scatter-updates the full
    ``[max_flows, buckets, events]`` window tensors, and without donation
    XLA must copy them first (measured 22% of a 64-bucket step at 100k
    flows on CPU; on TPU it is HBM traffic and allocator churn).

    Returns a cached-callable ``step(state, rules, batch, now)``. The
    caller contract: nothing else may hold the passed state (the token
    service's lock makes ``self._state, v = step(self._state, …)`` the
    only reader), and warmup-style calls must feed throwaway states.
    """
    return jax.jit(
        partial(
            _core_for(config, grouped), config, axis_name=None,
            grouped=grouped, uniform=uniform,
        ),
        donate_argnums=(0,),
    )


def decide_fused_donating(config: EngineConfig, depth: int,
                          grouped: bool = False, uniform: bool = False):
    """A chained multi-frame step: ``lax.scan`` of :func:`_decide_core`
    over ``depth`` stacked request frames, donating the state buffers like
    :func:`decide_donating`.

    Returns ``step(state, rules, batches, now) -> (state', verdicts)``
    where every ``batches`` leaf is ``[depth, batch_size]``-shaped (the
    per-frame :class:`RequestBatch` leaves stacked along a new leading
    axis) and the ``verdicts`` leaves come back ``[depth, batch_size]``
    in the same frame order. Frame ``k`` sees exactly the state frame
    ``k-1`` produced — the on-device equivalent of ``depth`` consecutive
    :func:`decide_donating` calls at one shared ``now``, with the
    per-dispatch host/RTT overhead paid once for the whole chain.

    The scanned batch VARIES per iteration, so XLA cannot hoist the
    request-dependent chains out of the loop body (the failure mode
    ``benchmarks/step_ablation.py`` documents for loop-constant operands).
    """
    if depth < 1:
        raise ValueError(f"fused depth must be >= 1, got {depth}")
    core = partial(
        _core_for(config, grouped), config, axis_name=None, grouped=grouped,
        uniform=uniform,
    )

    def fused(state, rules, batches, now):
        def body(st, batch):
            st, verdicts = core(st, rules, batch, now)
            return st, verdicts

        return jax.lax.scan(body, state, batches, length=depth)

    return jax.jit(fused, donate_argnums=(0,))
