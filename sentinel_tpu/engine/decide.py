"""The batched token-verdict kernel.

One jitted pure function replaces the reference's per-request server hot loop
(``DefaultTokenService.requestToken`` → ``ClusterFlowChecker.acquireClusterToken``,
``ClusterFlowChecker.java:36-120``):

1. **Namespace guard** — ``GlobalRequestLimiter.tryPass`` (30k-QPS default
   self-protection, ``GlobalRequestLimiter.java:46-55``) as a windowed
   request counter per namespace.
2. **Threshold** — ``count × (GLOBAL ? 1 : connectedCount) × exceedCount``
   (``ClusterFlowChecker.java:38-48``).
3. **Admission** — window PASS sum + *in-batch prefix sums*: request *i*
   passes iff already-passed + tokens of earlier admitted same-flow requests
   + its own acquire fits the threshold. The prefix refinement iterates an
   odd number of times, which provably yields a subset of the exact
   sequential (greedy) admission set — a batch can *never* collectively
   overshoot a threshold, unlike the reference's benign cross-thread TOCTOU.
   Equal-acquire batches (the common case) are exact after one iteration.
4. **Priority occupy** — blocked prioritized requests borrow the next window
   if it has headroom (``ClusterFlowChecker.canOccupy`` + ``tryOccupyNext``),
   yielding SHOULD_WAIT + wait-ms. Borrowed tokens live in a future-window
   tensor; they fold into the PASS read automatically once their window
   arrives (no transfer step — the validity masks do it).

The in-batch prefix sums are [N, N] masked matmuls — MXU-friendly by
construction (N = batch_size ≤ ~2k ⇒ ≤ 4M MACs, noise for the systolic
array).
"""

from __future__ import annotations

import enum
from functools import partial
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.engine.config import EngineConfig
from sentinel_tpu.engine.rules import RuleTable, ThresholdMode
from sentinel_tpu.engine.state import ClusterEvent, EngineState, flow_spec
from sentinel_tpu.stats import window as W


class TokenStatus(enum.IntEnum):
    """Verdict statuses (``TokenResultStatus.java`` names)."""

    OK = 0
    BLOCKED = 1
    SHOULD_WAIT = 2
    NO_RULE_EXISTS = 3
    TOO_MANY_REQUEST = 4
    FAIL = 5
    # concurrent (cluster-semaphore) mode only:
    RELEASE_OK = 6
    ALREADY_RELEASE = 7


class RequestBatch(NamedTuple):
    flow_slot: jax.Array  # int32 [N]; -1 → NO_RULE
    acquire: jax.Array  # int32 [N]
    prioritized: jax.Array  # bool [N]
    valid: jax.Array  # bool [N] — padding mask


class VerdictBatch(NamedTuple):
    status: jax.Array  # int8 [N]
    wait_ms: jax.Array  # int32 [N]
    remaining: jax.Array  # int32 [N]


def make_batch(
    config: EngineConfig,
    flow_slots: Sequence[int],
    acquires: Optional[Sequence[int]] = None,
    prioritized: Optional[Sequence[bool]] = None,
) -> RequestBatch:
    """Pad host request lists to the static batch size."""
    n = len(flow_slots)
    N = config.batch_size
    if n > N:
        raise ValueError(f"batch of {n} exceeds configured size {N}")
    slot = np.full(N, -1, dtype=np.int32)
    acq = np.zeros(N, dtype=np.int32)
    prio = np.zeros(N, dtype=bool)
    valid = np.zeros(N, dtype=bool)
    slot[:n] = np.asarray(flow_slots, dtype=np.int32)
    acq[:n] = np.asarray(acquires, dtype=np.int32) if acquires is not None else 1
    if prioritized is not None:
        prio[:n] = np.asarray(prioritized, dtype=bool)
    valid[:n] = True
    return RequestBatch(
        flow_slot=jnp.asarray(slot),
        acquire=jnp.asarray(acq),
        prioritized=jnp.asarray(prio),
        valid=jnp.asarray(valid),
    )


from sentinel_tpu.engine.prefix import segment_prefix_builder as _segment_prefix_builder


def _decide_core(
    config: EngineConfig,
    state: EngineState,
    rules: RuleTable,
    batch: RequestBatch,
    now: jax.Array,
    axis_name: Optional[str] = None,
) -> tuple:
    """The decision pipeline, single-shard or mesh-sharded.

    With ``axis_name`` set (inside ``shard_map`` over a mesh axis that shards
    the flow dimension of ``state.flow``/``state.occupy`` and the per-flow
    rule arrays), each device evaluates the requests whose flow slot it owns
    and three ``psum``\\ s stitch the global picture together: rule ownership,
    namespace ids, and the final verdicts. The namespace window is replicated
    and updated identically on every device (its inputs are all global), so
    no collective is needed for its state. These are tiny ``[N]``-sized
    collectives riding ICI — the flow tensors themselves never move.
    """
    spec = flow_spec(config)
    now = jnp.asarray(now, jnp.int32)
    N = config.batch_size
    f_local = rules.valid.shape[0]

    if axis_name is not None:
        offset = jax.lax.axis_index(axis_name).astype(jnp.int32) * f_local
        psum = partial(jax.lax.psum, axis_name=axis_name)
        pmax = partial(jax.lax.pmax, axis_name=axis_name)
    else:
        offset = jnp.int32(0)
        psum = lambda x: x  # noqa: E731
        pmax = lambda x: x  # noqa: E731

    local_slot = batch.flow_slot - offset
    in_range = (batch.flow_slot >= 0) & (local_slot >= 0) & (local_slot < f_local)
    safe_slot = jnp.where(in_range, local_slot, 0)
    owned = in_range & rules.valid[safe_slot]
    has_rule = psum(owned.astype(jnp.int32)) > 0
    live = batch.valid & has_rule
    no_rule = batch.valid & ~has_rule

    acquire_f = batch.acquire.astype(jnp.float32)

    # ------------------------------------------------------------------
    # 1. namespace guard (request-count qps, GlobalRequestLimiter.java:46)
    #    — computed identically on every device from global inputs
    # ------------------------------------------------------------------
    ns_id = psum(jnp.where(owned, rules.namespace_id[safe_slot], 0))
    ns_already = W.window_sum(spec, state.ns, now, 0)[ns_id].astype(jnp.float32)
    ns_prefix = _segment_prefix_builder(ns_id, config.prefix_impl)(
        live.astype(jnp.float32)
    )
    ns_budget = rules.ns_max_qps[ns_id] * (spec.interval_ms / 1000.0)
    ns_ok = (ns_already + ns_prefix + 1.0) <= ns_budget
    too_many = live & ~ns_ok
    ns_admitted = live & ns_ok  # global mask — identical on every device
    active = ns_admitted & owned  # flow evaluation happens on the owner

    # ------------------------------------------------------------------
    # 2. per-request threshold (ClusterFlowChecker.java:38-48)
    # ------------------------------------------------------------------
    conn = rules.ns_connected[ns_id].astype(jnp.float32)
    factor = jnp.where(
        rules.mode[safe_slot] == int(ThresholdMode.AVG_LOCAL), conn, 1.0
    )
    # rule count is per-second (ClusterMetric.getAvg divides by interval
    # seconds before comparing); the window budget scales by interval length
    threshold = (
        rules.count[safe_slot] * factor * config.exceed_count
        * (spec.interval_ms / 1000.0)
    )

    # ------------------------------------------------------------------
    # 3. prefix-sum admission (odd refinement count ⇒ ⊆ sequential-exact)
    # ------------------------------------------------------------------
    passed = (
        W.window_sum(spec, state.flow, now, ClusterEvent.PASS)
        + W.window_sum(spec, state.occupy, now, 0)  # matured borrows
    ).astype(jnp.float32)[safe_slot]
    flow_prefix = _segment_prefix_builder(safe_slot, config.prefix_impl)

    admit = active
    iters = config.admission_refine_iters
    if iters % 2 == 0:
        raise ValueError(
            "admission_refine_iters must be odd: an odd iteration count makes "
            "the final admission mask a subset of the greedy-exact set "
            "(no-overshoot guarantee)"
        )
    for _ in range(iters):
        contrib = jnp.where(admit, acquire_f, 0.0)
        prefix = flow_prefix(contrib)  # tokens of earlier admitted same-flow reqs
        admit = active & (passed + prefix + acquire_f <= threshold)

    contrib = jnp.where(admit, acquire_f, 0.0)
    admitted_prefix = flow_prefix(contrib)

    # ------------------------------------------------------------------
    # 4. priority occupy of the next window (ClusterFlowChecker.java:84-97)
    # ------------------------------------------------------------------
    blocked = active & ~admit
    wait_next = spec.bucket_ms - (now % spec.bucket_ms)
    next_start = now + wait_next
    # currently-valid PASS tokens that will have expired by the next window
    horizon = next_start - spec.interval_ms
    cur_valid = W.valid_mask(spec, state.flow, now)
    expiring_mask = cur_valid & (state.flow.starts <= horizon)
    expiring = jnp.sum(
        state.flow.counts[:, :, ClusterEvent.PASS]
        * expiring_mask[None, :].astype(state.flow.counts.dtype),
        axis=1,
    ).astype(jnp.float32)[safe_slot]
    waiting = W.future_sum(spec, state.occupy, now, 0).astype(jnp.float32)[safe_slot]

    try_occupy = blocked & batch.prioritized
    occ_contrib = jnp.where(try_occupy, acquire_f, 0.0)
    occ_prefix = flow_prefix(occ_contrib)  # conservative: all triers contribute
    # admitted_prefix: tokens admitted earlier in THIS batch land in the
    # current bucket, which is still valid at the next window — without this
    # term a borrow could overcommit the window the batch just filled
    can_occupy = try_occupy & (
        passed - expiring + admitted_prefix + waiting + occ_prefix + acquire_f
        <= config.max_occupy_ratio * threshold
    )
    hard_block = blocked & ~can_occupy

    # ------------------------------------------------------------------
    # 5. window updates — ONE roll + ONE fused scatter for all five flow
    #    event channels (separate add_events calls would each re-roll and
    #    re-materialize the [F, B, E] tensor; fusing keeps HBM traffic to
    #    a single read-modify-write)
    # ------------------------------------------------------------------
    ones_n = jnp.ones((N,), jnp.int32)
    ev = ClusterEvent
    flow_slots5 = jnp.concatenate([safe_slot] * 5)
    flow_chans5 = jnp.concatenate(
        [
            jnp.full((N,), int(c), jnp.int32)
            for c in (ev.PASS, ev.PASS_REQUEST, ev.BLOCK, ev.BLOCK_REQUEST,
                      ev.OCCUPIED_PASS)
        ]
    )
    flow_vals5 = jnp.concatenate(
        [batch.acquire, ones_n, batch.acquire, ones_n, batch.acquire]
    )
    # OCCUPIED_PASS marks prioritized requests admitted normally (the
    # reference's OK branch adds OCCUPIED_PASS when prioritized; the occupy
    # path records only the future-window WAITING, which is `occupy_ws` below)
    flow_valid5 = jnp.concatenate(
        [admit, admit, hard_block, hard_block, admit & batch.prioritized]
    )
    flow_ws = W.add_events(
        spec, state.flow, now, flow_slots5, flow_chans5, flow_vals5,
        valid=flow_valid5,
    )
    # pmax over the mesh axis keeps the replicated occupy.starts identical on
    # every device even when only the owner shard sees a borrow (each shard
    # then also zeroes its own stale counts column for the reset slot)
    occupy_ws = W.add_future(
        spec, state.occupy, now,
        wait_ms=jnp.full((N,), wait_next, jnp.int32),
        resource_ids=safe_slot,
        channel_ids=jnp.zeros((N,), jnp.int32),
        values=batch.acquire,
        valid=can_occupy,
        combine_desired=pmax,
    )
    # namespace guard counts every ns-admitted request (the guard counts
    # arrivals, not flow verdicts — GlobalRequestLimiter adds on tryPass);
    # the mask is global, so the replicated ns window stays consistent
    ns_ws = W.add_events(
        spec, state.ns, now,
        ns_id,
        jnp.zeros((N,), jnp.int32),
        jnp.ones((N,), jnp.int32),
        valid=ns_admitted,
    )

    # ------------------------------------------------------------------
    # 6. verdicts — owner emits status+1, psum stitches shards together
    # ------------------------------------------------------------------
    local_status = jnp.where(
        admit,
        int(TokenStatus.OK) + 1,
        jnp.where(
            can_occupy,
            int(TokenStatus.SHOULD_WAIT) + 1,
            jnp.where(hard_block, int(TokenStatus.BLOCKED) + 1, 0),
        ),
    ).astype(jnp.int32)
    combined = psum(local_status)
    status = jnp.where(
        ~batch.valid,
        int(TokenStatus.FAIL),
        jnp.where(
            no_rule,
            int(TokenStatus.NO_RULE_EXISTS),
            jnp.where(
                too_many,
                int(TokenStatus.TOO_MANY_REQUEST),
                jnp.where(combined > 0, combined - 1, int(TokenStatus.FAIL)),
            ),
        ),
    ).astype(jnp.int8)

    wait_ms = psum(jnp.where(can_occupy, wait_next, 0).astype(jnp.int32))
    remaining_local = jnp.clip(
        threshold - passed - admitted_prefix - jnp.where(admit, acquire_f, 0.0),
        0.0,
        2**30,
    ).astype(jnp.int32)
    # blockedResult() in the reference always carries remaining=0
    remaining = psum(jnp.where(admit, remaining_local, 0))

    new_state = EngineState(flow=flow_ws, occupy=occupy_ws, ns=ns_ws)
    verdicts = VerdictBatch(status=status, wait_ms=wait_ms, remaining=remaining)
    return new_state, verdicts


@partial(jax.jit, static_argnames=("config",))
def decide(
    config: EngineConfig,
    state: EngineState,
    rules: RuleTable,
    batch: RequestBatch,
    now: jax.Array,
) -> tuple:
    """``(state, rules, batch, now) -> (state', verdicts)`` — single shard."""
    return _decide_core(config, state, rules, batch, now, axis_name=None)
