"""The batched token-verdict kernel.

One jitted pure function replaces the reference's per-request server hot loop
(``DefaultTokenService.requestToken`` → ``ClusterFlowChecker.acquireClusterToken``,
``ClusterFlowChecker.java:36-120``):

1. **Namespace guard** — ``GlobalRequestLimiter.tryPass`` (30k-QPS default
   self-protection, ``GlobalRequestLimiter.java:46-55``) as a windowed
   request counter per namespace.
2. **Threshold** — ``count × (GLOBAL ? 1 : connectedCount) × exceedCount``
   (``ClusterFlowChecker.java:38-48``).
3. **Admission** — window PASS sum + *in-batch prefix sums*: request *i*
   passes iff already-passed + tokens of earlier admitted same-flow requests
   + its own acquire fits the threshold. The prefix refinement iterates an
   odd number of times, which provably yields a subset of the exact
   sequential (greedy) admission set — a batch can *never* collectively
   overshoot a threshold, unlike the reference's benign cross-thread TOCTOU.
   Equal-acquire batches (the common case) are exact after one iteration.
4. **Priority occupy** — blocked prioritized requests borrow the next window
   if it has headroom (``ClusterFlowChecker.canOccupy`` + ``tryOccupyNext``),
   yielding SHOULD_WAIT + wait-ms. Borrowed tokens live in a future-window
   tensor; they fold into the PASS read automatically once their window
   arrives (no transfer step — the validity masks do it).

The in-batch prefix sums are [N, N] masked matmuls — MXU-friendly by
construction (N = batch_size ≤ ~2k ⇒ ≤ 4M MACs, noise for the systolic
array).
"""

from __future__ import annotations

import enum
from functools import partial
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.engine.config import EngineConfig
from sentinel_tpu.engine.rules import RuleTable, ThresholdMode
from sentinel_tpu.engine.state import ClusterEvent, EngineState, flow_spec
from sentinel_tpu.stats import window as W


class TokenStatus(enum.IntEnum):
    """Verdict statuses (``TokenResultStatus.java`` names)."""

    OK = 0
    BLOCKED = 1
    SHOULD_WAIT = 2
    NO_RULE_EXISTS = 3
    TOO_MANY_REQUEST = 4
    FAIL = 5


class RequestBatch(NamedTuple):
    flow_slot: jax.Array  # int32 [N]; -1 → NO_RULE
    acquire: jax.Array  # int32 [N]
    prioritized: jax.Array  # bool [N]
    valid: jax.Array  # bool [N] — padding mask


class VerdictBatch(NamedTuple):
    status: jax.Array  # int8 [N]
    wait_ms: jax.Array  # int32 [N]
    remaining: jax.Array  # int32 [N]


def make_batch(
    config: EngineConfig,
    flow_slots: Sequence[int],
    acquires: Optional[Sequence[int]] = None,
    prioritized: Optional[Sequence[bool]] = None,
) -> RequestBatch:
    """Pad host request lists to the static batch size."""
    n = len(flow_slots)
    N = config.batch_size
    if n > N:
        raise ValueError(f"batch of {n} exceeds configured size {N}")
    slot = np.full(N, -1, dtype=np.int32)
    acq = np.zeros(N, dtype=np.int32)
    prio = np.zeros(N, dtype=bool)
    valid = np.zeros(N, dtype=bool)
    slot[:n] = np.asarray(flow_slots, dtype=np.int32)
    acq[:n] = np.asarray(acquires, dtype=np.int32) if acquires is not None else 1
    if prioritized is not None:
        prio[:n] = np.asarray(prioritized, dtype=bool)
    valid[:n] = True
    return RequestBatch(
        flow_slot=jnp.asarray(slot),
        acquire=jnp.asarray(acq),
        prioritized=jnp.asarray(prio),
        valid=jnp.asarray(valid),
    )


def _prefix_mats(n: int):
    """Strictly-lower triangular [N, N] mask (row i sees columns j < i)."""
    i = jnp.arange(n)
    strict = (i[:, None] > i[None, :]).astype(jnp.float32)
    return strict


@partial(jax.jit, static_argnames=("config",))
def decide(
    config: EngineConfig,
    state: EngineState,
    rules: RuleTable,
    batch: RequestBatch,
    now: jax.Array,
) -> tuple:
    """``(state, rules, batch, now) -> (state', verdicts)`` — fully on device."""
    spec = flow_spec(config)
    now = jnp.asarray(now, jnp.int32)
    N = config.batch_size

    safe_slot = jnp.where(batch.flow_slot >= 0, batch.flow_slot, 0)
    has_rule = (batch.flow_slot >= 0) & rules.valid[safe_slot]
    live = batch.valid & has_rule
    no_rule = batch.valid & ~has_rule

    acquire_f = batch.acquire.astype(jnp.float32)
    tri = _prefix_mats(N)  # [N, N] strictly-lower

    # ------------------------------------------------------------------
    # 1. namespace guard (request-count qps, GlobalRequestLimiter.java:46)
    # ------------------------------------------------------------------
    ns_id = rules.namespace_id[safe_slot]
    ns_already = W.window_sum(spec, state.ns, now, 0)[ns_id].astype(jnp.float32)
    same_ns = (ns_id[:, None] == ns_id[None, :]) & live[None, :]
    ones = live.astype(jnp.float32)
    ns_prefix = (same_ns.astype(jnp.float32) * tri) @ ones  # earlier same-ns reqs
    ns_budget = rules.ns_max_qps[ns_id] * (spec.interval_ms / 1000.0)
    ns_ok = (ns_already + ns_prefix + 1.0) <= ns_budget
    too_many = live & ~ns_ok
    active = live & ns_ok

    # ------------------------------------------------------------------
    # 2. per-request threshold (ClusterFlowChecker.java:38-48)
    # ------------------------------------------------------------------
    conn = rules.ns_connected[ns_id].astype(jnp.float32)
    factor = jnp.where(
        rules.mode[safe_slot] == int(ThresholdMode.AVG_LOCAL), conn, 1.0
    )
    # rule count is per-second (ClusterMetric.getAvg divides by interval
    # seconds before comparing); the window budget scales by interval length
    threshold = (
        rules.count[safe_slot] * factor * config.exceed_count
        * (spec.interval_ms / 1000.0)
    )

    # ------------------------------------------------------------------
    # 3. prefix-sum admission (odd refinement count ⇒ ⊆ sequential-exact)
    # ------------------------------------------------------------------
    passed = (
        W.window_sum(spec, state.flow, now, ClusterEvent.PASS)
        + W.window_sum(spec, state.occupy, now, 0)  # matured borrows
    ).astype(jnp.float32)[safe_slot]
    same_flow = (safe_slot[:, None] == safe_slot[None, :]).astype(jnp.float32) * tri

    admit = active
    iters = config.admission_refine_iters
    if iters % 2 == 0:
        raise ValueError(
            "admission_refine_iters must be odd: an odd iteration count makes "
            "the final admission mask a subset of the greedy-exact set "
            "(no-overshoot guarantee)"
        )
    for _ in range(iters):
        contrib = jnp.where(admit, acquire_f, 0.0)
        prefix = same_flow @ contrib  # tokens of earlier admitted same-flow reqs
        admit = active & (passed + prefix + acquire_f <= threshold)

    contrib = jnp.where(admit, acquire_f, 0.0)
    admitted_prefix = same_flow @ contrib

    # ------------------------------------------------------------------
    # 4. priority occupy of the next window (ClusterFlowChecker.java:84-97)
    # ------------------------------------------------------------------
    blocked = active & ~admit
    wait_next = spec.bucket_ms - (now % spec.bucket_ms)
    next_start = now + wait_next
    # currently-valid PASS tokens that will have expired by the next window
    horizon = next_start - spec.interval_ms
    cur_valid = W.valid_mask(spec, state.flow, now)
    expiring_mask = cur_valid & (state.flow.starts <= horizon)
    expiring = jnp.sum(
        state.flow.counts[:, :, ClusterEvent.PASS]
        * expiring_mask[None, :].astype(state.flow.counts.dtype),
        axis=1,
    ).astype(jnp.float32)[safe_slot]
    waiting = W.future_sum(spec, state.occupy, now, 0).astype(jnp.float32)[safe_slot]

    try_occupy = blocked & batch.prioritized
    occ_contrib = jnp.where(try_occupy, acquire_f, 0.0)
    occ_prefix = same_flow @ occ_contrib  # conservative: all triers contribute
    # admitted_prefix: tokens admitted earlier in THIS batch land in the
    # current bucket, which is still valid at the next window — without this
    # term a borrow could overcommit the window the batch just filled
    can_occupy = try_occupy & (
        passed - expiring + admitted_prefix + waiting + occ_prefix + acquire_f
        <= config.max_occupy_ratio * threshold
    )
    hard_block = blocked & ~can_occupy

    # ------------------------------------------------------------------
    # 5. window updates (segment scatter-adds)
    # ------------------------------------------------------------------
    flow_ws = state.flow
    slot2 = jnp.concatenate([safe_slot, safe_slot])
    # PASS tokens + PASS_REQUEST rpcs for admitted
    flow_ws = W.add_events(
        spec, flow_ws, now,
        slot2,
        jnp.concatenate([
            jnp.full((N,), int(ClusterEvent.PASS), jnp.int32),
            jnp.full((N,), int(ClusterEvent.PASS_REQUEST), jnp.int32),
        ]),
        jnp.concatenate([batch.acquire, jnp.ones((N,), jnp.int32)]),
        valid=jnp.concatenate([admit, admit]),
    )
    # BLOCK tokens + BLOCK_REQUEST rpcs for hard-blocked
    flow_ws = W.add_events(
        spec, flow_ws, now,
        slot2,
        jnp.concatenate([
            jnp.full((N,), int(ClusterEvent.BLOCK), jnp.int32),
            jnp.full((N,), int(ClusterEvent.BLOCK_REQUEST), jnp.int32),
        ]),
        jnp.concatenate([batch.acquire, jnp.ones((N,), jnp.int32)]),
        valid=jnp.concatenate([hard_block, hard_block]),
    )
    # OCCUPIED_PASS marks prioritized requests admitted normally (the
    # reference's OK branch adds OCCUPIED_PASS when prioritized; the occupy
    # path records only the future-window WAITING, which is `occupy_ws` below)
    flow_ws = W.add_events(
        spec, flow_ws, now,
        safe_slot,
        jnp.full((N,), int(ClusterEvent.OCCUPIED_PASS), jnp.int32),
        batch.acquire,
        valid=admit & batch.prioritized,
    )
    occupy_ws = W.add_future(
        spec, state.occupy, now,
        wait_ms=jnp.full((N,), wait_next, jnp.int32),
        resource_ids=safe_slot,
        channel_ids=jnp.zeros((N,), jnp.int32),
        values=batch.acquire,
        valid=can_occupy,
    )
    # namespace guard counts every ns-admitted request (the guard counts
    # arrivals, not flow verdicts — GlobalRequestLimiter adds on tryPass)
    ns_ws = W.add_events(
        spec, state.ns, now,
        ns_id,
        jnp.zeros((N,), jnp.int32),
        jnp.ones((N,), jnp.int32),
        valid=active,
    )

    # ------------------------------------------------------------------
    # 6. verdicts
    # ------------------------------------------------------------------
    status = jnp.full((N,), int(TokenStatus.FAIL), jnp.int8)
    status = jnp.where(no_rule, int(TokenStatus.NO_RULE_EXISTS), status)
    status = jnp.where(too_many, int(TokenStatus.TOO_MANY_REQUEST), status)
    status = jnp.where(hard_block, int(TokenStatus.BLOCKED), status)
    status = jnp.where(can_occupy, int(TokenStatus.SHOULD_WAIT), status)
    status = jnp.where(admit, int(TokenStatus.OK), status)

    wait_ms = jnp.where(can_occupy, wait_next, 0).astype(jnp.int32)
    remaining = jnp.clip(
        threshold - passed - admitted_prefix - jnp.where(admit, acquire_f, 0.0),
        0.0,
        2**30,
    ).astype(jnp.int32)
    # blockedResult() in the reference always carries remaining=0
    remaining = jnp.where(admit, remaining, 0)

    new_state = EngineState(flow=flow_ws, occupy=occupy_ws, ns=ns_ws)
    verdicts = VerdictBatch(status=status, wait_ms=wait_ms, remaining=remaining)
    return new_state, verdicts
