"""Hot-parameter counting as a windowed count-min sketch.

The reference bounds per-value cardinality with LRU maps — 4,000 values per
bucket / 200k per resource (``ParameterMetric.java:37-39``,
``ClusterParamMetric.java:37``) — which *undercounts* evicted keys. The TPU
build replaces LRU truncation with a count-min sketch per (rule, time
bucket): fixed memory, vectorized, and it *over*-estimates (CMS guarantee) —
the safe direction for rate limiting. The documented drift (SURVEY.md §7):
a value sharing all ``depth`` cells with heavy hitters may be throttled
early; width/depth trade that probability.

Shapes: ``counts[P, B, depth, width]`` int32 — P param-rule slots, B time
buckets with the same ring/mask-on-read discipline as ``stats.window``.
Hash *indices* are computed host-side from the application's stable 64-bit
value hash (values never cross the wire — only hashes, see
``cluster.protocol``), so the device kernel is pure gather/scatter/min.
"""

from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Mixing constants for the host-side index derivation (splitmix64 finalizer
# per depth lane — public-domain construction).
_MIX = np.uint64(0x9E3779B97F4A7C15)
_FIN1 = np.uint64(0xBF58476D1CE4E5B9)
_FIN2 = np.uint64(0x94D049BB133111EB)


def hash_indices(
    value_hashes: np.ndarray, depth: int, width: int, salt: int = 0
) -> np.ndarray:
    """``[N] int64 -> [N, depth] int32`` CMS cell indices (host, vectorized).

    One broadcast over a ``[depth]`` lane-constant vector — this runs on the
    host for every param batch, so no per-depth Python loop. ``salt`` offsets
    the lane constants so an auxiliary sketch (the SF slim twin) draws its
    lanes from a disjoint part of the splitmix sequence; ``salt=0`` is
    byte-identical to the original per-depth loop.
    """
    h = value_hashes.astype(np.uint64)
    with np.errstate(over="ignore"):
        lane = np.arange(salt + 1, salt + depth + 1, dtype=np.uint64) * _MIX
        x = h[:, None] + lane[None, :]
        x = (x ^ (x >> np.uint64(30))) * _FIN1
        x = (x ^ (x >> np.uint64(27))) * _FIN2
        x = x ^ (x >> np.uint64(31))
        return (x % np.uint64(width)).astype(np.int32)


class ParamConfig(NamedTuple):
    max_param_rules: int = 256  # P
    depth: int = 2
    width: int = 2048
    bucket_ms: int = 500
    n_buckets: int = 2  # 1s sliding window like the local second-level
    # "jax" = pure-XLA path below; "pallas" = ops/cms_pallas.py kernel
    # (interpret mode off-TPU); "auto" = measured selection. Off-TPU,
    # "auto" resolves straight to "jax" — BENCH_r05 measured the
    # interpret-mode pallas step ~50× slower (76.7ms vs 1.54ms) — and on
    # TPU it micro-probes both kernels once per process, so production
    # never runs a kernel that was never timed on its own backend (the
    # VERDICT r4 concern about a blind selector). SENTINEL_PARAM_IMPL=
    # jax|pallas overrides the probe for deployments that pin a choice.
    impl: str = "auto"
    # "cms" = plain int32 count-min (the seed); "salsa" = self-adjusting
    # int16 counters (sketch/salsa.py, arXiv:2102.12531): 2× the cells at
    # the same HBM bytes, neighboring cells merging into double-width
    # logical counters on saturation.
    sketch: str = "cms"
    # SF-sketch slim twin geometry (sketch/slim.py, arXiv:1701.04148):
    # updates go to the fat sketch above, a [P, B, slim_depth, slim_width]
    # int32 twin is maintained incrementally and is what replication deltas
    # ship. slim_width=0 disables the twin (deltas ship fat rows).
    slim_depth: int = 2
    slim_width: int = 256

    @property
    def interval_ms(self) -> int:
        return self.bucket_ms * self.n_buckets

    @property
    def cell_width(self) -> int:
        """Host hash width: SALSA packs 2× int16 cells into the int32
        footprint, so its index space is ``2*width``."""
        return self.width * (2 if self.sketch == "salsa" else 1)

    @property
    def slim_enabled(self) -> bool:
        return self.slim_depth > 0 and self.slim_width > 0


class ParamState(NamedTuple):
    starts: jax.Array  # [B] int32 engine-ms (shared ring, as stats.window)
    counts: jax.Array  # fat: [P, B, depth, width] int32 (cms)
    #                        [P, B, depth, 2*width] int16 (salsa)
    slim: jax.Array  # [P, B, slim_depth, slim_width] int32 SF slim twin
    slim_auth: jax.Array  # [B] bool — buckets whose slim rows arrived via a
    #     replication delta and must contribute to estimates (standby only;
    #     cleared as buckets rotate, so a promoted standby converges to
    #     fat-only serving within one window)
    merges: jax.Array  # [P] int32 cumulative SALSA pair merges (metrics)


NEVER = jnp.int32(-(2**30))


def make_param_state(config: ParamConfig) -> ParamState:
    P, B = config.max_param_rules, config.n_buckets
    fat_dtype = jnp.int16 if config.sketch == "salsa" else jnp.int32
    return ParamState(
        starts=jnp.full((B,), NEVER, jnp.int32),
        counts=jnp.zeros((P, B, config.depth, config.cell_width), fat_dtype),
        slim=jnp.zeros((P, B, config.slim_depth, config.slim_width),
                       jnp.int32),
        slim_auth=jnp.zeros((B,), bool),
        merges=jnp.zeros((P,), jnp.int32),
    )


def param_decide(
    config: ParamConfig,
    state: ParamState,
    rule_slot: jax.Array,
    idx: jax.Array,
    acquire: jax.Array,
    threshold: jax.Array,
    valid: jax.Array,
    now: jax.Array,
    idx_slim: jax.Array = None,
) -> Tuple[ParamState, jax.Array, jax.Array]:
    """Dispatch on ``config.sketch`` × ``config.impl``.

    The fat-sketch cores share one contract (see :func:`_param_decide_jax`);
    the SF slim twin is composed *around* whichever core runs, in three
    steps that keep every kernel slim-agnostic: (1) roll the slim ring and
    compute the per-request slim estimate over delta-authoritative buckets,
    (2) run the core with the threshold reduced by that estimate (identical
    admissions to adding it to the fat estimate), (3) scatter-max the
    post-update fat current-bucket estimate into the slim twin. Callers
    that pass ``idx_slim=None`` (probes, micro-benchmarks) skip the twin
    entirely — on a primary the twin is then simply not maintained.
    """
    impl = resolve_param_impl(config.impl)
    if config.sketch == "salsa":
        from sentinel_tpu.sketch.salsa import (
            salsa_decide_jax,
            salsa_decide_pallas,
        )

        core = salsa_decide_pallas if impl == "pallas" else salsa_decide_jax
    elif config.sketch == "cms":
        core = _param_decide_pallas if impl == "pallas" else _param_decide_jax
    else:
        raise ValueError(
            f"unknown param sketch {config.sketch!r}; use 'cms'|'salsa'"
        )
    if idx_slim is None or not config.slim_enabled:
        return core(config, state, rule_slot, idx, acquire, threshold, valid,
                    now)
    from sentinel_tpu.sketch.slim import slim_poststep, slim_prestep

    slim, slim_auth, est_slim = slim_prestep(
        config, state, rule_slot, idx_slim, now
    )
    state = state._replace(slim=slim, slim_auth=slim_auth)
    thr = jnp.asarray(threshold, jnp.float32) - est_slim.astype(jnp.float32)
    state2, admit, est_fat = core(
        config, state, rule_slot, idx, acquire, thr, valid, now
    )
    slim2 = slim_poststep(config, state2, rule_slot, idx, idx_slim, valid, now)
    return state2._replace(slim=slim2), admit, est_fat + est_slim


_AUTO_IMPL: dict = {}  # backend platform → probed choice (process-cached)


def resolve_param_impl(impl: str) -> str:
    """Resolve ``impl`` to a concrete kernel ("jax" | "pallas").

    "auto" picks per platform: the ``SENTINEL_PARAM_IMPL`` env var wins if
    set; off-TPU the XLA path is chosen outright (BENCH_r05: interpret-mode
    pallas is ~50× slower there); on TPU both kernels are micro-probed once
    per process and the faster one is cached. A pallas kernel that fails to
    compile (Mosaic version skew) simply loses the probe.
    """
    if impl in ("jax", "pallas"):
        return impl
    if impl != "auto":
        raise ValueError(
            f"unknown param impl {impl!r}; use 'auto'|'jax'|'pallas'"
        )
    env = os.environ.get("SENTINEL_PARAM_IMPL", "").strip().lower()
    if env in ("jax", "pallas"):
        return env
    platform = jax.default_backend()
    choice = _AUTO_IMPL.get(platform)
    if choice is None:
        choice = "jax" if platform != "tpu" else _probe_param_impl()
        _AUTO_IMPL[platform] = choice
    return choice


def _probe_param_impl() -> str:
    """Time one warm step of each kernel on the live backend (small probe
    shapes — the comparison is kernel-vs-kernel, not absolute)."""
    import time as _time

    cfg = ParamConfig(impl="jax")
    state = make_param_state(cfg)
    n = 8
    args = (
        jnp.zeros(n, jnp.int32),
        jnp.zeros((n, cfg.depth), jnp.int32),
        jnp.ones(n, jnp.int32),
        jnp.full(n, 1e9, jnp.float32),
        jnp.zeros(n, bool),  # nothing valid → probe leaves state unchanged
        jnp.int32(0),
    )
    best_dt = None
    choice = "jax"
    for name, fn in (("jax", _param_decide_jax),
                     ("pallas", _param_decide_pallas)):
        try:
            _, ok, _ = fn(cfg, state, *args)  # compile + warm
            jax.block_until_ready(ok)
            t0 = _time.perf_counter()
            for _ in range(3):
                _, ok, _ = fn(cfg, state, *args)
            jax.block_until_ready(ok)
            dt = _time.perf_counter() - t0
        except Exception:
            continue  # kernel unusable on this backend: the other wins
        if best_dt is None or dt < best_dt:
            best_dt, choice = dt, name
    return choice


@partial(jax.jit, static_argnames=("config",))
def _param_decide_pallas(
    config: ParamConfig,
    state: ParamState,
    rule_slot: jax.Array,
    idx: jax.Array,
    acquire: jax.Array,
    threshold: jax.Array,
    valid: jax.Array,
    now: jax.Array,
) -> Tuple[ParamState, jax.Array, jax.Array]:
    """Same contract as :func:`_param_decide_jax`, via the VMEM-resident
    one-hot-matmul kernel (``ops/cms_pallas.py``). The kernel's plane-major
    layout ``[B*D, P, W]`` is converted at the boundary."""
    from sentinel_tpu.ops.cms_pallas import cms_decide_update_pallas

    P, B, D, W = (
        config.max_param_rules,
        config.n_buckets,
        config.depth,
        config.width,
    )
    planes = jnp.transpose(state.counts, (1, 2, 0, 3)).reshape(B * D, P, W)
    planes, starts, admit, est = cms_decide_update_pallas(
        planes,
        state.starts,
        rule_slot,
        idx,
        acquire,
        threshold,
        valid,
        now,
        P=P,
        B=B,
        D=D,
        W=W,
        bucket_ms=config.bucket_ms,
        interpret=jax.default_backend() != "tpu",
    )
    counts = jnp.transpose(planes.reshape(B, D, P, W), (2, 0, 1, 3))
    return state._replace(starts=starts, counts=counts), admit, est


@partial(jax.jit, static_argnames=("config",))
def _param_decide_jax(
    config: ParamConfig,
    state: ParamState,
    rule_slot: jax.Array,  # [N] int32, -1 → no rule
    idx: jax.Array,  # [N, depth] int32 CMS cell indices
    acquire: jax.Array,  # [N] int32
    threshold: jax.Array,  # [N] float32 (rule count or per-item override)
    valid: jax.Array,  # [N] bool
    now: jax.Array,
) -> Tuple[ParamState, jax.Array, jax.Array]:
    """``-> (state', admit[N] bool, estimate[N] int32)``.

    Mirrors the cluster param checker (``ClusterParamFlowChecker.java:42-96``:
    sum per-value across buckets vs threshold) with CMS estimates and the
    same in-batch prefix discipline as the flow kernel: requests on the same
    (rule, value) are admitted in order against the shared budget. The
    prefix key uses the full index tuple so distinct values never couple
    unless they collide in *every* lane (exactly the CMS overestimate case).
    """
    now = jnp.asarray(now, jnp.int32)
    B = config.n_buckets
    cur_idx = (now // config.bucket_ms) % B
    cur_start = now - now % config.bucket_ms

    # roll current bucket (shared-ring lazy reset, as stats.window.roll)
    stale = state.starts[cur_idx] != cur_start
    counts = jnp.where(
        (jnp.arange(B)[None, :, None, None] == cur_idx) & stale,
        0,
        state.counts,
    )
    starts = state.starts.at[cur_idx].set(cur_start)

    age = now - starts
    bucket_ok = (age >= 0) & (age < config.interval_ms)  # [B]

    safe_slot = jnp.where(rule_slot >= 0, rule_slot, 0)
    live = valid & (rule_slot >= 0)

    # estimate = min over depth of windowed sums  [N]
    d_ar = jnp.arange(config.depth)[None, :]  # [1, D]

    def gather_sum(b):
        # counts[safe_slot, b, d, idx[:, d]] for each d → [N, D]
        per_d = counts[safe_slot[:, None], b, d_ar, idx]  # [N, D]
        return per_d * bucket_ok[b].astype(jnp.int32)

    sums = sum(gather_sum(b) for b in range(B))  # [N, D]
    estimate = jnp.min(sums, axis=1)  # [N]

    # in-batch prefix on the (slot, full index tuple) key — int32 wraparound
    # mix; a 32-bit key collision merely couples two values' in-batch budgets
    # conservatively (same direction as the CMS overestimate)
    from sentinel_tpu.engine.prefix import segment_prefix_builder

    key = safe_slot
    for d in range(config.depth):
        key = key * jnp.int32(-1640531527) + idx[:, d]  # 0x9E3779B9 mix
    seg_prefix = segment_prefix_builder(key, "sort")

    acq = acquire.astype(jnp.int32)
    admit = live
    for _ in range(3):  # odd refinement ⇒ never overshoot (see decide.py)
        contrib = jnp.where(admit, acq, 0)
        prefix = seg_prefix(contrib)
        admit = live & (
            estimate.astype(jnp.float32) + prefix + acq.astype(jnp.float32)
            <= threshold
        )

    # update: scatter admitted acquires into all depth lanes of current bucket
    upd_vals = jnp.where(admit, acq, 0)[:, None].repeat(config.depth, 1)
    counts = counts.at[
        safe_slot[:, None], cur_idx, d_ar, idx
    ].add(upd_vals, mode="drop")

    return state._replace(starts=starts, counts=counts), admit, estimate