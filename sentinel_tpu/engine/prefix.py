"""Exclusive segment-prefix-sum over batch order — shared by the flow and
param kernels (the in-batch "earlier same-key contributions" primitive).

Five implementations (measured on a v5e chip; all scan-free — cumulative
sums and maxes go through ``sentinel_tpu.ops.scan_mm`` blocked matmul /
reduce passes because XLA's 1-D scan lowering costs ~0.3ms at N=16k):

- ``matmul``: same-key strictly-lower mask @ contrib — one [N, N] masked
  matmul, nearly free on the MXU up to N≈4k but the mask materialization
  grows quadratically.
- ``sort``: one stable argsort per builder (shared by every call), then per
  call a gather + blocked cumsum + segment rebase + scatter-back. Stable
  sort preserves batch order within a segment, which greedy-admission
  semantics require.
- ``grouped``: the keys are already **grouped** (same-key rows contiguous —
  e.g. the host batcher sorted requests by flow slot); no device sort at
  all, just the cumsum + rebase. This is the serving fast path.
- ``pallas``: the tiled kernel in ``ops/prefix_pallas.py`` — same math as
  ``matmul`` but the [N, N] mask is built tile-by-tile in VMEM and never
  touches HBM (interpret mode off-TPU).

Contributions must be **non-negative** float32 (exact for counts < 2^24):
the segment rebase recovers each row's segment-head offset with a running
max over head-marked exclusive sums, which requires the exclusive sum to be
non-decreasing. Every caller feeds masked non-negative counts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sentinel_tpu.ops.scan_mm import blocked_cumsum, blocked_cummax

_IMPLS = ("matmul", "sort", "grouped", "pallas")


def _grouped_prefix(keys: jax.Array):
    """Prefix fn for keys whose equal values are contiguous in batch order."""
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), keys[1:] != keys[:-1]]
    )

    def prefix(c: jax.Array) -> jax.Array:
        c = c.astype(jnp.float32)
        incl = blocked_cumsum(c)
        excl = incl - c
        # exclusive sum at this row's segment head: heads carry their excl,
        # a running max propagates the latest head forward (valid because
        # contribs >= 0 keeps excl non-decreasing)
        base = blocked_cummax(jnp.where(seg_start, excl, -1.0))
        return excl - base

    return prefix


def segment_prefix_builder(keys: jax.Array, impl: str = "auto"):
    """Returns ``prefix(contrib)`` with
    ``prefix(contrib)[i] = sum(contrib[j] for j < i if keys[j] == keys[i])``.

    (The namespace axis uses an inline one-hot cumsum in ``decide`` instead
    of this builder — its one-hot matrix is reused for the guard-counter
    matvec, which a builder-shaped API can't share.)
    """
    n = keys.shape[0]
    if impl == "auto":
        impl = "matmul" if n <= 2048 else "sort"
    if impl not in _IMPLS:
        raise ValueError(
            f"unknown prefix_impl {impl!r}; use 'auto' or one of {_IMPLS}"
        )

    if impl == "grouped":
        return _grouped_prefix(keys)

    if impl == "pallas":
        from sentinel_tpu.ops.prefix_pallas import segment_prefix_pallas

        interpret = jax.default_backend() != "tpu"

        def prefix_pallas(contrib: jax.Array) -> jax.Array:
            return segment_prefix_pallas(keys, contrib, interpret=interpret)

        return prefix_pallas

    if impl == "matmul":
        i = jnp.arange(n)
        tri = i[:, None] > i[None, :]
        mat = ((keys[:, None] == keys[None, :]) & tri).astype(jnp.float32)

        def prefix_mat(contrib: jax.Array) -> jax.Array:
            return jnp.matmul(
                mat, contrib.astype(jnp.float32),
                precision=jax.lax.Precision.HIGHEST,  # exact integer counts
            )

        return prefix_mat

    # -- sort -------------------------------------------------------------
    # One argsort per builder, shared by every call (decide() makes up to 5
    # on one builder); the inverse permutation is a scatter of the identity,
    # not a second argsort.
    order = jnp.argsort(keys, stable=True)
    arange = jnp.arange(n)
    inv = jnp.zeros((n,), arange.dtype).at[order].set(arange)
    grouped = _grouped_prefix(keys[order])

    def prefix_sort(contrib: jax.Array) -> jax.Array:
        return grouped(contrib[order])[inv]

    return prefix_sort
