"""Exclusive segment-prefix-sum over batch order — shared by the flow and
param kernels (the in-batch "earlier same-key contributions" primitive).

Three implementations (measured on a v5e chip: the [N, N] masked matmul is
nearly free on the MXU up to N≈8k, sorts win beyond and avoid the [N, N]
materialization):

- ``matmul``: same-key strictly-lower mask @ contrib.
- ``sort``: stable argsort + cumsum + per-segment rebase; stable sort
  preserves batch order within a segment, which greedy-admission semantics
  require.
- ``pallas``: the tiled kernel in ``ops/prefix_pallas.py`` — same math as
  ``matmul`` but the [N, N] mask is built tile-by-tile in VMEM and never
  touches HBM (interpret mode off-TPU).

Contributions are float32 (exact for counts < 2^24).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def segment_prefix_builder(keys: jax.Array, impl: str = "auto"):
    """Returns ``prefix(contrib)`` with
    ``prefix(contrib)[i] = sum(contrib[j] for j < i if keys[j] == keys[i])``.
    """
    n = keys.shape[0]
    if impl == "auto":
        impl = "matmul" if n <= 8192 else "sort"
    if impl not in ("matmul", "sort", "pallas"):
        raise ValueError(
            f"unknown prefix_impl {impl!r}; use 'auto'|'matmul'|'sort'|'pallas'"
        )

    if impl == "pallas":
        from sentinel_tpu.ops.prefix_pallas import segment_prefix_pallas

        interpret = jax.default_backend() != "tpu"

        def prefix_pallas(contrib: jax.Array) -> jax.Array:
            return segment_prefix_pallas(keys, contrib, interpret=interpret)

        return prefix_pallas

    if impl == "matmul":
        i = jnp.arange(n)
        tri = i[:, None] > i[None, :]
        mat = ((keys[:, None] == keys[None, :]) & tri).astype(jnp.float32)

        def prefix_mat(contrib: jax.Array) -> jax.Array:
            return mat @ contrib.astype(jnp.float32)

        return prefix_mat

    order = jnp.argsort(keys, stable=True)
    keys_sorted = keys[order]
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), keys_sorted[1:] != keys_sorted[:-1]]
    )
    inv = jnp.argsort(order, stable=True)

    def prefix_sort(contrib: jax.Array) -> jax.Array:
        c = contrib[order].astype(jnp.float32)
        incl = jnp.cumsum(c)
        excl = incl - c
        base = jax.lax.cummax(jnp.where(seg_start, excl, -jnp.inf))
        return (excl - base)[inv]

    return prefix_sort
