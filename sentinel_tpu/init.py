"""One-shot environment init (analog of ``InitExecutor.doInit`` +
the transport/metric ``InitFunc`` set).

``init_default()`` starts, based on config:
- the HTTP command center (``CommandCenterInitFunc``)
- the heartbeat sender, if a dashboard address is configured
  (``HeartbeatSenderInitFunc``)
- the 1-second metric log aggregation (``MetricTimerListener`` scheduling —
  which the reference hangs off ``FlowRuleManager``'s static scheduler)

Returns the started components for lifecycle control. Python needs no
classpath magic, so this is an explicit call instead of a static block.
"""

from __future__ import annotations

import threading
from typing import Optional

from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.metrics.log import MetricTimer
from sentinel_tpu.transport.command import CommandCenter
from sentinel_tpu.transport.heartbeat import HeartbeatSender


class SentinelRuntime:
    def __init__(self, command_center=None, heartbeat=None, metric_timer=None):
        self.command_center: Optional[CommandCenter] = command_center
        self.heartbeat: Optional[HeartbeatSender] = heartbeat
        self.metric_timer: Optional[MetricTimer] = metric_timer

    def stop(self) -> None:
        if self.command_center is not None:
            self.command_center.stop()
        if self.heartbeat is not None:
            self.heartbeat.stop()
        if self.metric_timer is not None:
            self.metric_timer.stop()


_lock = threading.Lock()
_runtime: Optional[SentinelRuntime] = None


def init_default(
    command_port: Optional[int] = None,
    with_metric_log: bool = True,
) -> SentinelRuntime:
    """Idempotent: the first call wires the runtime, later calls return it."""
    global _runtime
    with _lock:
        if _runtime is not None:
            return _runtime
        port = (
            command_port
            if command_port is not None
            else SentinelConfig.get_int("csp.sentinel.api.port", 8719)
        )
        cc = CommandCenter(port=port).start()
        hb = HeartbeatSender(command_port=cc.port).start()
        mt = MetricTimer().start() if with_metric_log else None
        _runtime = SentinelRuntime(cc, hb, mt)
        return _runtime


def shutdown() -> None:
    global _runtime
    with _lock:
        if _runtime is not None:
            _runtime.stop()
            _runtime = None
