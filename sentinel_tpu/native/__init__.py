"""ctypes bindings for the native host runtime (``native/src/``).

The native library re-implements the host-side per-call hot paths — sliding
windows, token buckets, leaky-bucket pacers — as lock-free C++ (the analog
of the reference's LongAdder/CAS machinery; see
``native/src/sentinel_native.cpp``). It is optional: every consumer has a
pure-Python/numpy fallback with identical semantics, enforced by parity
tests (``tests/test_native.py``).

Build with ``make -C native`` or ``python -m sentinel_tpu.native.build``.
"""

from sentinel_tpu.native.lib import (
    NativePacerArray,
    NativeTokenBuckets,
    NativeWindow,
    available,
    load,
)

__all__ = [
    "available",
    "load",
    "NativeWindow",
    "NativeTokenBuckets",
    "NativePacerArray",
]
