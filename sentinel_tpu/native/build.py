"""Build the native runtime: ``python -m sentinel_tpu.native.build``.

Compiles ``native/src/*.cpp`` into
``sentinel_tpu/native/_sentinel_native.so`` with the ambient C++ compiler.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
SOURCES = [
    os.path.join(_REPO, "native", "src", "sentinel_native.cpp"),
    os.path.join(_REPO, "native", "src", "sentinel_frontdoor.cpp"),
    os.path.join(_REPO, "native", "src", "sentinel_shm.cpp"),
]
OUTPUT = os.path.join(_HERE, "_sentinel_native.so")


def build(verbose: bool = True) -> str:
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        raise RuntimeError("no C++ compiler found (need g++ or c++ on PATH)")
    # compile to a temp name, then atomically rename: a concurrent loader
    # must never CDLL a half-written library, and an interrupted compile
    # must not leave a corrupt artifact that pins every later run to the
    # pure-Python fallback
    tmp = f"{OUTPUT}.build-{os.getpid()}"
    cmd = [
        cxx,
        "-O3",
        "-std=c++17",
        "-fPIC",
        "-Wall",
        "-Wextra",
        "-shared",
        "-pthread",
        "-o",
        tmp,
        *SOURCES,
    ]
    if verbose:
        print("+", " ".join(cmd), file=sys.stderr)
    try:
        # quiet mode captures compiler chatter: the lazy autobuild promises
        # to degrade silently, so -Wall noise must not hit the host app's
        # stderr (the output is surfaced in the raised error on failure)
        subprocess.run(
            cmd, check=True,
            capture_output=not verbose, text=True,
        )
        os.replace(tmp, OUTPUT)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return OUTPUT


if __name__ == "__main__":
    print(build())
