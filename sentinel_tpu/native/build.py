"""Build the native runtime: ``python -m sentinel_tpu.native.build``.

Compiles ``native/src/*.cpp`` into
``sentinel_tpu/native/_sentinel_native.so`` with the ambient C++ compiler.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
SOURCES = [
    os.path.join(_REPO, "native", "src", "sentinel_native.cpp"),
    os.path.join(_REPO, "native", "src", "sentinel_frontdoor.cpp"),
]
OUTPUT = os.path.join(_HERE, "_sentinel_native.so")


def build(verbose: bool = True) -> str:
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        raise RuntimeError("no C++ compiler found (need g++ or c++ on PATH)")
    cmd = [
        cxx,
        "-O3",
        "-std=c++17",
        "-fPIC",
        "-Wall",
        "-Wextra",
        "-shared",
        "-pthread",
        "-o",
        OUTPUT,
        *SOURCES,
    ]
    if verbose:
        print("+", " ".join(cmd), file=sys.stderr)
    subprocess.run(cmd, check=True)
    return OUTPUT


if __name__ == "__main__":
    print(build())
