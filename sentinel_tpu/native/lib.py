"""ctypes loader + thin object wrappers over the native C API.

ctypes releases the GIL around every call, so under free-threaded Python the
native windows scale across threads the way the reference's LongAdders do —
the Python fallbacks serialize on the owning node's lock instead.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
# SENTINEL_NATIVE_SO overrides the library path — the ASan fuzz harness
# (`make -C native asan-check`) points it at the sanitizer build
_SO_PATH = os.environ.get(
    "SENTINEL_NATIVE_SO", os.path.join(_HERE, "_sentinel_native.so")
)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    P, I32, I64, F64 = (
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.c_double,
    )
    sig = {
        "sn_window_create": ([I32, I32, I32], P),
        "sn_window_destroy": ([P], None),
        "sn_window_add": ([P, I64, I32, F64], None),
        "sn_window_sum": ([P, I64, I32], F64),
        "sn_window_snapshot": ([P, I64, ctypes.POINTER(F64)], None),
        "sn_window_prev_bucket": ([P, I64, I32], F64),
        "sn_window_min_ratio": ([P, I64, I32, I32], F64),
        "sn_window_start_at": ([P, I32], I64),
        "sn_window_count_at": ([P, I32, I32], F64),
        "sn_window_add_future": ([P, I64, I32, F64], None),
        "sn_window_future_waiting": ([P, I64, I32], F64),
        "sn_window_take_matured": ([P, I64, I32], F64),
        "sn_stat_pass": ([P, P, P, I64, F64], None),
        "sn_stat_event": ([P, P, I64, I32, F64], None),
        "sn_stat_rt_success": ([P, P, I64, F64, F64], None),
        "sn_stat_touched_sum": ([P, P, P, I64, I32], F64),
        "sn_tb_create": ([I32], P),
        "sn_tb_destroy": ([P], None),
        "sn_tb_reset": ([P, I32], None),
        "sn_tb_try_acquire": ([P, I32, I64, I32, F64, F64, I64], I32),
        "sn_pacer_create": ([I32], P),
        "sn_pacer_destroy": ([P], None),
        "sn_pacer_reset": ([P, I32], None),
        "sn_pacer_try_pass": ([P, I32, I64, I32, F64, I64], I64),
        "sn_batch_decode_req": (
            [
                ctypes.c_char_p, I32, ctypes.POINTER(I32),
                ctypes.POINTER(I64), ctypes.POINTER(I32),
                ctypes.POINTER(ctypes.c_uint8), I32,
            ],
            I32,
        ),
        "sn_batch_encode_rsp": (
            [
                I32, I32, ctypes.POINTER(ctypes.c_int8),
                ctypes.POINTER(I32), ctypes.POINTER(I32),
                ctypes.POINTER(ctypes.c_uint8), I32,
            ],
            I32,
        ),
        # native TCP front door (sentinel_frontdoor.cpp)
        "sn_fd_create": ([ctypes.c_char_p, I32, I32], P),
        "sn_fd_port": ([P], I32),
        "sn_fd_stop": ([P], None),
        "sn_fd_destroy": ([P], None),
        "sn_fd_wait_batch": (
            [
                P, I32, ctypes.POINTER(I64), ctypes.POINTER(I32),
                ctypes.POINTER(ctypes.c_uint8), I32, ctypes.POINTER(I32),
                ctypes.POINTER(I32), ctypes.POINTER(I32),
                ctypes.POINTER(I32), ctypes.POINTER(ctypes.c_uint8), I32,
                ctypes.POINTER(I32),
            ],
            I32,
        ),
        "sn_fd_submit": (
            [
                P, I32, ctypes.POINTER(I32), ctypes.POINTER(I32),
                ctypes.POINTER(I32), ctypes.POINTER(I32),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int8), ctypes.POINTER(I32),
                ctypes.POINTER(I32),
            ],
            None,
        ),
        "sn_fd_send": ([P, I32, I32, ctypes.c_char_p, I32], None),
        "sn_fd_next_control": (
            [
                P, ctypes.POINTER(I32), ctypes.POINTER(I32),
                ctypes.POINTER(ctypes.c_uint8), I32, ctypes.POINTER(I32),
            ],
            I32,
        ),
        "sn_fd_stats": ([P, ctypes.POINTER(ctypes.c_uint64)], None),
        "sn_fd_set_idle_ttl": ([P, I64], None),
        "sn_fd_close_conn": ([P, I32, I32], None),
    }
    for name, (argtypes, restype) in sig.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    # shared-memory ring door (sentinel_shm.cpp) — resolved defensively so
    # a stale .so built before these exports existed still loads (the TCP
    # door and kernels keep working; ShmDoor raises with a rebuild hint)
    shm_sig = {
        "sn_shm_create": ([ctypes.c_char_p, I64, I32], P),
        "sn_shm_stop": ([P], None),
        "sn_shm_destroy": ([P], None),
        "sn_shm_wait_batch": (
            [
                P, I32, ctypes.POINTER(I64), ctypes.POINTER(I32),
                ctypes.POINTER(ctypes.c_uint8), I32, ctypes.POINTER(I32),
                ctypes.POINTER(I32), ctypes.POINTER(I32),
                ctypes.POINTER(I32), ctypes.POINTER(ctypes.c_uint8), I32,
                ctypes.POINTER(I32),
            ],
            I32,
        ),
        "sn_shm_submit": (
            [
                P, I32, ctypes.POINTER(I32), ctypes.POINTER(I32),
                ctypes.POINTER(I32), ctypes.POINTER(I32),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int8), ctypes.POINTER(I32),
                ctypes.POINTER(I32),
            ],
            None,
        ),
        "sn_shm_send": ([P, I32, I32, ctypes.c_char_p, I32], None),
        "sn_shm_next_control": (
            [
                P, ctypes.POINTER(I32), ctypes.POINTER(I32),
                ctypes.POINTER(ctypes.c_uint8), I32, ctypes.POINTER(I32),
            ],
            I32,
        ),
        "sn_shm_close_conn": ([P, I32, I32], None),
        "sn_shm_stats": ([P, ctypes.POINTER(ctypes.c_uint64)], None),
        "sn_shm_echo_start": ([P], None),
        "sn_shm_echo_stop": ([P], None),
        # TCP-door echo mirror, shipped in the same rebuild as the shm
        # exports — resolved in this defensive block for the same reason
        "sn_fd_echo_start": ([P], None),
        "sn_fd_echo_stop": ([P], None),
        "sn_shm_client_create": ([ctypes.c_char_p, I32, I32, I32], P),
        "sn_shm_client_destroy": ([P], None),
        "sn_shm_client_send": ([P, ctypes.c_char_p, I32], I32),
        "sn_shm_client_recv": (
            [P, ctypes.POINTER(ctypes.c_uint8), I32, I32], I32
        ),
        "sn_shm_client_rtt": (
            [P, ctypes.c_char_p, I32, I32, ctypes.POINTER(I64)], I32
        ),
        "sn_shm_client_fuzz": ([P, ctypes.c_char_p, I32, I32], I32),
        "sn_shm_client_alive": ([P], I32),
    }
    try:
        for name, (argtypes, restype) in shm_sig.items():
            fn = getattr(lib, name)
            fn.argtypes = argtypes
            fn.restype = restype
        lib._sn_has_shm = True
    except AttributeError:
        lib._sn_has_shm = False
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Load (once) the native library; on a fresh checkout, build it first.

    The ``.so`` is a build artifact (gitignored), so first use on a clean
    tree compiles it with the ambient C++ toolchain (~seconds; same
    command as ``make -C native``). Failures degrade to the pure-Python
    paths exactly as a missing library always has. Set
    ``SENTINEL_NATIVE_AUTOBUILD=0`` to disable, or ``SENTINEL_NATIVE_SO``
    to point at a prebuilt library (never auto-built over).
    """
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_SO_PATH):
            if (
                "SENTINEL_NATIVE_SO" in os.environ
                or os.environ.get("SENTINEL_NATIVE_AUTOBUILD") == "0"
            ):
                _load_failed = True
                return None
            try:
                from sentinel_tpu.native.build import build

                build(verbose=False)
            except Exception:
                _load_failed = True
                return None
        if not os.path.exists(_SO_PATH):
            _load_failed = True
            return None
        try:
            _lib = _configure(ctypes.CDLL(_SO_PATH))
        except OSError:
            _load_failed = True
            return None
    return _lib


def available() -> bool:
    return load() is not None


def shm_available() -> bool:
    """True when the loaded .so exports the shared-memory ring door (a
    stale artifact from an older tree loads fine but lacks the exports —
    rebuild with ``python -m sentinel_tpu.native.build``)."""
    lib = load()
    return lib is not None and bool(getattr(lib, "_sn_has_shm", False))


def batch_decode_req(payload: bytes):
    """BATCH_FLOW request payload → (xid, flow_ids int64[N], counts int32[N],
    prios bool[N]); None when the native lib is absent; raises ValueError on
    a malformed frame (mirrors the numpy codec's behavior)."""
    lib = load()
    if lib is None:
        return None
    import numpy as np

    max_n = max((len(payload) - 7) // 13, 0)
    xid = ctypes.c_int32()
    flow_ids = np.empty(max_n, np.int64)
    counts = np.empty(max_n, np.int32)
    prios = np.empty(max_n, np.uint8)
    n = lib.sn_batch_decode_req(
        payload, len(payload), ctypes.byref(xid),
        flow_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        prios.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        max_n,
    )
    if n < 0:
        raise ValueError("malformed BATCH_FLOW frame")
    return (
        int(xid.value), flow_ids[:n], counts[:n], prios[:n].astype(bool)
    )


def batch_encode_rsp(xid: int, status, remaining, wait_ms):
    """(status int8[N], remaining int32[N], wait int32[N]) → full response
    frame bytes (length prefix included); None when the lib is absent."""
    lib = load()
    if lib is None:
        return None
    import numpy as np

    status = np.ascontiguousarray(status, np.int8)
    remaining = np.ascontiguousarray(remaining, np.int32)
    wait_ms = np.ascontiguousarray(wait_ms, np.int32)
    n = status.shape[0]
    out = np.empty(2 + 7 + n * 9, np.uint8)
    wrote = lib.sn_batch_encode_rsp(
        xid, n,
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        remaining.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        wait_ms.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.shape[0],
    )
    if wrote < 0:
        raise ValueError("batch too large for one frame")
    return out[:wrote].tobytes()


class NativeWindow:
    """Sliding window backed by the native lib — drop-in for
    ``local.stat.HostWindow`` plus the future/occupy ops."""

    __slots__ = ("_lib", "_h", "bucket_ms", "n_buckets", "n_channels",
                 "interval_ms")

    def __init__(self, bucket_ms: int, n_buckets: int, n_channels: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native library not built")
        self._lib = lib
        self._h = lib.sn_window_create(bucket_ms, n_buckets, n_channels)
        if not self._h:
            raise MemoryError("sn_window_create failed")
        self.bucket_ms = bucket_ms
        self.n_buckets = n_buckets
        self.n_channels = n_channels
        self.interval_ms = bucket_ms * n_buckets

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.sn_window_destroy(h)
            self._h = None

    def add(self, now: int, chan: int, n: float = 1.0) -> None:
        self._lib.sn_window_add(self._h, now, chan, n)

    def sum(self, now: int, chan: int) -> float:
        return self._lib.sn_window_sum(self._h, now, chan)

    def qps(self, now: int, chan: int) -> float:
        return self.sum(now, chan) * 1000.0 / self.interval_ms

    def snapshot(self, now: int) -> list:
        out = (ctypes.c_double * self.n_channels)()
        self._lib.sn_window_snapshot(self._h, now, out)
        return list(out)

    def previous_bucket(self, now: int, chan: int) -> float:
        return self._lib.sn_window_prev_bucket(self._h, now, chan)

    def min_ratio(self, now: int, num_chan: int, den_chan: int) -> float:
        return self._lib.sn_window_min_ratio(self._h, now, num_chan, den_chan)

    def start_at(self, b: int) -> int:
        return self._lib.sn_window_start_at(self._h, b)

    def count_at(self, b: int, chan: int) -> float:
        return self._lib.sn_window_count_at(self._h, b, chan)

    # future/occupy ops (FutureWindow analog; use a dedicated instance)
    def add_future(self, future_time: int, n: float, chan: int = 0) -> None:
        self._lib.sn_window_add_future(self._h, future_time, chan, n)

    def future_waiting(self, now: int, chan: int = 0) -> float:
        return self._lib.sn_window_future_waiting(self._h, now, chan)

    def take_matured(self, now: int, chan: int = 0) -> float:
        return self._lib.sn_window_take_matured(self._h, now, chan)


class NativeTokenBuckets:
    """Array of token buckets (hot-param local QPS mode)."""

    __slots__ = ("_lib", "_h", "n_slots")

    def __init__(self, n_slots: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native library not built")
        self._lib = lib
        self._h = lib.sn_tb_create(n_slots)
        if not self._h:
            raise MemoryError("sn_tb_create failed")
        self.n_slots = n_slots

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.sn_tb_destroy(h)
            self._h = None

    def reset(self, slot: int) -> None:
        self._lib.sn_tb_reset(self._h, slot)

    def try_acquire(
        self,
        slot: int,
        now: int,
        acquire: int,
        count: float,
        burst: float,
        interval_ms: int,
    ) -> bool:
        return bool(
            self._lib.sn_tb_try_acquire(
                self._h, slot, now, acquire, count, burst, interval_ms
            )
        )


class NativePacerArray:
    """Array of leaky-bucket pacers (RateLimiter behavior)."""

    __slots__ = ("_lib", "_h", "n_slots")

    def __init__(self, n_slots: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native library not built")
        self._lib = lib
        self._h = lib.sn_pacer_create(n_slots)
        if not self._h:
            raise MemoryError("sn_pacer_create failed")
        self.n_slots = n_slots

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.sn_pacer_destroy(h)
            self._h = None

    def reset(self, slot: int) -> None:
        self._lib.sn_pacer_reset(self._h, slot)

    def try_pass(
        self,
        slot: int,
        now: int,
        acquire: int,
        count_per_sec: float,
        max_queue_ms: int,
    ) -> int:
        """wait-ms to sleep (0 = immediate) or -1 = block."""
        return int(
            self._lib.sn_pacer_try_pass(
                self._h, slot, now, acquire, count_per_sec, max_queue_ms
            )
        )


class Frontdoor:
    """The native epoll TCP front door (``sentinel_frontdoor.cpp``).

    One IO thread owns sockets, framing, decode, and response writes; Python
    pulls whole request batches with :meth:`wait_batch` (GIL released while
    blocked), runs the device step, and answers with :meth:`submit`.
    Control-plane frames (PING, param, concurrent) surface through
    :meth:`next_control`; replies go back via :meth:`send`.
    """

    CTRL_FRAME, CTRL_OPEN, CTRL_CLOSE = 0, 1, 2

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 arena_cap: int = 65536):
        import numpy as np

        lib = load()
        if lib is None:
            raise RuntimeError("native library not built")
        self._lib = lib
        # the arena must fit at least one max-size frame or a full frame
        # could never be admitted and its connection would park forever
        # (MAX_BATCH_PER_FRAME is derived from the wire layout in
        # protocol.py, the single source of truth the C++ codec mirrors)
        from sentinel_tpu.cluster.protocol import MAX_BATCH_PER_FRAME

        arena_cap = max(arena_cap, MAX_BATCH_PER_FRAME)
        # the C side binds with inet_addr (IPv4 literals only) — resolve
        # names like "localhost" here so the API matches the asyncio server
        if host:
            import socket as _socket

            host = _socket.gethostbyname(host)
        self._h = lib.sn_fd_create(host.encode(), port, arena_cap)
        if not self._h:
            raise OSError(f"native front door failed to bind {host}:{port}")
        self.port = int(lib.sn_fd_port(self._h))
        self.arena_cap = arena_cap
        # batch buffers are per-THREAD (threading.local): multiple
        # dispatcher threads may call wait_batch concurrently, and each
        # result stays valid until that same thread's next call
        self._tls = threading.local()
        self._ctrl_buf = ctypes.create_string_buffer(70000)
        self._ctrl_lock = threading.Lock()
        self._stopped = False

    def _bufs(self):
        import numpy as np

        b = getattr(self._tls, "bufs", None)
        if b is None:
            cap = self.arena_cap
            b = dict(
                ids=np.empty(cap, np.int64),
                counts=np.empty(cap, np.int32),
                prios=np.empty(cap, np.uint8),
                f_fd=np.empty(cap, np.int32),
                f_gen=np.empty(cap, np.int32),
                f_xid=np.empty(cap, np.int32),
                f_n=np.empty(cap, np.int32),
                f_type=np.empty(cap, np.uint8),
            )
            self._tls.bufs = b
        return b

    def _ptr(self, arr, ctype):
        return arr.ctypes.data_as(ctypes.POINTER(ctype))

    def wait_batch(self, timeout_ms: int = 100, max_n: Optional[int] = None):
        """Block for data-plane requests. Returns ``None`` on timeout, else
        ``(ids, counts, prios, frames)`` where the first three are int64/
        int32/bool views in request order and ``frames`` is the opaque
        per-frame metadata to hand back to :meth:`submit`. ``max_n`` bounds
        one pull (whole frames only, so it is clamped to at least one
        max-size frame); the remainder stays queued for the next pull."""
        if max_n is None:
            max_n = self.arena_cap
        from sentinel_tpu.cluster.protocol import MAX_BATCH_PER_FRAME

        max_n = min(max(int(max_n), MAX_BATCH_PER_FRAME), self.arena_cap)
        b = self._bufs()
        n_frames = ctypes.c_int32()
        n = self._lib.sn_fd_wait_batch(
            self._h, timeout_ms,
            self._ptr(b["ids"], ctypes.c_int64),
            self._ptr(b["counts"], ctypes.c_int32),
            self._ptr(b["prios"], ctypes.c_uint8),
            max_n,
            self._ptr(b["f_fd"], ctypes.c_int32),
            self._ptr(b["f_gen"], ctypes.c_int32),
            self._ptr(b["f_xid"], ctypes.c_int32),
            self._ptr(b["f_n"], ctypes.c_int32),
            self._ptr(b["f_type"], ctypes.c_uint8),
            self.arena_cap, ctypes.byref(n_frames),
        )
        if n <= 0:
            return None
        k = n_frames.value
        frames = (
            b["f_fd"][:k], b["f_gen"][:k], b["f_xid"][:k], b["f_n"][:k],
            b["f_type"][:k],
        )
        return (
            b["ids"][:n], b["counts"][:n],
            b["prios"][:n].astype(bool), frames,
        )

    def wait_batch_into(self, staging: dict, timeout_ms: int = 100,
                        max_n: Optional[int] = None):
        """:meth:`wait_batch`, but decoded rows land directly in the
        caller's ``staging`` arrays (same keys/dtypes as :meth:`_bufs`)
        instead of thread-local buffers — the zero-copy intake path: the
        IO thread's arena is memcpy'd once into a recycled staging block
        and never touched by the allocator again. Returns ``None`` on
        timeout, else ``(n, k)`` row/frame counts; the caller owns slicing
        views out of ``staging`` and keeping the block alive until the
        verdicts for those rows have been submitted. ``max_n`` additionally
        clamps to the staging row capacity, and the frame-array length
        bounds how many frames one pull may take (the remainder stays
        queued)."""
        from sentinel_tpu.cluster.protocol import MAX_BATCH_PER_FRAME

        cap = int(staging["ids"].shape[0])
        max_f = int(staging["f_fd"].shape[0])
        if max_n is None:
            max_n = cap
        max_n = min(
            max(int(max_n), MAX_BATCH_PER_FRAME), cap, self.arena_cap
        )
        n_frames = ctypes.c_int32()
        n = self._lib.sn_fd_wait_batch(
            self._h, timeout_ms,
            self._ptr(staging["ids"], ctypes.c_int64),
            self._ptr(staging["counts"], ctypes.c_int32),
            self._ptr(staging["prios"], ctypes.c_uint8),
            max_n,
            self._ptr(staging["f_fd"], ctypes.c_int32),
            self._ptr(staging["f_gen"], ctypes.c_int32),
            self._ptr(staging["f_xid"], ctypes.c_int32),
            self._ptr(staging["f_n"], ctypes.c_int32),
            self._ptr(staging["f_type"], ctypes.c_uint8),
            max_f, ctypes.byref(n_frames),
        )
        if n <= 0:
            return None
        return n, n_frames.value

    def submit(self, frames, status, remaining, wait_ms) -> None:
        """Encode + send verdict frames for a ``wait_batch`` result."""
        import numpy as np

        # every array binds to a local: an unnamed ascontiguousarray copy
        # would be freed the moment _ptr() returns, leaving sn_fd_submit
        # reading freed memory whenever a caller passes a non-contiguous
        # or wrongly-typed array
        f_fd, f_gen, f_xid, f_n, f_type = frames
        f_fd = np.ascontiguousarray(f_fd, np.int32)
        f_gen = np.ascontiguousarray(f_gen, np.int32)
        f_xid = np.ascontiguousarray(f_xid, np.int32)
        f_n = np.ascontiguousarray(f_n, np.int32)
        f_type = np.ascontiguousarray(f_type, np.uint8)
        status = np.ascontiguousarray(status, np.int8)
        remaining = np.ascontiguousarray(remaining, np.int32)
        wait_ms = np.ascontiguousarray(wait_ms, np.int32)
        self._lib.sn_fd_submit(
            self._h, len(f_fd),
            self._ptr(f_fd, ctypes.c_int32),
            self._ptr(f_gen, ctypes.c_int32),
            self._ptr(f_xid, ctypes.c_int32),
            self._ptr(f_n, ctypes.c_int32),
            self._ptr(f_type, ctypes.c_uint8),
            self._ptr(status, ctypes.c_int8),
            self._ptr(remaining, ctypes.c_int32),
            self._ptr(wait_ms, ctypes.c_int32),
        )

    def submit_many(self, frames_list, status, remaining, wait_ms) -> None:
        """Answer SEVERAL ``wait_batch`` pulls with one native call.

        ``frames_list`` holds each pull's frame-metadata tuple, in the same
        order their requests are concatenated in the verdict arrays. One
        ``sn_fd_submit`` call means one outbox lock acquisition and one IO
        wakeup for the whole fused group, and the C++ scatter encode can
        group consecutive same-connection frames ACROSS pull boundaries
        into single per-writer buffers."""
        import numpy as np

        if len(frames_list) == 1:
            return self.submit(frames_list[0], status, remaining, wait_ms)
        merged = tuple(
            np.concatenate([np.asarray(fr[i]) for fr in frames_list])
            for i in range(5)
        )
        self.submit(merged, status, remaining, wait_ms)

    def send(self, fd: int, gen: int, frame: bytes) -> None:
        self._lib.sn_fd_send(self._h, fd, gen, frame, len(frame))

    def set_idle_ttl(self, ttl_ms: int) -> None:
        """Enable the IO-thread idle sweep (0 disables)."""
        self._lib.sn_fd_set_idle_ttl(self._h, int(ttl_ms))

    def close_conn(self, fd: int, gen: int) -> None:
        self._lib.sn_fd_close_conn(self._h, fd, gen)

    def next_control(self):
        """``None`` or ``(kind, fd, gen, payload bytes)``."""
        fd = ctypes.c_int32()
        gen = ctypes.c_int32()
        ln = ctypes.c_int32()
        with self._ctrl_lock:
            kind = self._lib.sn_fd_next_control(
                self._h, ctypes.byref(fd), ctypes.byref(gen),
                ctypes.cast(self._ctrl_buf, ctypes.POINTER(ctypes.c_uint8)),
                len(self._ctrl_buf), ctypes.byref(ln),
            )
            if kind < 0:
                return None
            # string_at copies only the written bytes — .raw would build
            # the full 70KB buffer as bytes for every 7-byte PING
            payload = (
                ctypes.string_at(self._ctrl_buf, ln.value)
                if ln.value > 0 else b""
            )
        return kind, fd.value, gen.value, payload

    def stats(self):
        """Counters are independently monotonic (relaxed atomics read
        without a common lock): the dict is NOT a consistent cross-counter
        snapshot — e.g. ``frames_in`` may already include a frame whose
        rows are not yet in ``requests_in``. Consumers diffing two reads
        (bench occupancy math) must clamp derived deltas at zero."""
        import numpy as np

        out = np.zeros(4, np.uint64)
        self._lib.sn_fd_stats(
            self._h, self._ptr(out, ctypes.c_uint64)
        )
        return {
            "frames_in": int(out[0]), "requests_in": int(out[1]),
            "bytes_in": int(out[2]), "bytes_out": int(out[3]),
        }

    def echo_start(self) -> None:
        """Bench/test helper: a pure-C wait→all-GRANTED-submit loop — the
        TCP mirror of :meth:`ShmDoor.echo_start`, so both doors' transport
        host cost is measured behind an identical serving loop."""
        if not getattr(self._lib, "_sn_has_shm", False):
            raise RuntimeError(
                "native library predates the door echo exports — rebuild "
                "with `python -m sentinel_tpu.native.build`"
            )
        self._lib.sn_fd_echo_start(self._h)

    def echo_stop(self) -> None:
        if getattr(self._lib, "_sn_has_shm", False):
            self._lib.sn_fd_echo_stop(self._h)

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._lib.sn_fd_stop(self._h)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                self.stop()
            except Exception:
                pass
            self._lib.sn_fd_destroy(h)
            self._h = None


class ShmDoor:
    """The shared-memory ring front door (``sentinel_shm.cpp``).

    Same batch contract as :class:`Frontdoor` — ``wait_batch_into`` /
    ``submit`` / ``submit_many`` / ``next_control`` / ``send`` — so the
    server's intake, reply, and control lanes drive either door through
    one code path. The "fd" of a frame is the client segment id; replies
    are scatter-encoded straight into that client's response ring by the
    C side. A C++ poller thread (spin-then-sleep on a shared futex
    doorbell) replaces the epoll IO thread; co-located clients attach by
    dropping a segment file into ``shm_dir``.
    """

    CTRL_FRAME, CTRL_OPEN, CTRL_CLOSE = 0, 1, 2

    def __init__(self, shm_dir: str, arena_cap: int = 65536,
                 spin_us: Optional[int] = None):
        # Adaptive spin default: on a single-core host the spinner only
        # burns the peer's timeslice (measured: RTT ~= 2x the spin window),
        # so go straight to the futex; with spare cores a short spin dodges
        # the syscall entirely in the steady state.
        if spin_us is None:
            spin_us = 0 if (os.cpu_count() or 1) <= 1 else 100
        lib = load()
        if lib is None:
            raise RuntimeError("native library not built")
        if not getattr(lib, "_sn_has_shm", False):
            raise RuntimeError(
                "native library predates the shm door — rebuild with "
                "`python -m sentinel_tpu.native.build`"
            )
        self._lib = lib
        from sentinel_tpu.cluster.protocol import MAX_BATCH_PER_FRAME

        arena_cap = max(arena_cap, MAX_BATCH_PER_FRAME)
        self._h = lib.sn_shm_create(
            os.fsencode(shm_dir), arena_cap, int(spin_us)
        )
        if not self._h:
            raise OSError(f"shm door failed to initialize in {shm_dir!r}")
        self.dir = shm_dir
        self.arena_cap = arena_cap
        self.port = -1  # no TCP endpoint; keeps door-agnostic logging sane
        self._tls = threading.local()
        self._ctrl_buf = ctypes.create_string_buffer(70000)
        self._ctrl_lock = threading.Lock()
        self._stopped = False

    _ptr = Frontdoor._ptr
    _bufs = Frontdoor._bufs
    # identical pull/answer surface — the ctypes marshaling only differs in
    # the export name, so rebind the TCP door's methods over sn_shm_*
    def wait_batch_into(self, staging: dict, timeout_ms: int = 100,
                        max_n: Optional[int] = None):
        from sentinel_tpu.cluster.protocol import MAX_BATCH_PER_FRAME

        cap = int(staging["ids"].shape[0])
        max_f = int(staging["f_fd"].shape[0])
        if max_n is None:
            max_n = cap
        max_n = min(
            max(int(max_n), MAX_BATCH_PER_FRAME), cap, self.arena_cap
        )
        n_frames = ctypes.c_int32()
        n = self._lib.sn_shm_wait_batch(
            self._h, timeout_ms,
            self._ptr(staging["ids"], ctypes.c_int64),
            self._ptr(staging["counts"], ctypes.c_int32),
            self._ptr(staging["prios"], ctypes.c_uint8),
            max_n,
            self._ptr(staging["f_fd"], ctypes.c_int32),
            self._ptr(staging["f_gen"], ctypes.c_int32),
            self._ptr(staging["f_xid"], ctypes.c_int32),
            self._ptr(staging["f_n"], ctypes.c_int32),
            self._ptr(staging["f_type"], ctypes.c_uint8),
            max_f, ctypes.byref(n_frames),
        )
        if n <= 0:
            return None
        return n, n_frames.value

    def wait_batch(self, timeout_ms: int = 100, max_n: Optional[int] = None):
        if max_n is None:
            max_n = self.arena_cap
        from sentinel_tpu.cluster.protocol import MAX_BATCH_PER_FRAME

        max_n = min(max(int(max_n), MAX_BATCH_PER_FRAME), self.arena_cap)
        b = self._bufs()
        n_frames = ctypes.c_int32()
        n = self._lib.sn_shm_wait_batch(
            self._h, timeout_ms,
            self._ptr(b["ids"], ctypes.c_int64),
            self._ptr(b["counts"], ctypes.c_int32),
            self._ptr(b["prios"], ctypes.c_uint8),
            max_n,
            self._ptr(b["f_fd"], ctypes.c_int32),
            self._ptr(b["f_gen"], ctypes.c_int32),
            self._ptr(b["f_xid"], ctypes.c_int32),
            self._ptr(b["f_n"], ctypes.c_int32),
            self._ptr(b["f_type"], ctypes.c_uint8),
            self.arena_cap, ctypes.byref(n_frames),
        )
        if n <= 0:
            return None
        k = n_frames.value
        frames = (
            b["f_fd"][:k], b["f_gen"][:k], b["f_xid"][:k], b["f_n"][:k],
            b["f_type"][:k],
        )
        return (
            b["ids"][:n], b["counts"][:n],
            b["prios"][:n].astype(bool), frames,
        )

    def submit(self, frames, status, remaining, wait_ms) -> None:
        import numpy as np

        f_fd, f_gen, f_xid, f_n, f_type = frames
        f_fd = np.ascontiguousarray(f_fd, np.int32)
        f_gen = np.ascontiguousarray(f_gen, np.int32)
        f_xid = np.ascontiguousarray(f_xid, np.int32)
        f_n = np.ascontiguousarray(f_n, np.int32)
        f_type = np.ascontiguousarray(f_type, np.uint8)
        status = np.ascontiguousarray(status, np.int8)
        remaining = np.ascontiguousarray(remaining, np.int32)
        wait_ms = np.ascontiguousarray(wait_ms, np.int32)
        self._lib.sn_shm_submit(
            self._h, len(f_fd),
            self._ptr(f_fd, ctypes.c_int32),
            self._ptr(f_gen, ctypes.c_int32),
            self._ptr(f_xid, ctypes.c_int32),
            self._ptr(f_n, ctypes.c_int32),
            self._ptr(f_type, ctypes.c_uint8),
            self._ptr(status, ctypes.c_int8),
            self._ptr(remaining, ctypes.c_int32),
            self._ptr(wait_ms, ctypes.c_int32),
        )

    submit_many = Frontdoor.submit_many

    def send(self, fd: int, gen: int, frame: bytes) -> None:
        # TCP frames carry a 2-byte length prefix; ring slots carry the
        # payload with the slot len word playing the prefix's role
        payload = frame[2:]
        self._lib.sn_shm_send(self._h, fd, gen, payload, len(payload))

    def set_idle_ttl(self, ttl_ms: int) -> None:
        # liveness is pid-based (the poller sweep), not activity-based
        pass

    def close_conn(self, fd: int, gen: int) -> None:
        self._lib.sn_shm_close_conn(self._h, fd, gen)

    def next_control(self):
        fd = ctypes.c_int32()
        gen = ctypes.c_int32()
        ln = ctypes.c_int32()
        with self._ctrl_lock:
            kind = self._lib.sn_shm_next_control(
                self._h, ctypes.byref(fd), ctypes.byref(gen),
                ctypes.cast(self._ctrl_buf, ctypes.POINTER(ctypes.c_uint8)),
                len(self._ctrl_buf), ctypes.byref(ln),
            )
            if kind < 0:
                return None
            payload = (
                ctypes.string_at(self._ctrl_buf, ln.value)
                if ln.value > 0 else b""
            )
        return kind, fd.value, gen.value, payload

    def stats(self):
        """Counters are independently monotonic (relaxed atomics): the
        dict is NOT a consistent cross-counter snapshot. Consumers diffing
        two reads must clamp derived deltas at zero."""
        import numpy as np

        out = np.zeros(10, np.uint64)
        self._lib.sn_shm_stats(self._h, self._ptr(out, ctypes.c_uint64))
        return {
            "frames_in": int(out[0]), "requests_in": int(out[1]),
            "bytes_in": int(out[2]), "bytes_out": int(out[3]),
            "shm_polls": int(out[4]), "shm_doorbells": int(out[5]),
            "shm_ring_full": int(out[6]), "shm_segments": int(out[7]),
            "shm_req_slots_used": int(out[8]),
            "shm_req_slots_total": int(out[9]),
        }

    def echo_start(self) -> None:
        """Bench/test helper: a pure-C wait→all-GRANTED-submit loop, for
        measuring the raw transport round trip with no Python in it."""
        self._lib.sn_shm_echo_start(self._h)

    def echo_stop(self) -> None:
        self._lib.sn_shm_echo_stop(self._h)

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._lib.sn_shm_stop(self._h)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                self.stop()
            except Exception:
                pass
            self._lib.sn_shm_destroy(h)
            self._h = None


class ShmRingClient:
    """Low-level client half of one shm segment (``sn_shm_client_*``).

    Byte-level transport only: callers hand it full wire frames (with the
    2-byte length prefix, exactly what the TCP socket would carry) and get
    response payloads back; the prefix is stripped/re-added here so
    ``cluster.shm_client`` reuses the ``protocol.py`` codecs verbatim.
    Raises ``ConnectionRefusedError`` when no live door owns ``shm_dir``.
    """

    def __init__(self, shm_dir: str, slot_payload: int = 65536,
                 n_slots: int = 16, spin_us: Optional[int] = None):
        if spin_us is None:  # same adaptive rule as ShmDoor
            spin_us = 0 if (os.cpu_count() or 1) <= 1 else 50
        lib = load()
        if lib is None:
            raise RuntimeError("native library not built")
        if not getattr(lib, "_sn_has_shm", False):
            raise RuntimeError(
                "native library predates the shm door — rebuild with "
                "`python -m sentinel_tpu.native.build`"
            )
        self._lib = lib
        self._h = lib.sn_shm_client_create(
            os.fsencode(shm_dir), int(slot_payload), int(n_slots),
            int(spin_us)
        )
        if not self._h:
            raise ConnectionRefusedError(
                f"no live shm door in {shm_dir!r}"
            )
        self._rbuf = ctypes.create_string_buffer(70000)
        self._lock = threading.Lock()

    def send_frame(self, frame: bytes, timeout_ms: int = 100) -> bool:
        """Publish one length-prefixed wire frame. Spins/backs off while
        the request ring is full, up to ``timeout_ms``. False = give up
        (ring still full); raises ``ConnectionResetError`` once the server
        dropped the segment or died."""
        import time as _time

        payload = frame[2:]
        deadline = _time.monotonic() + timeout_ms / 1000.0
        while True:
            h = self._h
            if not h:
                raise ConnectionResetError("shm segment closed")
            rc = self._lib.sn_shm_client_send(h, payload, len(payload))
            if rc == 1:
                return True
            if rc < 0:
                raise ConnectionResetError("shm door dropped this segment")
            if _time.monotonic() >= deadline:
                return False
            _time.sleep(0.0002)

    def recv_payload(self, timeout_ms: int = 100) -> Optional[bytes]:
        """One response frame payload (no length prefix), or ``None`` on
        timeout; raises ``ConnectionResetError`` when the server is gone."""
        with self._lock:
            n = self._lib.sn_shm_client_recv(
                self._h,
                ctypes.cast(self._rbuf, ctypes.POINTER(ctypes.c_uint8)),
                len(self._rbuf), int(timeout_ms),
            )
            if n > 0:
                return ctypes.string_at(self._rbuf, n)
        if n < 0:
            raise ConnectionResetError("shm door dropped this segment")
        return None

    def rtt_probe(self, frame: bytes, iters: int = 1000):
        """Per-iteration transport round-trip times in ns (C-side send +
        spin-recv loop — no ctypes/codec cost inside the timed region)."""
        import numpy as np

        payload = frame[2:]
        out = np.zeros(iters, np.int64)
        done = self._lib.sn_shm_client_rtt(
            self._h, payload, len(payload), iters,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return out[:max(done, 0)]

    def fuzz(self, data: bytes, stage: int) -> bool:
        """Test hook: torn/hostile slot writes (see sn_shm_client_fuzz)."""
        return bool(
            self._lib.sn_shm_client_fuzz(self._h, data, len(data), stage)
        )

    def alive(self) -> bool:
        return bool(self._lib.sn_shm_client_alive(self._h))

    def close(self) -> None:
        h = self._h
        if h:
            self._h = None
            self._lib.sn_shm_client_destroy(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
