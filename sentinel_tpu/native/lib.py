"""ctypes loader + thin object wrappers over the native C API.

ctypes releases the GIL around every call, so under free-threaded Python the
native windows scale across threads the way the reference's LongAdders do —
the Python fallbacks serialize on the owning node's lock instead.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "_sentinel_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    P, I32, I64, F64 = (
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.c_double,
    )
    sig = {
        "sn_window_create": ([I32, I32, I32], P),
        "sn_window_destroy": ([P], None),
        "sn_window_add": ([P, I64, I32, F64], None),
        "sn_window_sum": ([P, I64, I32], F64),
        "sn_window_snapshot": ([P, I64, ctypes.POINTER(F64)], None),
        "sn_window_prev_bucket": ([P, I64, I32], F64),
        "sn_window_min_ratio": ([P, I64, I32, I32], F64),
        "sn_window_start_at": ([P, I32], I64),
        "sn_window_count_at": ([P, I32, I32], F64),
        "sn_window_add_future": ([P, I64, I32, F64], None),
        "sn_window_future_waiting": ([P, I64, I32], F64),
        "sn_window_take_matured": ([P, I64, I32], F64),
        "sn_tb_create": ([I32], P),
        "sn_tb_destroy": ([P], None),
        "sn_tb_reset": ([P, I32], None),
        "sn_tb_try_acquire": ([P, I32, I64, I32, F64, F64, I64], I32),
        "sn_pacer_create": ([I32], P),
        "sn_pacer_destroy": ([P], None),
        "sn_pacer_reset": ([P, I32], None),
        "sn_pacer_try_pass": ([P, I32, I64, I32, F64, I64], I64),
        "sn_batch_decode_req": (
            [
                ctypes.c_char_p, I32, ctypes.POINTER(I32),
                ctypes.POINTER(I64), ctypes.POINTER(I32),
                ctypes.POINTER(ctypes.c_uint8), I32,
            ],
            I32,
        ),
        "sn_batch_encode_rsp": (
            [
                I32, I32, ctypes.POINTER(ctypes.c_int8),
                ctypes.POINTER(I32), ctypes.POINTER(I32),
                ctypes.POINTER(ctypes.c_uint8), I32,
            ],
            I32,
        ),
    }
    for name, (argtypes, restype) in sig.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Load (once) the native library; None if not built or unloadable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_SO_PATH):
            _load_failed = True
            return None
        try:
            _lib = _configure(ctypes.CDLL(_SO_PATH))
        except OSError:
            _load_failed = True
            return None
    return _lib


def available() -> bool:
    return load() is not None


def batch_decode_req(payload: bytes):
    """BATCH_FLOW request payload → (xid, flow_ids int64[N], counts int32[N],
    prios bool[N]); None when the native lib is absent; raises ValueError on
    a malformed frame (mirrors the numpy codec's behavior)."""
    lib = load()
    if lib is None:
        return None
    import numpy as np

    max_n = max((len(payload) - 7) // 13, 0)
    xid = ctypes.c_int32()
    flow_ids = np.empty(max_n, np.int64)
    counts = np.empty(max_n, np.int32)
    prios = np.empty(max_n, np.uint8)
    n = lib.sn_batch_decode_req(
        payload, len(payload), ctypes.byref(xid),
        flow_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        prios.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        max_n,
    )
    if n < 0:
        raise ValueError("malformed BATCH_FLOW frame")
    return (
        int(xid.value), flow_ids[:n], counts[:n], prios[:n].astype(bool)
    )


def batch_encode_rsp(xid: int, status, remaining, wait_ms):
    """(status int8[N], remaining int32[N], wait int32[N]) → full response
    frame bytes (length prefix included); None when the lib is absent."""
    lib = load()
    if lib is None:
        return None
    import numpy as np

    status = np.ascontiguousarray(status, np.int8)
    remaining = np.ascontiguousarray(remaining, np.int32)
    wait_ms = np.ascontiguousarray(wait_ms, np.int32)
    n = status.shape[0]
    out = np.empty(2 + 7 + n * 9, np.uint8)
    wrote = lib.sn_batch_encode_rsp(
        xid, n,
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        remaining.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        wait_ms.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.shape[0],
    )
    if wrote < 0:
        raise ValueError("batch too large for one frame")
    return out[:wrote].tobytes()


class NativeWindow:
    """Sliding window backed by the native lib — drop-in for
    ``local.stat.HostWindow`` plus the future/occupy ops."""

    __slots__ = ("_lib", "_h", "bucket_ms", "n_buckets", "n_channels",
                 "interval_ms")

    def __init__(self, bucket_ms: int, n_buckets: int, n_channels: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native library not built")
        self._lib = lib
        self._h = lib.sn_window_create(bucket_ms, n_buckets, n_channels)
        if not self._h:
            raise MemoryError("sn_window_create failed")
        self.bucket_ms = bucket_ms
        self.n_buckets = n_buckets
        self.n_channels = n_channels
        self.interval_ms = bucket_ms * n_buckets

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.sn_window_destroy(h)
            self._h = None

    def add(self, now: int, chan: int, n: float = 1.0) -> None:
        self._lib.sn_window_add(self._h, now, chan, n)

    def sum(self, now: int, chan: int) -> float:
        return self._lib.sn_window_sum(self._h, now, chan)

    def qps(self, now: int, chan: int) -> float:
        return self.sum(now, chan) * 1000.0 / self.interval_ms

    def snapshot(self, now: int) -> list:
        out = (ctypes.c_double * self.n_channels)()
        self._lib.sn_window_snapshot(self._h, now, out)
        return list(out)

    def previous_bucket(self, now: int, chan: int) -> float:
        return self._lib.sn_window_prev_bucket(self._h, now, chan)

    def min_ratio(self, now: int, num_chan: int, den_chan: int) -> float:
        return self._lib.sn_window_min_ratio(self._h, now, num_chan, den_chan)

    def start_at(self, b: int) -> int:
        return self._lib.sn_window_start_at(self._h, b)

    def count_at(self, b: int, chan: int) -> float:
        return self._lib.sn_window_count_at(self._h, b, chan)

    # future/occupy ops (FutureWindow analog; use a dedicated instance)
    def add_future(self, future_time: int, n: float, chan: int = 0) -> None:
        self._lib.sn_window_add_future(self._h, future_time, chan, n)

    def future_waiting(self, now: int, chan: int = 0) -> float:
        return self._lib.sn_window_future_waiting(self._h, now, chan)

    def take_matured(self, now: int, chan: int = 0) -> float:
        return self._lib.sn_window_take_matured(self._h, now, chan)


class NativeTokenBuckets:
    """Array of token buckets (hot-param local QPS mode)."""

    __slots__ = ("_lib", "_h", "n_slots")

    def __init__(self, n_slots: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native library not built")
        self._lib = lib
        self._h = lib.sn_tb_create(n_slots)
        if not self._h:
            raise MemoryError("sn_tb_create failed")
        self.n_slots = n_slots

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.sn_tb_destroy(h)
            self._h = None

    def reset(self, slot: int) -> None:
        self._lib.sn_tb_reset(self._h, slot)

    def try_acquire(
        self,
        slot: int,
        now: int,
        acquire: int,
        count: float,
        burst: float,
        interval_ms: int,
    ) -> bool:
        return bool(
            self._lib.sn_tb_try_acquire(
                self._h, slot, now, acquire, count, burst, interval_ms
            )
        )


class NativePacerArray:
    """Array of leaky-bucket pacers (RateLimiter behavior)."""

    __slots__ = ("_lib", "_h", "n_slots")

    def __init__(self, n_slots: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native library not built")
        self._lib = lib
        self._h = lib.sn_pacer_create(n_slots)
        if not self._h:
            raise MemoryError("sn_pacer_create failed")
        self.n_slots = n_slots

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.sn_pacer_destroy(h)
            self._h = None

    def reset(self, slot: int) -> None:
        self._lib.sn_pacer_reset(self._h, slot)

    def try_pass(
        self,
        slot: int,
        now: int,
        acquire: int,
        count_per_sec: float,
        max_queue_ms: int,
    ) -> int:
        """wait-ms to sleep (0 = immediate) or -1 = block."""
        return int(
            self._lib.sn_pacer_try_pass(
                self._h, slot, now, acquire, count_per_sec, max_queue_ms
            )
        )
