"""File-backed datasources (``FileRefreshableDataSource.java:39`` /
``FileWritableDataSource``)."""

from __future__ import annotations

import os
from typing import Optional

from sentinel_tpu.datasource.base import (
    AutoRefreshDataSource,
    Converter,
    WritableDataSource,
)


class FileRefreshableDataSource(AutoRefreshDataSource[str, object]):
    """Re-reads a file when its mtime/size changes."""

    def __init__(self, path: str, converter: Converter,
                 refresh_interval_s: float = 3.0, encoding: str = "utf-8"):
        self.path = path
        self.encoding = encoding
        self._last_sig: Optional[tuple] = None
        super().__init__(converter, refresh_interval_s)

    def read_source(self) -> str:
        with open(self.path, "r", encoding=self.encoding) as f:
            return f.read()

    def is_modified(self) -> bool:
        try:
            st = os.stat(self.path)
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            return False
        if sig != self._last_sig:
            self._last_sig = sig
            return True
        return False

    def refresh(self) -> bool:
        try:
            st = os.stat(self.path)
            self._last_sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            pass
        return super().refresh()


class FileWritableDataSource(WritableDataSource):
    def __init__(self, path: str, serializer, encoding: str = "utf-8"):
        self.path = path
        self.serializer = serializer
        self.encoding = encoding

    def write(self, value) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding=self.encoding) as f:
            f.write(self.serializer(value))
        os.replace(tmp, self.path)
