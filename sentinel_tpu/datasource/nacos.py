"""Nacos config datasource (analog of ``sentinel-datasource-nacos``).

The reference wires the Nacos Java client's ``addListener``; the client
implements that with the open long-poll protocol spoken here directly:

- read:  ``GET /nacos/v1/cs/configs?dataId&group[&tenant]``
- watch: ``POST /nacos/v1/cs/configs/listener`` with
  ``Listening-Configs = dataId^2group^2md5[^2tenant]^1`` and a
  ``Long-Pulling-Timeout`` header; the server parks the request until the
  config's md5 diverges (response non-empty → changed).

(^1/^2 are the protocol's 0x01/0x02 field separators.)
"""

from __future__ import annotations

import hashlib
import urllib.parse
from typing import Optional

from sentinel_tpu.datasource.base import Converter
from sentinel_tpu.datasource.http_util import request
from sentinel_tpu.datasource.push_base import WatchingDataSource

_SEP_FIELD = "\x02"
_SEP_LINE = "\x01"


class NacosDataSource(WatchingDataSource):
    def __init__(
        self,
        converter: Converter,
        server_addr: str = "127.0.0.1:8848",
        data_id: str = "sentinel-rules",
        group: str = "DEFAULT_GROUP",
        namespace: Optional[str] = None,
        long_poll_timeout_ms: int = 30_000,
        context_path: str = "/nacos",
    ):
        self.base = f"http://{server_addr}{context_path}/v1/cs"
        self.data_id = data_id
        self.group = group
        self.namespace = namespace
        self.long_poll_timeout_ms = long_poll_timeout_ms
        self._md5 = ""
        super().__init__(converter)

    def read_source(self) -> str:
        params = {"dataId": self.data_id, "group": self.group}
        if self.namespace:
            params["tenant"] = self.namespace
        resp = request(f"{self.base}/configs", params=params, timeout_s=5.0)
        if resp.status == 404:
            self._md5 = ""
            return ""
        if resp.status != 200:
            raise RuntimeError(f"nacos get failed: {resp.status}")
        self._md5 = hashlib.md5(resp.body).hexdigest()
        return resp.text

    def watch_once(self) -> bool:
        fields = [self.data_id, self.group, self._md5]
        if self.namespace:
            fields.append(self.namespace)
        listening = _SEP_FIELD.join(fields) + _SEP_LINE
        resp = request(
            f"{self.base}/configs/listener",
            method="POST",
            data=urllib.parse.urlencode(
                {"Listening-Configs": listening}
            ).encode(),
            headers={
                "Long-Pulling-Timeout": str(self.long_poll_timeout_ms),
                "Content-Type": "application/x-www-form-urlencoded",
            },
            timeout_s=self.long_poll_timeout_ms / 1000.0 + 10.0,
        )
        if resp.status != 200:
            raise RuntimeError(f"nacos listener failed: {resp.status}")
        return bool(resp.text.strip())  # non-empty body names changed configs
