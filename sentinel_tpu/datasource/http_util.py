"""Tiny stdlib HTTP helper shared by the HTTP-API datasource backends.

The reference ships one Maven submodule per config backend, each pulling the
vendor's Java client (Nacos client, CuratorFramework, etc.). Here every
backend with an HTTP API (consul, etcd v3 gateway, nacos, apollo, eureka,
spring-cloud-config) speaks it directly through urllib — no vendored SDKs,
which also keeps the image dependency-free.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Optional, Tuple


class HttpResponse:
    def __init__(self, status: int, headers: Dict[str, str], body: bytes):
        self.status = status
        self.headers = headers
        self.body = body

    @property
    def text(self) -> str:
        return self.body.decode("utf-8", errors="replace")

    def json(self):
        return json.loads(self.text)


def request(
    url: str,
    method: str = "GET",
    params: Optional[Dict[str, str]] = None,
    data: Optional[bytes] = None,
    headers: Optional[Dict[str, str]] = None,
    timeout_s: float = 5.0,
) -> HttpResponse:
    """One HTTP exchange; non-2xx returns the response rather than raising
    (datasources treat 404 'no config yet' as empty, not an error)."""
    if params:
        url = url + ("&" if "?" in url else "?") + urllib.parse.urlencode(params)
    req = urllib.request.Request(url, data=data, method=method)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return HttpResponse(resp.status, dict(resp.headers), resp.read())
    except urllib.error.HTTPError as e:  # non-2xx still carries a body
        return HttpResponse(e.code, dict(e.headers or {}), e.read() or b"")
