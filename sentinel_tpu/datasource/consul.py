"""Consul KV datasource (analog of ``sentinel-datasource-consul``).

The reference module long-polls the KV endpoint with Consul *blocking
queries* (wait + last index); same here, directly over the HTTP API:
``GET /v1/kv/<key>?index=<last>&wait=<s>s`` blocks until the key changes or
the wait elapses. The value arrives base64-encoded in the JSON body and the
``X-Consul-Index`` header carries the next cursor.
"""

from __future__ import annotations

import base64
from typing import Optional

from sentinel_tpu.datasource.base import Converter
from sentinel_tpu.datasource.http_util import request
from sentinel_tpu.datasource.push_base import WatchingDataSource


class ConsulDataSource(WatchingDataSource):
    def __init__(
        self,
        converter: Converter,
        host: str = "127.0.0.1",
        port: int = 8500,
        rule_key: str = "sentinel/rules",
        token: Optional[str] = None,
        wait_s: int = 60,
    ):
        self.base_url = f"http://{host}:{port}/v1/kv/{rule_key}"
        self.token = token
        self.wait_s = wait_s
        self._index = 0
        super().__init__(converter)

    def _headers(self):
        return {"X-Consul-Token": self.token} if self.token else {}

    def read_source(self) -> str:
        resp = request(self.base_url, headers=self._headers(), timeout_s=5.0)
        if resp.status == 404:
            return ""
        self._index = int(resp.headers.get("X-Consul-Index", self._index) or 0)
        entries = resp.json()
        if not entries:
            return ""
        raw = entries[0].get("Value")
        return base64.b64decode(raw).decode("utf-8") if raw else ""

    def watch_once(self) -> bool:
        resp = request(
            self.base_url,
            params={"index": str(self._index), "wait": f"{self.wait_s}s"},
            headers=self._headers(),
            # the blocking query may legitimately hold the connection the
            # whole wait window plus consul's jitter
            timeout_s=self.wait_s + 10.0,
        )
        # 404 is a valid blocking-query answer (key absent yet — the index
        # still advances when it is created); anything else non-200 must
        # raise so the watch loop backs off instead of hot-looping (e.g. an
        # instant 403 on an expired ACL token carries no index and would
        # otherwise spin at network speed)
        if resp.status not in (200, 404):
            raise RuntimeError(f"consul blocking query failed: {resp.status}")
        new_index = int(resp.headers.get("X-Consul-Index", 0) or 0)
        changed = new_index != self._index
        self._index = new_index
        return changed
