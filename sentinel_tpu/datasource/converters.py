"""JSON ⇄ rule converters for every rule type.

Field names follow the reference's JSON rule schema (what the dashboard and
``sentinel-demo`` file datasources exchange: camelCase ``FlowRule`` fields
etc.), so existing Sentinel rule files load unchanged.
"""

from __future__ import annotations

import json
from typing import List

from sentinel_tpu.local.authority import AuthorityRule, AuthorityStrategy
from sentinel_tpu.local.degrade import DegradeGrade, DegradeRule
from sentinel_tpu.local.flow import ControlBehavior, FlowGrade, FlowRule, FlowStrategy
from sentinel_tpu.local.param import ParamFlowItem, ParamFlowRule
from sentinel_tpu.local.system_adaptive import SystemRule


def flow_rules_from_json(text: str) -> List[FlowRule]:
    return [
        FlowRule(
            resource=d["resource"],
            count=float(d.get("count", 0)),
            grade=FlowGrade(d.get("grade", 1)),
            limit_app=d.get("limitApp", "default"),
            strategy=FlowStrategy(d.get("strategy", 0)),
            ref_resource=d.get("refResource", "") or "",
            control_behavior=ControlBehavior(d.get("controlBehavior", 0)),
            warm_up_period_sec=int(d.get("warmUpPeriodSec", 10)),
            max_queueing_time_ms=int(d.get("maxQueueingTimeMs", 500)),
            cluster_mode=bool(d.get("clusterMode", False)),
            cluster_config=d.get("clusterConfig"),
        )
        for d in json.loads(text) or []
    ]


def flow_rules_to_json(rules: List[FlowRule]) -> str:
    return json.dumps(
        [
            {
                "resource": r.resource,
                "count": r.count,
                "grade": int(r.grade),
                "limitApp": r.limit_app,
                "strategy": int(r.strategy),
                "refResource": r.ref_resource,
                "controlBehavior": int(r.control_behavior),
                "warmUpPeriodSec": r.warm_up_period_sec,
                "maxQueueingTimeMs": r.max_queueing_time_ms,
                "clusterMode": r.cluster_mode,
                "clusterConfig": r.cluster_config,
            }
            for r in rules
        ],
        indent=2,
    )


def degrade_rules_from_json(text: str) -> List[DegradeRule]:
    return [
        DegradeRule(
            resource=d["resource"],
            grade=DegradeGrade(d.get("grade", 0)),
            count=float(d.get("count", 0)),
            time_window_sec=int(d.get("timeWindow", 0)),
            min_request_amount=int(d.get("minRequestAmount", 5)),
            stat_interval_ms=int(d.get("statIntervalMs", 1000)),
            slow_ratio_threshold=float(d.get("slowRatioThreshold", 1.0)),
            limit_app=d.get("limitApp", "default"),
        )
        for d in json.loads(text) or []
    ]


def degrade_rules_to_json(rules: List[DegradeRule]) -> str:
    return json.dumps(
        [
            {
                "resource": r.resource,
                "grade": int(r.grade),
                "count": r.count,
                "timeWindow": r.time_window_sec,
                "minRequestAmount": r.min_request_amount,
                "statIntervalMs": r.stat_interval_ms,
                "slowRatioThreshold": r.slow_ratio_threshold,
                "limitApp": r.limit_app,
            }
            for r in rules
        ],
        indent=2,
    )


def system_rules_from_json(text: str) -> List[SystemRule]:
    return [
        SystemRule(
            highest_system_load=float(d.get("highestSystemLoad", -1)),
            highest_cpu_usage=float(d.get("highestCpuUsage", -1)),
            qps=float(d.get("qps", -1)),
            avg_rt=float(d.get("avgRt", -1)),
            max_thread=float(d.get("maxThread", -1)),
        )
        for d in json.loads(text) or []
    ]


def system_rules_to_json(rules: List[SystemRule]) -> str:
    return json.dumps(
        [
            {
                "highestSystemLoad": r.highest_system_load,
                "highestCpuUsage": r.highest_cpu_usage,
                "qps": r.qps,
                "avgRt": r.avg_rt,
                "maxThread": r.max_thread,
            }
            for r in rules
        ],
        indent=2,
    )


def authority_rules_from_json(text: str) -> List[AuthorityRule]:
    return [
        AuthorityRule(
            resource=d["resource"],
            limit_app=d.get("limitApp", ""),
            strategy=AuthorityStrategy(d.get("strategy", 0)),
        )
        for d in json.loads(text) or []
    ]


def authority_rules_to_json(rules: List[AuthorityRule]) -> str:
    return json.dumps(
        [
            {
                "resource": r.resource,
                "limitApp": r.limit_app,
                "strategy": int(r.strategy),
            }
            for r in rules
        ],
        indent=2,
    )


def param_flow_rules_from_json(text: str) -> List[ParamFlowRule]:
    return [
        ParamFlowRule(
            resource=d["resource"],
            param_idx=int(d.get("paramIdx", 0)),
            count=float(d.get("count", 0)),
            grade=FlowGrade(d.get("grade", 1)),
            duration_sec=int(d.get("durationInSec", 1)),
            burst_count=int(d.get("burstCount", 0)),
            control_behavior=ControlBehavior(d.get("controlBehavior", 0)),
            max_queueing_time_ms=int(d.get("maxQueueingTimeMs", 0)),
            items=[
                ParamFlowItem(
                    object_value=i.get("object"), count=float(i.get("count", 0))
                )
                for i in d.get("paramFlowItemList", [])
            ],
            cluster_mode=bool(d.get("clusterMode", False)),
            cluster_config=d.get("clusterConfig"),
        )
        for d in json.loads(text) or []
    ]


def param_flow_rules_to_json(rules: List[ParamFlowRule]) -> str:
    return json.dumps(
        [
            {
                "resource": r.resource,
                "paramIdx": r.param_idx,
                "count": r.count,
                "grade": int(r.grade),
                "durationInSec": r.duration_sec,
                "burstCount": r.burst_count,
                "controlBehavior": int(r.control_behavior),
                "maxQueueingTimeMs": r.max_queueing_time_ms,
                "paramFlowItemList": [
                    {"object": i.object_value, "count": i.count} for i in r.items
                ],
                "clusterMode": r.cluster_mode,
                "clusterConfig": r.cluster_config,
            }
            for r in rules
        ],
        indent=2,
    )


def gateway_flow_rules_from_json(text: str):
    """Gateway rule schema mirrors ``GatewayFlowRule.java`` field names (what
    the reference dashboard's gateway UI exchanges)."""
    from sentinel_tpu.adapters.gateway import (
        GatewayFlowRule,
        GatewayParamFlowItem,
        MatchStrategy,
        ParseStrategy,
        ResourceMode,
    )

    out = []
    for d in json.loads(text) or []:
        item = d.get("paramItem")
        out.append(
            GatewayFlowRule(
                resource=d["resource"],
                resource_mode=ResourceMode(d.get("resourceMode", 0)),
                count=float(d.get("count", 0)),
                grade=FlowGrade(d.get("grade", 1)),
                interval_sec=int(d.get("intervalSec", 1)),
                control_behavior=ControlBehavior(d.get("controlBehavior", 0)),
                burst=int(d.get("burst", 0)),
                max_queueing_time_ms=int(d.get("maxQueueingTimeoutMs", 500)),
                param_item=(
                    GatewayParamFlowItem(
                        parse_strategy=ParseStrategy(item.get("parseStrategy", 0)),
                        field_name=item.get("fieldName"),
                        pattern=item.get("pattern"),
                        match_strategy=MatchStrategy(item.get("matchStrategy", 0)),
                    )
                    if item
                    else None
                ),
            )
        )
    return out


def gateway_flow_rules_to_json(rules) -> str:
    return json.dumps(
        [
            {
                "resource": r.resource,
                "resourceMode": int(r.resource_mode),
                "count": r.count,
                "grade": int(r.grade),
                "intervalSec": r.interval_sec,
                "controlBehavior": int(r.control_behavior),
                "burst": r.burst,
                "maxQueueingTimeoutMs": r.max_queueing_time_ms,
                "paramItem": (
                    {
                        "parseStrategy": int(r.param_item.parse_strategy),
                        "fieldName": r.param_item.field_name,
                        "pattern": r.param_item.pattern,
                        "matchStrategy": int(r.param_item.match_strategy),
                    }
                    if r.param_item
                    else None
                ),
            }
            for r in rules
        ],
        indent=2,
    )


def cluster_flow_rules_from_json(text: str):
    """Cluster (token-server) rule schema: the ``FlowRule`` +
    ``ClusterFlowConfig`` subset the device engine consumes
    (``ClusterFlowRuleManager`` parses the same shape from its namespace
    datasources — ``flowId``/``count``/``thresholdType``/``namespace``)."""
    from sentinel_tpu.engine import ClusterFlowRule
    from sentinel_tpu.engine.rules import ThresholdMode

    return [
        ClusterFlowRule(
            flow_id=int(
                d.get("flowId", (d.get("clusterConfig") or {}).get("flowId", 0))
            ),
            count=float(d.get("count", 0)),
            mode=ThresholdMode(
                int(
                    d.get(
                        "thresholdType",
                        (d.get("clusterConfig") or {}).get("thresholdType", 0),
                    )
                )
            ),
            namespace=str(d.get("namespace", "default") or "default"),
            control_behavior=int(d.get("controlBehavior", 0)),
            warm_up_period_sec=int(d.get("warmUpPeriodSec", 10)),
            cold_factor=int(d.get("coldFactor", 3)),
            max_queueing_time_ms=int(d.get("maxQueueingTimeMs", 500)),
        )
        for d in json.loads(text) or []
    ]


def cluster_flow_rules_to_json(rules) -> str:
    docs = []
    for r in rules:
        d = {
            "flowId": r.flow_id,
            "count": r.count,
            "thresholdType": int(r.mode),
            "namespace": r.namespace,
        }
        if int(getattr(r, "control_behavior", 0)) != 0:
            d["controlBehavior"] = int(r.control_behavior)
            d["warmUpPeriodSec"] = int(r.warm_up_period_sec)
            d["coldFactor"] = int(r.cold_factor)
            d["maxQueueingTimeMs"] = int(r.max_queueing_time_ms)
        docs.append(d)
    return json.dumps(docs, indent=2)
