"""Eureka metadata datasource (analog of ``sentinel-datasource-eureka``).

The reference reads rule JSON out of a Eureka *instance's metadata map*
(``metadata.<ruleKey>``), polling the registry. Same model here over the
open REST API: ``GET /eureka/apps/{appId}`` (JSON accept), take the first
UP instance's ``metadata[rule_key]``. Multiple registry URLs are tried in
order — the reference's fallback-server behavior.
"""

from __future__ import annotations

from typing import List, Sequence

from sentinel_tpu.datasource.base import AutoRefreshDataSource, Converter
from sentinel_tpu.datasource.http_util import request


class EurekaDataSource(AutoRefreshDataSource):
    def __init__(
        self,
        converter: Converter,
        app_id: str,
        instance_id: str,
        service_urls: Sequence[str] = ("http://127.0.0.1:8761/eureka",),
        rule_key: str = "sentinel.rules",
        refresh_interval_s: float = 3.0,
    ):
        self.app_id = app_id
        self.instance_id = instance_id
        self.service_urls: List[str] = [u.rstrip("/") for u in service_urls]
        self.rule_key = rule_key
        super().__init__(converter, refresh_interval_s)

    def read_source(self) -> str:
        last_err: Exception = RuntimeError("no eureka service urls")
        for base in self.service_urls:
            try:
                resp = request(
                    f"{base}/apps/{self.app_id}",
                    headers={"Accept": "application/json"},
                    timeout_s=5.0,
                )
                if resp.status != 200:
                    raise RuntimeError(f"eureka status {resp.status}")
                instances = (resp.json().get("application") or {}).get(
                    "instance"
                ) or []
                if isinstance(instances, dict):
                    # Eureka's XStream JSON renders a single-instance app as
                    # an object, not a one-element list
                    instances = [instances]
                for inst in instances:
                    if inst.get("instanceId") != self.instance_id:
                        continue
                    return (inst.get("metadata") or {}).get(self.rule_key, "")
                return ""  # instance not registered (yet) → no rules
            except Exception as e:  # try the next registry replica
                last_err = e
        raise last_err
