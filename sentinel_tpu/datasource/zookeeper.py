"""ZooKeeper datasource (analog of ``sentinel-datasource-zookeeper``).

The reference watches a znode with Curator's ``NodeCache``. ZooKeeper speaks
a binary protocol with session heartbeats — not something to hand-roll —
so this backend drives an injectable client object with the tiny surface it
needs (``get(path) -> (bytes, stat)`` and ``DataWatch``-style callbacks).
``kazoo.client.KazooClient`` satisfies it directly when kazoo is installed;
environments without kazoo can inject any conforming client (tests use a
fake), and constructing without either raises with guidance instead of
failing at import time.
"""

from __future__ import annotations

from typing import Optional

from sentinel_tpu.core.log import record_log
from sentinel_tpu.datasource.base import Converter, ReadableDataSource


class ZookeeperDataSource(ReadableDataSource):
    def __init__(
        self,
        converter: Converter,
        server_addr: str = "127.0.0.1:2181",
        path: str = "/sentinel/rules",
        client=None,
    ):
        super().__init__(converter)
        self.path = path
        self._owns_client = client is None
        if client is None:
            try:
                from kazoo.client import KazooClient  # type: ignore
            except ImportError as e:  # pragma: no cover - env-dependent
                raise ImportError(
                    "ZookeeperDataSource needs the 'kazoo' package (not "
                    "bundled in this image) or an injected client exposing "
                    "get(path) and DataWatch(path, func)"
                ) from e
            client = KazooClient(hosts=server_addr)
        self.client = client

    def start(self) -> "ZookeeperDataSource":
        if self._owns_client:
            self.client.start()
        # ensure_path keeps first-boot ordering race-free: watch an existing
        # (possibly empty) node rather than racing its creation
        ensure = getattr(self.client, "ensure_path", None)
        if ensure is not None:
            ensure(self.path)

        def _on_change(data, stat, *_):
            if data is None:
                return
            try:
                self.property.update_value(self.converter(data.decode()))
            except Exception as e:
                record_log.warning("zookeeper rule payload rejected: %s", e)

        # kazoo's DataWatch fires immediately with the current value, which
        # doubles as the initial load
        self.client.DataWatch(self.path, _on_change)
        return self

    def read_source(self) -> str:
        data, _stat = self.client.get(self.path)
        return (data or b"").decode()

    def close(self) -> None:
        if self._owns_client:
            try:
                self.client.stop()
            except Exception:
                pass
