"""etcd v3 datasource (analog of ``sentinel-datasource-etcd``).

Speaks the etcd v3 JSON/gRPC-gateway API directly: ``POST /v3/kv/range``
with base64 keys. The reference registers a jetcd ``Watch``; the gateway's
watch is a chunked stream that urllib can't consume incrementally, so this
backend polls the key's ``mod_revision`` cheaply (count-only range) and
re-reads on change — same observable behavior, bounded staleness.
"""

from __future__ import annotations

import base64
import json
from typing import Optional

from sentinel_tpu.datasource.base import AutoRefreshDataSource, Converter
from sentinel_tpu.datasource.http_util import request


def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


class EtcdDataSource(AutoRefreshDataSource):
    def __init__(
        self,
        converter: Converter,
        endpoint: str = "http://127.0.0.1:2379",
        rule_key: str = "sentinel/rules",
        refresh_interval_s: float = 1.0,
        user: Optional[str] = None,
        password: Optional[str] = None,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.rule_key = rule_key
        self._auth_token: Optional[str] = None
        self._user, self._password = user, password
        self._last_mod_rev: Optional[int] = None
        super().__init__(converter, refresh_interval_s)

    def _headers(self):
        if self._user and self._auth_token is None:
            resp = request(
                f"{self.endpoint}/v3/auth/authenticate",
                method="POST",
                data=json.dumps(
                    {"name": self._user, "password": self._password}
                ).encode(),
            )
            if resp.status == 200:
                self._auth_token = resp.json().get("token")
        return {"Authorization": self._auth_token} if self._auth_token else {}

    def _range(self, keys_only: bool = False) -> dict:
        payload = {"key": _b64(self.rule_key)}
        if keys_only:
            # metadata-only poll: kvs come back with mod_revision but no
            # value, so the change check doesn't transfer the rule payload
            payload["keys_only"] = True
        for attempt in (0, 1):
            resp = request(
                f"{self.endpoint}/v3/kv/range",
                method="POST",
                data=json.dumps(payload).encode(),
                headers=self._headers(),
                timeout_s=5.0,
            )
            if resp.status == 200:
                return resp.json()
            # etcd simple tokens expire (default TTL 300s); drop the cached
            # token and re-authenticate once instead of failing every poll
            # until restart
            if resp.status in (401, 403) and self._user and attempt == 0:
                self._auth_token = None
                continue
            break
        raise RuntimeError(f"etcd range failed: {resp.status} {resp.text}")

    def read_source(self) -> str:
        body = self._range()
        kvs = body.get("kvs") or []
        if not kvs:
            self._last_mod_rev = 0
            return ""
        self._last_mod_rev = int(kvs[0].get("mod_revision", 0))
        return base64.b64decode(kvs[0].get("value", "")).decode("utf-8")

    def is_modified(self) -> bool:
        body = self._range(keys_only=True)
        kvs = body.get("kvs") or []
        rev = int(kvs[0].get("mod_revision", 0)) if kvs else 0
        return rev != (self._last_mod_rev or 0)
