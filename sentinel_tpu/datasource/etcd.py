"""etcd v3 datasource (analog of ``sentinel-datasource-etcd``).

Speaks the etcd v3 JSON/gRPC-gateway API directly: ``POST /v3/kv/range``
with base64 keys. Like the reference's jetcd ``Watch``
(``EtcdDataSource.java``), changes propagate through a real watch: a
``POST /v3/watch`` whose chunked response streams newline-delimited JSON
events, consumed incrementally with ``http.client`` (urllib can't). A
cheap ``mod_revision`` poll (keys-only range) stays on as the backstop, so
a dropped watch stream degrades to bounded staleness instead of silence.
"""

from __future__ import annotations

import base64
import http.client
import json
import threading
import urllib.parse
from typing import Optional

from sentinel_tpu.core.log import record_log
from sentinel_tpu.datasource.base import AutoRefreshDataSource, Converter
from sentinel_tpu.datasource.http_util import request

# one watch-stream JSON line (rule payloads are KBs; 16MB is generous)
_MAX_WATCH_LINE = 16 * 1024 * 1024


def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


class EtcdDataSource(AutoRefreshDataSource):
    def __init__(
        self,
        converter: Converter,
        endpoint: str = "http://127.0.0.1:2379",
        rule_key: str = "sentinel/rules",
        refresh_interval_s: float = 1.0,
        user: Optional[str] = None,
        password: Optional[str] = None,
        watch: bool = True,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.rule_key = rule_key
        self._auth_token: Optional[str] = None
        self._user, self._password = user, password
        self._last_mod_rev: Optional[int] = None
        self.watch = watch
        self._watch_thread: Optional[threading.Thread] = None
        self._watch_conn: Optional[http.client.HTTPConnection] = None
        self._watch_stop = threading.Event()
        super().__init__(converter, refresh_interval_s)

    def start(self) -> "EtcdDataSource":
        super().start()
        if self.watch:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, daemon=True,
                name="sentinel-etcd-watch",
            )
            self._watch_thread.start()
        return self

    def close(self) -> None:
        self._watch_stop.set()
        conn = self._watch_conn
        if conn is not None:
            try:
                # shutdown (not just close) — closing an fd from another
                # thread does not reliably wake a blocked recv on Linux,
                # but SHUT_RDWR makes the reader's recv return 0 at once
                if conn.sock is not None:
                    import socket as _socket

                    conn.sock.shutdown(_socket.SHUT_RDWR)
                conn.close()
            except Exception:
                pass
        super().close()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=2)
            self._watch_thread = None

    # -- watch stream -------------------------------------------------------
    def _watch_loop(self) -> None:
        """Consume ``POST /v3/watch``'s chunked stream; each events message
        triggers an immediate refresh. Any failure falls back to the poll
        loop's bounded staleness and reconnects after one interval."""
        parsed = urllib.parse.urlsplit(self.endpoint)
        conn_cls = (
            http.client.HTTPSConnection
            if parsed.scheme == "https" else http.client.HTTPConnection
        )
        while not self._watch_stop.is_set():
            conn = None
            try:
                # idle streams carry no bytes; the read timeout doubles as
                # a liveness bound after which we just re-establish
                conn = conn_cls(
                    parsed.hostname, parsed.port or 2379, timeout=60.0
                )
                # publish the conn BEFORE any blocking I/O (the constructor
                # doesn't connect) so close() can always interrupt us
                self._watch_conn = conn
                if self._watch_stop.is_set():
                    break
                headers = {"Content-Type": "application/json"}
                headers.update(self._headers())
                conn.request(
                    "POST", "/v3/watch",
                    body=json.dumps(
                        {"create_request": {"key": _b64(self.rule_key)}}
                    ),
                    headers=headers,
                )
                resp = conn.getresponse()
                if resp.status in (401, 403) and self._user:
                    # expired simple token: drop it so the next reconnect
                    # re-authenticates (same repair _range does) instead of
                    # silently degrading to poll-interval staleness
                    self._auth_token = None
                if resp.status != 200:
                    raise RuntimeError(f"watch HTTP {resp.status}")
                while not self._watch_stop.is_set():
                    # bounded read: a misbehaving gateway streaming one huge
                    # line must fail the stream (→ reconnect), not exhaust
                    # process memory (r4 advisor)
                    line = resp.readline(_MAX_WATCH_LINE + 1)
                    if not line:
                        break  # stream closed by server
                    if len(line) > _MAX_WATCH_LINE:
                        raise RuntimeError(
                            f"watch line exceeded {_MAX_WATCH_LINE} bytes"
                        )
                    try:
                        msg = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    result = msg.get("result") or {}
                    if result.get("events"):
                        self.refresh()
            except Exception as e:
                if not self._watch_stop.is_set():
                    record_log.info("etcd watch stream ended: %s", e)
            finally:
                self._watch_conn = None
                if conn is not None:
                    try:
                        conn.close()
                    except Exception:
                        pass
            self._watch_stop.wait(self.refresh_interval_s)

    def _headers(self):
        if self._user and self._auth_token is None:
            resp = request(
                f"{self.endpoint}/v3/auth/authenticate",
                method="POST",
                data=json.dumps(
                    {"name": self._user, "password": self._password}
                ).encode(),
            )
            if resp.status == 200:
                self._auth_token = resp.json().get("token")
        return {"Authorization": self._auth_token} if self._auth_token else {}

    def _range(self, keys_only: bool = False) -> dict:
        payload = {"key": _b64(self.rule_key)}
        if keys_only:
            # metadata-only poll: kvs come back with mod_revision but no
            # value, so the change check doesn't transfer the rule payload
            payload["keys_only"] = True
        for attempt in (0, 1):
            resp = request(
                f"{self.endpoint}/v3/kv/range",
                method="POST",
                data=json.dumps(payload).encode(),
                headers=self._headers(),
                timeout_s=5.0,
            )
            if resp.status == 200:
                return resp.json()
            # etcd simple tokens expire (default TTL 300s); drop the cached
            # token and re-authenticate once instead of failing every poll
            # until restart
            if resp.status in (401, 403) and self._user and attempt == 0:
                self._auth_token = None
                continue
            break
        raise RuntimeError(f"etcd range failed: {resp.status} {resp.text}")

    def read_source(self) -> str:
        body = self._range()
        kvs = body.get("kvs") or []
        if not kvs:
            self._last_mod_rev = 0
            return ""
        self._last_mod_rev = int(kvs[0].get("mod_revision", 0))
        return base64.b64decode(kvs[0].get("value", "")).decode("utf-8")

    def is_modified(self) -> bool:
        body = self._range(keys_only=True)
        kvs = body.get("kvs") or []
        rev = int(kvs[0].get("mod_revision", 0)) if kvs else 0
        return rev != (self._last_mod_rev or 0)
