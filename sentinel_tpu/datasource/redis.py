"""Redis datasource (analog of ``sentinel-datasource-redis``).

Reference model: initial rules from ``GET ruleKey``; updates arrive as
pub/sub messages on ``channel`` whose *payload is the new rule JSON* (the
publisher sends the full config, the datasource never re-reads the key on a
message). Same protocol here over a ~100-line RESP2 client — no vendored
driver.
"""

from __future__ import annotations

import socket
import threading
from typing import List, Optional

from sentinel_tpu.core.log import record_log
from sentinel_tpu.datasource.base import Converter, ReadableDataSource

_CRLF = b"\r\n"


class RespError(RuntimeError):
    pass


def encode_command(*parts: str) -> bytes:
    """RESP array of bulk strings — the only request shape clients send."""
    out = [b"*%d" % len(parts), _CRLF]
    for p in parts:
        raw = p.encode() if isinstance(p, str) else p
        out += [b"$%d" % len(raw), _CRLF, raw, _CRLF]
    return b"".join(out)


class _Reader:
    """Buffered RESP2 reply parser over a socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""

    def _line(self) -> bytes:
        while _CRLF not in self.buf:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self.buf += chunk
        line, self.buf = self.buf.split(_CRLF, 1)
        return line

    def _exact(self, n: int) -> bytes:
        while len(self.buf) < n + 2:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self.buf += chunk
        data, self.buf = self.buf[:n], self.buf[n + 2:]  # strip CRLF
        return data

    def read_reply(self):
        line = self._line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n == -1 else self._exact(n)
        if kind == b"*":
            n = int(rest)
            return None if n == -1 else [self.read_reply() for _ in range(n)]
        raise RespError(f"unexpected RESP type byte {kind!r}")


class RedisClient:
    """Minimal synchronous RESP2 client (GET/AUTH/SELECT/SUBSCRIBE)."""

    def __init__(self, host="127.0.0.1", port=6379, password: Optional[str] = None,
                 db: int = 0, timeout_s: float = 5.0):
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self.reader = _Reader(self.sock)
        if password:
            self.execute("AUTH", password)
        if db:
            self.execute("SELECT", str(db))

    def execute(self, *parts: str):
        self.sock.sendall(encode_command(*parts))
        return self.reader.read_reply()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class RedisDataSource(ReadableDataSource):
    def __init__(
        self,
        converter: Converter,
        host: str = "127.0.0.1",
        port: int = 6379,
        rule_key: str = "sentinel.rules",
        channel: str = "sentinel.rules.channel",
        password: Optional[str] = None,
        db: int = 0,
    ):
        super().__init__(converter)
        self._conn_args = (host, port, password, db)
        self.rule_key = rule_key
        self.channel = channel
        self._sub: Optional[RedisClient] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def read_source(self) -> str:
        host, port, password, db = self._conn_args
        client = RedisClient(host, port, password, db)
        try:
            raw = client.execute("GET", self.rule_key)
            return raw.decode() if isinstance(raw, bytes) else (raw or "")
        finally:
            client.close()

    _RECONNECT_DELAY_S = 1.0

    def start(self) -> "RedisDataSource":
        self.refresh()  # initial GET
        self._subscribe()  # fail fast if redis is down at startup
        self._thread = threading.Thread(
            target=self._listen, daemon=True, name="sentinel-redis-sub"
        )
        self._thread.start()
        return self

    def _subscribe(self) -> None:
        host, port, password, db = self._conn_args
        self._sub = RedisClient(host, port, password, db)
        self._sub.execute("SUBSCRIBE", self.channel)
        self._sub.sock.settimeout(None)  # block on messages indefinitely

    def _listen(self) -> None:
        while not self._stop.is_set():
            try:
                reply = self._sub.reader.read_reply()
            except (ConnectionError, OSError, RespError):
                if self._stop.is_set():
                    return
                # redis restarted / transient drop: resubscribe with backoff
                # and re-read the key — a publish during the gap is lost on
                # the pub/sub channel, so the GET resync is load-bearing
                record_log.warning(
                    "redis subscription lost; reconnecting in %ss",
                    self._RECONNECT_DELAY_S,
                )
                self._sub.close()
                if self._stop.wait(self._RECONNECT_DELAY_S):
                    return
                try:
                    self._subscribe()
                    self.refresh()
                except (ConnectionError, OSError, RespError) as e:
                    # RespError covers transient server states like
                    # "-LOADING ..." right after a restart — retry, don't die
                    record_log.warning("redis reconnect failed: %s", e)
                continue
            if not (isinstance(reply, list) and len(reply) == 3):
                continue
            kind, _chan, payload = reply
            if kind == b"message" and isinstance(payload, bytes):
                # the published payload IS the new config
                try:
                    self.property.update_value(
                        self.converter(payload.decode())
                    )
                except Exception as e:
                    record_log.warning("redis rule payload rejected: %s", e)

    def close(self) -> None:
        self._stop.set()
        if self._sub is not None:
            self._sub.close()
        if self._thread is not None:
            self._thread.join(timeout=2)


def parse_subscribe_messages(replies: List) -> List[bytes]:
    """Test helper: extract message payloads from raw pub/sub replies."""
    return [
        r[2] for r in replies
        if isinstance(r, list) and len(r) == 3 and r[0] == b"message"
    ]
