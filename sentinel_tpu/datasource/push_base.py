"""Push-style datasource base: a background watcher thread that blocks on the
backend's change-notification primitive (long-poll, subscription) and
refreshes the property when the source changes.

This is the structural analog of the reference's listener-based backends
(e.g. Nacos ``configService.addListener``, ZooKeeper ``NodeCacheListener``,
Redis pub/sub — one submodule each under ``sentinel-extension/
sentinel-datasource-*``): the vendor client's callback thread becomes an
explicit watch loop here.
"""

from __future__ import annotations

import threading
from typing import Optional

from sentinel_tpu.core.log import record_log
from sentinel_tpu.datasource.base import Converter, ReadableDataSource

# After a watch error, back off instead of hot-looping against a dead server.
WATCH_RETRY_DELAY_S = 1.0


class WatchingDataSource(ReadableDataSource):
    """Subclasses implement ``watch_once`` — block until a change is likely
    (or a timeout elapses) and return True to trigger a refresh."""

    def __init__(self, converter: Converter):
        super().__init__(converter)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "WatchingDataSource":
        self.refresh()  # initial load, like every reference datasource ctor
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"sentinel-datasource-{type(self).__name__}",
        )
        self._thread.start()
        return self

    def watch_once(self) -> bool:
        raise NotImplementedError

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self.watch_once() and not self._stop.is_set():
                    self.refresh()
            except Exception as e:
                record_log.warning(
                    "%s watch failed: %s", type(self).__name__, e
                )
                self._stop.wait(WATCH_RETRY_DELAY_S)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
