"""Spring Cloud Config datasource (analog of
``sentinel-datasource-spring-cloud-config``).

Reads one property out of a config-server environment:
``GET {uri}/{application}/{profile}[/{label}]`` → property sources searched
front-to-back (highest precedence first, config-server order) for
``rule_key``. The reference refreshes on Spring's ``RefreshEvent``; without
a Spring bus this polls, which is what the config-server's own clients do
absent a bus too.
"""

from __future__ import annotations

from typing import Optional

from sentinel_tpu.datasource.base import AutoRefreshDataSource, Converter
from sentinel_tpu.datasource.http_util import request


class SpringCloudConfigDataSource(AutoRefreshDataSource):
    def __init__(
        self,
        converter: Converter,
        uri: str = "http://127.0.0.1:8888",
        application: str = "sentinel",
        profile: str = "default",
        label: Optional[str] = None,
        rule_key: str = "sentinel.rules",
        refresh_interval_s: float = 3.0,
    ):
        self.uri = uri.rstrip("/")
        self.application = application
        self.profile = profile
        self.label = label
        self.rule_key = rule_key
        super().__init__(converter, refresh_interval_s)

    def read_source(self) -> str:
        path = f"{self.uri}/{self.application}/{self.profile}"
        if self.label:
            path += f"/{self.label}"
        resp = request(path, timeout_s=5.0)
        if resp.status != 200:
            raise RuntimeError(f"config server status {resp.status}")
        for source in resp.json().get("propertySources") or []:
            value = (source.get("source") or {}).get(self.rule_key)
            if value is not None:
                return str(value)
        return ""
