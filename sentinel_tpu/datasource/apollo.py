"""Apollo config datasource (analog of ``sentinel-datasource-apollo``).

The reference reads one property (``ruleKey``) of an Apollo namespace via
the Apollo OpenAPI client. Here the open HTTP API is used directly:

- read:  ``GET /configs/{appId}/{cluster}/{namespace}`` →
  ``{"releaseKey": ..., "configurations": {ruleKey: rulesJson}}``
- watch: ``GET /notifications/v2?appId&cluster&notifications=[...]`` —
  Apollo's long-poll; HTTP 200 means a listed namespace changed
  (304 = timeout, nothing changed).
"""

from __future__ import annotations

import json

from sentinel_tpu.datasource.base import Converter
from sentinel_tpu.datasource.http_util import request
from sentinel_tpu.datasource.push_base import WatchingDataSource


class ApolloDataSource(WatchingDataSource):
    def __init__(
        self,
        converter: Converter,
        server_url: str = "http://127.0.0.1:8080",
        app_id: str = "sentinel",
        cluster: str = "default",
        namespace: str = "application",
        rule_key: str = "sentinel.rules",
        default_value: str = "",
        long_poll_timeout_s: float = 60.0,
    ):
        self.server_url = server_url.rstrip("/")
        self.app_id = app_id
        self.cluster = cluster
        self.namespace = namespace
        self.rule_key = rule_key
        self.default_value = default_value
        self.long_poll_timeout_s = long_poll_timeout_s
        self._notification_id = -1
        super().__init__(converter)

    def read_source(self) -> str:
        resp = request(
            f"{self.server_url}/configs/{self.app_id}/{self.cluster}/"
            f"{self.namespace}",
            timeout_s=5.0,
        )
        if resp.status != 200:
            return self.default_value
        configs = resp.json().get("configurations") or {}
        return configs.get(self.rule_key, self.default_value)

    def watch_once(self) -> bool:
        notifications = json.dumps(
            [{"namespaceName": self.namespace,
              "notificationId": self._notification_id}]
        )
        resp = request(
            f"{self.server_url}/notifications/v2",
            params={
                "appId": self.app_id,
                "cluster": self.cluster,
                "notifications": notifications,
            },
            timeout_s=self.long_poll_timeout_s + 10.0,
        )
        if resp.status == 304:
            return False  # long-poll timeout, nothing changed
        if resp.status != 200:
            raise RuntimeError(f"apollo notifications failed: {resp.status}")
        for note in resp.json() or []:
            if note.get("namespaceName") == self.namespace:
                self._notification_id = note.get(
                    "notificationId", self._notification_id
                )
        return True
