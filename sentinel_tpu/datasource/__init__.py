"""Dynamic rule datasources (analog of ``sentinel-extension/sentinel-datasource-*``).

``ReadableDataSource`` parses an external source into rules and publishes
them into a ``DynamicProperty`` that rule managers subscribe to;
``WritableDataSource`` persists rules pushed through the command center.
"""

from sentinel_tpu.datasource.base import (
    Converter,
    ReadableDataSource,
    AutoRefreshDataSource,
    WritableDataSource,
    WritableDataSourceRegistry,
)
from sentinel_tpu.datasource.file import (
    FileRefreshableDataSource,
    FileWritableDataSource,
)
from sentinel_tpu.datasource.push_base import WatchingDataSource
from sentinel_tpu.datasource.consul import ConsulDataSource
from sentinel_tpu.datasource.etcd import EtcdDataSource
from sentinel_tpu.datasource.nacos import NacosDataSource
from sentinel_tpu.datasource.apollo import ApolloDataSource
from sentinel_tpu.datasource.eureka import EurekaDataSource
from sentinel_tpu.datasource.redis import RedisClient, RedisDataSource
from sentinel_tpu.datasource.spring_cloud_config import (
    SpringCloudConfigDataSource,
)
from sentinel_tpu.datasource.zookeeper import ZookeeperDataSource
from sentinel_tpu.datasource.converters import (
    flow_rules_from_json,
    flow_rules_to_json,
    degrade_rules_from_json,
    degrade_rules_to_json,
    system_rules_from_json,
    system_rules_to_json,
    authority_rules_from_json,
    authority_rules_to_json,
    param_flow_rules_from_json,
    param_flow_rules_to_json,
)

__all__ = [
    "Converter",
    "ReadableDataSource",
    "AutoRefreshDataSource",
    "WritableDataSource",
    "WritableDataSourceRegistry",
    "FileRefreshableDataSource",
    "FileWritableDataSource",
    "WatchingDataSource",
    "ConsulDataSource",
    "EtcdDataSource",
    "NacosDataSource",
    "ApolloDataSource",
    "EurekaDataSource",
    "RedisClient",
    "RedisDataSource",
    "SpringCloudConfigDataSource",
    "ZookeeperDataSource",
    "flow_rules_from_json",
    "flow_rules_to_json",
    "degrade_rules_from_json",
    "degrade_rules_to_json",
    "system_rules_from_json",
    "system_rules_to_json",
    "authority_rules_from_json",
    "authority_rules_to_json",
    "param_flow_rules_from_json",
    "param_flow_rules_to_json",
]
