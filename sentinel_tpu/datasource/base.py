"""Datasource abstractions.

Analogs: ``ReadableDataSource<S,T>`` / ``WritableDataSource<T>`` /
``Converter<S,T>`` and ``AbstractDataSource`` / ``AutoRefreshDataSource``
(``sentinel-datasource-extension/.../datasource/AbstractDataSource.java:29``,
``AutoRefreshDataSource.java:32``), plus ``WritableDataSourceRegistry``
(write-back target of the ``setRules`` command,
``ModifyRulesCommandHandler.java:46``).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Generic, Optional, TypeVar

from sentinel_tpu.core.log import record_log
from sentinel_tpu.core.property import DynamicProperty

S = TypeVar("S")
T = TypeVar("T")

Converter = Callable[[S], T]

# failed refreshes by datasource class, fed to the Prometheus surface as
# ``sentinel_datasource_refresh_failures_total`` (metrics.exporter)
_FAILURES_LOCK = threading.Lock()
_REFRESH_FAILURES: Dict[str, int] = {}


def _count_refresh_failure(source: "ReadableDataSource") -> None:
    name = type(source).__name__
    with _FAILURES_LOCK:
        _REFRESH_FAILURES[name] = _REFRESH_FAILURES.get(name, 0) + 1


def refresh_failure_totals() -> Dict[str, int]:
    """Cumulative failed refreshes per datasource class."""
    with _FAILURES_LOCK:
        return dict(_REFRESH_FAILURES)


def reset_refresh_failures_for_tests() -> None:
    with _FAILURES_LOCK:
        _REFRESH_FAILURES.clear()


class ReadableDataSource(Generic[S, T]):
    """Parses a source value into rules and publishes into ``property``."""

    def __init__(self, converter: Converter):
        self.converter = converter
        self.property: DynamicProperty = DynamicProperty()

    def read_source(self) -> S:
        raise NotImplementedError

    def load_config(self) -> Optional[T]:
        return self.converter(self.read_source())

    def refresh(self) -> bool:
        """One read→parse→publish cycle. Returns True on success. A failed
        read or parse keeps the last-known-good config published (a broken
        source must degrade to stale rules, never to NO rules) and counts
        toward ``sentinel_datasource_refresh_failures_total``."""
        try:
            config = self.load_config()
        except Exception as e:
            _count_refresh_failure(self)
            record_log.warning("datasource refresh failed: %s", e)
            return False
        if config is None and self.property.value is not None:
            # a parse that yields nothing while good config is live is a
            # failure (truncated file mid-write, empty GET on a flaky
            # backend) — publishing None would wipe the rules
            _count_refresh_failure(self)
            record_log.warning(
                "datasource refresh parsed no config; keeping last-known-good"
            )
            return False
        self.property.update_value(config)
        return True

    def close(self) -> None:
        pass


class AutoRefreshDataSource(ReadableDataSource[S, T]):
    """Polls ``read_source`` on a background thread
    (``AutoRefreshDataSource.java:32``). Subclasses may override
    ``is_modified`` to skip unchanged sources.

    Consecutive failures back the poll off exponentially (doubling per
    failure, capped at ``backoff_cap_x`` times the configured interval) so a
    dead backend is probed, not hammered; one success snaps the cadence
    back."""

    def __init__(self, converter: Converter, refresh_interval_s: float = 3.0,
                 backoff_cap_x: float = 10.0):
        super().__init__(converter)
        self.refresh_interval_s = refresh_interval_s
        self.backoff_cap_x = float(backoff_cap_x)
        self._consecutive_failures = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "AutoRefreshDataSource":
        self.refresh()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="sentinel-datasource-refresh"
        )
        self._thread.start()
        return self

    def _poll_interval_s(self) -> float:
        cap = self.refresh_interval_s * self.backoff_cap_x
        return min(
            self.refresh_interval_s * (2.0 ** self._consecutive_failures), cap
        )

    def _loop(self) -> None:
        while not self._stop.wait(self._poll_interval_s()):
            try:
                if not self.is_modified():
                    continue
                ok = self.refresh()
            except Exception as e:
                ok = False
                _count_refresh_failure(self)
                record_log.warning("datasource poll failed: %s", e)
            if ok:
                self._consecutive_failures = 0
            else:
                self._consecutive_failures += 1

    def is_modified(self) -> bool:
        return True

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


class WritableDataSource(Generic[T]):
    def write(self, value: T) -> None:
        raise NotImplementedError


class WritableDataSourceRegistry:
    """Per-rule-type write-back targets (``WritableDataSourceRegistry.java``)."""

    _lock = threading.RLock()
    _sources: Dict[str, WritableDataSource] = {}

    @classmethod
    def register(cls, rule_type: str, source: WritableDataSource) -> None:
        with cls._lock:
            cls._sources[rule_type] = source

    @classmethod
    def get(cls, rule_type: str) -> Optional[WritableDataSource]:
        return cls._sources.get(rule_type)

    @classmethod
    def write_if_registered(cls, rule_type: str, value) -> bool:
        src = cls.get(rule_type)
        if src is None:
            return False
        src.write(value)
        return True

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._sources.clear()
