"""Datasource abstractions.

Analogs: ``ReadableDataSource<S,T>`` / ``WritableDataSource<T>`` /
``Converter<S,T>`` and ``AbstractDataSource`` / ``AutoRefreshDataSource``
(``sentinel-datasource-extension/.../datasource/AbstractDataSource.java:29``,
``AutoRefreshDataSource.java:32``), plus ``WritableDataSourceRegistry``
(write-back target of the ``setRules`` command,
``ModifyRulesCommandHandler.java:46``).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Generic, Optional, TypeVar

from sentinel_tpu.core.log import record_log
from sentinel_tpu.core.property import DynamicProperty

S = TypeVar("S")
T = TypeVar("T")

Converter = Callable[[S], T]


class ReadableDataSource(Generic[S, T]):
    """Parses a source value into rules and publishes into ``property``."""

    def __init__(self, converter: Converter):
        self.converter = converter
        self.property: DynamicProperty = DynamicProperty()

    def read_source(self) -> S:
        raise NotImplementedError

    def load_config(self) -> Optional[T]:
        return self.converter(self.read_source())

    def refresh(self) -> None:
        try:
            self.property.update_value(self.load_config())
        except Exception as e:
            record_log.warning("datasource refresh failed: %s", e)

    def close(self) -> None:
        pass


class AutoRefreshDataSource(ReadableDataSource[S, T]):
    """Polls ``read_source`` on a background thread
    (``AutoRefreshDataSource.java:32``). Subclasses may override
    ``is_modified`` to skip unchanged sources."""

    def __init__(self, converter: Converter, refresh_interval_s: float = 3.0):
        super().__init__(converter)
        self.refresh_interval_s = refresh_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "AutoRefreshDataSource":
        self.refresh()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="sentinel-datasource-refresh"
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.refresh_interval_s):
            try:
                if self.is_modified():
                    self.refresh()
            except Exception as e:
                record_log.warning("datasource poll failed: %s", e)

    def is_modified(self) -> bool:
        return True

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


class WritableDataSource(Generic[T]):
    def write(self, value: T) -> None:
        raise NotImplementedError


class WritableDataSourceRegistry:
    """Per-rule-type write-back targets (``WritableDataSourceRegistry.java``)."""

    _lock = threading.RLock()
    _sources: Dict[str, WritableDataSource] = {}

    @classmethod
    def register(cls, rule_type: str, source: WritableDataSource) -> None:
        with cls._lock:
            cls._sources[rule_type] = source

    @classmethod
    def get(cls, rule_type: str) -> Optional[WritableDataSource]:
        return cls._sources.get(rule_type)

    @classmethod
    def write_if_registered(cls, rule_type: str, value) -> bool:
        src = cls.get(rule_type)
        if src is None:
            return False
        src.write(value)
        return True

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._sources.clear()
