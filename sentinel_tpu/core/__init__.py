"""Core substrate: config, clock, registry (SPI analog), dynamic properties.

Analog of reference L0 (``sentinel-core/.../{util,spi,config,log,property}``).
"""
