"""Layered static configuration (analog of ``SentinelConfig.java:54`` +
``SentinelConfigLoader``).

Resolution order (highest wins), mirroring the reference's JVM-props-over-file:
1. explicit ``set()`` calls
2. environment variables: ``CSP_SENTINEL_<KEY>`` with dots → underscores
3. a properties file (``SENTINEL_TPU_CONFIG`` env var, else ``~/.sentinel_tpu.properties``)
4. built-in defaults

Keys keep the reference's ``csp.sentinel.*`` names where one exists so operators
can carry configs across.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional


_DEFAULTS: Dict[str, str] = {
    # reference: SentinelConfig.java:60-70
    "csp.sentinel.app.name": "",
    "csp.sentinel.app.type": "0",
    "csp.sentinel.metric.file.single.size": str(50 * 1024 * 1024),
    "csp.sentinel.metric.file.total.count": "6",
    "csp.sentinel.flow.cold.factor": "3",
    "csp.sentinel.statistic.max.rt": "5000",
    # tpu-build additions
    "sentinel.tpu.engine.max.resources": "4096",
    "sentinel.tpu.engine.batch.size": "1024",
    "sentinel.tpu.server.port": "18730",
    "sentinel.tpu.server.idle.seconds": "600",
    "csp.sentinel.api.port": "8719",
    "csp.sentinel.heartbeat.interval.ms": "10000",
    # cluster HA (sentinel_tpu.ha): endpoint circuit breaker + failover
    "sentinel.tpu.ha.failure.threshold": "3",
    "sentinel.tpu.ha.backoff.base.ms": "100",
    "sentinel.tpu.ha.backoff.max.ms": "10000",
    "sentinel.tpu.ha.backoff.jitter": "0.2",
    "sentinel.tpu.ha.failover.deadline.ms": "500",
    "sentinel.tpu.ha.snapshot.period.s": "30",
    # client reconnect backoff (cluster.client.TokenClient)
    "sentinel.tpu.client.reconnect.base.s": "0.1",
    "sentinel.tpu.client.reconnect.max.s": "30",
    # Envoy RLS behavior when the token service errors: allow | deny
    "csp.sentinel.rls.failure.mode": "allow",
}


class SentinelConfig:
    """Process-global property registry. Thread-safe."""

    _lock = threading.RLock()
    _props: Dict[str, str] = {}  # explicit set() layer only
    _file_props: Dict[str, str] = {}  # file layer, below env
    _file_loaded = False

    @classmethod
    def _load_file_once(cls) -> None:
        if cls._file_loaded:
            return
        cls._file_loaded = True
        path = os.environ.get(
            "SENTINEL_TPU_CONFIG", os.path.expanduser("~/.sentinel_tpu.properties")
        )
        if not os.path.isfile(path):
            return
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith("#") or "=" not in line:
                        continue
                    k, _, v = line.partition("=")
                    cls._file_props.setdefault(k.strip(), v.strip())
        except OSError:
            pass

    @classmethod
    def get(cls, key: str, default: Optional[str] = None) -> Optional[str]:
        with cls._lock:
            if key in cls._props:
                return cls._props[key]
            env_key = "CSP_SENTINEL_" + key.replace("csp.sentinel.", "").replace(
                "sentinel.tpu.", "TPU_"
            ).replace(".", "_").upper()
            if env_key in os.environ:
                return os.environ[env_key]
            cls._load_file_once()
            if key in cls._file_props:
                return cls._file_props[key]
            if key in _DEFAULTS:
                return _DEFAULTS[key]
            return default

    @classmethod
    def set(cls, key: str, value: str) -> None:
        with cls._lock:
            cls._props[key] = str(value)

    @classmethod
    def get_int(cls, key: str, default: int = 0) -> int:
        v = cls.get(key)
        try:
            return int(v) if v is not None else default
        except ValueError:
            return default

    @classmethod
    def get_float(cls, key: str, default: float = 0.0) -> float:
        v = cls.get(key)
        try:
            return float(v) if v is not None else default
        except ValueError:
            return default

    @classmethod
    def get_bool(cls, key: str, default: bool = False) -> bool:
        v = cls.get(key)
        if v is None:
            return default
        return v.strip().lower() in ("1", "true", "yes", "on")

    @classmethod
    def app_name(cls) -> str:
        return (
            cls.get("csp.sentinel.app.name")
            or os.environ.get("SENTINEL_APP_NAME")
            or "sentinel-tpu-app"
        )

    @classmethod
    def cold_factor(cls) -> int:
        # reference: SentinelConfig.java COLD_FACTOR, floor of 1 applied by WarmUpController
        return max(2, cls.get_int("csp.sentinel.flow.cold.factor", 3))

    @classmethod
    def max_rt(cls) -> int:
        return cls.get_int("csp.sentinel.statistic.max.rt", 5000)

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._props.clear()
            cls._file_props.clear()
            cls._file_loaded = False
