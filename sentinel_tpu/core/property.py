"""Push-based dynamic configuration primitive.

Analog of ``sentinel-core/.../property/{SentinelProperty,DynamicSentinelProperty,
PropertyListener}.java``: rule managers subscribe a listener to a property; data
sources (file/polling/push) publish new values into it; ``update_value`` fans out
to listeners only when the value actually changed.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")


class PropertyListener(Generic[T]):
    def config_update(self, value: Optional[T]) -> None:
        raise NotImplementedError

    def config_load(self, value: Optional[T]) -> None:
        # reference: PropertyListener.configLoad — first-load callback
        self.config_update(value)


class FuncListener(PropertyListener[T]):
    def __init__(self, fn: Callable[[Optional[T]], None]):
        self._fn = fn

    def config_update(self, value: Optional[T]) -> None:
        self._fn(value)


class DynamicProperty(Generic[T]):
    """``DynamicSentinelProperty``: value holder + listener fan-out."""

    def __init__(self, value: Optional[T] = None):
        self._lock = threading.RLock()
        self._value: Optional[T] = value
        self._listeners: List[PropertyListener[T]] = []

    @property
    def value(self) -> Optional[T]:
        return self._value

    def add_listener(self, listener: PropertyListener[T]) -> None:
        with self._lock:
            self._listeners.append(listener)
            listener.config_load(self._value)

    def listen(self, fn: Callable[[Optional[T]], None]) -> PropertyListener[T]:
        lst = FuncListener(fn)
        self.add_listener(lst)
        return lst

    def remove_listener(self, listener: PropertyListener[T]) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def update_value(self, value: Optional[T]) -> bool:
        """Publish; returns True if the value changed and listeners fired.

        reference: DynamicSentinelProperty.updateValue — no-op on equal value.

        Fan-out happens under the (re-entrant) lock so concurrent publishers
        cannot deliver values to listeners out of order — ``value`` and the
        listeners' view can never diverge. (The reference fires outside any
        lock and has this race; a ground-up redesign shouldn't.)
        """
        with self._lock:
            if self._value == value:
                return False
            self._value = value
            for lst in list(self._listeners):
                lst.config_update(value)
        return True
