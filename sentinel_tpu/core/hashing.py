"""Stable parameter-value hashing.

Hot-param values never cross the wire or touch the device — only their
stable 63-bit hash does (``cluster.protocol`` PARAM_FLOW frames, the CMS
kernel's host-side index derivation). Stability across processes matters:
client and server must agree, so Python's salted ``hash()`` is out.
"""

from __future__ import annotations

import hashlib
from typing import Any


def stable_param_hash(value: Any) -> int:
    if isinstance(value, bytes):
        data = value
    elif isinstance(value, str):
        data = value.encode()
    else:
        data = repr(value).encode()
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big"
    ) & ((1 << 63) - 1)
