"""Stable parameter-value hashing.

Hot-param values never cross the wire or touch the device — only their
stable 63-bit hash does (``cluster.protocol`` PARAM_FLOW frames, the CMS
kernel's host-side index derivation). Stability across processes matters:
client and server must agree, so Python's salted ``hash()`` is out.
"""

from __future__ import annotations

import hashlib
from typing import Any


def stable_param_hash(value: Any) -> int:
    """Type-tagged so ``1``, ``"1"`` and ``b"1"`` never share a bucket.

    Stability holds for values whose textual form is process-stable (str,
    bytes, numbers, bools, None, and containers thereof). Objects whose
    ``repr`` embeds ``id()`` hash per-instance — pass a stable key (e.g. the
    object's id field) as the parameter instead.

    **Wire contract**: these hashes cross the token-RPC wire (PARAM_FLOW
    requests carry hashes, not values — ``cluster/protocol.py``), so every
    node of a cluster must hash identically. Any change to the tagging or
    digest here is a protocol break and must ship with a wire-protocol
    version bump and a rolling-upgrade note.
    """
    if isinstance(value, bytes):
        tag, data = b"b", value
    elif isinstance(value, str):
        tag, data = b"s", value.encode()
    elif isinstance(value, bool):  # before int: bool is an int subclass
        tag, data = b"B", str(value).encode()
    elif isinstance(value, int):
        tag, data = b"i", str(value).encode()
    elif isinstance(value, float):
        tag, data = b"f", repr(value).encode()
    elif value is None:
        tag, data = b"n", b""
    else:
        tag, data = b"r", repr(value).encode()
    return int.from_bytes(
        hashlib.blake2b(tag + b"\x00" + data, digest_size=8).digest(), "big"
    ) & ((1 << 63) - 1)
