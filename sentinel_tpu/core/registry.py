"""Ordered extension registry — the SPI mechanism.

The reference glues every layer together with a classpath service loader
(``sentinel-core/.../spi/SpiLoader.java:73``) plus an ``@Spi(order=…, isDefault=…)``
annotation; slots, slot-chain builders, token services, command handlers and init
functions are all discovered this way.

Python needs no classpath scanning: the analog is a named registry with an
``@provides`` decorator carrying ``order`` / ``is_default``. Entry points are
explicit imports (``sentinel_tpu.init`` wires the default set), which keeps the
extension seam (register your own slot/handler/datasource) without the JVM
machinery.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Generic, List, Optional, Tuple, TypeVar

T = TypeVar("T")


class Registry(Generic[T]):
    """A named, order-sorted registry of factories (SpiLoader analog).

    ``loadInstanceListSorted()`` → :meth:`instances_sorted`;
    ``loadFirstInstanceOrDefault()`` → :meth:`first_or_default`.
    """

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.RLock()
        self._entries: List[Tuple[int, bool, str, Callable[[], T]]] = []

    def register(
        self,
        factory: Callable[[], T],
        *,
        order: int = 0,
        is_default: bool = False,
        name: Optional[str] = None,
    ) -> Callable[[], T]:
        with self._lock:
            self._entries.append(
                (order, is_default, name or getattr(factory, "__name__", "?"), factory)
            )
            self._entries.sort(key=lambda e: e[0])
        return factory

    def provides(self, *, order: int = 0, is_default: bool = False, name: Optional[str] = None):
        """Decorator form: ``@registry.provides(order=-7000)``."""

        def deco(factory: Callable[[], T]) -> Callable[[], T]:
            return self.register(factory, order=order, is_default=is_default, name=name)

        return deco

    def instances_sorted(self) -> List[T]:
        with self._lock:
            return [f() for _, _, _, f in self._entries]

    def first_or_default(self) -> Optional[T]:
        with self._lock:
            if not self._entries:
                return None
            for _, is_default, _, f in self._entries:
                if is_default:
                    return f()
            return self._entries[0][3]()

    def by_name(self, name: str) -> Optional[T]:
        with self._lock:
            for _, _, n, f in self._entries:
                if n == name:
                    return f()
        return None

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


_registries: Dict[str, Registry[Any]] = {}
_registries_lock = threading.Lock()


def registry(name: str) -> Registry[Any]:
    """Get or create the process-global registry for an extension point."""
    with _registries_lock:
        reg = _registries.get(name)
        if reg is None:
            reg = _registries[name] = Registry(name)
        return reg
