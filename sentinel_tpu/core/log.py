"""Record log (analog of ``sentinel-core/.../log/RecordLog.java``).

The reference writes an internal file-based record log with a pluggable SPI
(slf4j bridge in ``sentinel-logging``). Here: a stdlib logger named
``sentinel_tpu`` writing to ``$SENTINEL_LOG_DIR`` (default ``~/logs/csp`` like
the reference's ``LogBase``) when file logging is enabled, else stderr.
"""

from __future__ import annotations

import logging
import os

_LOGGER_NAME = "sentinel_tpu"


def _build_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if logger.handlers:
        return logger
    logger.setLevel(logging.INFO)
    log_dir = os.environ.get("SENTINEL_LOG_DIR")
    handler: logging.Handler
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        handler = logging.FileHandler(os.path.join(log_dir, "sentinel-record.log"))
    else:
        handler = logging.StreamHandler()
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s [%(name)s] %(message)s")
    )
    logger.addHandler(handler)
    logger.propagate = False
    return logger


record_log = _build_logger()
