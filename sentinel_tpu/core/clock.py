"""Millisecond clock with explicit injection for deterministic tests.

The reference uses an adaptive cached clock (``sentinel-core/.../util/TimeUtil.java:42``:
a dedicated thread writes a volatile millis when read rates exceed ~1200/s) and tests
mock the static method via PowerMock (``AbstractTimeBasedTest.java:28-55``).

The TPU build makes time an *explicit input* instead: every kernel takes ``now_ms``
as an argument, and the host obtains it from a swappable ``Clock``. This removes the
whole mock-the-static-clock test fixture class — tests pass a ``ManualClock``.

Python's ``time.time_ns`` is a vDSO call (~20ns); no caching thread is needed.
"""

from __future__ import annotations

import threading
import time

from sentinel_tpu import chaos as _chaos


class Clock:
    """Source of wall-clock milliseconds. Subclass to virtualize time."""

    def now_ms(self) -> int:
        raise NotImplementedError

    def wait_ms(self, ms: float) -> None:
        """Block for ``ms`` (traffic shapers queueing requests). Virtual clocks
        advance instead of sleeping, keeping shaper tests instantaneous."""
        raise NotImplementedError


class SystemClock(Clock):
    __slots__ = ()

    def now_ms(self) -> int:
        return time.time_ns() // 1_000_000

    def wait_ms(self, ms: float) -> None:
        if ms > 0:
            time.sleep(ms / 1000.0)


class ManualClock(Clock):
    """Deterministic clock for tests (analog of the reference's fake-clock fixture,
    ``sentinel-cluster-server-default/src/test/.../AbstractTimeBasedTest.java``)."""

    __slots__ = ("_ms",)

    def __init__(self, start_ms: int = 1_700_000_000_000):
        self._ms = int(start_ms)

    def now_ms(self) -> int:
        return self._ms

    def wait_ms(self, ms: float) -> None:
        if ms > 0:
            self._ms += int(ms)

    def set_ms(self, ms: int) -> None:
        self._ms = int(ms)

    def advance(self, delta_ms: int) -> None:
        self._ms += int(delta_ms)

    # Convenience names mirroring the reference fixture's sleep()/sleepSecond().
    def sleep(self, delta_ms: int) -> None:
        self.advance(delta_ms)

    def sleep_second(self, seconds: int = 1) -> None:
        self.advance(seconds * 1000)


_lock = threading.Lock()
_clock: Clock = SystemClock()


def get_clock() -> Clock:
    return _clock


def set_clock(clock: Clock) -> Clock:
    """Install a process-global clock; returns the previous one."""
    global _clock
    with _lock:
        prev, _clock = _clock, clock
        return prev


def now_ms() -> int:
    if _chaos.ARMED:  # clock_skew injection (constant offset while armed)
        return _clock.now_ms() + int(_chaos.skew_ms())
    return _clock.now_ms()
