"""Shared embedded-HTTP-server scaffolding.

Both control-plane surfaces — the command center (``transport/command.py``)
and the dashboard (``dashboard/server.py``) — are tiny threaded HTTP
services; this module owns the one copy of the handler/lifecycle plumbing
(stdlib ``ThreadingHTTPServer``, port-0 resolution, quiet logging).
"""

from __future__ import annotations

import inspect
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from sentinel_tpu.core.log import record_log

# (status code, body text, content type)
Response = Tuple[int, str, str]

# (method, path-without-leading-slash, query params, body) -> Response.
# A router declaring a parameter named ``headers`` (or **kwargs) also
# receives the request headers as a keyword (an email.message.Message-like
# mapping) — used for cookie-based auth.
Router = Callable[[str, str, dict, str], Response]

MAX_BODY_BYTES = 4 * 1024 * 1024  # rule payloads are small; cap abuse


def json_response(code: int, text: str) -> Response:
    return (code, text, "application/json; charset=utf-8")


def html_response(code: int, text: str) -> Response:
    return (code, text, "text/html; charset=utf-8")


class HttpService:
    """A routed, threaded HTTP server with start/stop lifecycle."""

    def __init__(self, router: Router, host: str, port: int, name: str):
        self.router = router
        self.host = host
        self.port = port
        self.name = name
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HttpService":
        router = self.router
        name = self.name
        # headers are passed as an opt-in KEYWORD, detected by name — a
        # positional count would misfire on variadic or defaulted routers
        try:
            sig_params = inspect.signature(router).parameters
            wants_headers = "headers" in sig_params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in sig_params.values()
            )
        except (TypeError, ValueError):  # builtins/partials w/o signature
            wants_headers = False

        class Handler(BaseHTTPRequestHandler):
            server_version = "SentinelTPU"

            def _dispatch(self, method: str, body: str) -> None:
                parsed = urlparse(self.path)
                params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                try:
                    args = (method, parsed.path.strip("/"), params, body)
                    if wants_headers:
                        result = router(*args, headers=self.headers)
                    else:
                        result = router(*args)
                except Exception as e:
                    record_log.exception("%s request failed", name)
                    result = json_response(500, json.dumps({"error": str(e)}))
                # routers may append a 4th element: extra response headers
                # (e.g. Set-Cookie for the dashboard login)
                code, text, ctype = result[:3]
                extra = result[3] if len(result) > 3 else {}
                data = text.encode()
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(data)))
                    for k, v in extra.items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(data)
                except (BrokenPipeError, ConnectionResetError):
                    # the client gave up (e.g. its timeout fired while a slow
                    # handler ran) — the work is done; dropping the response
                    # is not an error worth a traceback
                    record_log.warning(
                        "%s: client closed before response (%s)",
                        name, parsed.path,
                    )

            def do_GET(self):  # noqa: N802
                self._dispatch("GET", "")

            def _read_body(self) -> Optional[str]:
                length = int(self.headers.get("Content-Length") or 0)
                if length > MAX_BODY_BYTES:
                    self.send_response(413)
                    self.end_headers()
                    return None
                return self.rfile.read(length).decode() if length else ""

            def do_POST(self):  # noqa: N802
                body = self._read_body()
                if body is not None:
                    self._dispatch("POST", body)

            def do_PUT(self):  # noqa: N802
                body = self._read_body()
                if body is not None:
                    self._dispatch("PUT", body)

            def do_DELETE(self):  # noqa: N802
                self._dispatch("DELETE", "")

            def log_message(self, fmt, *args):  # record_log has the failures
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name=self.name
        )
        self._thread.start()
        record_log.info("%s on %s:%d", self.name, self.host, self.port)
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
