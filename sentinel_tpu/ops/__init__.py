"""Pallas TPU kernels for the engine's hot ops.

These are the "native" compute components of the framework (SURVEY.md §2:
the reference is 100% Java, so its JVM-concurrency hot paths — LongAdder
arrays, CAS window loops — map to device kernels here, not to C/C++):

- :mod:`sentinel_tpu.ops.prefix_pallas` — tiled in-batch segment prefix sums
  (the admission primitive) that never materializes the [N, N] mask in HBM.
- :mod:`sentinel_tpu.ops.cms_pallas` — the count-min-sketch decide+update
  kernel: whole sketch resident in VMEM, gathers/scatters expressed as
  one-hot MXU matmuls.

Every kernel has a pure-jax reference implementation elsewhere in the tree
(`engine/prefix.py`, `engine/param.py`); the kernels are selected on TPU
backends and fall back to interpret mode in tests.
"""

from sentinel_tpu.ops.prefix_pallas import segment_prefix_pallas
from sentinel_tpu.ops.cms_pallas import cms_decide_update_pallas

__all__ = ["segment_prefix_pallas", "cms_decide_update_pallas"]
