"""Cumulative sums via blocked lower-triangular matmuls.

XLA lowers 1-D ``cumsum``/``cummax`` on TPU to a log-depth sequence of
lane-crossing shifted adds; at N=16k that costs ~0.3ms of device time —
orders of magnitude more than the arithmetic warrants, and the single
largest cost in the decision kernel's segment-prefix sums. The MXU gives
the same result essentially for free: reshape ``[N] -> [R, C]``, multiply
each row block by a ``[C, C]`` lower-triangular ones matrix (one batched
matmul), then add exclusive block offsets computed by a tiny ``[R, R]``
triangular matmul over the block totals. Two matmuls, no scans.

Exact for integer-valued float32 inputs with totals < 2^24 (window counts
are ints and far smaller).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# TPU matmuls default to bf16 passes; these cumsums carry integer counts
# whose exactness the admission math relies on, so force full f32.
_EXACT = jax.lax.Precision.HIGHEST


def blocked_cumsum(x, block: int = 128):
    """Inclusive cumsum along axis 0 of ``[N]`` or ``[N, K]`` float32 ``x``.

    The matmul formulation exists for the MXU; off-TPU it costs ~``block``×
    the FLOPs of the native lowering for nothing (measured: the [N, 64]
    namespace-guard cumsum alone was ~3 ms of a 3.8 ms CPU step at
    N=4096), so other backends take XLA's own cumsum.
    """
    if jax.default_backend() != "tpu":
        return jnp.cumsum(x.astype(jnp.float32), axis=0)
    squeeze = x.ndim == 1
    if squeeze:
        x = x[:, None]
    n, k = x.shape
    x = x.astype(jnp.float32)
    r = -(-n // block)
    pad = r * block - n
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, k), jnp.float32)], axis=0)
    xb = x.reshape(r, block, k)
    i = jnp.arange(block)
    tri = (i[:, None] >= i[None, :]).astype(jnp.float32)  # inclusive [C, C]
    within = jnp.einsum(
        "dc,rck->rdk", tri, xb, precision=_EXACT
    )  # per-block inclusive sums
    totals = within[:, -1, :]  # [r, k]
    j = jnp.arange(r)
    tri_r = (j[:, None] > j[None, :]).astype(jnp.float32)  # exclusive [R, R]
    offsets = jnp.matmul(tri_r, totals, precision=_EXACT)  # [r, k]
    out = (within + offsets[:, None, :]).reshape(r * block, k)[:n]
    return out[:, 0] if squeeze else out


def blocked_cummax(x, block: int = 128):
    """Inclusive running max along axis 0 of ``[N]`` float32 ``x``.

    Same blocking idea as :func:`blocked_cumsum` — max isn't linear so the
    within-block pass is a masked reduce over a ``[R, C, C]`` broadcast
    instead of a matmul, but that is still a vector op, not a scan.
    Off-TPU the native lowering wins for the same reason as in
    :func:`blocked_cumsum`.
    """
    if jax.default_backend() != "tpu":
        return jax.lax.cummax(x.astype(jnp.float32), axis=0)
    n = x.shape[0]
    x = x.astype(jnp.float32)
    r = -(-n // block)
    pad = r * block - n
    neg = jnp.float32(-(2.0**30))
    if pad:
        x = jnp.concatenate([x, jnp.full((pad,), neg, jnp.float32)])
    xb = x.reshape(r, block)
    i = jnp.arange(block)
    keep = i[:, None] >= i[None, :]  # inclusive [C, C]
    within = jnp.max(
        jnp.where(keep[None, :, :], xb[:, None, :], neg), axis=2
    )  # [r, C]
    totals = within[:, -1]  # [r]
    j = jnp.arange(r)
    keep_r = j[:, None] > j[None, :]  # exclusive [R, R]
    offsets = jnp.max(
        jnp.where(keep_r, totals[None, :], neg), axis=1
    )  # [r]
    out = jnp.maximum(within, offsets[:, None]).reshape(r * block)[:n]
    return out
