"""Count-min-sketch decide+update as a single Pallas TPU kernel.

Semantics match ``engine.param.param_decide`` (the windowed-CMS re-design of
``ClusterParamFlowChecker.java:42-96`` / ``ParameterMetric.java`` — see
``engine/param.py``): roll the current time bucket, estimate each request's
windowed count (min over depth lanes), admit greedily against the threshold
with in-batch prefix refinement, scatter admitted acquires into the current
bucket's lanes.

Kernel design (vs. the pure-XLA fallback):

- The sketch lives in HBM as ``[B*D, P, W]``; each (bucket, depth) plane
  ``[P, W]`` is DMA'd into one VMEM scratch buffer on demand. Only the D
  current-bucket planes are written back — the roll's "zero a stale bucket"
  is folded into the write (replace instead of add), so stale planes are
  never even read twice.
- Gathers (``counts[slot, b, d, idx]``) and scatters become one-hot MXU
  matmuls: ``onehot(slot) @ plane`` → per-request rows, then a masked
  row-dot with ``onehot(idx)``; the update is ``onehot(slot)ᵀ @
  (onehot(idx) * contrib)``. XLA's TPU scatter lowers to a serialized loop;
  this is ~N·P·W MACs on the systolic array instead.
- The in-batch admission refinement is the same odd-iteration-count prefix
  loop as the fallback (subset-of-greedy guarantee, ``engine/decide.py``),
  with the [N, N] same-key mask built in VMEM (N is capped so it fits).

Backend selection: off-TPU this kernel runs in interpret mode and BENCH_r05
measured it ~50× slower than the XLA path (76.7ms vs 1.54ms per step), so
``ParamConfig(impl="auto")`` (the default) never picks it there; on TPU the
two are micro-probed once per process and the faster wins. See
``engine.param.resolve_param_impl`` — pin explicitly with ``impl=`` or the
``SENTINEL_PARAM_IMPL`` env var.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# [N, N] f32 prefix mask + [N, W] one-hots must fit VMEM next to a [P, W]
# plane; 1024 keeps the mask at 4 MB.
MAX_BATCH = 1024


def _make_kernel(P: int, B: int, D: int, W: int, bucket_ms: int, refine_iters: int):
    interval_ms = bucket_ms * B

    def kernel(
        counts_ref,  # ANY [B*D, P, W] int32 (aliased to counts_out_ref)
        starts_ref,  # SMEM [B, 1] int32
        now_ref,  # SMEM [1, 1] int32
        slot_ref,  # VMEM [N, 1] int32
        idx_ref,  # VMEM [N, D] int32
        acq_ref,  # VMEM [N, 1] int32
        thr_ref,  # VMEM [N, 1] float32
        valid_ref,  # VMEM [N, 1] int32
        counts_out_ref,  # ANY [B*D, P, W] int32
        starts_out_ref,  # SMEM [B, 1] int32
        admit_ref,  # VMEM [N, 1] int32
        est_ref,  # VMEM [N, 1] int32
        plane_buf,  # VMEM scratch [1, P, W] int32
        sem,  # DMA semaphore
    ):
        N = slot_ref.shape[0]
        now = now_ref[0, 0]
        cur_b = (now // bucket_ms) % B
        cur_start = now - now % bucket_ms

        # roll bookkeeping — static unroll over the (tiny) bucket ring
        stale = jnp.bool_(False)
        for b in range(B):
            is_cur = jnp.int32(b) == cur_b
            stale = jnp.where(is_cur, starts_ref[b, 0] != cur_start, stale)
            starts_out_ref[b, 0] = jnp.where(is_cur, cur_start, starts_ref[b, 0])

        slot = slot_ref[:, 0]
        live = (valid_ref[:, 0] != 0) & (slot >= 0)
        safe_slot = jnp.where(slot >= 0, slot, 0)
        oh_slot = (
            safe_slot[:, None]
            == jax.lax.broadcasted_iota(jnp.int32, (N, P), 1)
        ).astype(jnp.float32)
        oh_idx = [
            (
                idx_ref[:, d][:, None]
                == jax.lax.broadcasted_iota(jnp.int32, (N, W), 1)
            ).astype(jnp.float32)
            for d in range(D)
        ]
        acq = acq_ref[:, 0].astype(jnp.float32)

        # ---- estimate: min over depth of windowed per-cell sums ----
        est = None
        for d in range(D):
            acc = jnp.zeros((N,), jnp.float32)
            for b in range(B):
                start_b = starts_out_ref[b, 0]
                age = now - start_b
                ok = (age >= 0) & (age < interval_ms)
                # a stale current bucket is logically zero until rewritten
                ok = ok & ~(stale & (jnp.int32(b) == cur_b))
                dma = pltpu.make_async_copy(
                    counts_ref.at[pl.ds(b * D + d, 1)], plane_buf, sem
                )
                dma.start()
                dma.wait()
                rows = jnp.dot(
                    oh_slot,
                    plane_buf[0].astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                )  # [N, W]
                cell = jnp.sum(rows * oh_idx[d], axis=1)
                acc = acc + jnp.where(ok, cell, 0.0)
            est = acc if est is None else jnp.minimum(est, acc)

        # ---- in-batch prefix admission (odd refinement ⇒ ⊆ greedy-exact) ----
        key = safe_slot
        for d in range(D):
            key = key * jnp.int32(-1640531527) + idx_ref[:, d]
        row_i = jax.lax.broadcasted_iota(jnp.int32, (N, N), 0)
        col_i = jax.lax.broadcasted_iota(jnp.int32, (N, N), 1)
        mask = ((key[:, None] == key[None, :]) & (row_i > col_i)).astype(
            jnp.float32
        )
        thr = thr_ref[:, 0]
        admit = live
        for _ in range(refine_iters):
            contrib = jnp.where(admit, acq, 0.0)
            prefix = jnp.dot(
                mask, contrib[:, None], preferred_element_type=jnp.float32
            )[:, 0]
            admit = live & (est + prefix + acq <= thr)

        # ---- update the D current-bucket planes (replace-on-stale = roll) ----
        contrib = jnp.where(admit, acq, 0.0)
        for d in range(D):
            k = cur_b * D + jnp.int32(d)
            dma_in = pltpu.make_async_copy(
                counts_ref.at[pl.ds(k, 1)], plane_buf, sem
            )
            dma_in.start()
            dma_in.wait()
            old = jnp.where(stale, 0, plane_buf[0])
            delta = jnp.dot(
                oh_slot.T,
                oh_idx[d] * contrib[:, None],
                preferred_element_type=jnp.float32,
            )  # [P, W]
            plane_buf[0] = old + delta.astype(jnp.int32)
            dma_out = pltpu.make_async_copy(
                plane_buf, counts_out_ref.at[pl.ds(k, 1)], sem
            )
            dma_out.start()
            dma_out.wait()

        admit_ref[:, 0] = admit.astype(jnp.int32)
        est_ref[:, 0] = est.astype(jnp.int32)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("P", "B", "D", "W", "bucket_ms", "refine_iters", "interpret"),
)
def cms_decide_update_pallas(
    counts: jax.Array,  # [B*D, P, W] int32
    starts: jax.Array,  # [B] int32
    rule_slot: jax.Array,  # [N] int32 (-1 → no rule)
    idx: jax.Array,  # [N, D] int32 CMS cell indices
    acquire: jax.Array,  # [N] int32
    threshold: jax.Array,  # [N] float32
    valid: jax.Array,  # [N] bool
    now: jax.Array,  # int32 scalar
    *,
    P: int,
    B: int,
    D: int,
    W: int,
    bucket_ms: int,
    refine_iters: int = 3,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """``-> (counts', starts', admit [N] bool, estimate [N] int32)``."""
    N = rule_slot.shape[0]
    if N > MAX_BATCH:
        raise ValueError(f"param batch {N} exceeds pallas cap {MAX_BATCH}")
    if refine_iters % 2 == 0:
        raise ValueError("refine_iters must be odd (no-overshoot guarantee)")

    kernel = _make_kernel(P, B, D, W, bucket_ms, refine_iters)
    counts_out, starts_out, admit, est = pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B * D, P, W), jnp.int32),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
        ),
        input_output_aliases={0: 0},
        scratch_shapes=[
            pltpu.VMEM((1, P, W), jnp.int32),
            pltpu.SemaphoreType.DMA,
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * N * P * W * D * (B + 1) + 2 * refine_iters * N * N,
            bytes_accessed=4 * P * W * (B * D + 2 * D),
            transcendentals=0,
        ),
        interpret=interpret,
    )(
        counts,
        starts.reshape(B, 1).astype(jnp.int32),
        jnp.asarray(now, jnp.int32).reshape(1, 1),
        rule_slot.reshape(N, 1).astype(jnp.int32),
        idx.astype(jnp.int32),
        acquire.reshape(N, 1).astype(jnp.int32),
        threshold.reshape(N, 1).astype(jnp.float32),
        valid.reshape(N, 1).astype(jnp.int32),
    )
    return counts_out, starts_out[:, 0], admit[:, 0] != 0, est[:, 0]
