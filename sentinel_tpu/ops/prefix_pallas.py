"""Tiled Pallas kernel for the exclusive segment prefix sum.

Semantics match ``engine.prefix.segment_prefix_builder``:
``out[i] = sum(contrib[j] for j < i if keys[j] == keys[i])`` — the
"tokens claimed by earlier same-flow requests in this batch" primitive of
the admission kernels (``engine/decide.py`` step 3, ``engine/param.py``).

The pure-XLA ``matmul`` implementation materializes the [N, N] float32
same-key/strictly-lower mask in HBM (1 GB at N=16k). This kernel tiles the
mask: each grid step builds a [TILE_R, TILE_C] block on the fly from two
key slices and accumulates ``block @ contrib_slice`` into the output tile —
O(N) HBM traffic, MXU does the N² MACs.

Padding contract: callers may pass any N; inputs are zero-padded to tile
multiples. Padded *columns* carry contrib 0 so they never contribute;
padded *rows* are sliced off the output.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_R = 256
TILE_C = 512


def _kernel(keys_row_ref, keys_col_ref, contrib_col_ref, out_ref):
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    rk = keys_row_ref[:]  # [TILE_R, 1]
    ck = keys_col_ref[:]  # [TILE_C, 1]
    row_g = i * TILE_R + jax.lax.broadcasted_iota(jnp.int32, (TILE_R, 1), 0)
    col_g = j * TILE_C + jax.lax.broadcasted_iota(jnp.int32, (TILE_C, 1), 0)
    mask = (rk == ck.T) & (row_g > col_g.T)  # [TILE_R, TILE_C]
    out_ref[:] += jnp.dot(
        mask.astype(jnp.float32),
        contrib_col_ref[:],
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def segment_prefix_pallas(
    keys: jax.Array, contrib: jax.Array, *, interpret: bool = False
) -> jax.Array:
    """``([N] int32, [N] float-like) -> [N] float32`` exclusive segment prefix."""
    n = keys.shape[0]
    n_pad = max(TILE_R, TILE_C) * -(-n // max(TILE_R, TILE_C))
    keys_p = jnp.zeros((n_pad, 1), jnp.int32).at[:n, 0].set(keys.astype(jnp.int32))
    contrib_p = (
        jnp.zeros((n_pad, 1), jnp.float32).at[:n, 0].set(contrib.astype(jnp.float32))
    )

    grid = (n_pad // TILE_R, n_pad // TILE_C)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_R, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_C, 1), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_C, 1), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (TILE_R, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * n_pad * n_pad, bytes_accessed=3 * 4 * n_pad, transcendentals=0
        ),
        interpret=interpret,
    )(keys_p, keys_p, contrib_p)
    return out[:n, 0]
