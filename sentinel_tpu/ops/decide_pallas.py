"""One-HBM-traversal decide megakernel.

The XLA pipeline (``engine/decide._decide_core``) walks the flow-window
plane once per subsystem: three windowed gathers for the admission read
(PASS + matured borrows + LEASED), a fourth row gather for the occupy
headroom check, the roll's full-``[F, E]`` stale-column multiply, and four
to five scatter-adds for the event writes — every one of them a separate
XLA op with its own HBM round trip over the same ``[F, B, E]`` rows. This
kernel fuses the whole per-flow traversal into ONE ``pallas_call`` over the
flow plane (the single-pass update discipline of the FPGA sketch pipeline,
arXiv:2504.16896):

- each batch row's ``[B, E]`` flow window and ``[B, 1]`` occupancy/future
  ring row is DMA'd into VMEM exactly once;
- the roll's stale-column zero becomes a *conditional* tiled DMA pass
  (the XLA path multiplies the column by 1 every step, stale or not);
- all admission math — warmup slope curve, windowed threshold read,
  grouped segment-prefix admission, pacing closed form, occupy headroom —
  runs on the VMEM-resident rows, sharing the exact helper functions of
  the XLA path (``_warmup_curve``, ``_occupy_feasible``,
  ``_grouped_prefix``) so the two backends are **bitwise** equal;
- the event deltas (PASS / PASS_REQUEST / BLOCK / BLOCK_REQUEST /
  OCCUPIED_PASS) are folded into per-segment totals and written back with
  one read-modify-write DMA per *flow segment* — the grouped-batch
  contract (same-flow rows contiguous) makes segment-tail writes race-free.

What stays outside the kernel, by design:

- The namespace guard window (``[NS, B, 1]`` — replicated, tiny) and every
  ``[N]``-sized scatter into the per-flow shaper-clock columns and the
  occupancy ring: those are O(batch) writes, not O(state) traversals, and
  the occupy write's ``pmax``-combined slot reset is a mesh collective,
  which cannot run inside a kernel. The kernel *reads* the occupancy ring
  rows (fused with the flow gather) and emits the charge vectors; the
  epilogue applies them through the same ``W.add_future`` call as the XLA
  path.
- The param sketch plane: it serves separate PARAM_FLOW batches and
  already has its own fused one-pass kernels (``cms_pallas``/
  ``salsa_pallas`` — the SALSA int16 packed-cell encoding lives there).

Parity discipline (the ``ops/cms_pallas.py`` twin contract): off-TPU the
kernel runs in interpret mode and ``tests/test_ops_decide_pallas.py`` asserts
*bitwise* equality of verdicts and every state leaf against the XLA
pipeline over seeded mixed-behavior streams, including fused ``lax.scan``
depth and 8-virtual-device ``shard_map``. All cross-backend sums are
integer-valued float32 (< 2^24), where addition order cannot change the
result; ``lax.cond``-gated XLA arms are replaced by unconditional
compute + select, which is bitwise-identical because the gated-off values
coincide (see ``_warmup_curve``'s docstring).

Backend selection mirrors the sketch plane: ``EngineConfig.decide_impl``
("auto" probes on TPU, picks XLA elsewhere; ``SENTINEL_DECIDE_IMPL``
overrides) — see ``engine.decide.resolve_decide_impl``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sentinel_tpu.engine.config import EngineConfig
from sentinel_tpu.engine.prefix import _grouped_prefix
from sentinel_tpu.engine.rules import RuleTable, ThresholdMode
from sentinel_tpu.engine.state import (
    ClusterEvent,
    EngineState,
    N_CLUSTER_EVENTS,
    ShapingState,
    flow_spec,
)
from sentinel_tpu.stats import window as W
from sentinel_tpu.stats.window import WindowState

# Per-request VMEM row buffers: [N, B, E] i32 must fit next to the scratch
# planes (1024 × 64 buckets × 6 events × 4B ≈ 1.5 MB at the deepest serve
# config). Larger batches fall back to the XLA pipeline.
MAX_BATCH = 1024

# stale-column zero pass: flow rows zeroed per DMA burst
_ZCHUNK = 512


def _make_decide_kernel(config: EngineConfig, F: int, N: int, uniform: bool):
    spec = flow_spec(config)
    B = spec.n_buckets
    E = N_CLUSTER_EVENTS
    bucket_ms = spec.bucket_ms
    interval_ms = spec.interval_ms
    refine_iters = config.admission_refine_iters
    ev = ClusterEvent
    # shared helpers — imported lazily to keep engine.decide's lazy import
    # of this module cycle-free
    from sentinel_tpu.engine.decide import _occupy_feasible, _warmup_curve

    def kernel(
        # inputs -----------------------------------------------------------
        flow_ref,  # ANY [F, B, E] i32 (aliased to flow_out_ref)
        occ_ref,  # ANY [F, B, 1] i32 (occupancy/future ring — read only)
        fstarts_ref,  # SMEM [B, 1] i32 — flow ring starts (pre-roll)
        ostarts_ref,  # SMEM [B, 1] i32 — occupy ring starts
        now_ref,  # SMEM [1, 1] i32
        slot_smem_ref,  # SMEM [N, 1] i32 — safe_slot (DMA loop scalars)
        wok_smem_ref,  # SMEM [N, 1] i32 — segment-tail & in-range write mask
        slot_ref,  # VMEM [N, 1] i32 — safe_slot
        acq_ref,  # VMEM [N, 1] i32
        live_ref,  # VMEM [N, 1] i32
        active_ref,  # VMEM [N, 1] i32 — ns-admitted & owned
        beh_ref,  # VMEM [N, 1] i32 — ControlBehavior
        prio_ref,  # VMEM [N, 1] i32
        factor_ref,  # VMEM [N, 1] f32 — AVG_LOCAL connected-count factor
        cnt_ref,  # VMEM [N, 1] f32 — rule count
        warn_ref,  # VMEM [N, 1] f32 — warmup warning line
        maxtok_ref,  # VMEM [N, 1] f32 — warmup bucket capacity
        slope_ref,  # VMEM [N, 1] f32
        cold_ref,  # VMEM [N, 1] f32
        maxq_ref,  # VMEM [N, 1] i32 — pacing queue bound
        lpt_ref,  # VMEM [N, 1] i32 — latestPassedTime rows
        wtok_ref,  # VMEM [N, 1] f32 — warmup stored tokens rows
        wfill_ref,  # VMEM [N, 1] i32 — warmup fill stamps rows
        # outputs ----------------------------------------------------------
        flow_out_ref,  # ANY [F, B, E] i32 (aliased)
        fstarts_out_ref,  # SMEM [B, 1] i32
        admit_ref,  # VMEM [N, 1] i32
        canocc_ref,  # VMEM [N, 1] i32
        paceacc_ref,  # VMEM [N, 1] i32
        pacewait_ref,  # VMEM [N, 1] i32
        passed_ref,  # VMEM [N, 1] f32
        thr_ref,  # VMEM [N, 1] f32
        admp_ref,  # VMEM [N, 1] f32 — admitted in-batch prefix
        wtoknew_ref,  # VMEM [N, 1] f32
        dosync_ref,  # VMEM [N, 1] i32
        lptsched_ref,  # VMEM [N, 1] i32 — now + round(l_rel)
        # scratch ----------------------------------------------------------
        fbuf,  # VMEM [N, B, E] i32 — gathered flow rows
        obuf,  # VMEM [N, B, 1] i32 — gathered occupy rows
        wcol,  # VMEM [N, 1, E] i32 — write-back columns
        zbuf,  # VMEM [_ZCHUNK, 1, E] i32 — zeros for the roll pass
        sem,  # DMA semaphore
    ):
        now = now_ref[0, 0]
        idx_cur = (now // bucket_ms) % B
        cur_start = now - now % bucket_ms

        # ---- roll bookkeeping: static unroll over the (tiny) ring --------
        stale = jnp.bool_(False)
        for b in range(B):
            is_cur = jnp.int32(b) == idx_cur
            stale = jnp.where(
                is_cur, fstarts_ref[b, 0] != cur_start, stale
            )
            fstarts_out_ref[b, 0] = jnp.where(
                is_cur, cur_start, fstarts_ref[b, 0]
            )
        fstarts_old = jnp.stack([fstarts_ref[b, 0] for b in range(B)])
        ostarts_old = jnp.stack([ostarts_ref[b, 0] for b in range(B)])

        # ---- conditional stale-column zero (the roll), tiled over F ------
        # Must run BEFORE the row gather: the gathered current-bucket cells
        # seed the read-modify-write totals below, and reads of the stale
        # column are masked out by the pre-roll validity mask either way.
        zbuf[...] = jnp.zeros((_ZCHUNK, 1, E), jnp.int32)

        @pl.when(stale)
        def _zero_stale_column():
            n_full = F // _ZCHUNK
            if n_full:

                def zb(k, carry):
                    dma = pltpu.make_async_copy(
                        zbuf,
                        flow_out_ref.at[
                            pl.ds(k * _ZCHUNK, _ZCHUNK), pl.ds(idx_cur, 1)
                        ],
                        sem,
                    )
                    dma.start()
                    dma.wait()
                    return carry

                jax.lax.fori_loop(0, n_full, zb, 0)
            rem = F % _ZCHUNK
            if rem:
                dma = pltpu.make_async_copy(
                    zbuf.at[pl.ds(0, rem)],
                    flow_out_ref.at[
                        pl.ds(n_full * _ZCHUNK, rem), pl.ds(idx_cur, 1)
                    ],
                    sem,
                )
                dma.start()
                dma.wait()

        # ---- the one traversal: DMA each request's flow + occupy row -----
        def gather(i, carry):
            row = slot_smem_ref[i, 0]
            d1 = pltpu.make_async_copy(
                flow_out_ref.at[pl.ds(row, 1)], fbuf.at[pl.ds(i, 1)], sem
            )
            d1.start()
            d1.wait()
            d2 = pltpu.make_async_copy(
                occ_ref.at[pl.ds(row, 1)], obuf.at[pl.ds(i, 1)], sem
            )
            d2.start()
            d2.wait()
            return carry

        jax.lax.fori_loop(0, N, gather, 0)

        fvals = fbuf[...]  # [N, B, E] i32
        ovals = obuf[...][:, :, 0]  # [N, B] i32

        slot = slot_ref[:, 0]
        acquire = acq_ref[:, 0]
        acquire_f = acquire.astype(jnp.float32)
        live = live_ref[:, 0] != 0
        active = active_ref[:, 0] != 0
        beh = beh_ref[:, 0]
        prio = prio_ref[:, 0] != 0
        factor = factor_ref[:, 0]
        cnt = cnt_ref[:, 0]

        # window validity masks from the PRE-roll starts, exactly like the
        # XLA path's W.window_sum_at / future_sum_at reads
        f_age = now - fstarts_old
        f_valid = ((f_age >= 0) & (f_age < interval_ms)).astype(jnp.int32)
        o_age = now - ostarts_old
        o_valid = ((o_age >= 0) & (o_age < interval_ms)).astype(jnp.int32)
        o_ahead = ostarts_old - now
        o_future = ((o_ahead > 0) & (o_ahead <= interval_ms)).astype(
            jnp.int32
        )

        pass_rows = fvals[:, :, int(ev.PASS)]  # [N, B]
        leased_rows = fvals[:, :, int(ev.LEASED)]
        # same int32 sum-then-cast chain as the XLA read path (exact)
        passed = (
            jnp.sum(pass_rows * f_valid[None, :], axis=1)
            + jnp.sum(ovals * o_valid[None, :], axis=1)
            + jnp.sum(leased_rows * f_valid[None, :], axis=1)
        ).astype(jnp.float32)

        # ---- traffic shaping masks + warmup curve (shared helper) --------
        is_warm = (beh == 1) | (beh == 3)
        is_pace = (beh == 2) | (beh == 3)
        warm_rows = active & is_warm
        pace_try = active & is_pace
        active_window = active & ~is_pace

        cnt_safe = jnp.maximum(cnt, 1e-6)
        qps, tokens_new, do_sync, _cur_sec = _warmup_curve(
            spec, now, passed, cnt, cnt_safe,
            warn_ref[:, 0], maxtok_ref[:, 0], slope_ref[:, 0],
            cold_ref[:, 0], wfill_ref[:, 0], wtok_ref[:, 0], warm_rows,
        )

        rate_qps = qps * factor * config.exceed_count
        threshold = rate_qps * (spec.interval_ms / 1000.0)

        # ---- grouped segment-prefix admission (same builder as XLA) ------
        flow_prefix = _grouped_prefix(slot)

        if uniform:
            a = jnp.max(jnp.where(live, acquire, 0)).astype(jnp.float32)
            a_safe = jnp.maximum(a, 1.0)
            rank = flow_prefix(active_window.astype(jnp.float32))
            admit = active_window & (passed + rank * a + a <= threshold)
            quota = jnp.floor(
                jnp.maximum(threshold - passed, 0.0) / a_safe
            )
            admitted_prefix = jnp.minimum(rank, quota) * a
        else:
            admit = active_window
            for _ in range(refine_iters):
                contrib = jnp.where(admit, acquire_f, 0.0)
                prefix = flow_prefix(contrib)
                admit = active_window & (
                    passed + prefix + acquire_f <= threshold
                )
            admitted_prefix = flow_prefix(
                jnp.where(admit, acquire_f, 0.0)
            )

        # ---- pacing closed form (see _decide_core §3b) -------------------
        # Computed unconditionally: with no RATE_LIMITER rows every mask is
        # False and the outputs coincide with the XLA path's cond-off arm.
        cost_f = jnp.round(
            1000.0 * acquire_f / jnp.maximum(rate_qps, 1e-6)
        )
        rel0 = jnp.maximum(
            lpt_ref[:, 0] - now, jnp.int32(-(2 ** 20))
        ).astype(jnp.float32)
        maxq = maxq_ref[:, 0].astype(jnp.float32)
        rev_prefix = _grouped_prefix(jnp.flip(slot))

        def pace_pass(accept):
            contrib = jnp.where(accept, cost_f, 0.0)
            incl = flow_prefix(contrib) + cost_f
            rank_p = flow_prefix(accept.astype(jnp.float32))
            first = accept & (rank_p == 0.0)
            # Segment-wide broadcast of the first accepted row's cost. The
            # XLA path scatters it through a [f_local] staging vector; in
            # the kernel the same value is the SEGMENT SUM of the
            # first-row-only costs (at most one nonzero per segment, and
            # adding zeros is exact in fp32) — prefix + own + suffix.
            t = jnp.where(first, cost_f, 0.0)
            c_first = (
                flow_prefix(t) + t + jnp.flip(rev_prefix(jnp.flip(t)))
            )
            l_rel = jnp.maximum(rel0, -c_first) + incl
            return l_rel

        accept = pace_try
        l_rel = pace_pass(accept)
        for _i in range(0 if uniform else refine_iters):
            accept = pace_try & (l_rel <= maxq)
            l_rel = pace_pass(accept)
        accept = pace_try & (l_rel <= maxq)
        wait_i = jnp.maximum(l_rel, 0.0).astype(jnp.int32)
        lpt_sched = now + jnp.round(l_rel).astype(jnp.int32)
        pace_now = accept & (wait_i == 0)
        pace_reject = pace_try & ~accept

        # ---- priority occupy headroom (shared helper; fused occupy read) -
        blocked = active_window & ~admit
        wait_next = bucket_ms - (now % bucket_ms)
        try_occupy = blocked & prio & (beh == 0)
        next_start = now + wait_next
        horizon = next_start - interval_ms
        exp_mask = (
            (f_valid != 0) & (fstarts_old <= horizon)
        ).astype(jnp.int32)
        expiring = jnp.sum(pass_rows * exp_mask[None, :], axis=1).astype(
            jnp.float32
        )
        waiting = jnp.sum(ovals * o_future[None, :], axis=1).astype(
            jnp.float32
        )
        occ_prefix = flow_prefix(jnp.where(try_occupy, acquire_f, 0.0))
        can_occupy = _occupy_feasible(
            config, try_occupy, passed, expiring, admitted_prefix,
            waiting, occ_prefix, acquire_f, threshold,
        )
        hard_block = blocked & ~can_occupy

        # ---- event deltas → per-segment totals → tail RMW write-back -----
        admit_i = (admit | pace_now).astype(jnp.int32)
        hard_i = (hard_block | pace_reject).astype(jnp.int32)
        deltas = [jnp.zeros((N,), jnp.int32)] * E
        deltas[int(ev.PASS)] = acquire * admit_i
        deltas[int(ev.PASS_REQUEST)] = admit_i
        deltas[int(ev.BLOCK)] = acquire * hard_i
        deltas[int(ev.BLOCK_REQUEST)] = hard_i
        # prioritized traffic's OCCUPIED_PASS mark: unconditional here —
        # with no prioritized rows the delta is zero, which is the XLA
        # path's cond-off arm
        deltas[int(ev.OCCUPIED_PASS)] = acquire * (
            admit & prio
        ).astype(jnp.int32)
        # inclusive segment totals via the same exact-f32 grouped prefix;
        # the segment-tail row carries the whole segment's delta
        totals = [
            (flow_prefix(d.astype(jnp.float32)) + d.astype(jnp.float32))
            .astype(jnp.int32)
            for d in deltas
        ]
        cur_col = jax.lax.dynamic_slice_in_dim(fvals, idx_cur, 1, axis=1)[
            :, 0, :
        ]  # [N, E] — post-roll values (stale column was zeroed pre-gather)
        new_col = cur_col + jnp.stack(totals, axis=1)
        wcol[...] = new_col[:, None, :]

        def write_back(i, carry):
            @pl.when(wok_smem_ref[i, 0] != 0)
            def _():
                row = slot_smem_ref[i, 0]
                dma = pltpu.make_async_copy(
                    wcol.at[pl.ds(i, 1)],
                    flow_out_ref.at[pl.ds(row, 1), pl.ds(idx_cur, 1)],
                    sem,
                )
                dma.start()
                dma.wait()

            return carry

        jax.lax.fori_loop(0, N, write_back, 0)

        # ---- [N] decision outputs for the epilogue -----------------------
        admit_ref[:, 0] = admit.astype(jnp.int32)
        canocc_ref[:, 0] = can_occupy.astype(jnp.int32)
        paceacc_ref[:, 0] = accept.astype(jnp.int32)
        pacewait_ref[:, 0] = wait_i
        passed_ref[:, 0] = passed
        thr_ref[:, 0] = threshold
        admp_ref[:, 0] = admitted_prefix
        wtoknew_ref[:, 0] = tokens_new
        dosync_ref[:, 0] = do_sync.astype(jnp.int32)
        lptsched_ref[:, 0] = lpt_sched

    return kernel


def _call_decide_kernel(
    config: EngineConfig,
    flow_counts: jax.Array,  # [F, B, E] i32
    occ_counts: jax.Array,  # [F, B, 1] i32
    fstarts: jax.Array,  # [B] i32
    ostarts: jax.Array,  # [B] i32
    now: jax.Array,
    safe_slot: jax.Array,  # [N] i32
    write_ok: jax.Array,  # [N] bool — segment tail & in-range
    acquire: jax.Array,
    live: jax.Array,
    active: jax.Array,
    beh: jax.Array,
    prioritized: jax.Array,
    factor: jax.Array,
    cnt: jax.Array,
    warn: jax.Array,
    max_token: jax.Array,
    slope: jax.Array,
    cold_count: jax.Array,
    max_queue_ms: jax.Array,
    lpt_rows: jax.Array,
    wtok_rows: jax.Array,
    wfill_rows: jax.Array,
    uniform: bool,
    interpret: bool,
):
    F, B, E = flow_counts.shape
    N = safe_slot.shape[0]
    kernel = _make_decide_kernel(config, F, N, uniform)

    def col_i32(x):
        return x.astype(jnp.int32).reshape(N, 1)

    def col_f32(x):
        return x.astype(jnp.float32).reshape(N, 1)

    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            smem, smem, smem, smem, smem,
        ] + [vmem] * 16,
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            smem,
        ) + (vmem,) * 10,
        out_shape=(
            jax.ShapeDtypeStruct((F, B, E), jnp.int32),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),  # admit
            jax.ShapeDtypeStruct((N, 1), jnp.int32),  # can_occupy
            jax.ShapeDtypeStruct((N, 1), jnp.int32),  # pace accept
            jax.ShapeDtypeStruct((N, 1), jnp.int32),  # pace wait
            jax.ShapeDtypeStruct((N, 1), jnp.float32),  # passed
            jax.ShapeDtypeStruct((N, 1), jnp.float32),  # threshold
            jax.ShapeDtypeStruct((N, 1), jnp.float32),  # admitted prefix
            jax.ShapeDtypeStruct((N, 1), jnp.float32),  # warm tokens'
            jax.ShapeDtypeStruct((N, 1), jnp.int32),  # warm do_sync
            jax.ShapeDtypeStruct((N, 1), jnp.int32),  # lpt schedule
        ),
        input_output_aliases={0: 0},
        scratch_shapes=[
            pltpu.VMEM((N, B, E), jnp.int32),
            pltpu.VMEM((N, B, 1), jnp.int32),
            pltpu.VMEM((N, 1, E), jnp.int32),
            pltpu.VMEM((_ZCHUNK, 1, E), jnp.int32),
            pltpu.SemaphoreType.DMA,
        ],
        cost_estimate=pl.CostEstimate(
            # per step: N row gathers (flow + occupy) + N tail writes +
            # the amortized stale-column zero; flops dominated by the
            # [N]-vector admission math and the grouped prefixes
            flops=20 * N * B * E,
            bytes_accessed=4 * (2 * N * B * (E + 1) + N * E + F * E // B),
            transcendentals=0,
        ),
        interpret=interpret,
    )(
        flow_counts,
        occ_counts,
        fstarts.reshape(B, 1).astype(jnp.int32),
        ostarts.reshape(B, 1).astype(jnp.int32),
        jnp.asarray(now, jnp.int32).reshape(1, 1),
        col_i32(safe_slot),
        col_i32(write_ok),
        col_i32(safe_slot),
        col_i32(acquire),
        col_i32(live),
        col_i32(active),
        col_i32(beh),
        col_i32(prioritized),
        col_f32(factor),
        col_f32(cnt),
        col_f32(warn),
        col_f32(max_token),
        col_f32(slope),
        col_f32(cold_count),
        col_i32(max_queue_ms),
        col_i32(lpt_rows),
        col_f32(wtok_rows),
        col_i32(wfill_rows),
    )
    return outs


def decide_core_pallas(
    config: EngineConfig,
    state: EngineState,
    rules: RuleTable,
    batch,
    now: jax.Array,
    axis_name: Optional[str] = None,
    grouped: bool = False,
    uniform: bool = False,
) -> tuple:
    """Drop-in ``_decide_core`` twin backed by the megakernel.

    Same signature, same pytree outputs, bitwise-equal results. Requires
    the grouped-batch contract; non-grouped calls and batches beyond the
    kernel's VMEM cap fall back to the XLA pipeline (so ``decide_impl=
    "pallas"`` can never produce wrong answers, only a slower path).
    """
    # lazy (mutual recursion with engine.decide's backend dispatch), and via
    # importlib because the package re-exports a `decide` FUNCTION that
    # shadows the module attribute
    import importlib

    D = importlib.import_module("sentinel_tpu.engine.decide")

    N = batch.valid.shape[0]
    if not grouped or N > MAX_BATCH:
        return D._decide_core(
            config, state, rules, batch, now, axis_name=axis_name,
            grouped=grouped, uniform=uniform,
        )

    spec = flow_spec(config)
    now = jnp.asarray(now, jnp.int32)
    f_local = rules.valid.shape[0]

    if axis_name is not None:
        offset = jax.lax.axis_index(axis_name).astype(jnp.int32) * f_local
        psum = partial(jax.lax.psum, axis_name=axis_name)
        pmax = partial(jax.lax.pmax, axis_name=axis_name)
    else:
        offset = jnp.int32(0)
        psum = lambda x: x  # noqa: E731
        pmax = lambda x: x  # noqa: E731

    # ---- prologue: identical [N]-sized setup + namespace guard ----------
    local_slot = batch.flow_slot - offset
    in_range = (
        (batch.flow_slot >= 0) & (local_slot >= 0) & (local_slot < f_local)
    )
    safe_slot = jnp.where(in_range, local_slot, 0)
    owned = in_range & rules.valid[safe_slot]
    has_rule = psum(owned.astype(jnp.int32)) > 0
    live = batch.valid & has_rule
    no_rule = batch.valid & ~has_rule
    acquire_f = batch.acquire.astype(jnp.float32)

    ns_id, ns_ok, seg_ns_sum = D._ns_guard(
        config, spec, state.ns, rules, now, psum, owned, safe_slot, live
    )
    too_many = live & ~ns_ok
    ns_admitted = live & ns_ok
    active = ns_admitted & owned

    # circuit breakers run in the prologue with the SAME shared gate (and
    # the same grouped prefix builder) as the XLA path, and degraded rows
    # are stripped from `active` BEFORE the kernel sees it — the megakernel
    # then treats them exactly like inactive rows (zero event deltas, no
    # admission), so kernel parity holds by construction with zero kernel
    # changes
    degraded, br_retry, breaker_ws = D._breaker_gate(
        config, spec, state, rules, now, safe_slot, active,
        _grouped_prefix(safe_slot), psum,
    )
    active = active & ~degraded

    conn = rules.ns_connected[ns_id].astype(jnp.float32)
    factor = jnp.where(
        rules.mode[safe_slot] == int(ThresholdMode.AVG_LOCAL), conn, 1.0
    )
    beh = rules.behavior[safe_slot].astype(jnp.int32)
    is_pace = (beh == 2) | (beh == 3)
    pace_try_mask = active & is_pace
    active_window = active & ~is_pace

    # One write-back row per safe_slot segment: the LAST in-range row. The
    # grouped contract makes equal flow slots contiguous, but foreign-shard
    # and padding rows all collapse onto safe_slot 0 and can merge with an
    # owned slot-``offset`` segment on either side; their deltas are
    # provably zero (active ⊆ owned ⊆ in_range), so the last in-range row's
    # inclusive segment total already carries the whole segment — and
    # skipping the non-in-range tail keeps the slot-0 RMW from clobbering a
    # real segment's update. In-range rows of one segment share one
    # flow_slot, hence are contiguous: exactly one writer per physical row.
    next_same = jnp.concatenate(
        [safe_slot[1:] == safe_slot[:-1], jnp.zeros((1,), bool)]
    )
    next_in = jnp.concatenate([in_range[1:], jnp.zeros((1,), bool)])
    write_ok = in_range & ~(next_same & next_in)

    interpret = jax.default_backend() != "tpu"
    (
        flow_counts_out, fstarts_out,
        admit_o, canocc_o, paceacc_o, pacewait_o,
        passed_o, thr_o, admp_o, wtoknew_o, dosync_o, lpts_o,
    ) = _call_decide_kernel(
        config,
        state.flow.counts,
        state.occupy.counts,
        state.flow.starts,
        state.occupy.starts,
        now,
        safe_slot,
        write_ok,
        batch.acquire,
        live,
        active,
        beh,
        batch.prioritized,
        factor,
        rules.count[safe_slot],
        rules.warning_token[safe_slot],
        rules.max_token[safe_slot],
        rules.slope[safe_slot],
        rules.cold_count[safe_slot],
        rules.max_queue_ms[safe_slot],
        state.shaping.lpt[safe_slot],
        state.shaping.warm_tokens[safe_slot],
        state.shaping.warm_filled[safe_slot],
        uniform,
        interpret,
    )

    admit = admit_o[:, 0] != 0
    can_occupy = canocc_o[:, 0] != 0
    pace_admit = paceacc_o[:, 0] != 0
    pace_wait = pacewait_o[:, 0]
    passed = passed_o[:, 0]
    threshold = thr_o[:, 0]
    admitted_prefix = admp_o[:, 0]
    tokens_new = wtoknew_o[:, 0]
    do_sync = dosync_o[:, 0] != 0
    lpt_sched = lpts_o[:, 0]

    pace_now = pace_admit & (pace_wait == 0)
    pace_later = pace_admit & (pace_wait > 0)
    pace_reject = pace_try_mask & ~pace_admit
    hard_block = (active_window & ~admit) & ~can_occupy
    wait_next = spec.bucket_ms - (now % spec.bucket_ms)

    flow_ws = WindowState(starts=fstarts_out[:, 0], counts=flow_counts_out)

    # ---- epilogue: O(batch) scatters + collectives, same as the XLA path
    cur_sec = now - now % 1000
    scat_w = jnp.where(do_sync, safe_slot, f_local)
    warm_tokens_ws = state.shaping.warm_tokens.at[scat_w].set(
        tokens_new, mode="drop"
    )
    warm_filled_ws = state.shaping.warm_filled.at[scat_w].set(
        cur_sec, mode="drop"
    )
    scat_l = jnp.where(pace_admit, safe_slot, f_local)
    lpt_ws = state.shaping.lpt.at[scat_l].max(lpt_sched, mode="drop")

    any_prio = jnp.any(batch.prioritized & batch.valid)
    any_pace = jnp.any(psum(pace_try_mask.astype(jnp.int32)) > 0)
    charge_wait = jnp.where(
        can_occupy, jnp.full((N,), wait_next, jnp.int32), pace_wait
    )
    charge_valid = can_occupy | pace_later
    occupy_ws = jax.lax.cond(
        any_prio | any_pace,
        lambda occ: W.add_future(
            spec, occ, now,
            wait_ms=charge_wait,
            resource_ids=safe_slot,
            channel_ids=jnp.zeros((N,), jnp.int32),
            values=batch.acquire,
            valid=charge_valid,
            combine_desired=pmax,
        ),
        lambda occ: occ,
        state.occupy,
    )
    ns_deltas = seg_ns_sum(ns_admitted.astype(jnp.float32))
    ns_ws = W.add_column(spec, state.ns, now, ns_deltas)

    # ---- verdict stitching (identical to _decide_core §6) ---------------
    TokenStatus = D.TokenStatus
    local_status = jnp.where(
        degraded,
        int(TokenStatus.DEGRADED) + 1,
        jnp.where(
            admit | pace_now,
            int(TokenStatus.OK) + 1,
            jnp.where(
                can_occupy | pace_later,
                int(TokenStatus.SHOULD_WAIT) + 1,
                jnp.where(
                    hard_block | pace_reject,
                    int(TokenStatus.BLOCKED) + 1, 0
                ),
            ),
        ),
    ).astype(jnp.int32)
    combined = psum(local_status)
    status = jnp.where(
        ~batch.valid,
        int(TokenStatus.FAIL),
        jnp.where(
            no_rule,
            int(TokenStatus.NO_RULE_EXISTS),
            jnp.where(
                too_many,
                int(TokenStatus.TOO_MANY_REQUEST),
                jnp.where(
                    combined > 0, combined - 1, int(TokenStatus.FAIL)
                ),
            ),
        ),
    ).astype(jnp.int8)
    wait_ms = psum(
        jnp.where(
            can_occupy, wait_next, jnp.where(pace_later, pace_wait, 0)
        ).astype(jnp.int32)
    )
    remaining_local = jnp.clip(
        threshold - passed - admitted_prefix
        - jnp.where(admit, acquire_f, 0.0),
        0.0,
        2 ** 30,
    ).astype(jnp.int32)
    remaining = psum(
        jnp.where(admit, remaining_local, jnp.where(degraded, br_retry, 0))
    )

    new_state = EngineState(
        flow=flow_ws, occupy=occupy_ws, ns=ns_ws,
        shaping=ShapingState(
            lpt=lpt_ws, warm_tokens=warm_tokens_ws,
            warm_filled=warm_filled_ws,
        ),
        outcome=state.outcome,
        breaker=breaker_ws,
    )
    verdicts = D.VerdictBatch(
        status=status, wait_ms=wait_ms, remaining=remaining
    )
    return new_state, verdicts
