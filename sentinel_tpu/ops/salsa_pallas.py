"""SALSA decide+update as a single Pallas TPU kernel.

Semantics match ``sketch.salsa.salsa_decide_jax`` — the windowed-CMS decide
of ``ops/cms_pallas.py`` over the SALSA int16 pair encoding
(``sketch/salsa.py``): planes live in HBM as ``[B*D, P, C]`` int16 with
``C = 2*width`` cells, each plane is DMA'd into VMEM on demand, and all
gathers/scatters are the same one-hot MXU matmuls as the cms kernel, just
over a decoded int32 view of the plane.

Pair arithmetic avoids minor-dimension strided slices (which Mosaic may
refuse) by operating on full-width lane vectors: a cell's pair partner is a
parity-selected ``jnp.roll`` by ±1 lane, and even/odd masks come from a
lane iota. The decode/encode is therefore pure elementwise + roll — if a
Mosaic version can't lower it, the kernel simply loses the ``impl="auto"``
probe (``engine.param.resolve_param_impl``) and the XLA core serves.

Estimates travel through f32 accumulators exactly like the cms kernel, so
they are exact below 2^24 — far above any admissible window threshold, and
the parity suite pins the no-undercount behavior for both impls.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sentinel_tpu.sketch.salsa import CAP, MERGE_CEIL, SAT

MAX_BATCH = 1024


def _make_kernel(P: int, B: int, D: int, C: int, bucket_ms: int,
                 refine_iters: int):
    interval_ms = bucket_ms * B

    def _pairs(x32):
        """Elementwise pair views of a ``[P, C]`` int32 plane:
        ``(lo, hi, merged)`` per CELL (both lanes of a pair agree)."""
        even = (
            jax.lax.broadcasted_iota(jnp.int32, (P, C), 1) % 2 == 0
        )
        partner = jnp.where(
            even, jnp.roll(x32, -1, axis=1), jnp.roll(x32, 1, axis=1)
        )
        lo = jnp.where(even, x32, partner)
        hi = jnp.where(even, partner, x32)
        return even, lo, hi, hi < 0

    def _qdecode(x16):
        """Query view [P, C] f32: both cells of a merged pair read the
        merged value."""
        x32 = x16.astype(jnp.int32)
        _even, lo, hi, merged = _pairs(x32)
        mval = lo + CAP * (-hi - 1)
        return jnp.where(merged, mval, x32).astype(jnp.float32), merged

    def kernel(
        counts_ref,  # ANY [B*D, P, C] int16 (aliased to counts_out_ref)
        starts_ref,  # SMEM [B, 1] int32
        now_ref,  # SMEM [1, 1] int32
        slot_ref,  # VMEM [N, 1] int32
        idx_ref,  # VMEM [N, D] int32
        acq_ref,  # VMEM [N, 1] int32
        thr_ref,  # VMEM [N, 1] float32
        valid_ref,  # VMEM [N, 1] int32
        counts_out_ref,  # ANY [B*D, P, C] int16
        starts_out_ref,  # SMEM [B, 1] int32
        admit_ref,  # VMEM [N, 1] int32
        est_ref,  # VMEM [N, 1] int32
        merges_ref,  # VMEM [P, 1] int32 (newly merged pairs this step)
        plane_buf,  # VMEM scratch [1, P, C] int16
        sem,  # DMA semaphore
    ):
        N = slot_ref.shape[0]
        now = now_ref[0, 0]
        cur_b = (now // bucket_ms) % B
        cur_start = now - now % bucket_ms

        stale = jnp.bool_(False)
        for b in range(B):
            is_cur = jnp.int32(b) == cur_b
            stale = jnp.where(is_cur, starts_ref[b, 0] != cur_start, stale)
            starts_out_ref[b, 0] = jnp.where(
                is_cur, cur_start, starts_ref[b, 0]
            )

        slot = slot_ref[:, 0]
        live = (valid_ref[:, 0] != 0) & (slot >= 0)
        safe_slot = jnp.where(slot >= 0, slot, 0)
        oh_slot = (
            safe_slot[:, None]
            == jax.lax.broadcasted_iota(jnp.int32, (N, P), 1)
        ).astype(jnp.float32)
        oh_idx = [
            (
                idx_ref[:, d][:, None]
                == jax.lax.broadcasted_iota(jnp.int32, (N, C), 1)
            ).astype(jnp.float32)
            for d in range(D)
        ]
        acq = acq_ref[:, 0].astype(jnp.float32)

        # ---- estimate: min over depth of windowed decoded-cell sums ----
        est = None
        for d in range(D):
            acc = jnp.zeros((N,), jnp.float32)
            for b in range(B):
                start_b = starts_out_ref[b, 0]
                age = now - start_b
                ok = (age >= 0) & (age < interval_ms)
                ok = ok & ~(stale & (jnp.int32(b) == cur_b))
                dma = pltpu.make_async_copy(
                    counts_ref.at[pl.ds(b * D + d, 1)], plane_buf, sem
                )
                dma.start()
                dma.wait()
                qdec, _m = _qdecode(plane_buf[0])
                rows = jnp.dot(
                    oh_slot, qdec, preferred_element_type=jnp.float32
                )  # [N, C]
                cell = jnp.sum(rows * oh_idx[d], axis=1)
                acc = acc + jnp.where(ok, cell, 0.0)
            est = acc if est is None else jnp.minimum(est, acc)

        # ---- in-batch prefix admission (same as the cms kernel) ----
        key = safe_slot
        for d in range(D):
            key = key * jnp.int32(-1640531527) + idx_ref[:, d]
        row_i = jax.lax.broadcasted_iota(jnp.int32, (N, N), 0)
        col_i = jax.lax.broadcasted_iota(jnp.int32, (N, N), 1)
        mask = ((key[:, None] == key[None, :]) & (row_i > col_i)).astype(
            jnp.float32
        )
        thr = thr_ref[:, 0]
        admit = live
        for _ in range(refine_iters):
            contrib = jnp.where(admit, acq, 0.0)
            prefix = jnp.dot(
                mask, contrib[:, None], preferred_element_type=jnp.float32
            )[:, 0]
            admit = live & (est + prefix + acq <= thr)

        # ---- update current-bucket planes: decode → routed add → encode ----
        contrib = jnp.where(admit, acq, 0.0)
        macc = jnp.zeros((P,), jnp.float32)
        for d in range(D):
            k = cur_b * D + jnp.int32(d)
            dma_in = pltpu.make_async_copy(
                counts_ref.at[pl.ds(k, 1)], plane_buf, sem
            )
            dma_in.start()
            dma_in.wait()
            old16 = jnp.where(stale, jnp.int16(0), plane_buf[0])
            x32 = old16.astype(jnp.int32)
            even, lo, hi, merged = _pairs(x32)
            mval = lo + CAP * (-hi - 1)
            # accumulation view: merged value at the even cell only
            dec = jnp.where(merged, jnp.where(even, mval, 0), x32)
            # route adds targeting a merged pair to its even cell
            mrows = jnp.dot(
                oh_slot,
                merged.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )  # [N, C]
            flag = jnp.sum(mrows * oh_idx[d], axis=1) > 0.5  # [N]
            idx_d = idx_ref[:, d]
            idx_eff = jnp.where(flag, (idx_d // 2) * 2, idx_d)
            oh_eff = (
                idx_eff[:, None]
                == jax.lax.broadcasted_iota(jnp.int32, (N, C), 1)
            ).astype(jnp.float32)
            delta = jnp.dot(
                oh_slot.T,
                oh_eff * contrib[:, None],
                preferred_element_type=jnp.float32,
            )  # [P, C]
            dec = dec + delta.astype(jnp.int32)
            # re-encode with merge-on-saturation
            p2 = jnp.where(
                even, jnp.roll(dec, -1, axis=1), jnp.roll(dec, 1, axis=1)
            )
            ev = jnp.where(even, dec, p2)
            od = jnp.where(even, p2, dec)
            newly = (~merged) & ((ev > SAT) | (od > SAT))
            m2 = merged | newly
            val = jnp.where(newly, jnp.maximum(ev, od), ev)
            val = jnp.minimum(val, MERGE_CEIL)
            out = jnp.where(
                m2, jnp.where(even, val % CAP, -(val // CAP) - 1), dec
            )
            plane_buf[0] = out.astype(jnp.int16)
            macc = macc + jnp.sum(
                (newly & even).astype(jnp.float32), axis=1
            )
            dma_out = pltpu.make_async_copy(
                plane_buf, counts_out_ref.at[pl.ds(k, 1)], sem
            )
            dma_out.start()
            dma_out.wait()

        admit_ref[:, 0] = admit.astype(jnp.int32)
        est_ref[:, 0] = est.astype(jnp.int32)
        merges_ref[:, 0] = macc.astype(jnp.int32)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "P", "B", "D", "C", "bucket_ms", "refine_iters", "interpret",
    ),
)
def salsa_decide_update_pallas(
    counts: jax.Array,  # [B*D, P, C] int16
    starts: jax.Array,  # [B] int32
    rule_slot: jax.Array,  # [N] int32 (-1 → no rule)
    idx: jax.Array,  # [N, D] int32 cell indices over C lanes
    acquire: jax.Array,  # [N] int32
    threshold: jax.Array,  # [N] float32
    valid: jax.Array,  # [N] bool
    now: jax.Array,  # int32 scalar
    *,
    P: int,
    B: int,
    D: int,
    C: int,
    bucket_ms: int,
    refine_iters: int = 3,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """``-> (counts', starts', admit [N] bool, estimate [N] int32,
    merge_delta [P] int32)``."""
    N = rule_slot.shape[0]
    if N > MAX_BATCH:
        raise ValueError(f"param batch {N} exceeds pallas cap {MAX_BATCH}")
    if refine_iters % 2 == 0:
        raise ValueError("refine_iters must be odd (no-overshoot guarantee)")

    kernel = _make_kernel(P, B, D, C, bucket_ms, refine_iters)
    counts_out, starts_out, admit, est, merges = pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B * D, P, C), jnp.int16),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
            jax.ShapeDtypeStruct((P, 1), jnp.int32),
        ),
        input_output_aliases={0: 0},
        scratch_shapes=[
            pltpu.VMEM((1, P, C), jnp.int16),
            pltpu.SemaphoreType.DMA,
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * N * P * C * D * (B + 2) + 2 * refine_iters * N * N,
            bytes_accessed=2 * P * C * (B * D + 2 * D),
            transcendentals=0,
        ),
        interpret=interpret,
    )(
        counts,
        starts.reshape(B, 1).astype(jnp.int32),
        jnp.asarray(now, jnp.int32).reshape(1, 1),
        rule_slot.reshape(N, 1).astype(jnp.int32),
        idx.astype(jnp.int32),
        acquire.reshape(N, 1).astype(jnp.int32),
        threshold.reshape(N, 1).astype(jnp.float32),
        valid.reshape(N, 1).astype(jnp.int32),
    )
    return (
        counts_out,
        starts_out[:, 0],
        admit[:, 0] != 0,
        est[:, 0],
        merges[:, 0],
    )
