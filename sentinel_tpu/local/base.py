"""Local-engine fundamentals: block exceptions, resource identity, constants.

Analogs: ``BlockException`` hierarchy (``sentinel-core/.../slots/block/*``),
``ResourceWrapper``/``EntryType`` (``slotchain/``), order constants
(``Constants.java:76-83``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Optional


class EntryType(enum.Enum):
    IN = "IN"  # inbound traffic — subject to system-adaptive protection
    OUT = "OUT"


class BlockException(Exception):
    """Base for all flow-control verdict exceptions (``BlockException.java``)."""

    def __init__(self, rule_limit_app: str = "", message: str = "", rule: Any = None):
        super().__init__(message or self.__class__.__name__)
        self.rule_limit_app = rule_limit_app
        self.rule = rule


class FlowException(BlockException):
    pass


class DegradeException(BlockException):
    pass


class SystemBlockException(BlockException):
    def __init__(self, resource_name: str, limit_type: str):
        super().__init__(message=f"SystemBlock: {limit_type}")
        self.resource_name = resource_name
        self.limit_type = limit_type


class AuthorityException(BlockException):
    pass


class ParamFlowException(BlockException):
    def __init__(self, resource_name: str = "", message: str = "", rule: Any = None):
        super().__init__(message=message or "ParamFlowException", rule=rule)
        self.resource_name = resource_name


class PriorityWaitException(Exception):
    """Internal signal: prioritized request borrowed a future window and already
    waited; it passes without counting a new PASS (``PriorityWaitException.java``,
    handled at ``StatisticSlot.java:77-86``)."""

    def __init__(self, wait_ms: int):
        super().__init__(f"wait {wait_ms}ms")
        self.wait_ms = wait_ms


@dataclass(frozen=True)
class ResourceWrapper:
    """Resource identity: name + direction (``slotchain/ResourceWrapper.java``).

    Equality/hash are by name only, matching the reference (``ResourceWrapper
    .equals`` compares name) so one chain/node exists per name.
    """

    name: str
    entry_type: EntryType = EntryType.OUT

    def __eq__(self, other):
        return isinstance(other, ResourceWrapper) and self.name == other.name

    def __hash__(self):
        return hash(self.name)


# Slot order constants (reference Constants.java:76-83); smaller runs earlier.
ORDER_NODE_SELECTOR_SLOT = -10000
ORDER_CLUSTER_BUILDER_SLOT = -9000
ORDER_LOG_SLOT = -8000
ORDER_STATISTIC_SLOT = -7000
ORDER_AUTHORITY_SLOT = -6000
ORDER_SYSTEM_SLOT = -5000
ORDER_GATEWAY_FLOW_SLOT = -4000
ORDER_PARAM_FLOW_SLOT = -3000
ORDER_FLOW_SLOT = -2000
ORDER_DEGRADE_SLOT = -1000

# reference Constants.java:37 — beyond this many distinct resources, entries
# pass through unguarded rather than allocating more chains.
MAX_SLOT_CHAIN_SIZE = 6000

TOTAL_IN_RESOURCE_NAME = "__total_inbound_traffic__"  # Constants.TOTAL_IN_RESOURCE_NAME
CONTEXT_DEFAULT_NAME = "sentinel_default_context"
LIMIT_APP_DEFAULT = "default"
LIMIT_APP_OTHER = "other"
