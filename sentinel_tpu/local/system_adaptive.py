"""System-adaptive inbound protection (BBR-style).

Analog of ``slots/system/*`` — ``SystemSlot.java:33``,
``SystemRuleManager.java:242-340`` (qps / thread / rt / load-with-BBR / cpu
checks against the global inbound node) and ``SystemStatusListener.java:31-52``
(scheduled read of OS load + process CPU; here: lazy /proc sampling cached for
1s instead of a background thread).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.local.base import (
    EntryType,
    ORDER_SYSTEM_SLOT,
    SystemBlockException,
)
from sentinel_tpu.local.chain import ProcessorSlot, entry_node, slot_registry


@dataclass
class SystemRule:
    """``SystemRule.java`` — any threshold < 0 is disabled."""

    highest_system_load: float = -1.0
    highest_cpu_usage: float = -1.0
    qps: float = -1.0
    avg_rt: float = -1.0
    max_thread: float = -1.0


class SystemStatusListener:
    """Lazy system status: 1-minute loadavg and process-CPU fraction, sampled
    at most once per second (the reference polls on a scheduler)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._last_sample_wall = 0.0
        self._load = -1.0
        self._cpu = -1.0
        self._last_proc = None  # (wall_s, cpu_s)

    def _sample(self) -> None:
        now = time.monotonic()
        if now - self._last_sample_wall < 1.0:
            return
        with self._lock:
            if now - self._last_sample_wall < 1.0:
                return
            self._last_sample_wall = now
            try:
                self._load = os.getloadavg()[0]
            except OSError:
                self._load = -1.0
            try:
                cpu_s = time.process_time()
                if self._last_proc is not None:
                    dw = now - self._last_proc[0]
                    dc = cpu_s - self._last_proc[1]
                    ncpu = os.cpu_count() or 1
                    self._cpu = max(0.0, min(1.0, dc / dw / ncpu)) if dw > 0 else -1.0
                self._last_proc = (now, cpu_s)
            except Exception:
                self._cpu = -1.0

    def current_load(self) -> float:
        self._sample()
        return self._load

    def current_cpu_usage(self) -> float:
        self._sample()
        return self._cpu


class SystemRuleManager:
    """Aggregates loaded rules into effective minima
    (``SystemRuleManager.loadSystemConf``)."""

    _lock = threading.RLock()
    _effective = SystemRule()
    _any_enabled = False
    status = SystemStatusListener()

    @classmethod
    def load_rules(cls, rules: List[SystemRule]) -> None:
        eff = SystemRule()
        any_enabled = False

        def merge(cur: float, new: float) -> float:
            if new < 0:
                return cur
            return new if cur < 0 else min(cur, new)

        for r in rules or []:
            eff.highest_system_load = merge(eff.highest_system_load, r.highest_system_load)
            eff.highest_cpu_usage = merge(eff.highest_cpu_usage, r.highest_cpu_usage)
            eff.qps = merge(eff.qps, r.qps)
            eff.avg_rt = merge(eff.avg_rt, r.avg_rt)
            eff.max_thread = merge(eff.max_thread, r.max_thread)
        any_enabled = any(
            v >= 0
            for v in (
                eff.highest_system_load,
                eff.highest_cpu_usage,
                eff.qps,
                eff.avg_rt,
                eff.max_thread,
            )
        )
        with cls._lock:
            cls._effective = eff
            cls._any_enabled = any_enabled

    @classmethod
    def register_property(cls, prop) -> None:
        prop.listen(lambda rules: cls.load_rules(rules or []))

    @classmethod
    def check_system(cls, resource, count: int) -> None:
        """``SystemRuleManager.checkSystem`` (``SystemRuleManager.java:290-340``):
        applies to inbound traffic only."""
        if not cls._any_enabled or resource.entry_type != EntryType.IN:
            return
        eff = cls._effective
        node = entry_node()
        now = _clock.now_ms()
        if eff.qps >= 0:
            # reference checkSystem uses ENTRY_NODE.passQps() alone
            # (SystemRuleManager.java:305); matured borrows already fold into
            # pass_qps via StatisticNode._touch
            if node.pass_qps(now) + count > eff.qps:
                raise SystemBlockException(resource.name, "qps")
        if eff.max_thread >= 0 and node.cur_thread_num + 1 > eff.max_thread:
            raise SystemBlockException(resource.name, "thread")
        if eff.avg_rt >= 0 and node.avg_rt(now) > eff.avg_rt:
            raise SystemBlockException(resource.name, "rt")
        if eff.highest_system_load >= 0:
            if cls.status.current_load() > eff.highest_system_load:
                if not cls._check_bbr(node, now):
                    raise SystemBlockException(resource.name, "load")
        if eff.highest_cpu_usage >= 0:
            if cls.status.current_cpu_usage() > eff.highest_cpu_usage:
                raise SystemBlockException(resource.name, "cpu")

    @classmethod
    def _check_bbr(cls, node, now: int) -> bool:
        """BBR gate (``SystemRuleManager.java:334-340``): under high load still
        admit while concurrency <= estimated BDP = maxSuccessQps * minRt."""
        cur_thread = node.cur_thread_num
        if cur_thread > 1:
            return cur_thread <= node.success_qps(now) * node.min_rt(now) / 1000.0
        return True

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._effective = SystemRule()
            cls._any_enabled = False


class SystemSlot(ProcessorSlot):
    """``SystemSlot.java:33``."""

    def entry(self, context, resource, node, count, prioritized, args):
        SystemRuleManager.check_system(resource, count)
        self.fire_entry(context, resource, node, count, prioritized, args)


slot_registry.register(SystemSlot, order=ORDER_SYSTEM_SLOT, name="SystemSlot")
