"""Flow rules: QPS/concurrency limiting with four traffic-shaping behaviors.

Analog of ``slots/block/flow/*`` — ``FlowSlot.java:142``,
``FlowRuleChecker.java:42-208``, the four ``TrafficShapingController``s
(``controller/{Default,RateLimiter,WarmUp,WarmUpRateLimiter}Controller.java``)
and ``FlowRuleManager.java:49`` / ``FlowRuleUtil.java:102-148``.

Controllers are stateful per rule and are re-instantiated on rule reload
(matching the reference: warm-up curves and pacing state reset when rules
change, ``FlowRuleUtil.buildFlowRuleMap``).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.core.property import DynamicProperty
from sentinel_tpu.local import chain as chain_mod
from sentinel_tpu.local.base import (
    FlowException,
    LIMIT_APP_DEFAULT,
    LIMIT_APP_OTHER,
    ORDER_FLOW_SLOT,
    PriorityWaitException,
)
from sentinel_tpu.local.chain import ProcessorSlot, slot_registry
from sentinel_tpu.local.stat import DEFAULT_OCCUPY_TIMEOUT_MS, StatisticNode


class FlowGrade(enum.IntEnum):
    THREAD = 0  # concurrency
    QPS = 1


class FlowStrategy(enum.IntEnum):
    DIRECT = 0
    RELATE = 1
    CHAIN = 2


class ControlBehavior(enum.IntEnum):
    DEFAULT = 0  # reject (+ priority occupy)
    WARM_UP = 1
    RATE_LIMITER = 2
    WARM_UP_RATE_LIMITER = 3


@dataclass
class FlowRule:
    """``FlowRule.java`` — field names and defaults preserved."""

    resource: str
    count: float = 0.0
    grade: FlowGrade = FlowGrade.QPS
    limit_app: str = LIMIT_APP_DEFAULT
    strategy: FlowStrategy = FlowStrategy.DIRECT
    ref_resource: str = ""
    control_behavior: ControlBehavior = ControlBehavior.DEFAULT
    warm_up_period_sec: int = 10
    max_queueing_time_ms: int = 500
    cluster_mode: bool = False
    cluster_config: Optional[dict] = None
    # compare=False: the mutable controller must not defeat DynamicProperty's
    # equal-value dedup, or every republish of an identical config would reset
    # warm-up/pacing state
    _rater: "TrafficShapingController" = field(
        init=False, repr=False, compare=False, default=None
    )


# ---------------------------------------------------------------------------
# Traffic-shaping controllers
# ---------------------------------------------------------------------------


class TrafficShapingController:
    def can_pass(self, node: StatisticNode, acquire: int, prioritized: bool = False) -> bool:
        raise NotImplementedError


class DefaultController(TrafficShapingController):
    """Reject excess; prioritized QPS requests may borrow a future window
    (``DefaultController.java:49-69``)."""

    def __init__(self, count: float, grade: FlowGrade):
        self.count = count
        self.grade = grade

    def _used(self, node: StatisticNode, now: int) -> float:
        if self.grade == FlowGrade.THREAD:
            return float(node.cur_thread_num)
        return node.pass_qps(now)

    def can_pass(self, node, acquire, prioritized=False):
        now = _clock.now_ms()
        cur = self._used(node, now)
        if cur + acquire <= self.count:
            return True
        if prioritized and self.grade == FlowGrade.QPS:
            wait = node.try_occupy_next(now, acquire, self.count)
            if wait <= DEFAULT_OCCUPY_TIMEOUT_MS:
                node.add_occupied_pass(acquire, wait, now)
                _clock.get_clock().wait_ms(wait)
                raise PriorityWaitException(wait)
        return False


class RateLimiterController(TrafficShapingController):
    """Leaky-bucket pacing: requests queue up to ``max_queueing_time_ms``
    (``RateLimiterController.java:46-91``; the CAS on ``latestPassedTime``
    becomes a lock — the host path is not the hot path here)."""

    def __init__(self, count: float, max_queueing_time_ms: int):
        self.count = count
        self.max_queueing_time_ms = max_queueing_time_ms
        self._latest_passed_time = -1
        self._lock = threading.Lock()

    def can_pass(self, node, acquire, prioritized=False):
        if acquire <= 0:
            return True
        if self.count <= 0:
            return False
        now = _clock.now_ms()
        cost_ms = round(1000.0 * acquire / self.count)
        with self._lock:
            expected = self._latest_passed_time + cost_ms
            if expected <= now:
                self._latest_passed_time = now
                return True
            wait = expected - now
            if wait > self.max_queueing_time_ms:
                return False
            self._latest_passed_time = expected
        _clock.get_clock().wait_ms(wait)
        return True


class WarmUpController(TrafficShapingController):
    """Guava-SmoothWarmingUp-style cold-start curve
    (``WarmUpController.java:64-170``): a token bucket whose fill level above
    ``warning_token`` maps to a reduced admissible QPS along a linear slope;
    sustained traffic drains the bucket back to full speed over
    ``warm_up_period_sec``."""

    def __init__(self, count: float, warm_up_period_sec: int, cold_factor: Optional[int] = None):
        cold = cold_factor if cold_factor is not None else SentinelConfig.cold_factor()
        if cold <= 1:
            raise ValueError("cold factor must be > 1")
        if count <= 0:
            raise ValueError("warm-up requires count > 0")
        self.count = count
        self.cold_factor = cold
        # token maths (WarmUpController.java:94-111)
        self.warning_token = int((warm_up_period_sec * count) / (cold - 1))
        self.max_token = int(
            self.warning_token + 2.0 * warm_up_period_sec * count / (1.0 + cold)
        )
        self.slope = (cold - 1.0) / count / max(1, (self.max_token - self.warning_token))
        self._stored_tokens = 0.0
        self._last_filled_ms = 0
        self._lock = threading.Lock()

    def can_pass(self, node, acquire, prioritized=False):
        now = _clock.now_ms()
        pass_qps = node.pass_qps(now)
        previous_qps = node.previous_pass_qps(now)
        with self._lock:
            self._sync_token(previous_qps, now)
            rest = self._stored_tokens
            if rest >= self.warning_token:
                above = rest - self.warning_token
                warning_qps = 1.0 / (above * self.slope + 1.0 / self.count)
                return pass_qps + acquire <= warning_qps
            return pass_qps + acquire <= self.count

    def _sync_token(self, pass_qps: float, now: int) -> None:
        cur_sec = now - now % 1000
        if cur_sec <= self._last_filled_ms:
            return
        self._stored_tokens = self._cool_down(cur_sec, pass_qps)
        self._stored_tokens = max(0.0, self._stored_tokens - pass_qps)
        self._last_filled_ms = cur_sec

    def _cool_down(self, cur_sec: int, pass_qps: float) -> float:
        old = self._stored_tokens
        new = old
        refill = (cur_sec - self._last_filled_ms) * self.count / 1000.0
        if old < self.warning_token:
            new = old + refill
        elif old > self.warning_token:
            # below cold-rate traffic → keep cooling down (refilling); the
            # threshold floors like the reference's int division, so traffic
            # at exactly the admitted cold rate does drain the bucket
            if pass_qps < int(self.count) // self.cold_factor:
                new = old + refill
        return min(new, self.max_token)


class WarmUpRateLimiterController(TrafficShapingController):
    """Warm-up curve + pacing (``WarmUpRateLimiterController.java:27``): the
    pacing interval derives from the warm-up-adjusted admissible QPS."""

    def __init__(self, count: float, warm_up_period_sec: int, max_queueing_time_ms: int,
                 cold_factor: Optional[int] = None):
        self._warmup = WarmUpController(count, warm_up_period_sec, cold_factor)
        self.count = count
        self.max_queueing_time_ms = max_queueing_time_ms
        self._latest_passed_time = -1
        self._lock = threading.Lock()

    def can_pass(self, node, acquire, prioritized=False):
        now = _clock.now_ms()
        previous_qps = node.previous_pass_qps(now)
        with self._warmup._lock:
            self._warmup._sync_token(previous_qps, now)
            rest = self._warmup._stored_tokens
            if rest >= self._warmup.warning_token:
                above = rest - self._warmup.warning_token
                warning_qps = 1.0 / (above * self._warmup.slope + 1.0 / self.count)
                cost_ms = round(1000.0 * acquire / warning_qps)
            else:
                cost_ms = round(1000.0 * acquire / self.count)
        with self._lock:
            expected = self._latest_passed_time + cost_ms
            if expected <= now:
                self._latest_passed_time = now
                return True
            wait = expected - now
            if wait > self.max_queueing_time_ms:
                return False
            self._latest_passed_time = expected
        _clock.get_clock().wait_ms(wait)
        return True


def generate_rater(rule: FlowRule) -> TrafficShapingController:
    """``FlowRuleUtil.generateRater`` (``FlowRuleUtil.java:132-148``): shaping
    behaviors only apply to QPS-grade rules."""
    if rule.grade == FlowGrade.QPS:
        if rule.control_behavior == ControlBehavior.WARM_UP:
            return WarmUpController(rule.count, rule.warm_up_period_sec)
        if rule.control_behavior == ControlBehavior.RATE_LIMITER:
            return RateLimiterController(rule.count, rule.max_queueing_time_ms)
        if rule.control_behavior == ControlBehavior.WARM_UP_RATE_LIMITER:
            return WarmUpRateLimiterController(
                rule.count, rule.warm_up_period_sec, rule.max_queueing_time_ms
            )
    return DefaultController(rule.count, rule.grade)


def fallback_controller(
    count: float, max_queueing_time_ms: int = 0
) -> TrafficShapingController:
    """Controller for the cluster fail-to-local path (``ha.fallback``): a
    degraded QPS budget enforced locally while the token servers are down.
    ``max_queueing_time_ms > 0`` paces (leaky bucket) instead of rejecting —
    the same two shapes ``generate_rater`` picks between, minus warm-up
    (a fallback window is too short for a ramp to mean anything)."""
    if max_queueing_time_ms > 0:
        return RateLimiterController(count, max_queueing_time_ms)
    return DefaultController(count, FlowGrade.QPS)


# ---------------------------------------------------------------------------
# Rule manager
# ---------------------------------------------------------------------------


class FlowRuleManager:
    """Holds the active rule map; subscribes to a dynamic property
    (``FlowRuleManager.java:49-75``)."""

    _lock = threading.RLock()
    _rules: Dict[str, List[FlowRule]] = {}
    _property: Optional[DynamicProperty] = None

    @classmethod
    def load_rules(cls, rules: List[FlowRule]) -> None:
        new_map: Dict[str, List[FlowRule]] = {}
        for rule in rules or []:
            if rule.count < 0 or not rule.resource:
                continue
            try:
                rule._rater = generate_rater(rule)
            except Exception:
                # one malformed rule (e.g. WARM_UP with count=0) must not
                # abort the whole batch — matches the reference's per-rule
                # isValidRule filtering
                from sentinel_tpu.core.log import record_log

                record_log.warning("ignoring invalid flow rule: %r", rule)
                continue
            new_map.setdefault(rule.resource, []).append(rule)
        # FlowRuleComparator: specific-origin rules first, then 'other', then
        # 'default' — ensures origin-specific limits take precedence.
        def key(r: FlowRule) -> int:
            if r.limit_app == LIMIT_APP_DEFAULT:
                return 2
            if r.limit_app == LIMIT_APP_OTHER:
                return 1
            return 0

        for lst in new_map.values():
            lst.sort(key=key)
        with cls._lock:
            cls._rules = new_map

    @classmethod
    def register_property(cls, prop: DynamicProperty) -> None:
        """``register2Property``: rules then follow the datasource."""
        with cls._lock:
            cls._property = prop
            prop.listen(lambda rules: cls.load_rules(rules or []))

    @classmethod
    def get_rules(cls, resource: str) -> List[FlowRule]:
        return cls._rules.get(resource, [])

    @classmethod
    def all_rules(cls) -> List[FlowRule]:
        return [r for lst in cls._rules.values() for r in lst]

    @classmethod
    def has_limit_app(cls, resource: str, origin: str) -> bool:
        """Is ``origin`` named by any rule of this resource? (the 'other'
        semantics, ``FlowRuleChecker.java:115-145``)."""
        return any(r.limit_app == origin for r in cls.get_rules(resource))

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._rules = {}
            cls._property = None


# ---------------------------------------------------------------------------
# Checker + slot
# ---------------------------------------------------------------------------


def _filter_origin(origin: str) -> bool:
    return bool(origin) and origin not in (LIMIT_APP_DEFAULT, LIMIT_APP_OTHER)


def select_node(rule: FlowRule, context, node):
    """``selectNodeByRequesterAndStrategy`` (``FlowRuleChecker.java:115-145``)."""
    limit_app = rule.limit_app
    origin = context.origin
    if limit_app == origin and _filter_origin(origin):
        if rule.strategy == FlowStrategy.DIRECT:
            return context.cur_entry.origin_node
        return _select_reference_node(rule, context, node)
    if limit_app == LIMIT_APP_DEFAULT:
        if rule.strategy == FlowStrategy.DIRECT:
            return node.cluster_node
        return _select_reference_node(rule, context, node)
    if limit_app == LIMIT_APP_OTHER and not FlowRuleManager.has_limit_app(
        rule.resource, origin
    ):
        if rule.strategy == FlowStrategy.DIRECT:
            return context.cur_entry.origin_node
        return _select_reference_node(rule, context, node)
    return None


def _select_reference_node(rule: FlowRule, context, node):
    ref = rule.ref_resource
    if not ref:
        return None
    if rule.strategy == FlowStrategy.RELATE:
        return chain_mod.get_cluster_node(ref)
    if rule.strategy == FlowStrategy.CHAIN:
        return node if context.name == ref else None
    return None


def can_pass_check(rule: FlowRule, context, node, acquire: int,
                   prioritized: bool = False) -> bool:
    if rule.cluster_mode:
        return _pass_cluster_check(rule, context, node, acquire, prioritized)
    return _pass_local_check(rule, context, node, acquire, prioritized)


def _pass_local_check(rule, context, node, acquire, prioritized):
    selected = select_node(rule, context, node)
    if selected is None:
        return True
    return rule._rater.can_pass(selected, acquire, prioritized)


_cluster_api = None
_cluster_api_probed = False


def _get_cluster_api():
    """Import the cluster module once (failed imports are not cached by
    Python, so re-trying per request would re-scan sys.path every entry)."""
    global _cluster_api, _cluster_api_probed
    if not _cluster_api_probed:
        _cluster_api_probed = True
        try:
            from sentinel_tpu.cluster import api as cluster_api

            _cluster_api = cluster_api
        except ImportError:
            _cluster_api = None
    return _cluster_api


def _pass_cluster_check(rule, context, node, acquire, prioritized):
    """Cluster branch (``FlowRuleChecker.java:147-208``): ask the token
    service; on failure fall back to local or pass-through."""
    cluster_api = _get_cluster_api()
    if cluster_api is None:
        return _fallback(rule, context, node, acquire, prioritized)
    try:
        result = cluster_api.request_token(rule, acquire, prioritized)
    except Exception:
        return _fallback(rule, context, node, acquire, prioritized)
    if result is None:
        return _fallback(rule, context, node, acquire, prioritized)
    return cluster_api.apply_token_result(
        result, rule, context, node, acquire, prioritized, _fallback
    )


def _fallback(rule, context, node, acquire, prioritized):
    """``fallbackToLocalOrPass`` (``FlowRuleChecker.java:158-173``)."""
    cfg = rule.cluster_config or {}
    if cfg.get("fallback_to_local_when_fail", True):
        return _pass_local_check(rule, context, node, acquire, prioritized)
    return True


def check_flow(resource, context, node, count: int, prioritized: bool) -> None:
    for rule in FlowRuleManager.get_rules(resource.name):
        if not can_pass_check(rule, context, node, count, prioritized):
            raise FlowException(rule.limit_app, f"flow limit: {resource.name}", rule)


class FlowSlot(ProcessorSlot):
    """``FlowSlot.java:142``."""

    def entry(self, context, resource, node, count, prioritized, args):
        check_flow(resource, context, node, count, prioritized)
        self.fire_entry(context, resource, node, count, prioritized, args)


slot_registry.register(FlowSlot, order=ORDER_FLOW_SLOT, name="FlowSlot")
