"""Origin-based authority (white/black list) rules.

Analog of ``slots/block/authority/*`` — ``AuthoritySlot.java:36``,
``AuthorityRuleChecker.java:28-30``, ``AuthorityRuleManager``.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass
from typing import Dict, List

from sentinel_tpu.local.base import AuthorityException, ORDER_AUTHORITY_SLOT
from sentinel_tpu.local.chain import ProcessorSlot, slot_registry


class AuthorityStrategy(enum.IntEnum):
    WHITE = 0
    BLACK = 1


@dataclass
class AuthorityRule:
    resource: str
    limit_app: str  # comma-separated origins
    strategy: AuthorityStrategy = AuthorityStrategy.WHITE


def pass_check(rule: AuthorityRule, origin: str) -> bool:
    """``AuthorityRuleChecker.passCheck``: empty origin or empty list passes;
    WHITE requires membership, BLACK requires absence."""
    if not origin or not rule.limit_app:
        return True
    listed = origin in {s.strip() for s in rule.limit_app.split(",")}
    if rule.strategy == AuthorityStrategy.WHITE:
        return listed
    return not listed


class AuthorityRuleManager:
    _lock = threading.RLock()
    _rules: Dict[str, List[AuthorityRule]] = {}

    @classmethod
    def load_rules(cls, rules: List[AuthorityRule]) -> None:
        new_map: Dict[str, List[AuthorityRule]] = {}
        for r in rules or []:
            if r.resource:
                new_map.setdefault(r.resource, []).append(r)
        with cls._lock:
            cls._rules = new_map

    @classmethod
    def get_rules(cls, resource: str) -> List[AuthorityRule]:
        return cls._rules.get(resource, [])

    @classmethod
    def register_property(cls, prop) -> None:
        prop.listen(lambda rules: cls.load_rules(rules or []))

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._rules = {}


class AuthoritySlot(ProcessorSlot):
    """``AuthoritySlot.java:36``."""

    def entry(self, context, resource, node, count, prioritized, args):
        for rule in AuthorityRuleManager.get_rules(resource.name):
            if not pass_check(rule, context.origin):
                raise AuthorityException(
                    context.origin, f"authority: {resource.name}", rule
                )
        self.fire_entry(context, resource, node, count, prioritized, args)


slot_registry.register(AuthoritySlot, order=ORDER_AUTHORITY_SLOT, name="AuthoritySlot")
