"""Processor slot chain: the interception pipeline.

Analog of ``slotchain/ProcessorSlot.java:28`` (entry/fireEntry/exit/fireExit),
``DefaultProcessorSlotChain``, and the SPI-sorted ``DefaultSlotChainBuilder``
(``slots/DefaultSlotChainBuilder.java:37``). Slots register in the
``"slot"`` registry with their order constant; the chain is rebuilt per
resource from the sorted registry, so extensions (param-flow, gateway) insert
by registering a factory — same seam as the reference's ``META-INF/services``
file.
"""

from __future__ import annotations

from typing import List, Optional

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.registry import registry
from sentinel_tpu.local.base import (
    BlockException,
    EntryType,
    PriorityWaitException,
    ResourceWrapper,
)
from sentinel_tpu.local.context import Context
from sentinel_tpu.local.stat import ClusterNode, DefaultNode

slot_registry = registry("slot")


class ProcessorSlot:
    """A stage in the chain. ``entry`` runs checks/bookkeeping then must call
    ``fire_entry`` to continue; ``exit`` likewise with ``fire_exit``."""

    order: int = 0

    def __init__(self):
        self.next: Optional["ProcessorSlot"] = None

    # -- template ------------------------------------------------------------
    def entry(self, context: Context, resource: ResourceWrapper, node, count: int,
              prioritized: bool, args: tuple) -> None:
        self.fire_entry(context, resource, node, count, prioritized, args)

    def fire_entry(self, context: Context, resource: ResourceWrapper, node, count: int,
                   prioritized: bool, args: tuple) -> None:
        if self.next is not None:
            self.next.entry(context, resource, node, count, prioritized, args)

    def exit(self, context: Context, resource: ResourceWrapper, count: int,
             args: tuple) -> None:
        self.fire_exit(context, resource, count, args)

    def fire_exit(self, context: Context, resource: ResourceWrapper, count: int,
                  args: tuple) -> None:
        if self.next is not None:
            self.next.exit(context, resource, count, args)


class SlotChain:
    """Linked chain with a synthetic head (``DefaultProcessorSlotChain``)."""

    def __init__(self, slots: List[ProcessorSlot]):
        self.first: Optional[ProcessorSlot] = None
        tail: Optional[ProcessorSlot] = None
        for slot in slots:
            if self.first is None:
                self.first = tail = slot
            else:
                tail.next = slot  # type: ignore[union-attr]
                tail = slot

    def entry(self, context, resource, node, count, prioritized, args) -> None:
        if self.first is not None:
            self.first.entry(context, resource, node, count, prioritized, args)

    def exit(self, context, resource, count, args) -> None:
        if self.first is not None:
            self.first.exit(context, resource, count, args)


def build_chain() -> SlotChain:
    """Instantiate all registered slots, order-sorted (one fresh instance set
    per resource chain, as in the reference — slots hold per-chain state)."""
    return SlotChain(slot_registry.instances_sorted())


# ---------------------------------------------------------------------------
# Core slots
# ---------------------------------------------------------------------------


class NodeSelectorSlot(ProcessorSlot):
    """Builds the invocation tree: one DefaultNode per (resource, context
    name), cached per-chain (``slots/nodeselector/NodeSelectorSlot.java:128``).
    """

    def __init__(self):
        super().__init__()
        self._nodes = {}  # context name -> DefaultNode

    def entry(self, context, resource, node, count, prioritized, args):
        n = self._nodes.get(context.name)
        if n is None:
            n = self._nodes.setdefault(context.name, DefaultNode(resource))
            parent = context.cur_entry.parent_node() if context.cur_entry else None
            (parent or context.entrance_node).add_child(n)
        context.cur_entry.cur_node = n
        self.fire_entry(context, resource, n, count, prioritized, args)


_cluster_nodes = {}  # resource name -> ClusterNode (ClusterBuilderSlot.java:50)
import threading as _threading

_cluster_lock = _threading.RLock()


def get_cluster_node(resource_name: str) -> Optional[ClusterNode]:
    return _cluster_nodes.get(resource_name)


def cluster_node_map():
    return dict(_cluster_nodes)


def reset_cluster_nodes_for_tests():
    with _cluster_lock:
        _cluster_nodes.clear()


class ClusterBuilderSlot(ProcessorSlot):
    """One ClusterNode per resource + per-origin node selection
    (``slots/clusterbuilder/ClusterBuilderSlot.java:50-119``)."""

    def entry(self, context, resource, node, count, prioritized, args):
        cn = _cluster_nodes.get(resource.name)
        if cn is None:
            with _cluster_lock:
                cn = _cluster_nodes.get(resource.name)
                if cn is None:
                    cn = ClusterNode(resource.name)
                    _cluster_nodes[resource.name] = cn
        node.cluster_node = cn
        if context.origin:
            context.cur_entry.origin_node = cn.get_or_create_origin_node(
                context.origin
            )
        self.fire_entry(context, resource, node, count, prioritized, args)


class LogSlot(ProcessorSlot):
    """Logs block events (``slots/logger/LogSlot.java:32``). The reference's
    EagleEye block log aggregates per (resource, second); we throttle the same
    way — one line per resource per second with a suppressed-count."""

    _last_logged: dict = {}
    _suppressed: dict = {}

    def entry(self, context, resource, node, count, prioritized, args):
        try:
            self.fire_entry(context, resource, node, count, prioritized, args)
        except BlockException as e:
            from sentinel_tpu.core import clock as _clock
            from sentinel_tpu.core.log import record_log
            from sentinel_tpu.metrics.stat_logger import log_block

            # aggregated block log (EagleEyeLogUtil.log analog): every block
            # lands in the rolling stat log keyed (resource, origin, rule)
            log_block(resource.name, context.origin, type(e).__name__)
            sec = _clock.now_ms() // 1000
            key = resource.name
            if LogSlot._last_logged.get(key) != sec:
                suppressed = LogSlot._suppressed.pop(key, 0)
                LogSlot._last_logged[key] = sec
                record_log.info(
                    "block: resource=%s context=%s origin=%s rule=%s suppressed=%d",
                    resource.name, context.name, context.origin,
                    type(e).__name__, suppressed,
                )
            else:
                LogSlot._suppressed[key] = LogSlot._suppressed.get(key, 0) + 1
            raise


_ext_module = None


def _extension_hooks():
    """Cached handle to ``metrics.extension`` — imported lazily because
    ``metrics.__init__`` → ``exporter`` imports this module back, but cached
    in a module global so the entry hot path pays a dict lookup, not an
    import-machinery round trip per call."""
    global _ext_module
    if _ext_module is None:
        from sentinel_tpu.metrics import extension as _ext_mod

        _ext_module = _ext_mod
    return _ext_module


class StatisticSlot(ProcessorSlot):
    """The write path (``slots/statistic/StatisticSlot.java:52-153``):
    fire checks first; count pass/block/rt afterwards based on the outcome."""

    def entry(self, context, resource, node, count, prioritized, args):
        _ext = _extension_hooks()
        try:
            self.fire_entry(context, resource, node, count, prioritized, args)
        except PriorityWaitException:
            # borrowed a future window: concurrency counts, pass was pre-paid
            node.increase_thread()
            if node.cluster_node is not None:
                node.cluster_node.increase_thread()
            if context.cur_entry.origin_node is not None:
                context.cur_entry.origin_node.increase_thread()
            if resource.entry_type == EntryType.IN:
                _entry_node().increase_thread()
            # the borrow pre-paid the pass in the built-in counters, but
            # extension sinks still observe it as a pass (the reference
            # fires onPass in its PriorityWaitException catch too)
            _ext.on_pass(resource.name, count, args)
            _ext.on_thread_inc(resource.name, args)
        except BlockException as e:
            context.cur_entry.block_error = e
            now = _clock.now_ms()
            node.add_block(count, now=now)
            if node.cluster_node is not None:
                node.cluster_node.add_block(count, now=now)
            if context.cur_entry.origin_node is not None:
                context.cur_entry.origin_node.add_block(count, now=now)
            if resource.entry_type == EntryType.IN:
                _entry_node().add_block(count, now=now)
            _ext.on_block(resource.name, count, context.origin, e, args)
            raise
        else:
            now = _clock.now_ms()
            node.increase_thread()
            node.add_pass(count, now=now)
            if node.cluster_node is not None:
                node.cluster_node.increase_thread()
                node.cluster_node.add_pass(count, now=now)
            if context.cur_entry.origin_node is not None:
                context.cur_entry.origin_node.increase_thread()
                context.cur_entry.origin_node.add_pass(count, now=now)
            if resource.entry_type == EntryType.IN:
                en = _entry_node()
                en.increase_thread()
                en.add_pass(count, now=now)
            _ext.on_pass(resource.name, count, args)
            _ext.on_thread_inc(resource.name, args)

    def exit(self, context, resource, count, args):
        entry = context.cur_entry
        if entry is not None and entry.block_error is None:
            now = _clock.now_ms()
            rt = now - entry.create_ms
            node = entry.cur_node
            if node is not None:
                node.add_rt_and_success(rt, count, now=now)
                node.decrease_thread()
                if node.cluster_node is not None:
                    node.cluster_node.add_rt_and_success(rt, count, now=now)
                    node.cluster_node.decrease_thread()
            if entry.origin_node is not None:
                entry.origin_node.add_rt_and_success(rt, count, now=now)
                entry.origin_node.decrease_thread()
            if resource.entry_type == EntryType.IN:
                en = _entry_node()
                en.add_rt_and_success(rt, count, now=now)
                en.decrease_thread()
            _ext = _extension_hooks()
            _ext.on_complete(resource.name, count, rt, args)
            _ext.on_thread_dec(resource.name, args)
        self.fire_exit(context, resource, count, args)


# Global inbound-traffic node (Constants.ENTRY_NODE): target of the
# system-adaptive checks.
from sentinel_tpu.local.base import TOTAL_IN_RESOURCE_NAME

_entry_node_singleton: Optional[ClusterNode] = None


def _entry_node() -> ClusterNode:
    global _entry_node_singleton
    if _entry_node_singleton is None:
        _entry_node_singleton = ClusterNode(TOTAL_IN_RESOURCE_NAME)
    return _entry_node_singleton


def entry_node() -> ClusterNode:
    return _entry_node()


def reset_entry_node_for_tests() -> None:
    global _entry_node_singleton
    _entry_node_singleton = None


# Register core slots (orders from Constants.java:76-83).
from sentinel_tpu.local import base as _base

slot_registry.register(NodeSelectorSlot, order=_base.ORDER_NODE_SELECTOR_SLOT,
                       name="NodeSelectorSlot")
slot_registry.register(ClusterBuilderSlot, order=_base.ORDER_CLUSTER_BUILDER_SLOT,
                       name="ClusterBuilderSlot")
slot_registry.register(LogSlot, order=_base.ORDER_LOG_SLOT, name="LogSlot")
slot_registry.register(StatisticSlot, order=_base.ORDER_STATISTIC_SLOT,
                       name="StatisticSlot")
