"""Circuit breaking: slow-call ratio / error ratio / error count.

Analog of ``slots/block/degrade/*`` — ``DegradeSlot.java:38-66``,
``AbstractCircuitBreaker.java:33-155`` (CLOSED/OPEN/HALF_OPEN machine),
``ExceptionCircuitBreaker.java:35`` and ``ResponseTimeCircuitBreaker.java:34``,
``DegradeRuleManager.java:43``.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.local.base import DegradeException, ORDER_DEGRADE_SLOT
from sentinel_tpu.local.chain import ProcessorSlot, slot_registry
from sentinel_tpu.local.stat import HostWindow


class DegradeGrade(enum.IntEnum):
    # RuleConstant.java:29-37
    SLOW_REQUEST_RATIO = 0
    ERROR_RATIO = 1
    ERROR_COUNT = 2


class State(enum.IntEnum):
    CLOSED = 0
    OPEN = 1
    HALF_OPEN = 2


@dataclass
class DegradeRule:
    """``DegradeRule.java`` — for SLOW_REQUEST_RATIO, ``count`` is the max
    allowed RT (ms) and ``slow_ratio_threshold`` the trip ratio; for the error
    grades ``count`` is the ratio/count threshold."""

    resource: str
    grade: DegradeGrade = DegradeGrade.SLOW_REQUEST_RATIO
    count: float = 0.0
    time_window_sec: int = 0  # recovery (retry) timeout
    min_request_amount: int = 5
    stat_interval_ms: int = 1000
    slow_ratio_threshold: float = 1.0
    limit_app: str = "default"


# Window channels for breaker counters (one HostWindow, private channel use:
# chan 0 = total, 1 = error, 2 = slow; we reuse HostWindow's channel array).
_TOTAL, _ERROR, _SLOW = 0, 1, 2

StateChangeObserver = Callable[[str, State, State, DegradeRule], None]
_observers: List[StateChangeObserver] = []


def register_state_change_observer(obs: StateChangeObserver) -> None:
    """``EventObserverRegistry`` analog."""
    _observers.append(obs)


def clear_state_change_observers() -> None:
    _observers.clear()


class CircuitBreaker:
    """``AbstractCircuitBreaker``: the state machine; subclasses supply the
    trip condition from their sliding counters."""

    def __init__(self, rule: DegradeRule):
        self.rule = rule
        self.retry_timeout_ms = rule.time_window_sec * 1000
        self._state = State.CLOSED
        self._next_retry_ms = 0
        self._lock = threading.RLock()
        # sampleCount=1 per the reference's SimpleErrorCounterLeapArray —
        # one bucket spanning stat_interval_ms
        self._counter = HostWindow(rule.stat_interval_ms, 1)

    # -- state transitions (AbstractCircuitBreaker.java:93-155) -------------
    def _notify(self, prev: State, new: State) -> None:
        for obs in _observers:
            try:
                obs(self.rule.resource, prev, new, self.rule)
            except Exception:
                pass

    def _to_open(self) -> None:
        prev = self._state
        self._state = State.OPEN
        self._next_retry_ms = _clock.now_ms() + self.retry_timeout_ms
        self._notify(prev, State.OPEN)

    def _from_open_to_half_open(self) -> bool:
        if self._state == State.OPEN:
            self._state = State.HALF_OPEN
            self._notify(State.OPEN, State.HALF_OPEN)
            return True
        return False

    def _from_half_open_to_open(self) -> None:
        if self._state == State.HALF_OPEN:
            self._state = State.OPEN
            self._next_retry_ms = _clock.now_ms() + self.retry_timeout_ms
            self._notify(State.HALF_OPEN, State.OPEN)

    def _from_half_open_to_close(self) -> None:
        if self._state == State.HALF_OPEN:
            self._state = State.CLOSED
            self._counter = HostWindow(self.rule.stat_interval_ms, 1)
            self._notify(State.HALF_OPEN, State.CLOSED)

    @property
    def state(self) -> State:
        return self._state

    def try_pass(self) -> bool:
        """Entry-side gate (``AbstractCircuitBreaker.tryPass``): CLOSED passes;
        OPEN passes one probe once the retry timeout arrives (→ HALF_OPEN);
        HALF_OPEN rejects everything but the in-flight probe."""
        with self._lock:
            if self._state == State.CLOSED:
                return True
            if self._state == State.OPEN:
                if _clock.now_ms() >= self._next_retry_ms:
                    return self._from_open_to_half_open()
                return False
            return False  # HALF_OPEN: probe already in flight

    def on_request_complete(self, rt_ms: float, error: Optional[BaseException]) -> None:
        raise NotImplementedError


class ExceptionCircuitBreaker(CircuitBreaker):
    """ERROR_RATIO / ERROR_COUNT (``ExceptionCircuitBreaker.java:35``)."""

    def on_request_complete(self, rt_ms, error):
        with self._lock:
            now = _clock.now_ms()
            self._counter.add(now, _TOTAL, 1)
            if error is not None:
                self._counter.add(now, _ERROR, 1)
            self._handle_state(now, error is not None)

    def _handle_state(self, now: int, is_error: bool) -> None:
        if self._state == State.OPEN:
            return
        if self._state == State.HALF_OPEN:
            if is_error:
                self._from_half_open_to_open()
            else:
                self._from_half_open_to_close()
            return
        total = self._counter.sum(now, _TOTAL)
        errors = self._counter.sum(now, _ERROR)
        if total < self.rule.min_request_amount:
            return
        if self.rule.grade == DegradeGrade.ERROR_RATIO:
            if total > 0 and errors / total >= self.rule.count:
                self._to_open()
        else:  # ERROR_COUNT
            if errors >= self.rule.count:
                self._to_open()


class ResponseTimeCircuitBreaker(CircuitBreaker):
    """SLOW_REQUEST_RATIO (``ResponseTimeCircuitBreaker.java:34``):
    ``rule.count`` = max allowed RT; trips when the slow fraction over the stat
    interval reaches ``slow_ratio_threshold``."""

    def on_request_complete(self, rt_ms, error):
        with self._lock:
            now = _clock.now_ms()
            slow = rt_ms > self.rule.count
            self._counter.add(now, _TOTAL, 1)
            if slow:
                self._counter.add(now, _SLOW, 1)
            if self._state == State.OPEN:
                return
            if self._state == State.HALF_OPEN:
                if slow:
                    self._from_half_open_to_open()
                else:
                    self._from_half_open_to_close()
                return
            total = self._counter.sum(now, _TOTAL)
            slows = self._counter.sum(now, _SLOW)
            if total < self.rule.min_request_amount:
                return
            if total > 0 and slows / total >= self.rule.slow_ratio_threshold:
                self._to_open()


def _make_breaker(rule: DegradeRule) -> Optional[CircuitBreaker]:
    if rule.grade == DegradeGrade.SLOW_REQUEST_RATIO:
        return ResponseTimeCircuitBreaker(rule)
    if rule.grade in (DegradeGrade.ERROR_RATIO, DegradeGrade.ERROR_COUNT):
        return ExceptionCircuitBreaker(rule)
    return None


class DegradeRuleManager:
    """``DegradeRuleManager.java:43`` — breakers rebuild (and reset state) on
    rule reload, matching the reference."""

    _lock = threading.RLock()
    _breakers: Dict[str, List[CircuitBreaker]] = {}

    @classmethod
    def load_rules(cls, rules: List[DegradeRule]) -> None:
        new_map: Dict[str, List[CircuitBreaker]] = {}
        for rule in rules or []:
            if not rule.resource or rule.count < 0:
                continue
            cb = _make_breaker(rule)
            if cb is not None:
                new_map.setdefault(rule.resource, []).append(cb)
        with cls._lock:
            cls._breakers = new_map

    @classmethod
    def get_breakers(cls, resource: str) -> List[CircuitBreaker]:
        return cls._breakers.get(resource, [])

    @classmethod
    def register_property(cls, prop) -> None:
        prop.listen(lambda rules: cls.load_rules(rules or []))

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._breakers = {}


class DegradeSlot(ProcessorSlot):
    """``DegradeSlot.java:41-66``: gate on entry, feed breakers on exit."""

    def entry(self, context, resource, node, count, prioritized, args):
        for cb in DegradeRuleManager.get_breakers(resource.name):
            if not cb.try_pass():
                raise DegradeException(
                    cb.rule.limit_app, f"degrade: {resource.name}", cb.rule
                )
        self.fire_entry(context, resource, node, count, prioritized, args)

    def exit(self, context, resource, count, args):
        entry = context.cur_entry
        if entry is not None and entry.block_error is None:
            rt = _clock.now_ms() - entry.create_ms
            for cb in DegradeRuleManager.get_breakers(resource.name):
                cb.on_request_complete(rt, entry.error)
        self.fire_exit(context, resource, count, args)


slot_registry.register(DegradeSlot, order=ORDER_DEGRADE_SLOT, name="DegradeSlot")
