"""Call context and invocation tree.

Analog of ``context/Context.java:57`` + ``ContextUtil.java:45``. The reference
binds the context to a ``ThreadLocal``; here it lives in a ``contextvars.
ContextVar`` so the same engine works under threads *and* asyncio tasks (each
task gets its own context snapshot) — a strict capability superset of the
reference's ``AsyncEntry`` machinery.
"""

from __future__ import annotations

import contextvars
import threading
from typing import Dict, Optional

from sentinel_tpu.local.base import CONTEXT_DEFAULT_NAME
from sentinel_tpu.local.stat import EntranceNode
from sentinel_tpu.local.base import ResourceWrapper, EntryType


class Context:
    __slots__ = ("name", "origin", "entrance_node", "cur_entry", "async_mode")

    def __init__(self, name: str, entrance_node: EntranceNode, origin: str = ""):
        self.name = name
        self.origin = origin
        self.entrance_node = entrance_node
        self.cur_entry = None  # type: Optional["object"]
        self.async_mode = False


class NullContext(Context):
    """Returned when the context cap is exceeded (``NullContext.java``) —
    entries under it pass through unguarded."""

    def __init__(self):
        # entrance node unused; reuse a throwaway
        super().__init__("null_context_internal", _null_entrance())


_null_entrance_node: Optional[EntranceNode] = None


def _null_entrance() -> EntranceNode:
    global _null_entrance_node
    if _null_entrance_node is None:
        _null_entrance_node = EntranceNode(
            ResourceWrapper("null_context_internal", EntryType.IN)
        )
    return _null_entrance_node


_context_var: contextvars.ContextVar[Optional[Context]] = contextvars.ContextVar(
    "sentinel_context", default=None
)

# Cached EntranceNode per context name (ContextUtil.java:120 trueEnter caches
# into a static map + attaches to the global ROOT).
_lock = threading.RLock()
_entrance_nodes: Dict[str, EntranceNode] = {}
MAX_CONTEXT_NAME_SIZE = 2000  # Constants.MAX_CONTEXT_NAME_SIZE

ROOT = EntranceNode(ResourceWrapper("machine-root", EntryType.IN))


def enter(name: str = CONTEXT_DEFAULT_NAME, origin: str = "") -> Context:
    """``ContextUtil.enter`` — bind a named context to the current task/thread."""
    ctx = _context_var.get()
    if ctx is not None:
        return ctx
    node = _entrance_nodes.get(name)
    if node is None:
        with _lock:
            node = _entrance_nodes.get(name)
            if node is None:
                if len(_entrance_nodes) >= MAX_CONTEXT_NAME_SIZE:
                    ctx = NullContext()
                    _context_var.set(ctx)
                    return ctx
                node = EntranceNode(ResourceWrapper(name, EntryType.IN))
                ROOT.add_child(node)
                _entrance_nodes[name] = node
    ctx = Context(name, node, origin)
    _context_var.set(ctx)
    return ctx


def get_context() -> Optional[Context]:
    return _context_var.get()


def exit() -> None:
    """``ContextUtil.exit`` — drop the context if no entry is outstanding."""
    ctx = _context_var.get()
    if ctx is not None and ctx.cur_entry is None:
        _context_var.set(None)


def replace_context(ctx: Optional[Context]):
    """For async adapters: swap the bound context, returning the previous one
    (``ContextUtil.replaceContext``)."""
    prev = _context_var.get()
    _context_var.set(ctx)
    return prev


def reset_for_tests() -> None:
    with _lock:
        _entrance_nodes.clear()
        ROOT.children.clear()
    _context_var.set(None)
