"""Entry path and public guard API.

Analog of ``CtSph.java:43`` (per-resource chain cache, ``entryWithPriority``
at ``CtSph.java:117-158``), ``CtEntry.java:35`` (parent/child linking and
ordered exit), ``SphU``/``SphO`` and ``Tracer.java:31``.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.local import context as ctx_mod
from sentinel_tpu.local.base import (
    BlockException,
    EntryType,
    MAX_SLOT_CHAIN_SIZE,
    ResourceWrapper,
)
from sentinel_tpu.local.chain import SlotChain, build_chain
from sentinel_tpu.local.context import Context, NullContext


class Entry:
    """A live guarded invocation (``CtEntry``). Usable as a context manager;
    business exceptions raised inside the ``with`` are traced automatically
    (the reference requires an explicit ``Tracer.trace`` call)."""

    __slots__ = (
        "resource", "context", "chain", "create_ms", "completed_ms",
        "cur_node", "origin_node", "block_error", "error", "parent", "child",
        "count", "args", "_exited", "param_holds",
    )

    def __init__(self, resource: ResourceWrapper, chain: Optional[SlotChain],
                 context: Context, count: int, args: tuple):
        self.resource = resource
        self.context = context
        self.chain = chain
        self.count = count
        self.args = args
        self.create_ms = _clock.now_ms()
        self.completed_ms: Optional[int] = None
        self.cur_node = None
        self.origin_node = None
        self.block_error: Optional[BlockException] = None
        self.error: Optional[BaseException] = None
        self.param_holds = None
        self._exited = False
        # link into the context's entry stack (CtEntry.java:57-59)
        self.parent = context.cur_entry
        self.child = None
        if self.parent is not None:
            self.parent.child = self
        context.cur_entry = self

    def parent_node(self):
        return self.parent.cur_node if self.parent is not None else None

    def trace(self, error: BaseException, count: int = 1) -> None:
        """Record a business exception (``Tracer.traceEntry``)."""
        if self.error is not None or isinstance(error, BlockException):
            return
        self.error = error
        node = self.cur_node
        if node is not None:
            node.add_exception(count)
            if node.cluster_node is not None:
                node.cluster_node.add_exception(count)
        if self.origin_node is not None:
            self.origin_node.add_exception(count)
        from sentinel_tpu.metrics import extension as _ext

        _ext.on_exception(self.resource.name, count, error)

    def exit(self, count: int = 1) -> None:
        if self._exited:
            return
        ctx = self.context
        if ctx.cur_entry is not self:
            # out-of-order exit: unwind children first (CtEntry.exitForContext
            # throws ErrorEntryFreeException; we repair instead, exiting the
            # stack down to self — strictly more forgiving, same invariant)
            e = ctx.cur_entry
            while e is not None and e is not self:
                nxt = e.parent
                e.exit(e.count)
                e = nxt
            if ctx.cur_entry is not self:
                self._exited = True
                return
        self._exited = True
        self.completed_ms = _clock.now_ms()
        if self.chain is not None:
            self.chain.exit(ctx, self.resource, count, self.args)
        ctx.cur_entry = self.parent
        if self.parent is not None:
            self.parent.child = None
        # Clear the ambient context only when this thread/task actually holds
        # it — a detached (async) entry may complete on a foreign thread whose
        # own context must not be torn down.
        if (
            ctx.cur_entry is None
            and not isinstance(ctx, NullContext)
            and ctx_mod.get_context() is ctx
        ):
            ctx_mod.exit()

    # -- context manager -----------------------------------------------------
    def __enter__(self) -> "Entry":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None and not isinstance(exc, BlockException):
            self.trace(exc)
        self.exit(self.count)
        return False


# Global kill switch (Constants.ON analog, toggled by the reference's
# setSwitch/getSwitch commands): when off, every entry passes through
# unguarded and uncounted.
_enabled = True


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def is_enabled() -> bool:
    return _enabled


class Sph:
    """``CtSph``: chain cache + the entry path."""

    def __init__(self):
        self._lock = threading.RLock()
        self._chains: Dict[ResourceWrapper, SlotChain] = {}
        # wrapper cache: frozen-dataclass construction (object.__setattr__
        # per field) costs ~1µs on the entry hot path and identity is by
        # name anyway. Bounded like the chain cache; beyond the cap a fresh
        # wrapper still works (it just isn't cached).
        self._wrappers: Dict[tuple, ResourceWrapper] = {}

    def _lookup_chain(self, resource: ResourceWrapper) -> Optional[SlotChain]:
        chain = self._chains.get(resource)
        if chain is None:
            with self._lock:
                chain = self._chains.get(resource)
                if chain is None:
                    # CtSph.java:136-144: beyond the cap, guard nothing.
                    if len(self._chains) >= MAX_SLOT_CHAIN_SIZE:
                        return None
                    chain = build_chain()
                    self._chains[resource] = chain
        return chain

    def entry(
        self,
        name: str,
        entry_type: EntryType = EntryType.OUT,
        count: int = 1,
        args: tuple = (),
        prioritized: bool = False,
    ) -> Entry:
        """``entryWithPriority`` (``CtSph.java:117-158``). Raises
        ``BlockException`` on a block verdict."""
        key = (name, entry_type)
        resource = self._wrappers.get(key)
        if resource is None:
            resource = ResourceWrapper(name, entry_type)
            if len(self._wrappers) < MAX_SLOT_CHAIN_SIZE * 2:
                self._wrappers[key] = resource
        ctx = ctx_mod.get_context()
        if not _enabled:
            # global switch off (CtSph.entryWithPriority's Constants.ON
            # check): pass-through, no stats, no rules
            return Entry(resource, None, ctx or NullContext(), count, args)
        if isinstance(ctx, NullContext):
            return Entry(resource, None, ctx, count, args)
        if ctx is None:
            ctx = ctx_mod.enter()
        chain = self._lookup_chain(resource)
        if chain is None:
            return Entry(resource, None, ctx, count, args)
        e = Entry(resource, chain, ctx, count, args)
        try:
            # PriorityWaitException never reaches here: StatisticSlot (always
            # ahead of FlowSlot) absorbs it and the entry proceeds as a pass.
            chain.entry(ctx, resource, None, count, prioritized, args)
        except BlockException:
            e.exit(count)
            raise
        return e

    def reset_for_tests(self) -> None:
        with self._lock:
            self._chains.clear()
            self._wrappers.clear()


_sph = Sph()


def sph() -> Sph:
    return _sph


def entry(
    name: str,
    entry_type: EntryType = EntryType.OUT,
    count: int = 1,
    args: tuple = (),
    prioritized: bool = False,
) -> Entry:
    """Guard a resource (``SphU.entry``). Use as a context manager::

        try:
            with sentinel.entry("getUser") as e:
                do_work()
        except BlockException:
            fallback()
    """
    return _sph.entry(name, entry_type, count, args, prioritized)


def async_entry(
    name: str,
    entry_type: EntryType = EntryType.OUT,
    count: int = 1,
    args: tuple = (),
    prioritized: bool = False,
) -> Entry:
    """Guard an operation whose completion happens elsewhere — another
    thread, a done-callback, or a different asyncio task
    (``SphU.asyncEntry`` / ``AsyncEntry.java`` analog).

    The verdict is taken against the caller's context as usual, then the
    entry is detached into a private context snapshot: the caller's entry
    stack is restored immediately, and ``exit()``/``trace()`` may be called
    from any thread without corrupting concurrent entries. Statistics
    (RT, concurrency, exceptions) still cover the real operation duration.
    """
    e = _sph.entry(name, entry_type, count, args, prioritized)
    ctx = e.context
    if isinstance(ctx, NullContext):
        return e
    async_ctx = Context(ctx.name, ctx.entrance_node, ctx.origin)
    async_ctx.async_mode = True
    async_ctx.cur_entry = e
    # pop from the caller's stack (AsyncEntry.cleanCurrentEntryInLocal)
    ctx.cur_entry = e.parent
    if e.parent is not None:
        e.parent.child = None
    e.parent = None
    e.context = async_ctx
    # the caller's context is left in place (AsyncEntry.cleanCurrentEntryInLocal
    # only pops the entry) — a later sync entry's exit clears an empty one
    return e


def try_entry(name: str, entry_type: EntryType = EntryType.OUT, count: int = 1,
              args: tuple = ()) -> Optional[Entry]:
    """Boolean-style variant (``SphO``): returns None instead of raising."""
    try:
        return _sph.entry(name, entry_type, count, args)
    except BlockException:
        return None


def trace(error: BaseException, count: int = 1) -> None:
    """``Tracer.trace``: record a business exception on the current entry."""
    ctx = ctx_mod.get_context()
    if ctx is not None and ctx.cur_entry is not None:
        ctx.cur_entry.trace(error, count)
