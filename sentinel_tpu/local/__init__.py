"""Local (in-process) engine: the sentinel-core analog.

Importing this package wires the default slot set (the reference does this via
``Env`` static init → ``InitExecutor.doInit`` → SPI; here the module imports
below register each slot in order — the same extension seam, minus classpath
scanning).

Public API::

    from sentinel_tpu import local as sentinel
    from sentinel_tpu.local import FlowRule, FlowRuleManager, BlockException

    FlowRuleManager.load_rules([FlowRule(resource="hello", count=20)])
    try:
        with sentinel.entry("hello"):
            serve()
    except BlockException:
        fallback()
"""

# Slot registration order is by the order constants, not import order.
from sentinel_tpu.local import chain as _chain  # core slots
from sentinel_tpu.local import authority as _authority  # noqa: F401
from sentinel_tpu.local import system_adaptive as _system  # noqa: F401
from sentinel_tpu.local import param as _param  # noqa: F401
from sentinel_tpu.local import flow as _flow  # noqa: F401
from sentinel_tpu.local import degrade as _degrade  # noqa: F401

from sentinel_tpu.local.base import (
    AuthorityException,
    BlockException,
    DegradeException,
    EntryType,
    FlowException,
    ParamFlowException,
    ResourceWrapper,
    SystemBlockException,
)
from sentinel_tpu.local.authority import (
    AuthorityRule,
    AuthorityRuleManager,
    AuthorityStrategy,
)
from sentinel_tpu.local.context import enter as enter_context, exit as exit_context
from sentinel_tpu.local.degrade import (
    CircuitBreaker,
    DegradeGrade,
    DegradeRule,
    DegradeRuleManager,
    State as CircuitBreakerState,
    register_state_change_observer,
)
from sentinel_tpu.local.flow import (
    ControlBehavior,
    FlowGrade,
    FlowRule,
    FlowRuleManager,
    FlowStrategy,
)
from sentinel_tpu.local.param import (
    ParamFlowItem,
    ParamFlowRule,
    ParamFlowRuleManager,
)
from sentinel_tpu.local.sph import Entry, entry, sph, trace, try_entry
from sentinel_tpu.local.system_adaptive import SystemRule, SystemRuleManager

__all__ = [
    "entry",
    "try_entry",
    "trace",
    "Entry",
    "sph",
    "enter_context",
    "exit_context",
    "EntryType",
    "ResourceWrapper",
    "BlockException",
    "FlowException",
    "DegradeException",
    "SystemBlockException",
    "AuthorityException",
    "ParamFlowException",
    "FlowRule",
    "FlowRuleManager",
    "FlowGrade",
    "FlowStrategy",
    "ControlBehavior",
    "DegradeRule",
    "DegradeRuleManager",
    "DegradeGrade",
    "CircuitBreaker",
    "CircuitBreakerState",
    "register_state_change_observer",
    "SystemRule",
    "SystemRuleManager",
    "AuthorityRule",
    "AuthorityRuleManager",
    "AuthorityStrategy",
    "ParamFlowRule",
    "ParamFlowItem",
    "ParamFlowRuleManager",
]


def reset_for_tests() -> None:
    """Full local-engine reset (ContextTestUtil analog)."""
    from sentinel_tpu.local import chain, context
    from sentinel_tpu.local.sph import sph as _sph

    FlowRuleManager.reset_for_tests()
    ParamFlowRuleManager.reset_for_tests()
    DegradeRuleManager.reset_for_tests()
    SystemRuleManager.reset_for_tests()
    AuthorityRuleManager.reset_for_tests()
    chain.reset_cluster_nodes_for_tests()
    chain.reset_entry_node_for_tests()
    context.reset_for_tests()
    _sph().reset_for_tests()
    from sentinel_tpu.local.sph import set_enabled as _set_enabled

    _set_enabled(True)
