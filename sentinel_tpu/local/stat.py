"""Host-side statistics: per-node sliding windows and the node hierarchy.

This is the *local* (in-process, per-call) twin of the device tensors in
``sentinel_tpu.stats.window`` — same window semantics, numpy rings sized
``[buckets, channels]`` per node, O(buckets) per operation under a per-node
lock. Analog of ``StatisticNode``/``DefaultNode``/``EntranceNode``/
``ClusterNode`` (``sentinel-core/.../node/*.java``) minus the JVM concurrency
machinery (LongAdder/CAS → one small lock; the GIL makes contention cheap at
local-mode rates).

The device engine is the source of truth for batched/cluster decisions; this
module exists so a single ``entry()`` call costs microseconds, not a device
round-trip. Parity between the two is enforced by tests.

When the native C++ runtime is built (``native/``, loaded via
``sentinel_tpu.native``), windows are backed by its lock-free atomics instead
of numpy — same semantics (parity-tested in ``tests/test_native.py``), no GIL
hold during window ops. Set ``SENTINEL_TPU_NATIVE=0`` to force the numpy
backend.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

import numpy as np

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.local.base import ResourceWrapper

# Channels (host-side; RT is a float channel here, unlike the device split).
PASS = 0
BLOCK = 1
EXCEPTION = 2
SUCCESS = 3
RT = 4
OCCUPIED_PASS = 5
N_CHAN = 6

NEVER = -(2**60)


class HostWindow:
    """Ring of time buckets with mask-on-read deprecation.

    Same math as ``sentinel_tpu.stats.window`` (and the reference
    ``LeapArray.java:100-160``), specialized to one resource on the host.
    Not thread-safe by itself — callers hold the owning node's lock.
    """

    __slots__ = (
        "bucket_ms", "n_buckets", "n_channels", "interval_ms", "starts",
        "counts",
    )

    def __init__(self, bucket_ms: int, n_buckets: int, n_channels: int = N_CHAN):
        self.bucket_ms = bucket_ms
        self.n_buckets = n_buckets
        self.n_channels = n_channels
        self.interval_ms = bucket_ms * n_buckets
        self.starts = np.full(n_buckets, NEVER, dtype=np.int64)
        self.counts = np.zeros((n_buckets, n_channels), dtype=np.float64)

    def _roll(self, now: int) -> int:
        idx = (now // self.bucket_ms) % self.n_buckets
        start = now - now % self.bucket_ms
        if self.starts[idx] != start:
            self.counts[idx] = 0.0
            self.starts[idx] = start
        return idx

    def add(self, now: int, chan: int, n: float = 1.0) -> None:
        idx = self._roll(now)
        self.counts[idx, chan] += n

    def _valid(self, now: int) -> np.ndarray:
        age = now - self.starts
        return (age >= 0) & (age < self.interval_ms)

    def sum(self, now: int, chan: int) -> float:
        return float(self.counts[self._valid(now), chan].sum())

    def qps(self, now: int, chan: int) -> float:
        return self.sum(now, chan) * 1000.0 / self.interval_ms

    def previous_bucket(self, now: int, chan: int) -> float:
        """Count in the bucket one bucket-length before the current one
        (``ArrayMetric.previousWindowPass`` shape, used by warm-up)."""
        prev_start = (now - now % self.bucket_ms) - self.bucket_ms
        idx = (prev_start // self.bucket_ms) % self.n_buckets
        if self.starts[idx] == prev_start:
            return float(self.counts[idx, chan])
        return 0.0

    def min_rt(self, now: int) -> float:
        """Minimum average-RT across valid buckets (``MetricBucket.minRt``
        tracks per-bucket min; we approximate with per-bucket rt/success —
        documented drift, same monotonic use in BBR check)."""
        return self.min_ratio(now, RT, SUCCESS)

    def min_ratio(self, now: int, num_chan: int, den_chan: int) -> float:
        valid = self._valid(now)
        den = self.counts[valid, den_chan]
        num = self.counts[valid, num_chan]
        mask = den > 0
        if not mask.any():
            return 0.0
        return float((num[mask] / den[mask]).min())

    def snapshot(self, now: int) -> list:
        """Per-channel valid sums in one pass (metric-log path)."""
        valid = self._valid(now)
        return [float(x) for x in self.counts[valid].sum(axis=0)]

    def start_at(self, b: int) -> int:
        return int(self.starts[b])

    def count_at(self, b: int, chan: int) -> float:
        return float(self.counts[b, chan])


class FutureWindow:
    """Occupied (borrowed) tokens waiting in future buckets
    (``FutureBucketLeapArray``). Host twin of ``window.add_future``."""

    __slots__ = ("bucket_ms", "n_buckets", "interval_ms", "starts", "counts")

    def __init__(self, bucket_ms: int, n_buckets: int):
        self.bucket_ms = bucket_ms
        self.n_buckets = n_buckets
        self.interval_ms = bucket_ms * n_buckets
        self.starts = np.full(n_buckets, NEVER, dtype=np.int64)
        self.counts = np.zeros(n_buckets, dtype=np.float64)

    def add(self, future_time: int, n: float) -> None:
        idx = (future_time // self.bucket_ms) % self.n_buckets
        start = future_time - future_time % self.bucket_ms
        if self.starts[idx] != start:
            self.counts[idx] = 0.0
            self.starts[idx] = start
        self.counts[idx] += n

    def waiting(self, now: int) -> float:
        ahead = self.starts - now
        return float(self.counts[(ahead > 0) & (ahead <= self.interval_ms)].sum())

    def take_matured(self, now: int) -> float:
        """Tokens whose window start has arrived — they become OCCUPIED_PASS."""
        cur_start = now - now % self.bucket_ms
        idx = (cur_start // self.bucket_ms) % self.n_buckets
        if self.starts[idx] == cur_start:
            n = float(self.counts[idx])
            self.counts[idx] = 0.0
            return n
        return 0.0


class _NativeFutureWindow:
    """FutureWindow API over a 1-channel native window."""

    __slots__ = ("_w", "bucket_ms", "n_buckets", "interval_ms")

    def __init__(self, native_window):
        self._w = native_window
        self.bucket_ms = native_window.bucket_ms
        self.n_buckets = native_window.n_buckets
        self.interval_ms = native_window.interval_ms

    def add(self, future_time: int, n: float) -> None:
        self._w.add_future(future_time, n)

    def waiting(self, now: int) -> float:
        return self._w.future_waiting(now)

    def take_matured(self, now: int) -> float:
        return self._w.take_matured(now)


def _native_enabled() -> bool:
    if os.environ.get("SENTINEL_TPU_NATIVE", "") == "0":
        return False
    try:
        from sentinel_tpu.native import available

        return available()
    except Exception:
        return False


_NATIVE = _native_enabled()


def make_window(bucket_ms: int, n_buckets: int, n_channels: int = N_CHAN):
    """Window factory: native C++ backend when built, numpy otherwise."""
    if _NATIVE:
        from sentinel_tpu.native import NativeWindow

        return NativeWindow(bucket_ms, n_buckets, n_channels)
    return HostWindow(bucket_ms, n_buckets, n_channels)


def make_future_window(bucket_ms: int, n_buckets: int):
    if _NATIVE:
        from sentinel_tpu.native import NativeWindow

        return _NativeFutureWindow(NativeWindow(bucket_ms, n_buckets, 1))
    return FutureWindow(bucket_ms, n_buckets)


DEFAULT_OCCUPY_TIMEOUT_MS = 500  # OccupyTimeoutProperty default


class StatisticNode:
    """One metric owner: second-level + minute-level windows + concurrency.

    reference: ``node/StatisticNode.java:90-108`` (1s/2-bucket second window,
    60s/60-bucket minute window, ``curThreadNum`` LongAdder).
    """

    def __init__(self, sec_buckets: int = 2, sec_interval_ms: int = 1000):
        self._lock = threading.RLock()
        self.sec = make_window(sec_interval_ms // sec_buckets, sec_buckets)
        self.minute = make_window(1000, 60)
        self.future = make_future_window(self.sec.bucket_ms, sec_buckets)
        self.cur_thread_num = 0
        # composite-write fast path: when every window is native, one ctypes
        # call covers a whole logical write (touch+PASS, SUCCESS+RT, …) with
        # no Python lock — each C op is atomic and the reference's
        # StatisticNode holds no cross-window lock either. ctypes round
        # trips otherwise dominate the entry hot path.
        self._fast = None
        if _NATIVE:
            from sentinel_tpu.native import NativeWindow

            if (
                isinstance(self.sec, NativeWindow)
                and isinstance(self.minute, NativeWindow)
                and isinstance(self.future, _NativeFutureWindow)
            ):
                self._fast = (
                    self.sec._lib, self.sec._h, self.minute._h,
                    self.future._w._h,
                )

    # -- write path ---------------------------------------------------------
    def increase_thread(self) -> None:
        with self._lock:
            self.cur_thread_num += 1

    def decrease_thread(self) -> None:
        with self._lock:
            self.cur_thread_num -= 1

    def _touch(self, now: int) -> None:
        """Convert matured borrowed tokens (``OccupiableBucketLeapArray``'s
        window-roll transfer): they count as PASS — consuming the new window's
        capacity, preventing double admission — and as OCCUPIED_PASS for
        observability. Callers hold the lock."""
        matured = self.future.take_matured(now)
        if matured:
            self.sec.add(now, PASS, matured)
            self.sec.add(now, OCCUPIED_PASS, matured)
            self.minute.add(now, PASS, matured)
            self.minute.add(now, OCCUPIED_PASS, matured)

    def add_pass(self, n: int = 1, now: Optional[int] = None) -> None:
        now = _clock.now_ms() if now is None else now
        fast = self._fast
        if fast is not None:
            fast[0].sn_stat_pass(fast[1], fast[2], fast[3], now, float(n))
            return
        with self._lock:
            self._touch(now)
            self.sec.add(now, PASS, n)
            self.minute.add(now, PASS, n)

    def add_block(self, n: int = 1, now: Optional[int] = None) -> None:
        now = _clock.now_ms() if now is None else now
        fast = self._fast
        if fast is not None:
            fast[0].sn_stat_event(fast[1], fast[2], now, BLOCK, float(n))
            return
        with self._lock:
            self.sec.add(now, BLOCK, n)
            self.minute.add(now, BLOCK, n)

    def add_exception(self, n: int = 1, now: Optional[int] = None) -> None:
        now = _clock.now_ms() if now is None else now
        fast = self._fast
        if fast is not None:
            fast[0].sn_stat_event(fast[1], fast[2], now, EXCEPTION, float(n))
            return
        with self._lock:
            self.sec.add(now, EXCEPTION, n)
            self.minute.add(now, EXCEPTION, n)

    def add_rt_and_success(self, rt_ms: float, n: int = 1, now: Optional[int] = None) -> None:
        now = _clock.now_ms() if now is None else now
        fast = self._fast
        if fast is not None:
            fast[0].sn_stat_rt_success(
                fast[1], fast[2], now, float(rt_ms), float(n)
            )
            return
        with self._lock:
            self.sec.add(now, SUCCESS, n)
            self.sec.add(now, RT, rt_ms)
            self.minute.add(now, SUCCESS, n)
            self.minute.add(now, RT, rt_ms)

    def add_occupied_pass(self, n: int, wait_ms: int, now: Optional[int] = None) -> None:
        """Borrow from a future window (``StatisticNode.addOccupiedPass``).

        On fast (native-window) nodes this is a single atomic bucket add —
        no xfer_lock needed: the lock exists to make the drain→credit
        TRANSFER atomic; depositing NEW tokens into a future bucket is one
        atomic op that no reader can observe half-done. The composite
        readers (``sn_stat_touched_sum``) and ``try_occupy_next``'s
        ``waiting`` probe may race a concurrent transfer by design — the
        same drift the reference's unsynchronized LeapArray readers accept.
        """
        now = _clock.now_ms() if now is None else now
        with self._lock:
            self.future.add(now + wait_ms, n)

    # -- read path ----------------------------------------------------------
    def _now(self, now: Optional[int]) -> int:
        return _clock.now_ms() if now is None else now

    def pass_qps(self, now: Optional[int] = None) -> float:
        now = self._now(now)
        fast = self._fast
        if fast is not None:
            total = fast[0].sn_stat_touched_sum(
                fast[1], fast[2], fast[3], now, PASS
            )
            return total * 1000.0 / self.sec.interval_ms
        with self._lock:
            self._touch(now)
            return self.sec.qps(now, PASS)

    def occupied_pass_qps(self, now: Optional[int] = None) -> float:
        now = self._now(now)
        fast = self._fast
        if fast is not None:
            # same xfer-locked composite read as pass_qps: the drain+credit
            # transfer can never be observed half-done (r4 advisor)
            total = fast[0].sn_stat_touched_sum(
                fast[1], fast[2], fast[3], now, OCCUPIED_PASS
            )
            return total * 1000.0 / self.sec.interval_ms
        with self._lock:
            self._touch(now)
            return self.sec.qps(now, OCCUPIED_PASS)

    def block_qps(self, now: Optional[int] = None) -> float:
        now = self._now(now)
        with self._lock:
            return self.sec.qps(now, BLOCK)

    def total_qps(self, now: Optional[int] = None) -> float:
        return self.pass_qps(now) + self.block_qps(now)

    def success_qps(self, now: Optional[int] = None) -> float:
        now = self._now(now)
        with self._lock:
            return self.sec.qps(now, SUCCESS)

    def exception_qps(self, now: Optional[int] = None) -> float:
        now = self._now(now)
        with self._lock:
            return self.sec.qps(now, EXCEPTION)

    def avg_rt(self, now: Optional[int] = None) -> float:
        now = self._now(now)
        with self._lock:
            succ = self.sec.sum(now, SUCCESS)
            if succ <= 0:
                return 0.0
            return self.sec.sum(now, RT) / succ

    def min_rt(self, now: Optional[int] = None) -> float:
        now = self._now(now)
        with self._lock:
            return self.sec.min_ratio(now, RT, SUCCESS)

    def previous_pass_qps(self, now: Optional[int] = None) -> float:
        now = self._now(now)
        with self._lock:
            return (
                self.sec.previous_bucket(now, PASS)
                * 1000.0
                / self.sec.bucket_ms
            )

    def total_pass_minute(self, now: Optional[int] = None) -> float:
        now = self._now(now)
        with self._lock:
            return self.minute.sum(now, PASS)

    def try_occupy_next(
        self, now: int, acquire: int, threshold: float
    ) -> int:
        """Can a prioritized request borrow from an upcoming window?

        Returns wait-ms (> 0) if the borrow succeeded, else ``OccupyTimeoutMs+1``
        meaning "cannot occupy" — mirrors ``StatisticNode.tryOccupyNext``
        (``StatisticNode.java:288``) which probes successive future windows
        within the occupy timeout.
        """
        with self._lock:
            max_wait = DEFAULT_OCCUPY_TIMEOUT_MS
            bucket_ms = self.sec.bucket_ms
            interval = self.sec.interval_ms
            # earliest future window start strictly after now
            first_wait = bucket_ms - (now % bucket_ms)
            wait = first_wait
            while wait <= max_wait and wait < interval:
                window_start = now + wait  # a bucket boundary
                # currently-valid passes that will have slid out of the
                # interval by window_start
                horizon = window_start - interval
                expired = 0.0
                for b in range(self.sec.n_buckets):
                    s = self.starts_at(b)
                    if s != NEVER and 0 <= now - s < interval and s <= horizon:
                        expired += self.sec.count_at(b, PASS)
                cur_pass = self.sec.sum(now, PASS)
                occupied = self.future.waiting(now)
                if cur_pass - expired + occupied + acquire <= threshold:
                    return int(wait)
                wait += bucket_ms
            return DEFAULT_OCCUPY_TIMEOUT_MS + 1

    def starts_at(self, b: int) -> int:
        return int(self.sec.start_at(b))


class DefaultNode(StatisticNode):
    """Per-(resource, context) node forming the invocation tree
    (``node/DefaultNode.java:41``)."""

    def __init__(self, resource: ResourceWrapper):
        super().__init__()
        self.resource = resource
        self.cluster_node: Optional["ClusterNode"] = None
        self.children: list = []
        self._child_lock = threading.Lock()

    def add_child(self, node: "DefaultNode") -> None:
        with self._child_lock:
            if node not in self.children:
                self.children.append(node)

    # DefaultNode mirrors every stat into its ClusterNode (DefaultNode.java:
    # increaseBlockQps etc. delegate to clusterNode) — the chain's
    # StatisticSlot drives both explicitly here for clarity.


class EntranceNode(DefaultNode):
    """Per-context root node (``node/EntranceNode.java:39``)."""


class ClusterNode(StatisticNode):
    """Per-resource global node + per-origin children
    (``node/ClusterNode.java:45``)."""

    def __init__(self, resource_name: str):
        super().__init__()
        self.resource_name = resource_name
        self._origin_lock = threading.Lock()
        self._origin_nodes: Dict[str, StatisticNode] = {}

    def get_or_create_origin_node(self, origin: str) -> StatisticNode:
        node = self._origin_nodes.get(origin)
        if node is None:
            with self._origin_lock:
                node = self._origin_nodes.get(origin)
                if node is None:
                    node = StatisticNode()
                    # copy-on-write in the reference (ClusterNode.java:100);
                    # dict assignment under lock is the host equivalent
                    self._origin_nodes[origin] = node
        return node

    @property
    def origin_nodes(self) -> Dict[str, StatisticNode]:
        return dict(self._origin_nodes)
