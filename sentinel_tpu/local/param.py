"""Hot-parameter flow control (local mode).

Analog of ``sentinel-extension/sentinel-parameter-flow-control``:
``ParamFlowSlot`` (@Spi order −3000, ``ParamFlowSlot.java:34-84`` — picks
``args[param_idx]``), ``ParamFlowChecker.java:46-190``:

- **QPS mode** — a decentralized token bucket per parameter value: token
  count + last-refill-time per value, refill ``elapsed × count / duration``,
  optional burst headroom (``passLocalCheck``/``passDefaultLocalCheck``).
- **RATE_LIMITER mode** — per-value leaky-bucket pacing
  (``passThrottleLocalCheck``).
- **THREAD mode** — per-value concurrency, decremented on exit.
- per-item overrides (``parsedHotItems``), LRU-bounded value maps
  (``ParameterMetric.java:35-55``: 4,000 values per metric by default).
- cluster branch → ``requestParamsToken`` with the value's stable hash
  (``ParamFlowChecker.java:72``), falling back to local on failure.
"""

from __future__ import annotations

import enum
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.hashing import stable_param_hash
from sentinel_tpu.local.base import ORDER_PARAM_FLOW_SLOT, ParamFlowException
from sentinel_tpu.local.chain import ProcessorSlot, slot_registry
from sentinel_tpu.local.flow import ControlBehavior, FlowGrade


@dataclass
class ParamFlowItem:
    """Per-value threshold override (``ParamFlowItem.java``)."""

    object_value: Any
    count: float


@dataclass
class ParamFlowRule:
    resource: str
    param_idx: int = 0
    count: float = 0.0
    grade: FlowGrade = FlowGrade.QPS
    duration_sec: int = 1
    burst_count: int = 0
    control_behavior: ControlBehavior = ControlBehavior.DEFAULT
    max_queueing_time_ms: int = 0
    items: List[ParamFlowItem] = field(default_factory=list)
    cluster_mode: bool = False
    cluster_config: Optional[dict] = None

    # built once at rule load (parsedHotItems analog); falls back to a scan
    # for unhashable override values
    _item_map: Optional[Dict[Any, float]] = field(
        default=None, repr=False, compare=False
    )

    def item_threshold(self, value: Any) -> float:
        if self._item_map is not None:
            try:
                return self._item_map.get(value, self.count)
            except TypeError:
                pass  # unhashable value
        for item in self.items:
            if item.object_value == value:
                return item.count
        return self.count


class _Lru(OrderedDict):
    """Bounded map (ConcurrentLinkedHashMapWrapper analog)."""

    def __init__(self, cap: int):
        super().__init__()
        self.cap = cap

    def touch(self, key, default):
        if key in self:
            self.move_to_end(key)
            return self[key]
        self[key] = default
        if len(self) > self.cap:
            self.popitem(last=False)
        return default


MAX_VALUES_PER_RULE = 4000  # ParameterMetric.BASE_PARAM_MAX_CAPACITY


class _RuleState:
    """Per-rule mutable value maps (ParameterMetric analog)."""

    __slots__ = ("lock", "tokens", "last_fill_ms", "latest_passed_ms", "threads")

    def __init__(self):
        self.lock = threading.Lock()
        self.tokens: _Lru = _Lru(MAX_VALUES_PER_RULE)
        self.last_fill_ms: _Lru = _Lru(MAX_VALUES_PER_RULE)
        self.latest_passed_ms: _Lru = _Lru(MAX_VALUES_PER_RULE)
        self.threads: Dict[Any, int] = {}


def _check_qps(rule: ParamFlowRule, st: _RuleState, value: Any, acquire: int) -> bool:
    """Token bucket per value (``ParamFlowChecker.passDefaultLocalCheck``)."""
    now = _clock.now_ms()
    threshold = rule.item_threshold(value)
    burst = rule.burst_count
    duration_ms = rule.duration_sec * 1000
    with st.lock:
        last = st.last_fill_ms.touch(value, None)
        if last is None:
            # first sight: full bucket minus this acquisition
            if threshold + burst < acquire:
                st.last_fill_ms[value] = now
                st.tokens[value] = 0.0
                return False
            st.last_fill_ms[value] = now
            st.tokens[value] = threshold + burst - acquire
            return True
        tokens = st.tokens.touch(value, 0.0)
        elapsed = now - last
        if elapsed >= 0:
            refill = elapsed * threshold / duration_ms
            tokens = min(tokens + refill, threshold + burst)
            st.last_fill_ms[value] = now
        if tokens < acquire:
            st.tokens[value] = tokens
            return False
        st.tokens[value] = tokens - acquire
        return True


def _check_throttle(rule: ParamFlowRule, st: _RuleState, value: Any, acquire: int) -> bool:
    """Leaky bucket per value (``passThrottleLocalCheck``)."""
    now = _clock.now_ms()
    threshold = rule.item_threshold(value)
    if threshold <= 0:
        return False
    cost_ms = round(rule.duration_sec * 1000.0 * acquire / threshold)
    with st.lock:
        latest = st.latest_passed_ms.touch(value, -1)
        expected = latest + cost_ms
        if expected <= now:
            st.latest_passed_ms[value] = now
            return True
        wait = expected - now
        if wait > rule.max_queueing_time_ms:
            return False
        st.latest_passed_ms[value] = expected
    _clock.get_clock().wait_ms(wait)
    return True


def _check_thread(rule: ParamFlowRule, st: _RuleState, value: Any, acquire: int) -> bool:
    """Check-and-increment atomically under the rule lock; the caller rolls
    back on a later rule's block (reference splits check and increment across
    the slot chain, widening a TOCTOU window — here the cap cannot be
    exceeded)."""
    threshold = rule.item_threshold(value)
    with st.lock:
        cur = st.threads.get(value, 0)
        if cur + acquire > threshold:
            return False
        st.threads[value] = cur + acquire
        return True


def _release_thread(st: _RuleState, value: Any, count: int) -> None:
    with st.lock:
        remaining = st.threads.get(value, 0) - count
        if remaining > 0:
            st.threads[value] = remaining
        else:
            st.threads.pop(value, None)


class ParamFlowRuleManager:
    _lock = threading.RLock()
    _rules: Dict[str, List[Tuple[ParamFlowRule, _RuleState]]] = {}

    @classmethod
    def load_rules(cls, rules: List[ParamFlowRule]) -> None:
        with cls._lock:
            # preserve counters for rules that did not change (the reference's
            # ParameterMetric cache keyed by rule survives reloads) — a
            # datasource republish must not refill every value's bucket or
            # orphan in-flight THREAD holds
            old: Dict[str, List[Tuple[ParamFlowRule, _RuleState]]] = cls._rules
            leftovers = {res: list(lst) for res, lst in old.items()}
            new_map: Dict[str, List[Tuple[ParamFlowRule, _RuleState]]] = {}
            for rule in rules or []:
                if not rule.resource or rule.count < 0 or rule.param_idx < 0:
                    continue
                state = None
                for i, (old_rule, old_state) in enumerate(
                    leftovers.get(rule.resource, [])
                ):
                    if old_rule == rule:
                        state = old_state
                        del leftovers[rule.resource][i]
                        break
                if state is None:
                    state = _RuleState()
                try:
                    rule._item_map = {i.object_value: i.count for i in rule.items}
                except TypeError:
                    rule._item_map = None
                new_map.setdefault(rule.resource, []).append((rule, state))
            cls._rules = new_map

    @classmethod
    def get_rules(cls, resource: str):
        return cls._rules.get(resource, [])

    @classmethod
    def all_rules(cls) -> Dict[str, List[ParamFlowRule]]:
        with cls._lock:
            return {res: [r for r, _ in lst] for res, lst in cls._rules.items()}

    @classmethod
    def register_property(cls, prop) -> None:
        prop.listen(lambda rules: cls.load_rules(rules or []))

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._rules = {}


def _pass_check(
    rule: ParamFlowRule, st: _RuleState, value: Any, acquire: int
) -> Tuple[bool, bool]:
    """Returns ``(passed, thread_hold_taken)``."""
    if rule.cluster_mode:
        ok = _pass_cluster_check(rule, value, acquire)
        if ok is not None:
            return ok, False
        # fall through to local when the cluster path is unavailable
        cfg = rule.cluster_config or {}
        if not cfg.get("fallback_to_local_when_fail", True):
            return True, False
    if rule.grade == FlowGrade.THREAD:
        ok = _check_thread(rule, st, value, acquire)
        return ok, ok
    if rule.control_behavior == ControlBehavior.RATE_LIMITER:
        return _check_throttle(rule, st, value, acquire), False
    return _check_qps(rule, st, value, acquire), False


def _pass_cluster_check(rule: ParamFlowRule, value: Any, acquire: int):
    """Returns True/False on a definitive cluster verdict, None to fall back."""
    try:
        from sentinel_tpu.cluster import api as cluster_api
        from sentinel_tpu.engine import TokenStatus

        service = cluster_api._pick_service()
        flow_id = (rule.cluster_config or {}).get("flow_id")
        if service is None or flow_id is None:
            return None
        result = service.request_params_token(
            int(flow_id), acquire, [stable_param_hash(value)]
        )
        if result.status == TokenStatus.OK:
            return True
        if result.status == TokenStatus.BLOCKED:
            return False
        return None
    except Exception:
        return None


class ParamFlowSlot(ProcessorSlot):
    """``ParamFlowSlot.java:34-84``."""

    def entry(self, context, resource, node, count, prioritized, args):
        rules = ParamFlowRuleManager.get_rules(resource.name)
        if rules:
            holds = []  # THREAD increments already taken, for exit/rollback
            for rule, st in rules:
                if rule.param_idx >= len(args):
                    continue  # no such arg → rule not applicable
                value = args[rule.param_idx]
                if value is None:
                    continue
                ok, held = _pass_check(rule, st, value, count)
                if held:
                    holds.append((st, value))
                if not ok:
                    # roll back holds taken by earlier rules of this entry
                    for h_st, h_value in holds:
                        _release_thread(h_st, h_value, count)
                    raise ParamFlowException(
                        resource.name, f"param flow: {resource.name}", rule
                    )
            if holds:
                context.cur_entry.param_holds = holds
        self.fire_entry(context, resource, node, count, prioritized, args)

    def exit(self, context, resource, count, args):
        entry = context.cur_entry
        holds = getattr(entry, "param_holds", None) if entry else None
        if holds:
            for st, value in holds:
                _release_thread(st, value, count)
        self.fire_exit(context, resource, count, args)


slot_registry.register(ParamFlowSlot, order=ORDER_PARAM_FLOW_SLOT, name="ParamFlowSlot")
