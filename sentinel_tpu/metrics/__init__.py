"""Metric pipeline (analog of ``node/metric/*`` + ``metric/extension/*`` +
``sentinel-metric-exporter``): 1-second aggregation of every resource's
cluster node into rolling log files, the searcher the dashboard's
``/metric`` command reads, the pluggable extension SPI on the statistic
write path, and the Prometheus scrape exporter."""

from sentinel_tpu.metrics.log import (
    MetricNode,
    MetricWriter,
    MetricSearcher,
    MetricTimer,
)
from sentinel_tpu.metrics.extension import (
    MetricExtension,
    register_extension,
    clear_extensions_for_tests,
)
from sentinel_tpu.metrics.histogram import LatencyHistogram, log_buckets
from sentinel_tpu.metrics.profiler import ProfilerHook
from sentinel_tpu.metrics.server import (
    ServerMetrics,
    reset_server_metrics_for_tests,
    server_metrics,
)
from sentinel_tpu.metrics.exporter import PrometheusExporter, render

__all__ = [
    "MetricNode",
    "MetricWriter",
    "MetricSearcher",
    "MetricTimer",
    "MetricExtension",
    "register_extension",
    "clear_extensions_for_tests",
    "LatencyHistogram",
    "log_buckets",
    "ProfilerHook",
    "ServerMetrics",
    "server_metrics",
    "reset_server_metrics_for_tests",
    "PrometheusExporter",
    "render",
]
