"""Metric log pipeline (analog of ``node/metric/*`` in the reference):
1-second aggregation of every resource's cluster node into rolling log files,
plus the searcher the dashboard's ``/metric`` command reads."""

from sentinel_tpu.metrics.log import (
    MetricNode,
    MetricWriter,
    MetricSearcher,
    MetricTimer,
)

__all__ = ["MetricNode", "MetricWriter", "MetricSearcher", "MetricTimer"]
