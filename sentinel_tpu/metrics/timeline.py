"""Per-namespace per-second metric timeline: the cluster-door analog of
``metrics/log.py``.

The reference's ``metric.log`` answers "which resource degraded when" —
``MetricWriter`` appends one line per resource per second into size-rolled
files and ``MetricSearcher`` reads a time range back for the dashboard's
realtime fetch. On the cluster serving path the resource axis is the tenant
namespace and the interesting fields are the verdict classes the doors
actually emit, so this module keeps a per-namespace per-second ring of

    pass / block / shed / other counts  +  log-bucketed decision latency

with the same two read surfaces as the local metric log:

- an **in-memory queryable window** (default 10 minutes) behind the
  ``cluster/server/metric`` transport command and the scenario gates, and
- **append-only size-rolled files** (``{app}-timeline.log.N`` + ``.idx``
  second→offset index, MetricWriter parity) when a directory is configured
  (``SENTINEL_TIMELINE_DIR`` or :func:`configure_timeline`), so the window
  survives the process for post-hoc analysis.

Feeding happens on the paths that already exist: ``ServerMetrics``'s
verdict-batch accounting records served rows (with the batch's decision
latency) and ``SloPlane.record_shed`` forwards every refusal, so each row
lands in the timeline exactly once — timeline ``pass``/``block`` sums
reconcile with ``sentinel_server_verdicts_total`` deltas for the same
window, and ``shed`` sums with ``sentinel_slo_shed_total``.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from sentinel_tpu.core.config import SentinelConfig

KEY_WINDOW_S = "sentinel.tpu.timeline.window.s"
ENV_DIR = "SENTINEL_TIMELINE_DIR"

# latency bucket edges (ms): 6/decade over 0.01ms..10s — fine enough to
# resolve a 2ms p99 objective, coarse enough that a second's worth of
# buckets is 37 small ints per tenant
_EDGES = np.geomspace(0.01, 10_000.0, 37)
_N_LAT = len(_EDGES)  # searchsorted index 0.._N_LAT (last = overflow)


@dataclass
class TimelineSample:
    """One (second, namespace) point — the line unit of the timeline log,
    ``MetricNode`` parity with the namespace as the resource."""

    timestamp_ms: int
    namespace: str
    passed: int = 0
    blocked: int = 0
    shed: int = 0
    other: int = 0
    p99_ms: Optional[float] = None
    max_ms: Optional[float] = None
    waited: int = 0  # SHOULD_WAIT: delayed admissions (pacing / occupy)
    completed: int = 0  # reported completions landing this second
    exceptions: int = 0  # completions that ended in a business exception
    rt_sum_ms: int = 0  # summed completion RT (avg = rt_sum_ms / completed)

    def to_line(self) -> str:
        ts = self.timestamp_ms // 1000 * 1000
        ns = self.namespace.replace("|", "_")
        p99 = -1.0 if self.p99_ms is None else self.p99_ms
        mx = -1.0 if self.max_ms is None else self.max_ms
        # waited rides as a 9th field so pre-shaping readers (8-field
        # parsers) keep working on new files; the outcome columns
        # (completed/exceptions/rt_sum) ride as fields 10-12 the same way
        return (
            f"{ts}|{ns}|{self.passed}|{self.blocked}|{self.shed}|"
            f"{self.other}|{p99:g}|{mx:g}|{self.waited}|"
            f"{self.completed}|{self.exceptions}|{self.rt_sum_ms}"
        )

    @classmethod
    def from_line(cls, line: str) -> "TimelineSample":
        p = line.rstrip("\n").split("|")
        p99 = float(p[6])
        mx = float(p[7]) if len(p) > 7 else -1.0
        return cls(
            timestamp_ms=int(p[0]),
            namespace=p[1],
            passed=int(p[2]),
            blocked=int(p[3]),
            shed=int(p[4]),
            other=int(p[5]),
            p99_ms=None if p99 < 0 else p99,
            max_ms=None if mx < 0 else mx,
            waited=int(p[8]) if len(p) > 8 else 0,
            completed=int(p[9]) if len(p) > 9 else 0,
            exceptions=int(p[10]) if len(p) > 10 else 0,
            rt_sum_ms=int(p[11]) if len(p) > 11 else 0,
        )

    def as_dict(self) -> dict:
        return {
            "timestampMs": self.timestamp_ms,
            "namespace": self.namespace,
            "pass": self.passed,
            "block": self.blocked,
            "shed": self.shed,
            "other": self.other,
            "waited": self.waited,
            "p99Ms": self.p99_ms,
            "maxMs": self.max_ms,
            "completed": self.completed,
            "exceptions": self.exceptions,
            "rtSumMs": self.rt_sum_ms,
            "rtAvgMs": (
                self.rt_sum_ms / self.completed if self.completed else None
            ),
        }


class _NsRing:
    """Per-namespace ring of ``window_s`` seconds; stale slots are lazily
    reused on write (same model as the SLO plane's burn windows) so
    recording never sweeps."""

    __slots__ = ("window_s", "stamp", "counts", "lat", "lat_max")

    def __init__(self, window_s: int):
        self.window_s = window_s
        self.stamp = np.zeros(window_s, np.int64)
        # columns: pass, block, shed, other, waited, completed, exceptions,
        # rt_sum_ms
        self.counts = np.zeros((window_s, 8), np.int64)
        self.lat = np.zeros((window_s, _N_LAT + 1), np.int64)
        self.lat_max = np.zeros(window_s, np.float64)

    def slot(self, sec: int) -> int:
        i = sec % self.window_s
        if self.stamp[i] != sec:
            self.stamp[i] = sec
            self.counts[i] = 0
            self.lat[i] = 0
            self.lat_max[i] = 0.0
        return i

    def sample(self, namespace: str, sec: int) -> Optional[TimelineSample]:
        i = sec % self.window_s
        if self.stamp[i] != sec:
            return None
        c = self.counts[i]
        row = self.lat[i]
        total = int(row.sum())
        p99 = mx = None
        if total:
            k = int(np.searchsorted(np.cumsum(row), 0.99 * total))
            p99 = float(_EDGES[min(k, _N_LAT - 1)])
            mx = float(self.lat_max[i])
        return TimelineSample(
            timestamp_ms=sec * 1000,
            namespace=namespace,
            passed=int(c[0]),
            blocked=int(c[1]),
            shed=int(c[2]),
            other=int(c[3]),
            p99_ms=p99,
            max_ms=mx,
            waited=int(c[4]),
            completed=int(c[5]),
            exceptions=int(c[6]),
            rt_sum_ms=int(c[7]),
        )


class MetricTimeline:
    """Process-wide per-namespace per-second timeline. Thread-safe; the
    recording path is one dict lookup + a handful of array adds per
    (namespace, batch)."""

    def __init__(self, window_s: Optional[int] = None,
                 writer: Optional["TimelineWriter"] = None):
        if window_s is None:
            window_s = SentinelConfig.get_int(KEY_WINDOW_S, 600)
        self.window_s = max(2, int(window_s))
        self.writer = writer
        self._lock = threading.Lock()
        self._rings: Dict[str, _NsRing] = {}
        # seconds ≤ this are on disk; flush() bounds its scan to the ring
        # window, so the first flush writes at most window_s seconds
        self._flushed_upto = 0

    # -- recording ----------------------------------------------------------
    def record(self, namespace: str, n_pass: int = 0, n_block: int = 0,
               n_shed: int = 0, n_other: int = 0,
               latency_ms: Optional[float] = None,
               lat_n: Optional[int] = None,
               now_s: Optional[int] = None,
               n_waited: int = 0,
               n_complete: int = 0,
               n_exception: int = 0,
               rt_sum_ms: float = 0.0) -> None:
        """Fold one verdict-batch contribution for ``namespace`` into the
        current second. ``latency_ms`` is the batch's shared decision
        latency, applied to ``lat_n`` rows (default: the served rows of
        this call — pass + block + other + waited; sheds never reached a
        device step so they carry no latency). ``n_waited`` counts
        SHOULD_WAIT verdicts — served-with-delay (pacing / priority
        occupy), their own column so shaping is visible per second.
        ``n_complete``/``n_exception``/``rt_sum_ms`` fold a batched
        completion report (the rev-6 outcome plane) into the second the
        report LANDED — the admission columns describe the decision path,
        these describe what happened after."""
        if (n_pass <= 0 and n_block <= 0 and n_shed <= 0 and n_other <= 0
                and n_waited <= 0 and n_complete <= 0 and n_exception <= 0):
            return
        sec = int(now_s if now_s is not None else time.time())
        with self._lock:
            ring = self._rings.get(namespace)
            if ring is None:
                ring = self._rings.setdefault(namespace, _NsRing(self.window_s))
            i = ring.slot(sec)
            c = ring.counts[i]
            c[0] += max(0, n_pass)
            c[1] += max(0, n_block)
            c[2] += max(0, n_shed)
            c[3] += max(0, n_other)
            c[4] += max(0, n_waited)
            c[5] += max(0, n_complete)
            c[6] += max(0, n_exception)
            c[7] += max(0, int(rt_sum_ms))
            if latency_ms is not None:
                if lat_n is None:
                    lat_n = (max(0, n_pass) + max(0, n_block)
                             + max(0, n_other) + max(0, n_waited))
                if lat_n > 0:
                    k = int(np.searchsorted(_EDGES, latency_ms))
                    ring.lat[i, k] += lat_n
                    if latency_ms > ring.lat_max[i]:
                        ring.lat_max[i] = latency_ms
        if self.writer is not None and sec - 1 > self._flushed_upto:
            self.flush(upto_s=sec - 1)

    # -- persistence --------------------------------------------------------
    def flush(self, upto_s: Optional[int] = None) -> int:
        """Write every completed second in ``(_flushed_upto, upto_s]`` to
        the rolled files (no-op without a writer). Returns lines written.
        Benches call this at scenario end so the artifact and the on-disk
        log agree to the last second."""
        if self.writer is None:
            return 0
        if upto_s is None:
            upto_s = int(time.time())
        n = 0
        with self._lock:
            lo = max(self._flushed_upto + 1, upto_s - self.window_s + 1)
            for sec in range(lo, upto_s + 1):
                batch = []
                for ns in sorted(self._rings):
                    s = self._rings[ns].sample(ns, sec)
                    if s is not None:
                        batch.append(s)
                if batch:
                    self.writer.write(batch)
                    n += len(batch)
            if upto_s > self._flushed_upto:
                self._flushed_upto = upto_s
        return n

    # -- reading ------------------------------------------------------------
    def query(self, begin_ms: int = 0, end_ms: Optional[int] = None,
              namespace: Optional[str] = None) -> List[TimelineSample]:
        """In-memory window read, time-ordered (namespace-ordered within a
        second)."""
        if end_ms is None:
            end_ms = int(time.time() * 1000)
        lo = begin_ms // 1000
        hi = end_ms // 1000
        out: List[TimelineSample] = []
        with self._lock:
            names = (
                [namespace] if namespace is not None else sorted(self._rings)
            )
            for ns in names:
                ring = self._rings.get(ns)
                if ring is None:
                    continue
                for i in range(ring.window_s):
                    sec = int(ring.stamp[i])
                    if lo <= sec <= hi and sec != 0:
                        s = ring.sample(ns, sec)
                        if s is not None:
                            out.append(s)
        out.sort(key=lambda s: (s.timestamp_ms, s.namespace))
        return out

    def find(self, begin_ms: int = 0, end_ms: Optional[int] = None,
             namespace: Optional[str] = None,
             max_lines: int = 12000) -> List[TimelineSample]:
        """Memory + files merged (memory wins on overlap — it includes the
        current incomplete second). The ``cluster/server/metric`` backend."""
        mem = self.query(begin_ms, end_ms, namespace)
        merged = {(s.timestamp_ms, s.namespace): s for s in mem}
        if self.writer is not None:
            searcher = TimelineSearcher(self.writer.base_dir, self.writer.app)
            for s in searcher.find(
                begin_ms,
                end_ms if end_ms is not None else int(time.time() * 1000),
                namespace=namespace, max_lines=max_lines,
            ):
                merged.setdefault((s.timestamp_ms, s.namespace), s)
        out = sorted(merged.values(),
                     key=lambda s: (s.timestamp_ms, s.namespace))
        return out[:max_lines]

    def namespaces(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def status(self) -> dict:
        """The ``clusterServerStats`` ``timeline`` block."""
        with self._lock:
            names = sorted(self._rings)
            last = 0
            for ring in self._rings.values():
                m = int(ring.stamp.max()) if ring.stamp.size else 0
                last = max(last, m)
        return {
            "windowSeconds": self.window_s,
            "namespaces": names,
            "lastSecondMs": last * 1000,
            "fileDir": self.writer.base_dir if self.writer else None,
        }

    def reset(self) -> None:
        with self._lock:
            self._rings.clear()
            self._flushed_upto = 0


class TimelineWriter:
    """Size-rolled timeline files with a second→offset index
    (``MetricWriter`` parity: shift-rename rotation, oldest dropped)."""

    def __init__(self, base_dir: str,
                 single_file_size: Optional[int] = None,
                 total_file_count: Optional[int] = None):
        self.base_dir = base_dir
        os.makedirs(self.base_dir, exist_ok=True)
        self.single_file_size = single_file_size or SentinelConfig.get_int(
            "csp.sentinel.metric.file.single.size", 50 * 1024 * 1024
        )
        self.total_file_count = total_file_count or SentinelConfig.get_int(
            "csp.sentinel.metric.file.total.count", 6
        )
        self.app = SentinelConfig.app_name()
        self._lock = threading.Lock()
        self._cur_file = None
        self._cur_idx = None

    def _file_name(self, n: int) -> str:
        return os.path.join(self.base_dir, f"{self.app}-timeline.log.{n}")

    def _roll_if_needed(self) -> None:
        if (self._cur_file is not None
                and self._cur_file.tell() < self.single_file_size):
            return
        if self._cur_file is not None:
            self._cur_file.close()
            self._cur_idx.close()
            for n in range(self.total_file_count - 1, 0, -1):
                src, dst = self._file_name(n - 1), self._file_name(n)
                if os.path.exists(src):
                    os.replace(src, dst)
                    if os.path.exists(src + ".idx"):
                        os.replace(src + ".idx", dst + ".idx")
        path = self._file_name(0)
        self._cur_file = open(path, "a", encoding="utf-8")
        self._cur_idx = open(path + ".idx", "a", encoding="utf-8")

    def write(self, samples: List[TimelineSample]) -> None:
        if not samples:
            return
        with self._lock:
            self._roll_if_needed()
            sec = samples[0].timestamp_ms // 1000
            self._cur_idx.write(f"{sec} {self._cur_file.tell()}\n")
            for s in samples:
                self._cur_file.write(s.to_line() + "\n")
            self._cur_file.flush()
            self._cur_idx.flush()

    def close(self) -> None:
        with self._lock:
            if self._cur_file is not None:
                self._cur_file.close()
                self._cur_idx.close()
                self._cur_file = self._cur_idx = None


class TimelineSearcher:
    """Reads timeline lines in a time range across the rolling files
    (``MetricSearcher`` parity; oldest file first, .idx seek)."""

    def __init__(self, base_dir: str, app: str):
        self.base_dir = base_dir
        self.app = app

    @staticmethod
    def _seek_offset(idx_path: str, begin_ms: int) -> int:
        begin_sec = begin_ms // 1000
        offset = 0
        try:
            with open(idx_path, "r", encoding="utf-8") as f:
                for line in f:
                    try:
                        sec_s, off_s = line.split()
                        if int(sec_s) >= begin_sec:
                            break
                        offset = int(off_s)
                    except ValueError:
                        continue
        except OSError:
            return 0
        return offset

    def find(self, begin_ms: int, end_ms: int,
             namespace: Optional[str] = None,
             max_lines: int = 12000) -> List[TimelineSample]:
        out: List[TimelineSample] = []
        n = 0
        while True:
            path = os.path.join(
                self.base_dir, f"{self.app}-timeline.log.{n}")
            if not os.path.exists(path):
                break
            n += 1
        for i in range(n - 1, -1, -1):  # oldest file first
            path = os.path.join(
                self.base_dir, f"{self.app}-timeline.log.{i}")
            try:
                with open(path, "r", encoding="utf-8") as f:
                    f.seek(self._seek_offset(path + ".idx", begin_ms))
                    for line in f:
                        try:
                            s = TimelineSample.from_line(line)
                        except (ValueError, IndexError):
                            continue
                        if s.timestamp_ms < begin_ms:
                            continue
                        if s.timestamp_ms > end_ms:
                            break  # lines are time-ordered within a file
                        if namespace and s.namespace != namespace:
                            continue
                        out.append(s)
                        if len(out) >= max_lines:
                            return out
            except OSError:
                continue
        return out


# -- singleton ----------------------------------------------------------------
_HUB: Optional[MetricTimeline] = None
_HUB_LOCK = threading.Lock()


def timeline() -> MetricTimeline:
    """The process-wide timeline. File persistence turns on when
    ``SENTINEL_TIMELINE_DIR`` is set at first use (or via
    :func:`configure_timeline`); memory-only otherwise."""
    global _HUB
    if _HUB is None:
        with _HUB_LOCK:
            if _HUB is None:
                d = os.environ.get(ENV_DIR)
                writer = TimelineWriter(d) if d else None
                _HUB = MetricTimeline(writer=writer)
    return _HUB


def configure_timeline(base_dir: Optional[str] = None,
                       window_s: Optional[int] = None) -> MetricTimeline:
    """Replace the singleton with an explicitly configured timeline
    (benches point it at their artifact directory before the run)."""
    global _HUB
    with _HUB_LOCK:
        writer = TimelineWriter(base_dir) if base_dir else None
        _HUB = MetricTimeline(window_s=window_s, writer=writer)
        return _HUB


def reset_timeline_for_tests() -> None:
    global _HUB
    with _HUB_LOCK:
        if _HUB is not None and _HUB.writer is not None:
            _HUB.writer.close()
        _HUB = None
