"""Metric extension SPI — user-pluggable sinks on the statistic write path.

Analog of ``metric/extension/MetricExtension.java`` +
``MetricExtensionProvider`` and the ``MetricEntryCallback``/
``MetricExitCallback`` pair hooked into ``StatisticSlot`` via
``StatisticSlotCallbackRegistry``: every pass/block/success/exception/rt
event is fanned out to registered extensions (Prometheus, StatsD, custom
counters) in addition to the built-in window counters.

Extensions must be cheap and non-blocking — they run inline on the entry
hot path, exactly like the reference's callbacks.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

_lock = threading.Lock()
_extensions: Tuple["MetricExtension", ...] = ()


class MetricExtension:
    """Override any subset; default is no-op (``MetricExtension.java``)."""

    def add_pass(self, resource: str, n: int, args) -> None:
        pass

    def add_block(self, resource: str, n: int, origin: str, error, args) -> None:
        pass

    def add_success(self, resource: str, n: int, args) -> None:
        pass

    def add_exception(self, resource: str, n: int, error) -> None:
        pass

    def add_rt(self, resource: str, rt_ms: float, args) -> None:
        pass

    def increase_thread_num(self, resource: str, args) -> None:
        pass

    def decrease_thread_num(self, resource: str, args) -> None:
        pass


def register_extension(ext: MetricExtension) -> None:
    global _extensions
    with _lock:
        _extensions = _extensions + (ext,)


def get_extensions() -> Tuple[MetricExtension, ...]:
    return _extensions


def clear_extensions_for_tests() -> None:
    global _extensions
    with _lock:
        _extensions = ()


# Hot-path dispatch helpers: a single tuple read when nothing is registered.
# Each callback is isolated — a faulty extension must not corrupt the
# statistic slot's counting (an escaped error here would leak thread counts
# or mask a BlockException mid-flight; the reference catches Throwable
# around its callbacks for the same reason).

def _safe(fn, *args) -> None:
    try:
        fn(*args)
    except Exception:
        from sentinel_tpu.core.log import record_log

        record_log.exception("metric extension %r failed", fn)


def on_pass(resource: str, n: int, args) -> None:
    for ext in _extensions:
        _safe(ext.add_pass, resource, n, args)


def on_block(resource: str, n: int, origin: str, error, args) -> None:
    for ext in _extensions:
        _safe(ext.add_block, resource, n, origin, error, args)


def on_complete(resource: str, n: int, rt_ms: float, args) -> None:
    for ext in _extensions:
        _safe(ext.add_success, resource, n, args)
        _safe(ext.add_rt, resource, rt_ms, args)


def on_exception(resource: str, n: int, error) -> None:
    for ext in _extensions:
        _safe(ext.add_exception, resource, n, error)


def on_thread_inc(resource: str, args) -> None:
    for ext in _extensions:
        _safe(ext.increase_thread_num, resource, args)


def on_thread_dec(resource: str, args) -> None:
    for ext in _extensions:
        _safe(ext.decrease_thread_num, resource, args)
