"""HA counters: failover, fallback, and snapshot events.

Companion of :mod:`sentinel_tpu.metrics.server` for the cluster HA subsystem
(:mod:`sentinel_tpu.ha`): the failover client counts endpoint evictions, the
local fallback policy counts degraded verdicts, and the snapshot manager
counts save/restore cycles. One process-wide singleton, rendered under the
Prometheus surface (``sentinel_failover_total`` / ``sentinel_fallback_total``
/ ``sentinel_snapshot_total``) and as JSON for bench artifacts.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple


def _escape(label: str) -> str:
    return label.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class HaMetrics:
    """Failover/fallback/snapshot counters for this process."""

    def __init__(self):
        self._lock = threading.Lock()
        # (from_endpoint, to_endpoint) → count; to="" means "no endpoint
        # left" (the request degraded to the local fallback path)
        self._failover: Dict[Tuple[str, str], int] = {}
        # action → count: pass | block | throttle_pass | throttle_block |
        # rls_allow | rls_deny
        self._fallback: Dict[str, int] = {}
        self._snapshot: Dict[str, int] = {}  # op → count: save | restore
        self._last_failover_ms = 0
        # warm-standby replication (ha.replication): event → count, where
        # event ∈ shipped | applied | snapshot | need_snapshot | reconnect |
        # error | promoted
        self._repl: Dict[str, int] = {}
        self._repl_bytes = 0
        # acked end-to-end delta age, as observed by the sender (wall-clock
        # ms between export_delta's capture and the standby's ACK)
        self._repl_lag_ms = 0.0
        # live shard rebalancing (cluster.rebalance): event → count, where
        # event ∈ begin | commit | abort | advise
        self._rebalance: Dict[str, int] = {}
        self._rebalance_bytes = 0  # MOVE_STATE payload bytes shipped
        self._rebalance_redirects = 0  # MOVED verdicts answered
        # end-to-end move duration (begin → commit ack), wall-clock ms
        from sentinel_tpu.metrics.histogram import LatencyHistogram

        self._move_ms = LatencyHistogram(lo=1.0, hi=60_000.0)

    # -- writers ------------------------------------------------------------
    def count_failover(self, from_endpoint: str, to_endpoint: str,
                       now_ms: int = 0) -> None:
        key = (from_endpoint, to_endpoint)
        with self._lock:
            self._failover[key] = self._failover.get(key, 0) + 1
            if now_ms:
                self._last_failover_ms = now_ms

    def count_fallback(self, action: str, n: int = 1) -> None:
        with self._lock:
            self._fallback[action] = self._fallback.get(action, 0) + n

    def count_snapshot(self, op: str) -> None:
        with self._lock:
            self._snapshot[op] = self._snapshot.get(op, 0) + 1

    def count_repl(self, event: str, n: int = 1) -> None:
        with self._lock:
            self._repl[event] = self._repl.get(event, 0) + n

    def add_repl_bytes(self, n: int) -> None:
        with self._lock:
            self._repl_bytes += int(n)

    def set_repl_lag(self, ms: float) -> None:
        with self._lock:
            self._repl_lag_ms = float(ms)

    def count_rebalance(self, event: str, n: int = 1) -> None:
        with self._lock:
            self._rebalance[event] = self._rebalance.get(event, 0) + n

    def add_rebalance_state_bytes(self, n: int) -> None:
        with self._lock:
            self._rebalance_bytes += int(n)

    def count_rebalance_redirects(self, n: int = 1) -> None:
        with self._lock:
            self._rebalance_redirects += int(n)

    def observe_move_ms(self, ms: float) -> None:
        self._move_ms.record(float(ms))  # histogram is itself thread-safe

    # -- readers ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "failover": [
                    {"from": f, "to": t, "count": c}
                    for (f, t), c in sorted(self._failover.items())
                ],
                "fallback": dict(sorted(self._fallback.items())),
                "snapshots": dict(sorted(self._snapshot.items())),
                "lastFailoverMs": self._last_failover_ms,
                "replication": {
                    "events": dict(sorted(self._repl.items())),
                    "bytesTotal": self._repl_bytes,
                    "lagMs": self._repl_lag_ms,
                },
                "rebalance": {
                    "events": dict(sorted(self._rebalance.items())),
                    "stateBytesTotal": self._rebalance_bytes,
                    "redirectsTotal": self._rebalance_redirects,
                    "moveMs": self._move_ms.snapshot(),
                },
            }

    def fallback_totals(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._fallback)

    def render(self) -> str:
        """Prometheus exposition (no trailing newline; the exporter joins
        sections)."""
        lines = [
            "# HELP sentinel_failover_total Token-client endpoint failovers "
            "(from → to; to=\"\" means degraded to local fallback).",
            "# TYPE sentinel_failover_total counter",
        ]
        with self._lock:
            failover = sorted(self._failover.items())
            fallback = sorted(self._fallback.items())
            snapshots = sorted(self._snapshot.items())
        if failover:
            for (f, t), count in failover:
                lines.append(
                    "sentinel_failover_total"
                    f'{{from="{_escape(f)}",to="{_escape(t)}"}} {count}'
                )
        else:
            lines.append('sentinel_failover_total{from="",to=""} 0')
        lines.append(
            "# HELP sentinel_fallback_total Requests resolved by the local "
            "fallback policy, by action."
        )
        lines.append("# TYPE sentinel_fallback_total counter")
        if fallback:
            for action, count in fallback:
                lines.append(
                    f'sentinel_fallback_total{{action="{_escape(action)}"}}'
                    f" {count}"
                )
        else:
            lines.append('sentinel_fallback_total{action="pass"} 0')
        lines.append(
            "# HELP sentinel_snapshot_total Token-server state snapshot "
            "operations."
        )
        lines.append("# TYPE sentinel_snapshot_total counter")
        if snapshots:
            for op, count in snapshots:
                lines.append(
                    f'sentinel_snapshot_total{{op="{_escape(op)}"}} {count}'
                )
        else:
            lines.append('sentinel_snapshot_total{op="save"} 0')
        with self._lock:
            repl = sorted(self._repl.items())
            repl_bytes = self._repl_bytes
            repl_lag = self._repl_lag_ms
        lines.append(
            "# HELP sentinel_repl_deltas_total Warm-standby replication "
            "events (shipped/applied/snapshot/need_snapshot/reconnect/"
            "error/promoted)."
        )
        lines.append("# TYPE sentinel_repl_deltas_total counter")
        if repl:
            for event, count in repl:
                lines.append(
                    "sentinel_repl_deltas_total"
                    f'{{event="{_escape(event)}"}} {count}'
                )
        else:
            lines.append('sentinel_repl_deltas_total{event="shipped"} 0')
        lines.append(
            "# HELP sentinel_repl_bytes_total Replication payload bytes "
            "shipped to standbys."
        )
        lines.append("# TYPE sentinel_repl_bytes_total counter")
        lines.append(f"sentinel_repl_bytes_total {repl_bytes}")
        lines.append(
            "# HELP sentinel_repl_lag_ms Age of the last acked delta "
            "(capture → standby ACK, wall-clock ms)."
        )
        lines.append("# TYPE sentinel_repl_lag_ms gauge")
        lines.append(f"sentinel_repl_lag_ms {repl_lag:g}")
        with self._lock:
            rebalance = sorted(self._rebalance.items())
            reb_bytes = self._rebalance_bytes
            reb_redirects = self._rebalance_redirects
        lines.append(
            "# HELP sentinel_rebalance_moves_total Live namespace-move "
            "protocol events (begin/commit/abort) and sustained-pressure "
            "advisories (advise)."
        )
        lines.append("# TYPE sentinel_rebalance_moves_total counter")
        if rebalance:
            for event, count in rebalance:
                lines.append(
                    "sentinel_rebalance_moves_total"
                    f'{{event="{_escape(event)}"}} {count}'
                )
        else:
            lines.append('sentinel_rebalance_moves_total{event="begin"} 0')
        lines.append(
            "# HELP sentinel_rebalance_state_bytes_total MOVE_STATE payload "
            "bytes shipped during namespace moves."
        )
        lines.append("# TYPE sentinel_rebalance_state_bytes_total counter")
        lines.append(f"sentinel_rebalance_state_bytes_total {reb_bytes}")
        lines.append(
            "# HELP sentinel_rebalance_redirects_total MOVED verdicts "
            "answered for flows of a moving (or moved-away) namespace."
        )
        lines.append("# TYPE sentinel_rebalance_redirects_total counter")
        lines.append(
            f"sentinel_rebalance_redirects_total {reb_redirects}"
        )
        lines.append(self._move_ms.render_prometheus(
            "sentinel_rebalance_move_duration_ms",
            "End-to-end namespace move duration (begin to commit ack, "
            "wall-clock ms).",
        ))
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._failover.clear()
            self._fallback.clear()
            self._snapshot.clear()
            self._last_failover_ms = 0
            self._repl.clear()
            self._repl_bytes = 0
            self._repl_lag_ms = 0.0
            self._rebalance.clear()
            self._rebalance_bytes = 0
            self._rebalance_redirects = 0
            self._move_ms.reset()


_SINGLETON = HaMetrics()


def ha_metrics() -> HaMetrics:
    """The process-wide HA metrics registry."""
    return _SINGLETON


def reset_ha_metrics_for_tests() -> None:
    _SINGLETON.reset()
