"""Metric log: per-second resource metrics in rolling files.

Line format matches the reference's ``MetricNode.toString`` (what the
dashboard's ``MetricFetcher`` parses)::

    timestamp|yyyy-MM-dd HH:mm:ss|resource|passQps|blockQps|successQps|
    exceptionQps|rt|occupiedPassQps|concurrency|classification

Analogs: ``MetricWriter.java:47-92`` (50MB × 6 rolling files + ``.idx``
second→offset index), ``MetricSearcher.java:34``, ``MetricTimerListener.java:
34-59`` (the 1s aggregation task over ``ClusterBuilderSlot.clusterNodeMap``).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.core.log import record_log


@dataclass
class MetricNode:
    timestamp_ms: int
    resource: str
    pass_qps: float = 0.0
    block_qps: float = 0.0
    success_qps: float = 0.0
    exception_qps: float = 0.0
    rt: float = 0.0
    occupied_pass_qps: float = 0.0
    concurrency: int = 0
    classification: int = 0

    def to_line(self) -> str:
        ts = self.timestamp_ms // 1000 * 1000
        date = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts / 1000))
        res = self.resource.replace("|", "_")
        return (
            f"{ts}|{date}|{res}|{self.pass_qps:g}|{self.block_qps:g}|"
            f"{self.success_qps:g}|{self.exception_qps:g}|{self.rt:g}|"
            f"{self.occupied_pass_qps:g}|{self.concurrency}|{self.classification}"
        )

    @classmethod
    def from_line(cls, line: str) -> "MetricNode":
        p = line.rstrip("\n").split("|")
        return cls(
            timestamp_ms=int(p[0]),
            resource=p[2],
            pass_qps=float(p[3]),
            block_qps=float(p[4]),
            success_qps=float(p[5]),
            exception_qps=float(p[6]),
            rt=float(p[7]),
            occupied_pass_qps=float(p[8]),
            concurrency=int(p[9]),
            classification=int(p[10]) if len(p) > 10 else 0,
        )


def default_metric_dir() -> str:
    """Where metric logs live unless overridden (shared by the writer and the
    ``/metric`` command handler, which must read the same directory)."""
    return os.path.join(
        os.environ.get("SENTINEL_LOG_DIR") or os.path.expanduser("~/logs/csp"),
        "metrics",
    )


class MetricWriter:
    """Size-rolled metric files with a second→offset index."""

    def __init__(self, base_dir: Optional[str] = None,
                 single_file_size: Optional[int] = None,
                 total_file_count: Optional[int] = None):
        self.base_dir = base_dir or default_metric_dir()
        os.makedirs(self.base_dir, exist_ok=True)
        self.single_file_size = single_file_size or SentinelConfig.get_int(
            "csp.sentinel.metric.file.single.size", 50 * 1024 * 1024
        )
        self.total_file_count = total_file_count or SentinelConfig.get_int(
            "csp.sentinel.metric.file.total.count", 6
        )
        self.app = SentinelConfig.app_name()
        self._lock = threading.Lock()
        self._cur_path: Optional[str] = None
        self._cur_file = None
        self._cur_idx = None

    def _file_name(self, n: int) -> str:
        return os.path.join(self.base_dir, f"{self.app}-metrics.log.{n}")

    def _roll_if_needed(self) -> None:
        if self._cur_file is not None and self._cur_file.tell() < self.single_file_size:
            return
        if self._cur_file is not None:
            self._cur_file.close()
            self._cur_idx.close()
            # shift files: .N-1 ← .N (drop the oldest)
            for n in range(self.total_file_count - 1, 0, -1):
                src, dst = self._file_name(n - 1), self._file_name(n)
                if os.path.exists(src):
                    os.replace(src, dst)
                    if os.path.exists(src + ".idx"):
                        os.replace(src + ".idx", dst + ".idx")
        path = self._file_name(0)
        self._cur_path = path
        self._cur_file = open(path, "a", encoding="utf-8")
        self._cur_idx = open(path + ".idx", "a", encoding="utf-8")

    def write(self, nodes: List[MetricNode]) -> None:
        if not nodes:
            return
        with self._lock:
            self._roll_if_needed()
            sec = nodes[0].timestamp_ms // 1000
            self._cur_idx.write(f"{sec} {self._cur_file.tell()}\n")
            for node in nodes:
                self._cur_file.write(node.to_line() + "\n")
            self._cur_file.flush()
            self._cur_idx.flush()

    def close(self) -> None:
        with self._lock:
            if self._cur_file is not None:
                self._cur_file.close()
                self._cur_idx.close()
                self._cur_file = self._cur_idx = None


class MetricSearcher:
    """Reads metric lines in a time range across the rolling files
    (``MetricSearcher.find``; the ``/metric`` command's backend)."""

    def __init__(self, base_dir: str, app: str):
        self.base_dir = base_dir
        self.app = app

    @staticmethod
    def _seek_offset(idx_path: str, begin_ms: int) -> int:
        """Largest indexed offset whose second precedes ``begin_ms`` — the
        reference seeks the same way (``MetricSearcher.java``: binary-search
        the .idx, then read forward)."""
        begin_sec = begin_ms // 1000
        offset = 0
        try:
            with open(idx_path, "r", encoding="utf-8") as f:
                for line in f:
                    try:
                        sec_s, off_s = line.split()
                        if int(sec_s) >= begin_sec:
                            break
                        offset = int(off_s)
                    except ValueError:
                        continue
        except OSError:
            return 0
        return offset

    def find(self, begin_ms: int, end_ms: int,
             identity: Optional[str] = None, max_lines: int = 12000) -> List[MetricNode]:
        out: List[MetricNode] = []
        n = 0
        while True:
            path = os.path.join(self.base_dir, f"{self.app}-metrics.log.{n}")
            if not os.path.exists(path):
                break
            n += 1
        for i in range(n - 1, -1, -1):  # oldest file first
            path = os.path.join(self.base_dir, f"{self.app}-metrics.log.{i}")
            try:
                with open(path, "r", encoding="utf-8") as f:
                    f.seek(self._seek_offset(path + ".idx", begin_ms))
                    for line in f:
                        try:
                            node = MetricNode.from_line(line)
                        except (ValueError, IndexError):
                            continue
                        if node.timestamp_ms < begin_ms:
                            continue
                        if node.timestamp_ms > end_ms:
                            break  # lines are time-ordered within a file
                        if identity and node.resource != identity:
                            continue
                        out.append(node)
                        if len(out) >= max_lines:
                            return out
            except OSError:
                continue
        return out


class MetricTimer:
    """1-second aggregation task (``MetricTimerListener``): snapshots every
    resource's ClusterNode into metric lines."""

    def __init__(self, writer: Optional[MetricWriter] = None, interval_s: float = 1.0):
        self.writer = writer or MetricWriter()
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricTimer":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="sentinel-metric-timer"
        )
        self._thread.start()
        return self

    def collect_once(self) -> List[MetricNode]:
        from sentinel_tpu.local.chain import cluster_node_map

        now = _clock.now_ms()
        # aggregate the PREVIOUS full second (it is complete)
        ts = (now // 1000 - 1) * 1000
        read_at = ts + 999
        nodes = []
        for name, cn in cluster_node_map().items():
            node = MetricNode(
                timestamp_ms=ts,
                resource=name,
                pass_qps=cn.pass_qps(read_at),
                block_qps=cn.block_qps(read_at),
                success_qps=cn.success_qps(read_at),
                exception_qps=cn.exception_qps(read_at),
                rt=cn.avg_rt(read_at),
                occupied_pass_qps=cn.occupied_pass_qps(read_at),
                concurrency=cn.cur_thread_num,
            )
            if (node.pass_qps or node.block_qps or node.success_qps
                    or node.exception_qps):
                nodes.append(node)
        return nodes

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.writer.write(self.collect_once())
            except Exception as e:
                record_log.warning("metric aggregation failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        self.writer.close()
