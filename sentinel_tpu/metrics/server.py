"""Server-side pipeline metrics: stage histograms, verdict counters, gauges.

The analog of the reference's ``ClusterServerStatLogUtil`` + dashboard state
commands, grown into an always-on Prometheus surface: the ``TokenServer``
micro-batcher (asyncio and native front doors) records per-stage timings
here, ``DefaultTokenService`` feeds per-namespace verdict counters from each
materialized batch, and the Envoy RLS adapter mirrors its OK/OVER_LIMIT
responses in. One process-wide singleton — multiple servers in one process
(tests, port moves) share it, which matches Prometheus's per-process scrape
model.

Everything here renders under the ``sentinel_server_*`` prefix via
:func:`ServerMetrics.render` (appended to the exporter body) and as JSON via
:func:`ServerMetrics.snapshot` (the ``clusterServerStats`` command).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.metrics.histogram import LatencyHistogram

# TokenStatus codes that appear on the flow batch path → series label.
# (RELEASE_OK / ALREADY_RELEASE ride the host-side concurrent path, which
# answers per-request, not per-batch — they never reach this counter.)
VERDICT_NAMES: Dict[int, str] = {
    0: "pass",            # OK
    1: "block",           # BLOCKED
    2: "should_wait",     # SHOULD_WAIT (occupied-ahead admission)
    3: "no_rule",         # NO_RULE_EXISTS
    4: "too_many_request",  # namespace guard tripped
    5: "fail",            # device step failed / degraded
    8: "overload",        # admission refused: queue full / deadline / brownout
    9: "standby",         # unpromoted warm standby refused to decide
    10: "moved",          # namespace rebalanced away: redirect to new owner
    12: "degraded",       # circuit breaker OPEN/HALF_OPEN refused the row
}

# reasons on the sentinel_server_shed_total counter: every dropped or
# refused frame lands in exactly one of these
SHED_REASONS = (
    "queue_full",    # front-door queue at capacity → answered OVERLOAD
    "deadline",      # client deadline already blown → dropped (no answer)
    "brownout",      # SHED_LOW: non-prioritized rows answered OVERLOAD
    "degrade",       # DEGRADE: rows refused by the probabilistic local gate
    "lane_abandon",  # shutdown abandoned a wedged lane handoff
    "chaos_drop",    # a chaos frame_drop injector ate the frame
)

NO_RULE_NAMESPACE = "(no-rule)"  # requests whose flow_id has no loaded rule


def _escape(label: str) -> str:
    return label.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _RateWindow:
    """Windowed events/sec over the last ``seconds`` wall seconds, current
    second included (so short-lived tests and fresh servers report > 0)."""

    def __init__(self, seconds: int = 8):
        self.seconds = max(1, int(seconds))
        self._slots = [(-1, 0)] * self.seconds  # (second, count)
        self._lock = threading.Lock()

    def add(self, n: int) -> None:
        sec = _clock.now_ms() // 1000
        i = sec % self.seconds
        with self._lock:
            slot_sec, count = self._slots[i]
            self._slots[i] = (sec, count + n if slot_sec == sec else n)

    def rate(self) -> float:
        sec = _clock.now_ms() // 1000
        lo = sec - self.seconds + 1
        with self._lock:
            total = sum(c for s, c in self._slots if s >= lo)
        return total / float(self.seconds)

    def reset(self) -> None:
        with self._lock:
            self._slots = [(-1, 0)] * self.seconds


class ServerMetrics:
    """All ``sentinel_server_*`` state for this process's token server(s)."""

    # gauges every scrape shows even before a server registers a live reader
    _GAUGE_NAMES = (
        "queue_depth", "inflight_batches", "connections",
        "dispatch_lane_depth", "reply_lane_depth",
        "shm_ring_occupancy", "device_inflight",
    )

    def __init__(self):
        # stage histograms, all in milliseconds except batch_size (requests).
        # 1µs..10s covers a sub-100µs device step and a 1s cold compile alike.
        self.queue_wait_ms = LatencyHistogram(lo=0.001, hi=10_000.0)
        self.decide_ms = LatencyHistogram(lo=0.001, hi=10_000.0)
        self.write_ms = LatencyHistogram(lo=0.001, hi=10_000.0)
        self.batch_size = LatencyHistogram(
            bounds=[float(1 << i) for i in range(17)]  # 1..65536, ×2 ladder
        )
        # per-lane stage histograms for the staged native pipeline:
        # intake_ms = wait_batch pull → handoff enqueue (decode copy + prep);
        # dispatch_ms = drain of the handoff queue → device dispatch issued
        # (host prep + async enqueue; the device step itself is decide_ms).
        self.intake_ms = LatencyHistogram(lo=0.001, hi=10_000.0)
        self.dispatch_ms = LatencyHistogram(lo=0.001, hi=10_000.0)
        # fused multi-frame dispatch: how many engine-batch frames each
        # chained device step folded together (depth 1 = unfused)
        self.fused_depth = LatencyHistogram(
            bounds=[float(1 << i) for i in range(7)]  # 1..64, ×2 ladder
        )
        self._fused_frames = 0
        self._fused_lock = threading.Lock()
        # traffic-shaping waits: every SHOULD_WAIT verdict that carried a
        # positive wait hint (paced admission or priority occupy) — count
        # plus the distribution of assigned waits (whole ms, ≥ 1)
        self.wait_assigned_ms = LatencyHistogram(lo=1.0, hi=60_000.0)
        self._wait_assigned = 0
        self._verdicts: Dict[Tuple[str, str], int] = {}
        self._verdict_lock = threading.Lock()
        self._rate = _RateWindow()
        # shed accounting: frames the server refused (answered OVERLOAD) or
        # dropped (deadline blown, abandoned lane), by reason — the number
        # that used to be invisible when _lane_put gave up silently
        self._shed: Dict[str, int] = {}
        self._shed_lock = threading.Lock()
        # per-intake-shard pull accounting (multi-door native server):
        # shard → {pulls, requests, busy_ms}. busy_ms is cumulative lane
        # busy time, so occupancy over a window is rate(busy_ms)/1000.
        self._shards: Dict[int, Dict[str, float]] = {}
        self._shard_lock = threading.Lock()
        # host bytes copied on the serving path (arena→staging memcpy,
        # fusion concatenate) — the bench divides by verdicts served to
        # report bytes-copied-per-verdict
        self._copy_bytes = 0
        self._copy_lock = threading.Lock()
        # double-buffered device lane: host prep/dispatch time spent while
        # an earlier fused group was still computing on device — work a
        # depth-1 lane would have serialized behind block_until_ready
        self._overlap_ms = 0.0
        self._overlap_lock = threading.Lock()
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._gauge_lock = threading.Lock()
        # sketch observability: the live token service registers a zero-arg
        # provider returning sketch.sketch_stats() (variant, fat/slim bytes,
        # merge counters). Most recent registration wins — same model as a
        # replacement server's gauges.
        self._sketch_provider: Optional[Callable[[], dict]] = None
        self._sketch_lock = threading.Lock()
        # shm front-door observability: the native server registers a
        # zero-arg provider returning the shm door's poll/doorbell/ring-full
        # counters (each independently monotonic; no cross-counter snapshot)
        self._shm_provider: Optional[Callable[[], dict]] = None
        self._shm_lock = threading.Lock()
        # wire-rev-5 lease observability: the live token service registers
        # a zero-arg provider returning its lease_stats() block (cumulative
        # granted/renewed/returned/revoked + outstanding gauges). Same
        # most-recent-wins weakref model as the sketch provider.
        self._lease_provider: Optional[Callable[[], dict]] = None
        self._lease_lock = threading.Lock()
        self._hier_provider: Optional[Callable[[], dict]] = None
        self._hier_lock = threading.Lock()
        # wire-rev-6 outcome observability: the live token service registers
        # a zero-arg provider returning its outcome_stats() block (reported/
        # exception/drop counters + per-flow windowed RT reads off the device
        # outcome columns). Same most-recent-wins weakref model as the rest.
        self._outcome_provider: Optional[Callable[[], dict]] = None
        self._outcome_lock = threading.Lock()
        # circuit-breaker observability: the live token service registers
        # a zero-arg reader returning its breaker_stats() block (per-flow
        # breaker state + clocks, read from the device state columns), and
        # pushes CLOSED/OPEN/HALF_OPEN transition edges through
        # count_breaker_transition as its host mirror observes them.
        self._breaker_provider: Optional[Callable[[], dict]] = None
        self._breaker_transitions: Dict[Tuple[str, str], int] = {}
        self._breaker_lock = threading.Lock()
        # wire-rev-7 push-plane observability: frames emitted by the push
        # hub (by type), lease revocations pushed, and the server-emit →
        # client-apply staleness histogram. Staleness is recorded by the
        # client-side apply off the frame's emit stamp — co-located
        # clients (shm, drills, sidecars sharing the exporter) land it in
        # this process; a remote client's applies surface on its own
        # exporter. A provider exposes the live hub's connection count
        # and drop counters.
        self._push_frames: Dict[str, int] = {}
        self._push_revocations = 0
        self._push_lock = threading.Lock()
        self.push_staleness_ms = LatencyHistogram(lo=0.01, hi=60_000.0)
        self._push_provider: Optional[Callable[[], dict]] = None

    # -- fused dispatch counters --------------------------------------------
    def record_fused(self, depth: int) -> None:
        """One fused device dispatch folding ``depth`` engine-batch frames
        into a single chained step (records the amortization the serving
        path achieved; depth 1 would mean no fusion and is not recorded)."""
        with self._fused_lock:
            self._fused_frames += int(depth)
        self.fused_depth.record(float(depth))

    @property
    def fused_frames_total(self) -> int:
        with self._fused_lock:
            return self._fused_frames

    @property
    def wait_assigned_total(self) -> int:
        with self._verdict_lock:
            return self._wait_assigned

    # -- intake shard + host-copy counters ----------------------------------
    def count_shard_pull(
        self, shard: int, n_rows: int, busy_ms: float
    ) -> None:
        """One intake pull handed to the device lane by ``shard``:
        ``n_rows`` requests, ``busy_ms`` of lane busy time."""
        with self._shard_lock:
            s = self._shards.setdefault(
                int(shard), {"pulls": 0, "requests": 0, "busy_ms": 0.0}
            )
            s["pulls"] += 1
            s["requests"] += int(n_rows)
            s["busy_ms"] += float(busy_ms)

    def shard_totals(self) -> Dict[int, Dict[str, float]]:
        with self._shard_lock:
            return {k: dict(v) for k, v in self._shards.items()}

    def count_copy_bytes(self, n: int) -> None:
        if n <= 0:
            return
        with self._copy_lock:
            self._copy_bytes += int(n)

    @property
    def host_copy_bytes_total(self) -> int:
        with self._copy_lock:
            return self._copy_bytes

    def count_overlap_saved_ms(self, ms: float) -> None:
        """``ms`` of host prep/dispatch that ran while an earlier fused
        group was still in flight on device (the pipelined device lane's
        measured win over a serialized depth-1 lane)."""
        if ms <= 0:
            return
        with self._overlap_lock:
            self._overlap_ms += float(ms)

    @property
    def overlap_saved_ms_total(self) -> float:
        with self._overlap_lock:
            return self._overlap_ms

    # -- shed counters ------------------------------------------------------
    def count_shed(self, reason: str, n: int = 1) -> None:
        """``n`` requests shed for ``reason`` (one of :data:`SHED_REASONS`,
        free-form tolerated so callers can't lose a count to a typo)."""
        if n <= 0:
            return
        with self._shed_lock:
            self._shed[reason] = self._shed.get(reason, 0) + int(n)

    def shed_totals(self) -> Dict[str, int]:
        with self._shed_lock:
            return dict(self._shed)

    @property
    def shed_total(self) -> int:
        with self._shed_lock:
            return sum(self._shed.values())

    def verdict_rate(self) -> float:
        """Windowed verdicts/sec — the throughput input of the BBR
        admission estimator (``overload/admission.py``)."""
        return self._rate.rate()

    # -- verdict counters ---------------------------------------------------
    def count_verdict(self, verdict: str, namespace: str, n: int = 1) -> None:
        key = (verdict, namespace)
        with self._verdict_lock:
            self._verdicts[key] = self._verdicts.get(key, 0) + n

    def verdict_totals_by_namespace(self) -> Dict[str, int]:
        """Cumulative verdicts served per namespace, all verdict classes
        summed — the admission gate diffs successive reads to rank the
        hottest namespaces for its rebalance advisories."""
        out: Dict[str, int] = {}
        with self._verdict_lock:
            for (_verdict, ns), count in self._verdicts.items():
                out[ns] = out.get(ns, 0) + count
        return out

    def record_verdict_batch(
        self,
        status: np.ndarray,
        ns_idx: Optional[np.ndarray],
        ns_names: Tuple[str, ...],
        latency_ms: Optional[float] = None,
        wait_ms: Optional[np.ndarray] = None,
    ) -> None:
        """Count one materialized batch: ``status`` int8[N] TokenStatus
        codes, ``ns_idx`` int32[N] namespace row per request (-1 → no rule;
        None → attribute everything to ``(no-rule)``). Vectorized — a few
        masked bincounts per batch, never a Python loop over requests.

        ``latency_ms`` (decision latency shared by the whole batch) feeds
        the per-tenant SLO plane; refusal statuses are attributed there as
        sheds either way. ``wait_ms`` int32[N] (the verdicts' wait hints)
        feeds the assigned-wait counter/histogram — only positive hints
        count, and only SHOULD_WAIT verdicts carry them."""
        status = np.asarray(status)
        n = int(status.shape[0])
        if n == 0:
            return
        self._rate.add(n)
        if wait_ms is not None:
            w = np.asarray(wait_ms)
            wmask = w > 0
            n_wait = int(wmask.sum())
            if n_wait:
                with self._verdict_lock:
                    self._wait_assigned += n_wait
                # batches repeat few distinct waits; record value-grouped
                for v, c in zip(*np.unique(w[wmask], return_counts=True)):
                    self.wait_assigned_ms.record(float(v), int(c))
        updates: Dict[Tuple[str, str], int] = {}
        for code, vname in VERDICT_NAMES.items():
            mask = status == code
            hits = int(mask.sum())
            if not hits:
                continue
            if ns_idx is None or not len(ns_names):
                updates[(vname, NO_RULE_NAMESPACE)] = hits
                continue
            counts = np.bincount(
                ns_idx[mask] + 1, minlength=len(ns_names) + 1
            )
            if counts[0]:
                updates[(vname, NO_RULE_NAMESPACE)] = int(counts[0])
            for j in np.nonzero(counts[1:])[0]:
                updates[(vname, ns_names[int(j)])] = int(counts[1 + j])
        with self._verdict_lock:
            for key, v in updates.items():
                self._verdicts[key] = self._verdicts.get(key, 0) + v
        self._feed_slo(updates, latency_ms)

    # refusal verdict → the SLO-plane shed reason it is attributed under
    _SLO_SHED_REASONS = {"overload": "overload", "too_many_request":
                         "namespace_guard", "moved": "moved",
                         "degraded": "degraded"}

    def _feed_slo(
        self,
        updates: Dict[Tuple[str, str], int],
        latency_ms: Optional[float],
    ) -> None:
        """Per-tenant SLO + timeline accounting off the verdict-batch
        updates: served rows record the batch's decision latency, refusals
        record as sheds (each row lands in exactly one window bucket —
        served OR shed). The timeline's shed column is fed from
        ``SloPlane.record_shed`` (which this calls), so timeline sums
        reconcile with both ``sentinel_server_verdicts_total`` and
        ``sentinel_slo_shed_total`` deltas."""
        from sentinel_tpu.metrics.timeline import timeline
        from sentinel_tpu.trace.slo import slo_plane

        plane = slo_plane()
        tl = timeline()
        served: Dict[str, int] = {}
        # timeline columns per namespace: [pass, block, other, waited]
        cols: Dict[str, List[int]] = {}
        for (vname, ns), v in updates.items():
            reason = self._SLO_SHED_REASONS.get(vname)
            if reason is not None:
                plane.record_shed(ns, reason, v)
                continue
            served[ns] = served.get(ns, 0) + v
            c = cols.setdefault(ns, [0, 0, 0, 0])
            if vname == "pass":
                c[0] += v
            elif vname == "block":
                c[1] += v
            elif vname == "should_wait":
                # delayed admission (pacing / priority occupy): served, but
                # attributed in its own column so a paced tenant's wall
                # shows shaping, not mystery "other" traffic
                c[3] += v
                plane.record_waited(ns, v)
            else:
                c[2] += v
        for ns, c in cols.items():
            tl.record(ns, n_pass=c[0], n_block=c[1], n_other=c[2],
                      latency_ms=latency_ms, n_waited=c[3])
        if latency_ms is not None:
            for ns, v in served.items():
                plane.record(ns, latency_ms, v)

    def count_rls(self, domain: str, ok_n: int, over_n: int) -> None:
        """Envoy RLS responses, per domain. The descriptors already counted
        once on the engine path under their rule namespace; this adds the
        RLS-shaped view (``namespace="rls:<domain>"``) without touching the
        verdicts/sec rate (no double counting)."""
        ns = f"rls:{domain}"
        with self._verdict_lock:
            if ok_n:
                key = ("pass", ns)
                self._verdicts[key] = self._verdicts.get(key, 0) + int(ok_n)
            if over_n:
                key = ("block", ns)
                self._verdicts[key] = self._verdicts.get(key, 0) + int(over_n)

    # -- gauges -------------------------------------------------------------
    def register_gauge(self, name: str, fn: Callable[[], float]) -> None:
        with self._gauge_lock:
            self._gauges[name] = fn

    def unregister_gauge(self, name: str, fn: Optional[Callable] = None) -> None:
        """Remove a gauge; with ``fn`` given, only if it is still the
        registered reader (a replacement server's gauge survives the old
        server's teardown)."""
        with self._gauge_lock:
            if fn is None or self._gauges.get(name) is fn:
                self._gauges.pop(name, None)

    def _gauge_values(self) -> Dict[str, float]:
        with self._gauge_lock:
            readers = dict(self._gauges)
        out = {name: 0.0 for name in self._GAUGE_NAMES}
        for name, fn in readers.items():
            try:
                out[name] = float(fn())
            except Exception:
                out[name] = 0.0  # a dying server's reader must not 500 a scrape
        return out

    # -- sketch provider ----------------------------------------------------
    def register_sketch_provider(self, fn: Callable[[], dict]) -> None:
        """Install the zero-arg reader for the param-sketch stats block
        (``sentinel_tpu.sketch.sketch_stats`` shape). The most recently
        constructed service wins; providers return ``{}`` once their
        service is gone."""
        with self._sketch_lock:
            self._sketch_provider = fn

    def sketch_stats(self) -> dict:
        with self._sketch_lock:
            fn = self._sketch_provider
        if fn is None:
            return {}
        try:
            return dict(fn() or {})
        except Exception:
            return {}  # a torn-down service's reader must not 500 a scrape

    # -- shm front door provider --------------------------------------------
    def register_shm_provider(self, fn: Callable[[], dict]) -> None:
        """Install the zero-arg reader for the shm ring door's counters
        (``{"polls", "doorbells", "ring_full", "segments"}``). Most recent
        registration wins; providers return ``{}`` once their door is
        gone. Values are independently monotonic relaxed atomics — the
        exporter renders each as its own counter, never arithmetic across
        them."""
        with self._shm_lock:
            self._shm_provider = fn

    def shm_stats(self) -> dict:
        with self._shm_lock:
            fn = self._shm_provider
        if fn is None:
            return {}
        try:
            return dict(fn() or {})
        except Exception:
            return {}  # a torn-down door's reader must not 500 a scrape

    # -- lease provider -----------------------------------------------------
    def register_lease_provider(self, fn: Callable[[], dict]) -> None:
        """Install the zero-arg reader for the token service's lease stats
        (``DefaultTokenService.lease_stats`` shape). Most recent
        registration wins; providers return ``{}`` once their service is
        gone."""
        with self._lease_lock:
            self._lease_provider = fn

    def lease_stats(self) -> dict:
        with self._lease_lock:
            fn = self._lease_provider
        if fn is None:
            return {}
        try:
            return dict(fn() or {})
        except Exception:
            return {}  # a torn-down service's reader must not 500 a scrape

    # -- hierarchy provider -------------------------------------------------
    def register_hier_provider(self, fn: Callable[[], dict]) -> None:
        """Install the zero-arg reader for the hierarchy tier's stats
        (``DefaultTokenService.hier_stats`` shape — coordinator ledger
        and/or share-agent counters, ``{}`` when neither is attached).
        Most recent registration wins."""
        with self._hier_lock:
            self._hier_provider = fn

    def hier_stats(self) -> dict:
        with self._hier_lock:
            fn = self._hier_provider
        if fn is None:
            return {}
        try:
            return dict(fn() or {})
        except Exception:
            return {}  # a torn-down service's reader must not 500 a scrape

    # -- outcome provider ---------------------------------------------------
    def register_outcome_provider(self, fn: Callable[[], dict]) -> None:
        """Install the zero-arg reader for the token service's completion
        outcome stats (``DefaultTokenService.outcome_stats`` shape:
        cumulative reported/exception/drop counters plus per-flow windowed
        complete/exception QPS and RT avg/p99 read from the device outcome
        columns). Most recent registration wins; providers return ``{}``
        once their service is gone."""
        with self._outcome_lock:
            self._outcome_provider = fn

    def outcome_stats(self) -> dict:
        with self._outcome_lock:
            fn = self._outcome_provider
        if fn is None:
            return {}
        try:
            return dict(fn() or {})
        except Exception:
            return {}  # a torn-down service's reader must not 500 a scrape

    # -- breaker provider ---------------------------------------------------
    def register_breaker_provider(self, fn: Callable[[], dict]) -> None:
        """Install the zero-arg reader for the token service's circuit
        breaker stats (``DefaultTokenService.breaker_stats`` shape:
        per-flow breaker state name + clocks read from the device
        ``BreakerState`` columns; ``{}`` with no breakers loaded). Most
        recent registration wins; providers return ``{}`` once their
        service is gone."""
        with self._breaker_lock:
            self._breaker_provider = fn

    def breaker_stats(self) -> dict:
        with self._breaker_lock:
            fn = self._breaker_provider
        if fn is None:
            return {}
        try:
            return dict(fn() or {})
        except Exception:
            return {}  # a torn-down service's reader must not 500 a scrape

    def count_breaker_transition(
        self, from_state: str, to_state: str, n: int = 1
    ) -> None:
        """``n`` breaker transitions ``from_state`` → ``to_state`` observed
        by the host mirror (state names: closed / open / half_open)."""
        if n <= 0:
            return
        key = (str(from_state), str(to_state))
        with self._breaker_lock:
            self._breaker_transitions[key] = (
                self._breaker_transitions.get(key, 0) + int(n)
            )

    def breaker_transition_totals(self) -> Dict[Tuple[str, str], int]:
        with self._breaker_lock:
            return dict(self._breaker_transitions)

    # -- push plane ---------------------------------------------------------
    def count_push_frame(self, type_name: str, n: int = 1) -> None:
        """``n`` rev-7 push frames of ``type_name`` handed to connection
        sinks (counted per delivery attempt that reached a sink, not per
        broadcast call — a hub with no connections counts nothing)."""
        if n <= 0:
            return
        with self._push_lock:
            self._push_frames[type_name] = (
                self._push_frames.get(type_name, 0) + int(n)
            )

    def count_push_revocation(self, n: int = 1) -> None:
        """``n`` leases recalled through pushed LEASE_REVOKE frames (one
        per revoked lease, regardless of how many connections heard it)."""
        if n <= 0:
            return
        with self._push_lock:
            self._push_revocations += int(n)

    def record_push_staleness(self, ms: float, n: int = 1) -> None:
        """One server-emit → client-apply staleness sample (ms), recorded
        by the client-side push apply off the frame's emit stamp."""
        self.push_staleness_ms.record(max(0.0, float(ms)), n)

    def push_frame_totals(self) -> Dict[str, int]:
        with self._push_lock:
            return dict(self._push_frames)

    @property
    def push_revocations_total(self) -> int:
        with self._push_lock:
            return self._push_revocations

    def register_push_provider(self, fn: Callable[[], dict]) -> None:
        """Install the zero-arg reader for the live push hub's state
        (``PushHub.stats`` shape: attached connections, per-type emit
        counts, drops). Most recent registration wins; providers return
        ``{}`` once their hub is gone."""
        with self._push_lock:
            self._push_provider = fn

    def push_stats(self) -> dict:
        with self._push_lock:
            fn = self._push_provider
        if fn is None:
            return {}
        try:
            return dict(fn() or {})
        except Exception:
            return {}  # a torn-down hub's reader must not 500 a scrape

    # -- snapshots ----------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON shape served by the ``clusterServerStats`` command — the
        same numbers the Prometheus surface renders."""
        with self._verdict_lock:
            verdicts = [
                {"verdict": v, "namespace": ns, "count": c}
                for (v, ns), c in sorted(self._verdicts.items())
            ]
        return {
            "verdicts": verdicts,
            "verdictsPerSec": self._rate.rate(),
            "fusedFramesTotal": self.fused_frames_total,
            "shedTotal": self.shed_total,
            "shedByReason": self.shed_totals(),
            "hostCopyBytesTotal": self.host_copy_bytes_total,
            "overlapSavedMsTotal": round(self.overlap_saved_ms_total, 3),
            "intakeShards": {
                str(k): v for k, v in sorted(self.shard_totals().items())
            },
            "sketch": self.sketch_stats(),
            "shm": self.shm_stats(),
            "lease": self.lease_stats(),
            "hier": self.hier_stats(),
            "outcome": self.outcome_stats(),
            "breaker": {
                **self.breaker_stats(),
                "transitions": [
                    {"from": f, "to": t, "count": c}
                    for (f, t), c in sorted(
                        self.breaker_transition_totals().items()
                    )
                ],
            },
            "push": {
                **self.push_stats(),
                "frames": self.push_frame_totals(),
                "revocations": self.push_revocations_total,
                "stalenessMs": self.push_staleness_ms.snapshot(),
            },
            "stages": {
                "queue_wait_ms": self.queue_wait_ms.snapshot(),
                "decide_ms": self.decide_ms.snapshot(),
                "write_ms": self.write_ms.snapshot(),
                "batch_size": self.batch_size.snapshot(),
                "intake_ms": self.intake_ms.snapshot(),
                "dispatch_ms": self.dispatch_ms.snapshot(),
                "fused_depth": self.fused_depth.snapshot(),
                "wait_assigned_ms": self.wait_assigned_ms.snapshot(),
            },
            "waitAssignedTotal": self.wait_assigned_total,
            "gauges": self._gauge_values(),
        }

    def stage_snapshot(self) -> Dict[str, dict]:
        """Trimmed per-stage view for bench artifacts: p50/p99/count."""
        out = {}
        for name, hist in (
            ("queue_wait_ms", self.queue_wait_ms),
            ("decide_ms", self.decide_ms),
            ("write_ms", self.write_ms),
            ("batch_size", self.batch_size),
            ("intake_ms", self.intake_ms),
            ("dispatch_ms", self.dispatch_ms),
            ("fused_depth", self.fused_depth),
            ("wait_assigned_ms", self.wait_assigned_ms),
        ):
            snap = hist.snapshot()
            out[name] = {
                "p50": snap["p50"], "p99": snap["p99"],
                "count": snap["count"],
                # per-lane busy time over the snapshot window — the serve
                # bench derives lane occupancy from sum/wall
                "sum": round(snap["sum"], 3),
            }
        out["fused_frames_total"] = self.fused_frames_total
        out["shed_total"] = self.shed_totals()
        out["host_copy_bytes_total"] = self.host_copy_bytes_total
        out["overlap_saved_ms_total"] = round(self.overlap_saved_ms_total, 3)
        out["intake_shards"] = {
            str(k): v for k, v in sorted(self.shard_totals().items())
        }
        return out

    def render(self) -> str:
        """``sentinel_server_*`` Prometheus exposition (no trailing
        newline; the exporter joins sections)."""
        lines = [
            "# HELP sentinel_server_verdicts_total Cluster token verdicts "
            "by class and namespace (cumulative).",
            "# TYPE sentinel_server_verdicts_total counter",
        ]
        with self._verdict_lock:
            items = sorted(self._verdicts.items())
        if items:
            for (verdict, ns), count in items:
                lines.append(
                    "sentinel_server_verdicts_total"
                    f'{{verdict="{_escape(verdict)}",'
                    f'namespace="{_escape(ns)}"}} {count}'
                )
        else:
            # zero-sample so the series exists on an idle server and rate()
            # queries don't gap at startup
            lines.append(
                'sentinel_server_verdicts_total{verdict="pass",'
                'namespace="default"} 0'
            )
        lines.append(
            "# HELP sentinel_server_verdicts_per_sec Verdicts per second "
            "(8s window)."
        )
        lines.append("# TYPE sentinel_server_verdicts_per_sec gauge")
        lines.append(f"sentinel_server_verdicts_per_sec {self._rate.rate():g}")
        lines.append(
            "# HELP sentinel_server_fused_frames_total Engine-batch frames "
            "folded into chained multi-frame device dispatches (cumulative)."
        )
        lines.append("# TYPE sentinel_server_fused_frames_total counter")
        lines.append(
            f"sentinel_server_fused_frames_total {self.fused_frames_total}"
        )
        lines.append(
            "# HELP sentinel_server_shed_total Requests refused (OVERLOAD) "
            "or dropped by the server, by reason (cumulative)."
        )
        lines.append("# TYPE sentinel_server_shed_total counter")
        shed = self.shed_totals()
        if shed:
            for reason, count in sorted(shed.items()):
                lines.append(
                    "sentinel_server_shed_total"
                    f'{{reason="{_escape(reason)}"}} {count}'
                )
        else:
            # zero-sample so the series exists before the first shed and
            # rate() queries don't gap when overload begins
            lines.append('sentinel_server_shed_total{reason="queue_full"} 0')
        lines.append(
            "# HELP sentinel_server_host_copy_bytes_total Host bytes "
            "copied on the serving path (arena staging + fusion concat)."
        )
        lines.append("# TYPE sentinel_server_host_copy_bytes_total counter")
        lines.append(
            f"sentinel_server_host_copy_bytes_total "
            f"{self.host_copy_bytes_total}"
        )
        lines.append(
            "# HELP sentinel_server_overlap_saved_ms_total Host prep/"
            "dispatch time spent while an earlier fused group was still "
            "computing on device — serialized time a depth-1 device lane "
            "would have added (ms, cumulative)."
        )
        lines.append("# TYPE sentinel_server_overlap_saved_ms_total counter")
        lines.append(
            "sentinel_server_overlap_saved_ms_total "
            f"{self.overlap_saved_ms_total:g}"
        )
        shards = self.shard_totals()
        if shards:
            for mname, skey, help_text in (
                ("shard_pulls_total", "pulls",
                 "Intake pulls handed to the device lane, per shard."),
                ("shard_requests_total", "requests",
                 "Requests pulled through each intake shard."),
                ("shard_intake_busy_ms_total", "busy_ms",
                 "Cumulative intake-lane busy time per shard (ms); "
                 "rate()/1000 is the shard's occupancy."),
            ):
                lines.append(f"# HELP sentinel_server_{mname} {help_text}")
                lines.append(f"# TYPE sentinel_server_{mname} counter")
                for shard, vals in sorted(shards.items()):
                    lines.append(
                        f'sentinel_server_{mname}{{shard="{shard}"}} '
                        f"{vals[skey]:g}"
                    )
        sketch = self.sketch_stats()
        lines.append(
            "# HELP sentinel_sketch_merges_total SALSA counter-pair merges "
            "in the param sketch, by rule slot (cumulative)."
        )
        lines.append("# TYPE sentinel_sketch_merges_total counter")
        by_slot = sketch.get("mergesBySlot") or {}
        if by_slot:
            for slot, count in sorted(
                (int(s), int(c)) for s, c in by_slot.items()
            ):
                lines.append(
                    f'sentinel_sketch_merges_total{{slot="{slot}"}} {count}'
                )
        else:
            # zero-sample so the series exists before the first merge (or on
            # the cms variant, which never merges)
            lines.append('sentinel_sketch_merges_total{slot="0"} 0')
        for mname, skey, help_text in (
            ("sentinel_sketch_slim_bytes_total", "slimBytes",
             "HBM bytes held by the SF slim twin of the param sketch "
             "(what per-tick replication deltas ship)."),
            ("sentinel_sketch_fat_bytes_total", "fatBytes",
             "HBM bytes held by the fat (update) param sketch."),
        ):
            lines.append(f"# HELP {mname} {help_text}")
            lines.append(f"# TYPE {mname} gauge")
            lines.append(f"{mname} {int(sketch.get(skey, 0) or 0)}")
        shm = self.shm_stats()
        for mname, skey, help_text in (
            ("shm_polls_total", "polls",
             "Shm ring poller wake-to-idle cycles (spin or futex) "
             "(cumulative)."),
            ("shm_doorbells_total", "doorbells",
             "Futex doorbell rings by co-located shm clients — each one is "
             "a syscall the steady state avoided elsewhere (cumulative)."),
            ("shm_ring_full_total", "ring_full",
             "Response-ring pushes dropped after the bounded wait because "
             "the client stopped draining (cumulative)."),
        ):
            lines.append(f"# HELP sentinel_server_{mname} {help_text}")
            lines.append(f"# TYPE sentinel_server_{mname} counter")
            lines.append(
                f"sentinel_server_{mname} {int(shm.get(skey, 0) or 0)}"
            )
        lease = self.lease_stats()
        for mname, skey, help_text in (
            ("sentinel_lease_granted_total", "granted",
             "Wire-rev-5 leases granted: short-TTL client-local admission "
             "slices charged to the LEASED window column (cumulative)."),
            ("sentinel_lease_renewed_total", "renewed",
             "Lease renewals: unused tokens credited, fresh slice granted "
             "(cumulative)."),
            ("sentinel_lease_returned_total", "returned",
             "Leases returned early by clients (cumulative)."),
            ("sentinel_lease_revoked_total", "revoked",
             "Leases ended server-side: TTL expiry, rule-reload drop, or "
             "MOVE recall (cumulative)."),
        ):
            lines.append(f"# HELP {mname} {help_text}")
            lines.append(f"# TYPE {mname} counter")
            lines.append(f"{mname} {int(lease.get(skey, 0) or 0)}")
        for mname, skey, help_text in (
            ("sentinel_lease_outstanding", "outstanding",
             "Live (unexpired, unreturned) leases right now."),
            ("sentinel_lease_outstanding_tokens", "outstanding_tokens",
             "Tokens currently delegated on live leases — the bound on "
             "crash over-admission."),
        ):
            lines.append(f"# HELP {mname} {help_text}")
            lines.append(f"# TYPE {mname} gauge")
            lines.append(f"{mname} {int(lease.get(skey, 0) or 0)}")
        hier = self.hier_stats()
        if hier:
            for mname, skey, help_text in (
                ("sentinel_hier_share_grants_total", "share_grants",
                 "Global-budget shares granted/regranted to pods by the "
                 "coordinator (cumulative)."),
                ("sentinel_hier_reconciles_total", "reconciles",
                 "Coordinator reconciliation passes: water-fill share "
                 "targets over reported demand (cumulative)."),
                ("sentinel_hier_demand_reports_total", "demand_reports",
                 "Per-tick pod demand reports received by the coordinator "
                 "(cumulative)."),
            ):
                lines.append(f"# HELP {mname} {help_text}")
                lines.append(f"# TYPE {mname} counter")
                lines.append(f"{mname} {int(hier.get(skey, 0) or 0)}")
            shares = hier.get("share_tokens") or {}
            if isinstance(shares, dict):
                lines.append(
                    "# HELP sentinel_hier_share_tokens Tokens of the global "
                    "budget currently provisioned to pod shares, per flow "
                    "(coordinator view when co-located, else this pod's own "
                    "share)."
                )
                lines.append("# TYPE sentinel_hier_share_tokens gauge")
                for fid in sorted(shares, key=str):
                    lines.append(
                        f'sentinel_hier_share_tokens{{flow="{fid}"}} '
                        f"{int(shares[fid] or 0)}"
                    )
        outcome = self.outcome_stats()
        for mname, skey, help_text in (
            ("sentinel_outcome_reported_total", "reported",
             "Completion outcomes accepted into the device outcome columns "
             "(OUTCOME_REPORT rows past validation) (cumulative)."),
            ("sentinel_outcome_exceptions_total", "exceptions",
             "Accepted completion outcomes flagged as exceptions "
             "(cumulative)."),
            ("sentinel_outcome_batches_total", "batches",
             "OUTCOME_REPORT batches ingested (cumulative)."),
            ("sentinel_outcome_rt_sum_ms_total", "rt_sum_ms",
             "Sum of accepted reported response times (ms, cumulative) — "
             "divide rates for the fleet RT average."),
        ):
            lines.append(f"# HELP {mname} {help_text}")
            lines.append(f"# TYPE {mname} counter")
            lines.append(f"{mname} {int(outcome.get(skey, 0) or 0)}")
        lines.append(
            "# HELP sentinel_outcome_dropped_total Reported outcomes "
            "rejected at the wire boundary, by reason (negative / "
            "non_finite / too_large / unknown_flow) (cumulative)."
        )
        lines.append("# TYPE sentinel_outcome_dropped_total counter")
        dropped = outcome.get("dropped") or {}
        if dropped:
            for reason, count in sorted(dropped.items()):
                lines.append(
                    "sentinel_outcome_dropped_total"
                    f'{{reason="{_escape(str(reason))}"}} {int(count)}'
                )
        else:
            # zero-sample so the series exists before the first bad report
            lines.append(
                'sentinel_outcome_dropped_total{reason="negative"} 0'
            )
        flows = outcome.get("flows") or {}
        if flows:
            for mname, fkey, help_text in (
                ("sentinel_flow_complete_qps", "complete_qps",
                 "Windowed reported completions per second, per flow "
                 "(device outcome columns)."),
                ("sentinel_flow_exception_qps", "exception_qps",
                 "Windowed reported exceptions per second, per flow."),
                ("sentinel_flow_rt_avg_ms", "rt_avg_ms",
                 "Windowed average reported RT per flow (ms)."),
                ("sentinel_flow_rt_p99_ms", "rt_p99_ms",
                 "Windowed p99 reported RT per flow (ms), from the "
                 "device-side log2 RT histogram (bucket upper edge)."),
            ):
                lines.append(f"# HELP {mname} {help_text}")
                lines.append(f"# TYPE {mname} gauge")
                for fid in sorted(flows, key=int):
                    vals = flows[fid] or {}
                    lines.append(
                        f'{mname}{{flow_id="{int(fid)}"}} '
                        f"{float(vals.get(fkey, 0.0) or 0.0):g}"
                    )
        lines.append(
            "# HELP sentinel_breaker_transitions_total Circuit-breaker "
            "state transitions observed by the host mirror, by edge "
            "(cumulative)."
        )
        lines.append("# TYPE sentinel_breaker_transitions_total counter")
        transitions = self.breaker_transition_totals()
        if transitions:
            for (frm, to), count in sorted(transitions.items()):
                lines.append(
                    "sentinel_breaker_transitions_total"
                    f'{{from="{_escape(frm)}",to="{_escape(to)}"}} {count}'
                )
        else:
            # zero-sample so the series exists before the first trip
            lines.append(
                'sentinel_breaker_transitions_total'
                '{from="closed",to="open"} 0'
            )
        lines.append(
            "# HELP sentinel_push_frames_total Wire-rev-7 push frames "
            "handed to connection sinks, by type (cumulative)."
        )
        lines.append("# TYPE sentinel_push_frames_total counter")
        push_frames = self.push_frame_totals()
        if push_frames:
            for tname, count in sorted(push_frames.items()):
                lines.append(
                    "sentinel_push_frames_total"
                    f'{{type="{_escape(tname)}"}} {count}'
                )
        else:
            # zero-sample so the series exists before the first push
            lines.append('sentinel_push_frames_total{type="lease_revoke"} 0')
        lines.append(
            "# HELP sentinel_push_revocations_total Leases recalled through "
            "pushed LEASE_REVOKE frames (cumulative)."
        )
        lines.append("# TYPE sentinel_push_revocations_total counter")
        lines.append(
            f"sentinel_push_revocations_total {self.push_revocations_total}"
        )
        lines.append(self.push_staleness_ms.render_prometheus(
            "sentinel_push_staleness_ms",
            "Server-emit to client-apply staleness of rev-7 push frames "
            "(ms), recorded by co-located client applies off the frame's "
            "emit stamp.",
        ))
        breaker = self.breaker_stats()
        br_flows = breaker.get("flows") or {}
        if br_flows:
            lines.append(
                "# HELP sentinel_breaker_state Circuit-breaker state per "
                "flow (0 = closed, 1 = open, 2 = half_open), read from the "
                "device BreakerState columns."
            )
            lines.append("# TYPE sentinel_breaker_state gauge")
            for fid in sorted(br_flows, key=int):
                vals = br_flows[fid] or {}
                lines.append(
                    f'sentinel_breaker_state{{flow_id="{int(fid)}"}} '
                    f"{int(vals.get('state_code', 0) or 0)}"
                )
        gauges = self._gauge_values()
        for name, help_text in (
            ("queue_depth", "Requests queued awaiting a device step."),
            ("inflight_batches", "Batches currently in the device pipeline."),
            ("connections", "Open client connections."),
            ("dispatch_lane_depth",
             "Decoded pulls queued between the intake and device lanes."),
            ("reply_lane_depth",
             "Dispatched batches queued between the device and reply lanes."),
            ("shm_ring_occupancy",
             "Fraction of shm request-ring slots occupied across attached "
             "segments (sampled; 0 when no shm door is serving)."),
            ("device_inflight",
             "Fused groups dispatched to the device and not yet "
             "materialized (bounded by max_device_inflight)."),
        ):
            lines.append(f"# HELP sentinel_server_{name} {help_text}")
            lines.append(f"# TYPE sentinel_server_{name} gauge")
            lines.append(f"sentinel_server_{name} {gauges[name]:g}")
        for name, help_text, hist in (
            ("sentinel_server_queue_wait_ms",
             "Enqueue-to-batch-drain wait per queue item (ms).",
             self.queue_wait_ms),
            ("sentinel_server_decide_ms",
             "Device decide step per batch, dispatch to materialized (ms).",
             self.decide_ms),
            ("sentinel_server_write_ms",
             "Host write-out per batch: verdict encode + socket write (ms).",
             self.write_ms),
            ("sentinel_server_batch_size",
             "Requests per device batch.",
             self.batch_size),
            ("sentinel_server_intake_ms",
             "Intake lane: front-door pull to handoff enqueue (ms).",
             self.intake_ms),
            ("sentinel_server_dispatch_ms",
             "Device lane: handoff drain to device dispatch issued (ms).",
             self.dispatch_ms),
            ("sentinel_server_fused_depth",
             "Engine-batch frames per fused device dispatch.",
             self.fused_depth),
            ("sentinel_server_wait_assigned_ms",
             "Wait assigned per SHOULD_WAIT verdict: paced admission or "
             "priority occupy delay (ms).",
             self.wait_assigned_ms),
        ):
            lines.append(hist.render_prometheus(name, help_text))
        lines.append(
            "# HELP sentinel_server_wait_assigned_total SHOULD_WAIT "
            "verdicts that carried a positive wait hint (cumulative)."
        )
        lines.append("# TYPE sentinel_server_wait_assigned_total counter")
        lines.append(
            f"sentinel_server_wait_assigned_total {self.wait_assigned_total}"
        )
        return "\n".join(lines)

    def reset(self) -> None:
        """Zero counters and histograms in place (gauge readers stay —
        their owners' lifecycles manage them). Benches call this between
        load points; tests via :func:`reset_server_metrics_for_tests`."""
        self.queue_wait_ms.reset()
        self.decide_ms.reset()
        self.write_ms.reset()
        self.batch_size.reset()
        self.intake_ms.reset()
        self.dispatch_ms.reset()
        self.fused_depth.reset()
        self.wait_assigned_ms.reset()
        with self._fused_lock:
            self._fused_frames = 0
        with self._verdict_lock:
            self._verdicts.clear()
            self._wait_assigned = 0
        with self._shed_lock:
            self._shed.clear()
        with self._shard_lock:
            self._shards.clear()
        with self._copy_lock:
            self._copy_bytes = 0
        with self._sketch_lock:
            self._sketch_provider = None
        with self._shm_lock:
            self._shm_provider = None
        with self._lease_lock:
            self._lease_provider = None
        with self._hier_lock:
            self._hier_provider = None
        with self._outcome_lock:
            self._outcome_provider = None
        with self._breaker_lock:
            self._breaker_provider = None
            self._breaker_transitions.clear()
        with self._push_lock:
            self._push_provider = None
            self._push_frames.clear()
            self._push_revocations = 0
        self.push_staleness_ms.reset()
        self._rate.reset()


_SINGLETON = ServerMetrics()


def server_metrics() -> ServerMetrics:
    """The process-wide server metrics registry."""
    return _SINGLETON


def reset_server_metrics_for_tests() -> None:
    _SINGLETON.reset()
    # the SLO plane, metric timeline, and flight-recorder rings are fed off
    # this registry's paths; a test that resets one expects all to start clean
    from sentinel_tpu.metrics.timeline import reset_timeline_for_tests
    from sentinel_tpu.trace import ring as _trace_ring
    from sentinel_tpu.trace.slo import reset_slo_plane_for_tests

    reset_slo_plane_for_tests()
    reset_timeline_for_tests()
    _trace_ring.reset_for_tests()
