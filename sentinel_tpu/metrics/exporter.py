"""Prometheus-format metric exporter (analog of ``sentinel-metric-exporter``).

The reference exposes one JMX MBean per resource, refreshed by a collector
(``exporter/jmx/{JMXMetricExporter,MBeanRegistry,MetricBeanWriter}.java``);
the Python-ecosystem equivalent is a Prometheus scrape endpoint. Rendering
happens at scrape time straight off the live ``ClusterNode`` windows — no
refresh thread needed (Prometheus pulls; JMX needed push-into-beans).

Exposed series (labels: ``resource``):

- ``sentinel_pass_qps`` / ``sentinel_block_qps`` / ``sentinel_success_qps``
  / ``sentinel_exception_qps`` — 1s-window rates
- ``sentinel_rt_avg_ms`` — average response time over the window
- ``sentinel_concurrency`` — current in-flight entries

Alongside the window gauges, two cumulative ``counter`` series
(``sentinel_pass_total`` / ``sentinel_block_total``, fed by a built-in
:class:`MetricExtension` on the entry hot path) give scrapers proper
``rate()``-able totals, and the body ends with the token server's
``sentinel_server_*`` section (:mod:`sentinel_tpu.metrics.server`). The
exposition is 0.0.4: newline-terminated, no ``# EOF`` marker (that is
OpenMetrics 1.0; sending it under the 0.0.4 content type breaks strict
parsers).

Serve standalone via :class:`PrometheusExporter` (its own port, like the
JMX exporter's own registry), or mount :func:`render` under any existing
HTTP surface (the command center registers it at ``/metric/prometheus``).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.httpd import HttpService, Response
from sentinel_tpu.datasource import base as _datasource_base
from sentinel_tpu.local import chain as _chain
from sentinel_tpu.metrics import extension as _ext
from sentinel_tpu.metrics.ha import ha_metrics
from sentinel_tpu.metrics.server import server_metrics

_HELP = """\
# HELP sentinel_pass_qps Admitted requests per second (1s sliding window).
# TYPE sentinel_pass_qps gauge
# HELP sentinel_block_qps Blocked requests per second (1s sliding window).
# TYPE sentinel_block_qps gauge
# HELP sentinel_success_qps Completed requests per second (1s sliding window).
# TYPE sentinel_success_qps gauge
# HELP sentinel_exception_qps Business exceptions per second (1s sliding window).
# TYPE sentinel_exception_qps gauge
# HELP sentinel_rt_avg_ms Average response time over the 1s window.
# TYPE sentinel_rt_avg_ms gauge
# HELP sentinel_concurrency Current in-flight entries.
# TYPE sentinel_concurrency gauge
"""


def _escape(label: str) -> str:
    return label.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _CumulativeCounters(_ext.MetricExtension):
    """Built-in extension feeding ``sentinel_pass_total`` /
    ``sentinel_block_total`` — the window gauges answer "how fast right
    now", these answer "how much since start", which is what Prometheus
    ``rate()``/``increase()`` want as input."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pass: Dict[str, int] = {}
        self._block: Dict[str, int] = {}

    def add_pass(self, resource: str, n: int, args) -> None:
        with self._lock:
            self._pass[resource] = self._pass.get(resource, 0) + n

    def add_block(self, resource: str, n: int, origin, error, args) -> None:
        with self._lock:
            self._block[resource] = self._block.get(resource, 0) + n

    def totals(self) -> Tuple[Dict[str, int], Dict[str, int]]:
        with self._lock:
            return dict(self._pass), dict(self._block)

    def reset(self) -> None:
        with self._lock:
            self._pass.clear()
            self._block.clear()


_COUNTERS = _CumulativeCounters()
_ENSURE_LOCK = threading.Lock()


def _ensure_counters_registered() -> None:
    """(Re)register the counter extension. ``clear_extensions_for_tests``
    wipes the registry between tests; re-arming at render time (with a data
    reset, so each re-arm starts a fresh cumulative epoch) keeps production
    monotonic and tests deterministic."""
    with _ENSURE_LOCK:
        if _COUNTERS not in _ext.get_extensions():
            _COUNTERS.reset()
            _ext.register_extension(_COUNTERS)


_ensure_counters_registered()

_COUNTER_HELP = """\
# HELP sentinel_pass_total Admitted requests since process start.
# TYPE sentinel_pass_total counter
# HELP sentinel_block_total Blocked requests since process start.
# TYPE sentinel_block_total counter\
"""

_START_TIME_S = time.time()


def build_info() -> Dict[str, str]:
    """Identity labels for ``sentinel_build_info`` — also stamped into
    bench artifacts and black-box dumps so any saved document names the
    build that produced it."""
    from sentinel_tpu import __version__
    from sentinel_tpu.cluster.protocol import WIRE_REV

    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        backend = "unavailable"
    return {
        "version": __version__,
        "wire_rev": str(WIRE_REV),
        "jax_backend": backend,
    }


def uptime_seconds() -> float:
    """Seconds since this process imported the exporter (the scrape
    surface's lifetime — counter resets correlate with this going to 0)."""
    return time.time() - _START_TIME_S


def _render_build_info() -> str:
    info = build_info()
    labels = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(info.items()))
    return (
        "# HELP sentinel_build_info Build identity (constant 1; labels "
        "carry version, wire rev, jax backend).\n"
        "# TYPE sentinel_build_info gauge\n"
        f"sentinel_build_info{{{labels}}} 1\n"
        "# HELP sentinel_server_uptime_seconds Seconds since process "
        "start (exporter import).\n"
        "# TYPE sentinel_server_uptime_seconds gauge\n"
        f"sentinel_server_uptime_seconds {uptime_seconds():g}"
    )


def render(now_ms: Optional[int] = None) -> str:
    """Prometheus text exposition: per-resource window gauges + cumulative
    counters + the token server's ``sentinel_server_*`` section (which
    carries the ``sentinel_sketch_*`` param-sketch series)."""
    _ensure_counters_registered()
    now = _clock.now_ms() if now_ms is None else now_ms
    lines = [_HELP.rstrip("\n")]
    node_map = _chain.cluster_node_map()
    for name, node in sorted(node_map.items()):
        label = f'{{resource="{_escape(name)}"}}'
        success = node.success_qps(now)
        avg_rt = node.avg_rt(now)
        for metric, value in (
            ("sentinel_pass_qps", node.pass_qps(now)),
            ("sentinel_block_qps", node.block_qps(now)),
            ("sentinel_success_qps", success),
            ("sentinel_exception_qps", node.exception_qps(now)),
            ("sentinel_rt_avg_ms", avg_rt),
            ("sentinel_concurrency", node.cur_thread_num),
        ):
            lines.append(f"{metric}{label} {value:g}")
    passed, blocked = _COUNTERS.totals()
    lines.append(_COUNTER_HELP)
    for name in sorted(set(node_map) | set(passed) | set(blocked)):
        label = f'{{resource="{_escape(name)}"}}'
        lines.append(f"sentinel_pass_total{label} {passed.get(name, 0)}")
        lines.append(f"sentinel_block_total{label} {blocked.get(name, 0)}")
    lines.append(
        "# HELP sentinel_datasource_refresh_failures_total Failed rule "
        "datasource refreshes (read or parse), by datasource class."
    )
    lines.append("# TYPE sentinel_datasource_refresh_failures_total counter")
    failures = _datasource_base.refresh_failure_totals()
    if failures:
        for name, count in sorted(failures.items()):
            lines.append(
                "sentinel_datasource_refresh_failures_total"
                f'{{source="{_escape(name)}"}} {count}'
            )
    else:
        lines.append(
            'sentinel_datasource_refresh_failures_total{source=""} 0'
        )
    lines.append(server_metrics().render())
    lines.append(ha_metrics().render())
    # client-side receive accounting (import deferred: cluster.client pulls
    # in the token-service stack, which this module must not load eagerly)
    from sentinel_tpu.cluster import client as _client

    lines.append(
        "# HELP sentinel_client_recv_bytes_total Bytes received from token "
        "servers by this process's client readers."
    )
    lines.append("# TYPE sentinel_client_recv_bytes_total counter")
    lines.append(
        f"sentinel_client_recv_bytes_total "
        f"{_client.client_recv_bytes_total()}"
    )
    lines.append(
        "# HELP sentinel_client_recv_buf_grows_total Growable receive "
        "buffer expansions across client readers."
    )
    lines.append("# TYPE sentinel_client_recv_buf_grows_total counter")
    lines.append(
        f"sentinel_client_recv_buf_grows_total "
        f"{_client.client_recv_buf_grows_total()}"
    )
    lines.append(
        "# HELP sentinel_client_unknown_frames_total Frames with a type "
        "byte this build doesn't speak, skipped by client readers instead "
        "of dropping the connection (mixed-rev rollout canary)."
    )
    lines.append("# TYPE sentinel_client_unknown_frames_total counter")
    lines.append(
        f"sentinel_client_unknown_frames_total "
        f"{_client.client_unknown_frames_total()}"
    )
    # DCN-tier aggregation health (import deferred for the same reason)
    from sentinel_tpu.cluster import namespaces as _namespaces

    lines.append(
        "# HELP sentinel_assignment_snapshot_errors_total Pod metric "
        "snapshots that failed (raised or were malformed) during "
        "cross-pod aggregation."
    )
    lines.append(
        "# TYPE sentinel_assignment_snapshot_errors_total counter"
    )
    lines.append(
        f"sentinel_assignment_snapshot_errors_total "
        f"{_namespaces.snapshot_error_total()}"
    )
    lines.append(
        "# HELP sentinel_assignment_move_dedup_total Mid-MOVE duplicate "
        "flow copies dropped during cross-pod aggregation (source pod "
        "still reporting a moved namespace's frozen window)."
    )
    lines.append(
        "# TYPE sentinel_assignment_move_dedup_total counter"
    )
    lines.append(
        f"sentinel_assignment_move_dedup_total "
        f"{_namespaces.move_dedup_total()}"
    )
    # per-tenant SLO plane (burn rates, latency, shed attribution)
    from sentinel_tpu.trace.slo import slo_plane

    lines.append(slo_plane().render())
    lines.append(_render_build_info())
    return "\n".join(lines) + "\n"


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class PrometheusExporter:
    """Standalone scrape endpoint: ``GET /metrics``."""

    def __init__(self, host: str = "0.0.0.0", port: int = 9092):
        self._service = HttpService(self._route, host, port, "prom-exporter")

    def _route(self, method: str, path: str, params: dict, body: str) -> Response:
        if method == "GET" and path in ("metrics", ""):
            return (200, render(), CONTENT_TYPE)
        return (404, "not found\n", "text/plain")

    def start(self) -> "PrometheusExporter":
        self._service.start()
        return self

    @property
    def port(self) -> int:
        return self._service.port

    def stop(self) -> None:
        self._service.stop()
