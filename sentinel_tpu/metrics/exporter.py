"""Prometheus-format metric exporter (analog of ``sentinel-metric-exporter``).

The reference exposes one JMX MBean per resource, refreshed by a collector
(``exporter/jmx/{JMXMetricExporter,MBeanRegistry,MetricBeanWriter}.java``);
the Python-ecosystem equivalent is a Prometheus scrape endpoint. Rendering
happens at scrape time straight off the live ``ClusterNode`` windows — no
refresh thread needed (Prometheus pulls; JMX needed push-into-beans).

Exposed series (labels: ``resource``):

- ``sentinel_pass_qps`` / ``sentinel_block_qps`` / ``sentinel_success_qps``
  / ``sentinel_exception_qps`` — 1s-window rates
- ``sentinel_rt_avg_ms`` — average response time over the window
- ``sentinel_concurrency`` — current in-flight entries

Serve standalone via :class:`PrometheusExporter` (its own port, like the
JMX exporter's own registry), or mount :func:`render` under any existing
HTTP surface (the command center registers it at ``/metric/prometheus``).
"""

from __future__ import annotations

from typing import Optional

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.httpd import HttpService, Response
from sentinel_tpu.local import chain as _chain

_HELP = """\
# HELP sentinel_pass_qps Admitted requests per second (1s sliding window).
# TYPE sentinel_pass_qps gauge
# HELP sentinel_block_qps Blocked requests per second (1s sliding window).
# TYPE sentinel_block_qps gauge
# HELP sentinel_success_qps Completed requests per second (1s sliding window).
# TYPE sentinel_success_qps gauge
# HELP sentinel_exception_qps Business exceptions per second (1s sliding window).
# TYPE sentinel_exception_qps gauge
# HELP sentinel_rt_avg_ms Average response time over the 1s window.
# TYPE sentinel_rt_avg_ms gauge
# HELP sentinel_concurrency Current in-flight entries.
# TYPE sentinel_concurrency gauge
"""


def _escape(label: str) -> str:
    return label.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def render(now_ms: Optional[int] = None) -> str:
    """Prometheus text exposition of every resource's live window stats."""
    now = _clock.now_ms() if now_ms is None else now_ms
    lines = [_HELP]
    for name, node in sorted(_chain.cluster_node_map().items()):
        label = f'{{resource="{_escape(name)}"}}'
        success = node.success_qps(now)
        avg_rt = node.avg_rt(now)
        for metric, value in (
            ("sentinel_pass_qps", node.pass_qps(now)),
            ("sentinel_block_qps", node.block_qps(now)),
            ("sentinel_success_qps", success),
            ("sentinel_exception_qps", node.exception_qps(now)),
            ("sentinel_rt_avg_ms", avg_rt),
            ("sentinel_concurrency", node.cur_thread_num),
        ):
            lines.append(f"{metric}{label} {value:g}")
    return "\n".join(lines) + "\n"


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class PrometheusExporter:
    """Standalone scrape endpoint: ``GET /metrics``."""

    def __init__(self, host: str = "0.0.0.0", port: int = 9092):
        self._service = HttpService(self._route, host, port, "prom-exporter")

    def _route(self, method: str, path: str, params: dict, body: str) -> Response:
        if method == "GET" and path in ("metrics", ""):
            return (200, render(), CONTENT_TYPE)
        return (404, "not found\n", "text/plain")

    def start(self) -> "PrometheusExporter":
        self._service.start()
        return self

    @property
    def port(self) -> int:
        return self._service.port

    def stop(self) -> None:
        self._service.stop()
