"""Opt-in JAX profiler control for a live server.

``TokenServer``/``NativeTokenServer`` own one :class:`ProfilerHook` each so
the ``cluster/server/profiler`` command can start/stop a device trace on a
serving process without a restart (the always-on ``profile_dir`` /
``SENTINEL_PROFILE_DIR`` path stays — this is the on-demand variant).
jax.profiler allows ONE active trace per process; the hook serializes
start/stop and reports a clean error instead of the profiler's RuntimeError
when a trace is already running.
"""

from __future__ import annotations

import threading
from typing import Optional

from sentinel_tpu.core.log import record_log


class ProfilerHook:
    def __init__(self, default_dir: Optional[str] = None):
        self._lock = threading.Lock()
        self.default_dir = default_dir
        self.trace_dir: Optional[str] = None

    @property
    def active(self) -> bool:
        return self.trace_dir is not None

    def start(self, trace_dir: Optional[str] = None) -> dict:
        with self._lock:
            if self.trace_dir is not None:
                return {
                    "error": f"already profiling to {self.trace_dir}",
                    "profiling": True, "dir": self.trace_dir,
                }
            target = trace_dir or self.default_dir
            if not target:
                return {"error": "trace dir required (dir= or profile_dir)"}
            import jax.profiler

            jax.profiler.start_trace(target)
            self.trace_dir = target
            record_log.info("profiler trace started → %s", target)
            return {"profiling": True, "dir": target}

    def stop(self) -> dict:
        with self._lock:
            if self.trace_dir is None:
                return {"error": "not profiling", "profiling": False}
            target, self.trace_dir = self.trace_dir, None
            import jax.profiler

            try:
                jax.profiler.stop_trace()
            except Exception:
                record_log.exception("profiler stop failed")
                return {"error": "profiler stop failed", "dir": target,
                        "profiling": False}
            record_log.info("profiler trace written → %s", target)
            return {"profiling": False, "dir": target}

    def status(self) -> dict:
        return {"profiling": self.active, "dir": self.trace_dir}


_DEFAULT = ProfilerHook()


def default_hook() -> ProfilerHook:
    """Process-wide hook for the command surface when no token server is
    embedded (profiles whatever JAX work this process runs)."""
    return _DEFAULT
