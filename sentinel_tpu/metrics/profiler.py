"""Opt-in JAX profiler control for a live server.

``TokenServer``/``NativeTokenServer`` own one :class:`ProfilerHook` each so
the ``cluster/server/profiler`` command can start/stop a device trace on a
serving process without a restart (the always-on ``profile_dir`` /
``SENTINEL_PROFILE_DIR`` path stays — this is the on-demand variant).
jax.profiler allows ONE active trace per process; the hook serializes
start/stop and reports a clean error instead of the profiler's RuntimeError
when a trace is already running.

The hook also drives the host-side flight recorder (``sentinel_tpu.trace``):
``start`` arms the rings at full sampling so every request in the profiled
window is traceable end-to-end, and ``stop`` writes the assembled spans as
``trace-spans-<ms>.json`` next to the XProf trace — one command captures
BOTH the device timeline and the host pipeline stages that fed it. A window
where the device trace shows idle gaps and the span artifact shows frames
parked between ``enqueue`` and ``dispatch`` is the host starving the
device; without the span half that diagnosis needed a second tool.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.log import record_log


class ProfilerHook:
    def __init__(self, default_dir: Optional[str] = None):
        self._lock = threading.Lock()
        self.default_dir = default_dir
        self.trace_dir: Optional[str] = None
        self._was_armed = False

    @property
    def active(self) -> bool:
        return self.trace_dir is not None

    def start(self, trace_dir: Optional[str] = None) -> dict:
        from sentinel_tpu.trace import ring as trace_ring

        with self._lock:
            if self.trace_dir is not None:
                return {
                    "error": f"already profiling to {self.trace_dir}",
                    "profiling": True, "dir": self.trace_dir,
                }
            target = trace_dir or self.default_dir
            if not target:
                return {"error": "trace dir required (dir= or profile_dir)"}
            import jax.profiler

            jax.profiler.start_trace(target)
            self.trace_dir = target
            # an operator already arming a sampled recorder keeps it; the
            # profiled window itself records everything
            self._was_armed = trace_ring.ARMED
            trace_ring.arm(sample=1.0)
            record_log.info("profiler trace started → %s", target)
            return {"profiling": True, "dir": target}

    def stop(self) -> dict:
        from sentinel_tpu.trace import ring as trace_ring
        from sentinel_tpu.trace import spans as trace_spans

        with self._lock:
            if self.trace_dir is None:
                return {"error": "not profiling", "profiling": False}
            target, self.trace_dir = self.trace_dir, None
            import jax.profiler

            spans_path: Optional[str] = None
            try:
                spans_path = trace_spans.write_artifact(
                    os.path.join(
                        target, f"trace-spans-{_clock.now_ms()}.json"
                    )
                )
            except Exception:
                record_log.exception("span artifact write failed")
            if not self._was_armed:
                trace_ring.disarm()
            try:
                jax.profiler.stop_trace()
            except Exception:
                record_log.exception("profiler stop failed")
                return {"error": "profiler stop failed", "dir": target,
                        "profiling": False, "spans": spans_path}
            record_log.info("profiler trace written → %s", target)
            return {"profiling": False, "dir": target, "spans": spans_path}

    def status(self) -> dict:
        return {"profiling": self.active, "dir": self.trace_dir}


_DEFAULT = ProfilerHook()


def default_hook() -> ProfilerHook:
    """Process-wide hook for the command surface when no token server is
    embedded (profiles whatever JAX work this process runs)."""
    return _DEFAULT
