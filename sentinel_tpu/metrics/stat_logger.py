"""Rolling stat logger — the EagleEye analog.

The reference embeds a high-throughput keyed stat logger
(``eagleeye/EagleEye.java:25``, ``StatLogger.java:24,85``,
``StatRollingData``, ``EagleEyeRollingFileAppender``, ``TokenBucket``) used
for the block log (``slots/logger/EagleEyeLogUtil.java``) and the cluster
server's stat logs (``ClusterServerStatLogUtil``). Model: callers ``stat()``
keyed counters on the hot path; a time-window roll swaps the accumulation
map and a writer thread appends one line per key to a size-rolled file:

    timestamp|key1,key2|count          (count-only entries)
    timestamp|key1,key2|count,total    (value entries, e.g. rt sums)

Differences from the JVM design: accumulation is a dict under one lock
instead of CHM+LongAdder (host Python is not the hot path here — the hot
path is on-device; these logs serve the *control* plane), and the roll is
driven lazily by writers plus an explicit ``flush()``, with time from the
process clock so tests drive it with ``ManualClock``.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.log import record_log


def default_stat_log_dir() -> str:
    return os.environ.get("SENTINEL_LOG_DIR") or os.path.expanduser("~/logs/csp")


class RollingFileWriter:
    """Append-only writer with size-based rolling (``EagleEyeRollingFileAppender``):
    at ``max_bytes`` the file rotates to ``.1`` … ``.N`` (oldest dropped)."""

    def __init__(self, path: str, max_bytes: int = 300 * 1024 * 1024,
                 max_backups: int = 3):
        self.path = path
        self.max_bytes = max_bytes
        self.max_backups = max_backups
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def write_lines(self, lines: List[str]) -> None:
        if not lines:
            return
        data = "".join(line + "\n" for line in lines)
        with self._lock:
            try:
                if (
                    os.path.exists(self.path)
                    and os.path.getsize(self.path) + len(data) > self.max_bytes
                ):
                    self._roll()
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(data)
            except OSError as e:
                record_log.warning("stat log write failed: %s", e)

    def _roll(self) -> None:
        oldest = f"{self.path}.{self.max_backups}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.max_backups - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        if os.path.exists(self.path):
            os.replace(self.path, f"{self.path}.1")


class StatLogger:
    """Keyed counter accumulation over fixed time windows.

    ``stat(*key)`` adds to the current window; when a write lands in a new
    window (or ``flush()`` is called) the previous window's map is sealed
    and written out. ``max_entries`` bounds per-window cardinality the way
    EagleEye's entry cap does — overflow keys are dropped and counted in a
    ``__overflow__`` line rather than growing without bound.
    """

    def __init__(
        self,
        name: str,
        interval_ms: int = 1_000,
        log_dir: Optional[str] = None,
        max_bytes: int = 300 * 1024 * 1024,
        max_backups: int = 3,
        max_entries: int = 20_000,
    ):
        self.name = name
        self.interval_ms = interval_ms
        self.max_entries = max_entries
        log_dir = log_dir or default_stat_log_dir()
        self.writer = RollingFileWriter(
            os.path.join(log_dir, f"{name}.log"), max_bytes, max_backups
        )
        self._lock = threading.Lock()
        self._window_start = 0
        self._data: Dict[Tuple[str, ...], List[float]] = {}
        self._overflow = 0

    def stat(self, *key: str, count: int = 1, value: Optional[float] = None):
        now = _clock.now_ms()
        start = now - now % self.interval_ms
        sealed = None
        with self._lock:
            if start != self._window_start:
                sealed = self._seal(start)
            slot = self._data.get(key)
            if slot is None:
                if len(self._data) >= self.max_entries:
                    self._overflow += count
                    slot = None
                else:
                    slot = self._data[key] = [0.0, 0.0, False]
            if slot is not None:
                slot[0] += count
                if value is not None:
                    slot[1] += value
                    slot[2] = True  # any valued stat upgrades the line format
            if sealed:
                # enqueue under the lock: seal order == enqueue order ==
                # file order (the put itself is non-blocking)
                self._write_async(sealed)

    def _write_async(self, lines: List[str]) -> None:
        """Hand a sealed window to the shared writer thread — ``stat()``
        sits on the serving hot path (called per micro-batch by the token
        server), so the file open/roll/append must not stall the caller
        (this is the role EagleEye's dedicated writer thread plays)."""
        _writer_queue_put(self.writer, lines)

    def _seal(self, new_start: int) -> List[str]:
        """Format + clear the finished window. Caller holds the lock."""
        lines = []
        ts = self._window_start
        for key, (count, total, has_value) in self._data.items():
            joined = ",".join(key)
            if has_value:
                lines.append(f"{ts}|{joined}|{int(count)},{total:g}")
            else:
                lines.append(f"{ts}|{joined}|{int(count)}")
        if self._overflow:
            lines.append(f"{ts}|__overflow__|{self._overflow}")
        self._data = {}
        self._overflow = 0
        self._window_start = new_start
        return lines

    def flush(self) -> None:
        """Seal and write the current window immediately (shutdown/tests).

        Routes through the same writer queue as async seals (so the file
        stays in seal order) and waits until everything queued so far —
        including this window — is on disk. If the writer queue is wedged
        (stalled disk), the sealed window is written synchronously as a
        last resort so an explicit flush never silently drops data."""
        with self._lock:
            sealed = self._seal(self._window_start)
        if sealed:
            if not _writer_queue_put(self.writer, sealed):
                self.writer.write_lines(sealed)
                return
        _writer_drain_barrier()


# One shared background writer drains sealed windows for every StatLogger
# (lazily started, daemon — dies with the process). stat()'s hot-path seals
# are fire-and-forget (dropped with a warning if the queue is wedged);
# flush() falls back to a synchronous write so explicit flushes lose
# nothing.
_writer_queue: Optional["queue.Queue"] = None
_writer_lock = threading.Lock()


def _writer_queue_put(writer: RollingFileWriter, lines: List[str]) -> bool:
    """Enqueue for the shared writer thread; False if the queue is full."""
    global _writer_queue
    if _writer_queue is None:
        with _writer_lock:
            if _writer_queue is None:
                import queue as _queue_mod

                q: "queue.Queue" = _queue_mod.Queue(maxsize=1024)

                def drain() -> None:
                    while True:
                        w, ls = q.get()
                        if w is None:  # flush barrier
                            ls.set()
                            continue
                        try:
                            w.write_lines(ls)
                        except Exception:  # never kill the writer thread
                            record_log.exception("stat writer failed")

                threading.Thread(
                    target=drain, name="sentinel-stat-writer", daemon=True
                ).start()
                _writer_queue = q
    try:
        _writer_queue.put_nowait((writer, lines))
        return True
    except Exception:
        # queue full — a stalled disk must not back-pressure the serving
        # path; hot-path callers drop the window (EagleEye drops on
        # overload too), flush() falls back to a synchronous write
        record_log.warning("stat writer queue full; dropped a window")
        return False


def _writer_drain_barrier(timeout_s: float = 5.0) -> None:
    """Block until every window queued so far has been written (bounded:
    a stalled disk makes this a best-effort wait, never a hang)."""
    if _writer_queue is None:
        return
    import queue as _queue_mod

    done = threading.Event()
    try:
        _writer_queue.put((None, done), timeout=timeout_s)
    except _queue_mod.Full:
        return  # writer is wedged; don't hang shutdown on it
    done.wait(timeout_s)


@dataclass
class StatEntry:
    """One parsed stat-log line (``ts|key1,key2|count[,total]``)."""

    timestamp_ms: int
    key: Tuple[str, ...]
    count: int
    total: Optional[float] = None

    @classmethod
    def from_line(cls, line: str) -> "StatEntry":
        ts_s, joined, tail = line.rstrip("\n").split("|", 2)
        if "," in tail:
            count_s, total_s = tail.split(",", 1)
            return cls(int(ts_s), tuple(joined.split(",")),
                       int(count_s), float(total_s))
        return cls(int(ts_s), tuple(joined.split(",")), int(tail))


class StatLogSearcher:
    """Time-range search over one stat log's rotation chain.

    The complement ``RollingFileWriter`` lacks: reads ``<path>.N`` …
    ``<path>.1`` then ``<path>`` (oldest backup first — ``_roll`` shifts
    upward, so higher suffix = older data) and yields entries whose
    window start falls in ``[begin_ms, end_ms]``. Mirrors what
    ``MetricSearcher`` does for the per-resource metric log, minus the
    ``.idx`` seek: stat files are written a whole sealed window at a
    time, so a linear scan is the honest cost model.
    """

    def __init__(self, path: str, max_backups: int = 3):
        self.path = path
        self.max_backups = max_backups

    def _chain(self) -> List[str]:
        paths = [f"{self.path}.{i}"
                 for i in range(self.max_backups, 0, -1)]
        paths.append(self.path)
        return [p for p in paths if os.path.exists(p)]

    def find(self, begin_ms: int, end_ms: int,
             key_prefix: Optional[Tuple[str, ...]] = None,
             max_lines: int = 12_000) -> List[StatEntry]:
        out: List[StatEntry] = []
        for path in self._chain():
            try:
                with open(path, "r", encoding="utf-8") as f:
                    for line in f:
                        try:
                            entry = StatEntry.from_line(line)
                        except (ValueError, IndexError):
                            continue  # torn tail from a crash mid-append
                        if not begin_ms <= entry.timestamp_ms <= end_ms:
                            continue
                        if key_prefix and \
                                entry.key[:len(key_prefix)] != key_prefix:
                            continue
                        out.append(entry)
                        if len(out) >= max_lines:
                            return out
            except OSError:
                continue
        return out


def search_stat_log(name: str, begin_ms: int, end_ms: int,
                    key_prefix: Optional[Tuple[str, ...]] = None,
                    log_dir: Optional[str] = None,
                    max_backups: int = 3) -> List[StatEntry]:
    """Range-search a named stat log (e.g. ``CLUSTER_LOG`` for the
    ``outcome_reported`` lines) without needing the live logger."""
    log_dir = log_dir or default_stat_log_dir()
    return StatLogSearcher(
        os.path.join(log_dir, f"{name}.log"), max_backups=max_backups
    ).find(begin_ms, end_ms, key_prefix=key_prefix)


_registry_lock = threading.Lock()
_registry: Dict[str, StatLogger] = {}


def stat_logger(name: str, **kwargs) -> StatLogger:
    """Process-wide named loggers (``EagleEye.statLoggerBuilder`` registry)."""
    with _registry_lock:
        logger = _registry.get(name)
        if logger is None:
            logger = _registry[name] = StatLogger(name, **kwargs)
        return logger


def reset_registry_for_tests() -> None:
    with _registry_lock:
        _registry.clear()


# -- the two built-in stat logs -------------------------------------------

BLOCK_LOG = "sentinel-block-record"  # EagleEyeLogUtil's block.log analog
CLUSTER_LOG = "sentinel-cluster-server-stat"  # ClusterServerStatLogUtil


def log_block(resource: str, origin: str, rule_type: str, count: int = 1):
    """``EagleEyeLogUtil.log(resource, exceptionName, ruleLimitApp, origin,
    count)`` — one aggregated line per (resource, origin, rule) per second."""
    stat_logger(BLOCK_LOG).stat(resource, origin or "-", rule_type, count=count)


def log_cluster(event: str, flow_id: int = -1, count: int = 1):
    key = (event,) if flow_id < 0 else (event, str(flow_id))
    stat_logger(CLUSTER_LOG).stat(*key, count=count)
