"""Fixed-bucket latency histogram (log-spaced, lock-light, Prometheus-ready).

The serving-path stage timers need a recorder that is cheap enough to sit on
the hot path (one bisect + three integer adds per observation — the SALSA /
"Give Me Some Slack" lesson that always-on measurement must cost less than
the thing measured), yet rich enough for both a Prometheus ``histogram``
exposition (cumulative ``_bucket{le=...}`` counts) and direct p50/p90/p99
snapshot reads for the stats command and the bench artifact.

Buckets are fixed at construction (default: log-spaced, ``per_decade`` steps
per factor of 10), so recording never allocates and two snapshots diff
cleanly. Quantiles interpolate linearly inside the target bucket; the
overflow (+Inf) bucket clamps to the largest observed value so a stray
outlier reports its real magnitude instead of "somewhere above the range".
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left
from typing import Dict, Optional, Sequence, Tuple


def log_buckets(
    lo: float, hi: float, per_decade: int = 5
) -> Tuple[float, ...]:
    """Geometric bucket upper bounds from ``lo`` up to at least ``hi``,
    ``per_decade`` bounds per factor of 10 (e.g. 0.01..1000ms × 5/decade →
    26 bounds)."""
    if lo <= 0 or hi <= lo or per_decade < 1:
        raise ValueError(f"bad bucket spec lo={lo} hi={hi}/{per_decade}")
    ratio = 10.0 ** (1.0 / per_decade)
    bounds = []
    b = float(lo)
    # round to 4 significant digits so the rendered `le` labels stay stable
    # and human-readable (0.06309573444801933 → 0.0631)
    while b < hi * (1.0 - 1e-9):
        bounds.append(float(f"{b:.4g}"))
        b *= ratio
    bounds.append(float(f"{hi:.4g}"))
    return tuple(bounds)


class LatencyHistogram:
    """Thread-safe fixed-bucket histogram of a nonnegative quantity.

    ``record`` does the bucket search outside the lock and holds it only for
    three scalar updates — contended recorders serialize for ~100ns, not for
    a bisect. Values above the last bound land in the +Inf overflow bucket.
    """

    __slots__ = (
        "bounds", "_counts", "_count", "_sum", "_max", "_lock",
    )

    def __init__(
        self,
        lo: float = 0.001,
        hi: float = 10_000.0,
        per_decade: int = 5,
        bounds: Optional[Sequence[float]] = None,
    ):
        if bounds is not None:
            bs = tuple(float(b) for b in bounds)
            if not bs or any(
                b2 <= b1 for b1, b2 in zip(bs, bs[1:])
            ) or bs[0] <= 0:
                raise ValueError(f"bounds must be positive ascending: {bs}")
            self.bounds = bs
        else:
            self.bounds = log_buckets(lo, hi, per_decade)
        self._counts = [0] * (len(self.bounds) + 1)  # [-1] is +Inf overflow
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    # -- recording ----------------------------------------------------------
    def record(self, value: float, n: int = 1) -> None:
        v = float(value)
        if v < 0 or n <= 0 or math.isnan(v):
            return
        i = bisect_left(self.bounds, v)  # le-inclusive: v == bound fits in it
        with self._lock:
            self._counts[i] += n
            self._count += n
            self._sum += v * n
            if v > self._max:
                self._max = v

    # -- snapshot reads -----------------------------------------------------
    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _frozen(self) -> Tuple[Tuple[int, ...], int, float, float]:
        with self._lock:
            return tuple(self._counts), self._count, self._sum, self._max

    def quantile(self, q: float) -> Optional[float]:
        """q-quantile (0 < q <= 1) with linear interpolation inside the
        target bucket; None when empty."""
        counts, total, _s, vmax = self._frozen()
        if total == 0:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            prev_cum = cum
            cum += c
            if cum >= rank:
                lo = 0.0 if i == 0 else self.bounds[i - 1]
                hi = vmax if i == len(self.bounds) else self.bounds[i]
                hi = min(hi, vmax) if vmax > 0 else hi
                if hi <= lo:
                    return hi
                frac = (rank - prev_cum) / c
                return lo + (hi - lo) * frac
        return vmax  # pragma: no cover - rank <= total always hits above

    def snapshot(self) -> Dict[str, Optional[float]]:
        """{count, sum, avg, p50, p90, p99, max} — the stats-command /
        bench-artifact shape."""
        counts, total, s, vmax = self._frozen()
        if total == 0:
            return {
                "count": 0, "sum": 0.0, "avg": None,
                "p50": None, "p90": None, "p99": None, "max": None,
            }
        return {
            "count": total,
            "sum": s,
            "avg": s / total,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
            "max": vmax,
        }

    def reset(self) -> None:
        with self._lock:
            for i in range(len(self._counts)):
                self._counts[i] = 0
            self._count = 0
            self._sum = 0.0
            self._max = 0.0

    # -- Prometheus exposition ----------------------------------------------
    def render_prometheus(
        self, name: str, help_text: str, labels: str = "",
        header: bool = True,
    ) -> str:
        """0.0.4 ``histogram`` exposition: cumulative ``_bucket{le=...}``
        series + ``_sum`` / ``_count``. ``labels`` is a pre-rendered
        ``key="value"`` list (no braces) merged with the ``le`` label.
        Pass ``header=False`` from the second labelled instance of a
        family on — the text format allows one HELP/TYPE per family."""
        counts, total, s, _vmax = self._frozen()
        sep = "," if labels else ""
        lines = [
            f"# HELP {name} {help_text}",
            f"# TYPE {name} histogram",
        ] if header else []
        cum = 0
        for bound, c in zip(self.bounds, counts):
            cum += c
            lines.append(
                f'{name}_bucket{{{labels}{sep}le="{bound:g}"}} {cum}'
            )
        lines.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} {total}')
        brace = f"{{{labels}}}" if labels else ""
        lines.append(f"{name}_sum{brace} {s:g}")
        lines.append(f"{name}_count{brace} {total}")
        return "\n".join(lines)
