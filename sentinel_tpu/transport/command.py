"""Embedded HTTP command center.

Analog of ``SimpleHttpCommandCenter.java:48`` + the ``@CommandMapping``
handler SPI (``command/CommandHandler.java``): ``GET/POST /<command>?args``
dispatches to a registered handler; ``/api`` lists all commands
(``ApiCommandHandler``). Handlers register via the ``command_handler``
registry, so extensions add endpoints exactly like the reference's SPI.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, Optional

from sentinel_tpu.core.httpd import HttpService, Response, json_response
from sentinel_tpu.core.log import record_log
from sentinel_tpu.core.registry import registry

command_registry = registry("command_handler")

# handler signature: (params: Dict[str, str], body: str) -> str | dict
CommandHandler = Callable[[Dict[str, str], str], object]

_commands: Dict[str, tuple] = {}  # name -> (desc, handler)


def command_mapping(name: str, desc: str = ""):
    """``@CommandMapping(name, desc)`` analog."""

    def deco(fn: CommandHandler) -> CommandHandler:
        _commands[name] = (desc, fn)
        return fn

    return deco


def get_command(name: str):
    entry = _commands.get(name)
    return entry[1] if entry else None


def list_commands() -> Dict[str, str]:
    return {name: desc for name, (desc, _) in _commands.items()}


def _route(method: str, name: str, params: Dict[str, str], body: str) -> Response:
    handler = get_command(name)
    if handler is None and name == "api":
        # fallback if the default handler set was never imported
        return json_response(200, json.dumps(list_commands()))
    if handler is None:
        return json_response(404, f"Unknown command `{name}`; see /api")
    try:
        result = handler(params, body)
    except Exception as e:
        record_log.exception("command %s failed", name)
        return json_response(500, f"command failed: {e}")
    if isinstance(result, tuple) and len(result) == 3:
        return result  # handler provided a full (status, body, content-type)
    if isinstance(result, (dict, list)):
        return json_response(200, json.dumps(result))
    return json_response(200, str(result))


class CommandCenter:
    def __init__(self, host: Optional[str] = None, port: int = 8719):
        # loopback by default: the command surface mutates rules with no
        # auth; exposing it beyond the host is an explicit operator decision
        # (csp.sentinel.api.port.binding, the reference's key for this)
        from sentinel_tpu.core.config import SentinelConfig

        host = host or SentinelConfig.get(
            "csp.sentinel.api.port.binding"
        ) or "127.0.0.1"
        self._service = HttpService(
            _route, host, port, name="sentinel-command-center"
        )

    @property
    def host(self) -> str:
        return self._service.host

    @property
    def port(self) -> int:
        return self._service.port

    def start(self) -> "CommandCenter":
        # make sure the default handlers are registered
        from sentinel_tpu.transport import handlers  # noqa: F401

        self._service.start()
        return self

    def stop(self) -> None:
        self._service.stop()
