"""Embedded HTTP command center.

Analog of ``SimpleHttpCommandCenter.java:48`` + the ``@CommandMapping``
handler SPI (``command/CommandHandler.java``): ``GET/POST /<command>?args``
dispatches to a registered handler; ``/api`` lists all commands
(``ApiCommandHandler``). Handlers register via the ``command_handler``
registry, so extensions add endpoints exactly like the reference's SPI.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

from sentinel_tpu.core.log import record_log
from sentinel_tpu.core.registry import registry

command_registry = registry("command_handler")

# handler signature: (params: Dict[str, str], body: str) -> str | dict
CommandHandler = Callable[[Dict[str, str], str], object]

_commands: Dict[str, tuple] = {}  # name -> (desc, handler)


def command_mapping(name: str, desc: str = ""):
    """``@CommandMapping(name, desc)`` analog."""

    def deco(fn: CommandHandler) -> CommandHandler:
        _commands[name] = (desc, fn)
        return fn

    return deco


def get_command(name: str):
    entry = _commands.get(name)
    return entry[1] if entry else None


def list_commands() -> Dict[str, str]:
    return {name: desc for name, (desc, _) in _commands.items()}


class _Handler(BaseHTTPRequestHandler):
    server_version = "SentinelTPU"

    def _dispatch(self, body: str) -> None:
        parsed = urlparse(self.path)
        name = parsed.path.strip("/")
        params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        if name == "api":
            self._reply(200, json.dumps(list_commands()))
            return
        handler = get_command(name)
        if handler is None:
            self._reply(404, f"Unknown command `{name}`; see /api")
            return
        try:
            result = handler(params, body)
        except Exception as e:
            record_log.exception("command %s failed", name)
            self._reply(500, f"command failed: {e}")
            return
        if isinstance(result, (dict, list)):
            self._reply(200, json.dumps(result))
        else:
            self._reply(200, str(result))

    def _reply(self, code: int, text: str) -> None:
        data = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802
        self._dispatch("")

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length).decode() if length else ""
        self._dispatch(body)

    def log_message(self, fmt, *args):  # quiet; record_log has the failures
        pass


class CommandCenter:
    def __init__(self, host: Optional[str] = None, port: int = 8719):
        # loopback by default: the command surface mutates rules with no
        # auth; exposing it beyond the host is an explicit operator decision
        # (csp.sentinel.api.port.binding, the reference's key for this)
        from sentinel_tpu.core.config import SentinelConfig

        self.host = host or SentinelConfig.get(
            "csp.sentinel.api.port.binding"
        ) or "127.0.0.1"
        self.port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "CommandCenter":
        # make sure the default handlers are registered
        from sentinel_tpu.transport import handlers  # noqa: F401

        self._server = ThreadingHTTPServer((self.host, self.port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="sentinel-command-center",
        )
        self._thread.start()
        record_log.info("command center on %s:%d", self.host, self.port)
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
