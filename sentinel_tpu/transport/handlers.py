"""Default command handlers.

Analogs of the handler set in ``sentinel-transport-common/.../command/handler``
(``version``, ``basicInfo``, ``getRules``/``setRules``
(``FetchActiveRuleCommandHandler.java:31`` / ``ModifyRulesCommandHandler.java:
46``), ``metric`` (``SendMetricCommandHandler.java:41``), ``clusterNode``,
``tree``, ``systemStatus``, ``setClusterMode``/``getClusterMode`` and the
cluster-server metric fetch).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

import sentinel_tpu
from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.core.log import record_log
from sentinel_tpu.datasource import converters as conv
from sentinel_tpu.datasource.base import WritableDataSourceRegistry
from sentinel_tpu.local.authority import AuthorityRuleManager
from sentinel_tpu.local.degrade import DegradeRuleManager
from sentinel_tpu.local.flow import FlowRuleManager
from sentinel_tpu.local.param import ParamFlowRuleManager
from sentinel_tpu.local.system_adaptive import SystemRuleManager
from sentinel_tpu.transport.command import command_mapping

# rule type → (serialize current rules to json, parse json, load parsed rules)
_RULE_TYPES = {
    "flow": (
        lambda: conv.flow_rules_to_json(FlowRuleManager.all_rules()),
        conv.flow_rules_from_json,
        FlowRuleManager.load_rules,
    ),
    "degrade": (
        lambda: conv.degrade_rules_to_json(
            [cb.rule for lst in DegradeRuleManager._breakers.values() for cb in lst]
        ),
        conv.degrade_rules_from_json,
        DegradeRuleManager.load_rules,
    ),
    "system": (
        lambda: conv.system_rules_to_json(
            [SystemRuleManager._effective] if SystemRuleManager._any_enabled else []
        ),
        conv.system_rules_from_json,
        SystemRuleManager.load_rules,
    ),
    "authority": (
        lambda: conv.authority_rules_to_json(
            [r for lst in AuthorityRuleManager._rules.values() for r in lst]
        ),
        conv.authority_rules_from_json,
        AuthorityRuleManager.load_rules,
    ),
    "paramFlow": (
        lambda: conv.param_flow_rules_to_json(
            [r for lst in ParamFlowRuleManager.all_rules().values() for r in lst]
        ),
        conv.param_flow_rules_from_json,
        ParamFlowRuleManager.load_rules,
    ),
    "gateway": (
        lambda: conv.gateway_flow_rules_to_json(_gateway_rules()),
        conv.gateway_flow_rules_from_json,
        lambda rules: _gateway_manager().load_rules(rules),
    ),
}


def _gateway_manager():
    from sentinel_tpu.adapters.gateway import GatewayRuleManager

    return GatewayRuleManager


def _gateway_rules():
    return [
        r for lst in _gateway_manager()._rules.values() for r in lst
    ]


@command_mapping("version", "framework version")
def cmd_version(params, body):
    return f"sentinel-tpu/{sentinel_tpu.__version__}"


@command_mapping("basicInfo", "machine basic info")
def cmd_basic_info(params, body):
    import socket

    return {
        "appName": SentinelConfig.app_name(),
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "version": sentinel_tpu.__version__,
        "currentTime": _clock.now_ms(),
    }


@command_mapping("getRules", "get active rules; type=flow|degrade|system|authority|paramFlow|gateway")
def cmd_get_rules(params, body):
    rtype = params.get("type", "flow")
    if rtype not in _RULE_TYPES:
        return {"error": f"unknown rule type {rtype}"}
    return json.loads(_RULE_TYPES[rtype][0]())


@command_mapping("setRules", "replace rules; type=... body/data=json array")
def cmd_set_rules(params, body):
    rtype = params.get("type", "flow")
    if rtype not in _RULE_TYPES:
        return {"error": f"unknown rule type {rtype}"}
    data = body or params.get("data", "[]")
    _, parse, load = _RULE_TYPES[rtype]
    rules = parse(data)
    load(rules)
    # write-through to a registered writable datasource, passing the parsed,
    # normalized rules — the serializer contract takes rule objects
    # (ModifyRulesCommandHandler.java:58)
    WritableDataSourceRegistry.write_if_registered(rtype, rules)
    return "success"


@command_mapping("gateway/getApiDefinitions", "custom gateway API groups")
def cmd_gateway_get_api_definitions(params, body):
    """``GetGatewayApiDefinitionsCommandHandler`` analog."""
    from sentinel_tpu.adapters.gateway_api import (
        GatewayApiDefinitionManager,
        api_definition_to_dict,
    )

    return [
        api_definition_to_dict(d)
        for d in GatewayApiDefinitionManager.get_api_definitions()
    ]


@command_mapping(
    "gateway/updateApiDefinitions",
    "replace gateway API groups; body/data=json array",
)
def cmd_gateway_update_api_definitions(params, body):
    """``UpdateGatewayApiDefinitionGroupCommandHandler`` analog."""
    from sentinel_tpu.adapters.gateway_api import (
        GatewayApiDefinitionManager,
        parse_api_definition,
    )

    data = body or params.get("data", "[]")
    definitions = [parse_api_definition(obj) for obj in json.loads(data)]
    GatewayApiDefinitionManager.load_api_definitions(definitions)
    return "success"


@command_mapping("metric", "metric log lines; startTime&endTime[&identity]")
def cmd_metric(params, body):
    from sentinel_tpu.metrics.log import MetricSearcher, default_metric_dir

    begin = int(params.get("startTime", 0))
    end = int(params.get("endTime", 2**62))
    identity = params.get("identity")
    searcher = MetricSearcher(default_metric_dir(), SentinelConfig.app_name())
    lines = [n.to_line() for n in searcher.find(begin, end, identity)]
    return "\n".join(lines)


@command_mapping("metric/prometheus", "Prometheus text exposition of live stats")
def cmd_metric_prometheus(params, body):
    from sentinel_tpu.metrics.exporter import CONTENT_TYPE, render

    return (200, render(), CONTENT_TYPE)  # text format, not JSON


@command_mapping("clusterNode", "per-resource statistics snapshot")
def cmd_cluster_node(params, body):
    from sentinel_tpu.local.chain import cluster_node_map

    now = _clock.now_ms()
    out = []
    for name, cn in cluster_node_map().items():
        out.append(
            {
                "resourceName": name,
                "passQps": cn.pass_qps(now),
                "blockQps": cn.block_qps(now),
                "totalQps": cn.total_qps(now),
                "averageRt": cn.avg_rt(now),
                "exceptionQps": cn.exception_qps(now),
                "threadNum": cn.cur_thread_num,
                "oneMinutePass": cn.total_pass_minute(now),
            }
        )
    return out


@command_mapping("origin", "per-origin statistics for a resource; id=<resource>")
def cmd_origin(params, body):
    from sentinel_tpu.local.chain import get_cluster_node

    cn = get_cluster_node(params.get("id", ""))
    if cn is None:
        return []
    now = _clock.now_ms()
    return [
        {
            "origin": origin,
            "passQps": node.pass_qps(now),
            "blockQps": node.block_qps(now),
            "averageRt": node.avg_rt(now),
            "threadNum": node.cur_thread_num,
        }
        for origin, node in cn.origin_nodes.items()
    ]


@command_mapping("tree", "invocation tree")
def cmd_tree(params, body):
    from sentinel_tpu.local import context as ctx_mod

    def walk(node, depth=0):
        name = getattr(node, "resource", None)
        label = name.name if name else "?"
        lines = ["  " * depth + label]
        for child in getattr(node, "children", []):
            lines.extend(walk(child, depth + 1))
        return lines

    return "\n".join(walk(ctx_mod.ROOT))


@command_mapping("systemStatus", "system-adaptive state")
def cmd_system_status(params, body):
    from sentinel_tpu.local.chain import entry_node

    now = _clock.now_ms()
    en = entry_node()
    return {
        "load": SystemRuleManager.status.current_load(),
        "cpuUsage": SystemRuleManager.status.current_cpu_usage(),
        "inboundQps": en.pass_qps(now),
        "inboundThreads": en.cur_thread_num,
        "avgRt": en.avg_rt(now),
    }


@command_mapping("getClusterMode", "cluster state: -1 off, 0 client, 1 server")
def cmd_get_cluster_mode(params, body):
    from sentinel_tpu.cluster import api as cluster_api

    return {"mode": int(cluster_api.get_mode())}


_EMBEDDED_SERVER = {"server": None}
# Guards the check-create-store sequence below: a retried setClusterMode
# (promotion compiles the decision kernels, so the first call can be slow)
# must not race the in-flight first call and double-start port-bound servers.
_EMBEDDED_LOCK = threading.Lock()


def _server_class():
    """Transport selection: ``csp.sentinel.cluster.server.native=true``
    serves through the native epoll front door (C++ data plane) when the
    native library is built; default is the asyncio transport."""
    if SentinelConfig.get_bool("csp.sentinel.cluster.server.native"):
        from sentinel_tpu.cluster.server_native import (
            NativeTokenServer,
            native_available,
        )

        if native_available():
            return NativeTokenServer
        record_log.warning(
            "csp.sentinel.cluster.server.native requested but the native "
            "library is not built; using the asyncio transport"
        )
    from sentinel_tpu.cluster.server import TokenServer

    return TokenServer


def _rebind_server_port(prev, new_port: int):
    """Rebuild a running token server on ``new_port``, preserving its class
    (asyncio or native front door), its service (rules + counters), and its
    operator tuning; on failure roll back onto the old port so the fleet
    keeps a token server. Caller holds ``_EMBEDDED_LOCK`` and has cleared
    the registry slot. Returns the running replacement."""
    server_cls = type(prev)
    tuning = prev.tuning_kwargs()
    service = prev.service
    host = prev.host
    old_port = prev.port
    prev.stop()
    try:
        server = server_cls(service, host=host, port=new_port, **tuning)
        server.start()
        return server
    except Exception:
        rollback = server_cls(service, host=host, port=old_port, **tuning)
        rollback.start()
        _EMBEDDED_SERVER["server"] = rollback
        raise


def apply_cluster_mode(mode: int, token_port: int = 18730) -> None:
    """Switch this agent's cluster state. Mode 1 provisions the embedded
    token server (transport + device service) and registers it — the analog
    of ``ModifyClusterModeCommandHandler`` → ``DefaultEmbeddedTokenServer``
    start. Leaving server mode stops it. Idempotent: repeating the current
    mode (e.g. a dashboard retry after a slow first promote) reconciles
    instead of double-starting. Shared by the setClusterMode command and the
    datasource-driven path (``cluster.assign``)."""
    from sentinel_tpu.cluster import api as cluster_api

    with _EMBEDDED_LOCK:
        prev = _EMBEDDED_SERVER["server"]
        if mode == int(cluster_api.ClusterMode.SERVER):
            if prev is not None and token_port not in (0, prev.port):
                # port reconfiguration (e.g. a datasource edit): the running
                # server must move, not silently keep the old port. The
                # service (rules, counters), transport class, and tuning are
                # preserved across the move; failure rolls back.
                _EMBEDDED_SERVER["server"] = None
                _EMBEDDED_SERVER["server"] = _rebind_server_port(
                    prev, token_port
                )
            elif prev is None:
                from sentinel_tpu.cluster.token_service import (
                    DefaultTokenService,
                )

                server_cls = _server_class()
                server = server_cls(
                    DefaultTokenService(), host="0.0.0.0", port=token_port
                )
                try:
                    server.start()
                except Exception:
                    server.stop()  # release any half-bound resources
                    raise
                _EMBEDDED_SERVER["server"] = server
            cluster_api.set_embedded_server(_EMBEDDED_SERVER["server"].service)
            return
        if prev is not None:
            _EMBEDDED_SERVER["server"] = None
            prev.stop()
            # the demoted server's service must not keep answering
            # cluster/server/* commands as if this were still a token server
            cluster_api.clear_embedded_server()
        cluster_api.set_mode(cluster_api.ClusterMode(mode))


@command_mapping(
    "setClusterMode", "switch cluster state; mode=-1|0|1 [&tokenPort=18730]"
)
def cmd_set_cluster_mode(params, body):
    apply_cluster_mode(
        int(params.get("mode", -1)), int(params.get("tokenPort", 18730))
    )
    return "success"


def apply_client_assignment(data) -> Optional[str]:
    """(Re)install the global token client against an assigned server
    address (``ClusterClientConfigManager`` applying
    ``ClusterClientAssignConfig``). Returns an error string or None. Shared
    by the modifyConfig command and the datasource-driven path
    (``cluster.assign``). Idempotent on identical assignments so a polling
    datasource doesn't churn connections."""
    from sentinel_tpu.cluster import api as cluster_api
    from sentinel_tpu.cluster.client import TokenClient

    host = data.get("serverHost")
    port = int(data.get("serverPort", 0))
    if not host or not port:
        return "serverHost and serverPort required"
    timeout_ms = int(data.get("requestTimeout", 20))
    # the namespace this agent declares in its PING handshake — the server
    # scopes connection counts (AVG_LOCAL scaling) by it
    # (ClusterClientConfigManager's namespace config)
    namespace = str(data.get("namespace", "default") or "default")
    assignment = dict(
        serverHost=host, serverPort=port, requestTimeout=timeout_ms,
        namespace=namespace,
    )
    # idempotent ONLY while actually operating as a client: a repeated
    # assignment after a mode switch (or reset) must reinstall the client
    # and restore CLIENT mode, not silently no-op
    if (
        assignment == _CLUSTER_CLIENT_CONFIG
        and cluster_api.get_mode() == cluster_api.ClusterMode.CLIENT
        and cluster_api._client is not None
    ):
        return None
    cluster_api.set_client(
        TokenClient(host, port, timeout_ms=timeout_ms, namespace=namespace)
    )
    _CLUSTER_CLIENT_CONFIG.clear()
    _CLUSTER_CLIENT_CONFIG.update(assignment)
    return None


@command_mapping(
    "cluster/client/modifyConfig", "point the token client at a server; data={serverHost, serverPort}"
)
def cmd_cluster_client_modify_config(params, body):
    """``ModifyClusterClientConfigHandler`` analog."""
    data = json.loads(body) if body else params
    error = apply_client_assignment(data)
    return {"error": error} if error else "success"


_CLUSTER_CLIENT_CONFIG: dict = {}


@command_mapping("cluster/client/fetchConfig", "current token-client assignment")
def cmd_cluster_client_fetch_config(params, body):
    return dict(_CLUSTER_CLIENT_CONFIG)


@command_mapping(
    "clusterServerStats",
    "token-server pipeline stats: verdict counters, stage histograms, "
    "gauges, param-sketch block",
)
def cmd_cluster_server_stats(params, body):
    """JSON twin of the ``sentinel_server_*`` Prometheus section — the
    dashboard/command-center view of the serving pipeline, plus the HA
    rebalance block (move protocol events, shipped state bytes, redirect
    counts) so the dashboard sees live shard moves next to the pipeline.
    The ``sketch`` block mirrors ``sentinel_sketch_*``: the param sketch's
    variant, fat/slim HBM bytes, and SALSA merge counters per rule slot
    (docs/SKETCHES.md). The ``trace`` block is the flight recorder's
    arming state, the ``slo`` block the per-tenant latency/burn-rate
    plane, and ``buildInfo`` the version/wire-rev stamp — so one stats
    pull carries everything a fleet merge needs
    (docs/OBSERVABILITY.md)."""
    from sentinel_tpu.metrics import exporter
    from sentinel_tpu.metrics.ha import ha_metrics
    from sentinel_tpu.metrics.server import server_metrics
    from sentinel_tpu.trace import ring as trace_ring
    from sentinel_tpu.trace.slo import slo_plane

    from sentinel_tpu.metrics.timeline import timeline

    out = server_metrics().snapshot()
    out["rebalance"] = ha_metrics().snapshot()["rebalance"]
    out["trace"] = trace_ring.status()
    out["slo"] = slo_plane().snapshot()
    out["timeline"] = timeline().status()
    out["buildInfo"] = exporter.build_info()
    return out


@command_mapping(
    "cluster/server/metric",
    "per-namespace per-second timeline; "
    "startTime&endTime[&namespace][&maxLines]",
)
def cmd_cluster_server_metric(params, body):
    """``SendMetricCommandHandler`` parity for the cluster door: the
    local ``metric`` command reads per-resource seconds from the rolled
    metric log; this reads per-namespace seconds from the metric
    timeline (in-memory window merged with the rolled timeline files
    when ``SENTINEL_TIMELINE_DIR`` is configured). Times are epoch ms;
    the response is a JSON list of per-(second, namespace) samples with
    pass/block/shed/other counts and bucketed p99/max decision latency
    — the series the scenario harness gates on (docs/SCENARIOS.md)."""
    from sentinel_tpu.metrics.timeline import timeline

    begin = int(params.get("startTime", 0))
    end_raw = params.get("endTime")
    end = int(end_raw) if end_raw is not None else None
    namespace = params.get("namespace")
    max_lines = int(params.get("maxLines", 12000))
    samples = timeline().find(
        begin, end, namespace=namespace, max_lines=max_lines
    )
    return [s.as_dict() for s in samples]


@command_mapping(
    "cluster/server/profiler",
    "JAX profiler trace control; action=start|stop|status [&dir=/tmp/trace]",
)
def cmd_cluster_server_profiler(params, body):
    """Opt-in device-trace capture on a LIVE server: start writes a
    TensorBoard/XProf trace of every device step until stop. Targets the
    embedded token server's hook when one is running, else the process-wide
    hook (profiles local JAX work)."""
    from sentinel_tpu.metrics.profiler import default_hook

    with _EMBEDDED_LOCK:
        server = _EMBEDDED_SERVER["server"]
    hook = getattr(server, "profiler", None) or default_hook()
    action = params.get("action", "status")
    if action == "start":
        return hook.start(params.get("dir"))
    if action == "stop":
        return hook.stop()
    if action == "status":
        return hook.status()
    return {"error": "action must be start|stop|status"}


@command_mapping(
    "cluster/server/trace",
    "flight-recorder control; action=arm|disarm|status|spans|blackbox "
    "[&sample=0.01][&xid=][&limit=][&dir=]",
)
def cmd_cluster_server_trace(params, body):
    """Operator surface of the always-on flight recorder
    (``sentinel_tpu.trace``, docs/OBSERVABILITY.md):

    - ``arm``/``disarm``: start/stop recording (``sample`` = fraction of
      xids end-to-end sampled; control events always record while armed);
    - ``status``: arming state + per-thread ring occupancy;
    - ``spans``: assemble sampled end-to-end spans on demand — ``xid``
      picks one, otherwise the newest ``limit`` sampled xids; ``dir``
      additionally writes the JSON artifact and returns its path;
    - ``blackbox``: force a black-box dump now (``dir`` overrides the
      configured directory) — the same artifact brownout escalation,
      standby promotion, and MOVE aborts write automatically.
    """
    from sentinel_tpu.trace import blackbox, spans
    from sentinel_tpu.trace import ring as trace_ring

    action = params.get("action", "status")
    if action == "arm":
        trace_ring.arm(sample=float(params.get("sample", 0.01)))
        return trace_ring.status()
    if action == "disarm":
        trace_ring.disarm()
        return trace_ring.status()
    if action == "status":
        return trace_ring.status()
    if action == "spans":
        xid = params.get("xid")
        if xid is not None:
            span = spans.assemble(int(xid, 0) if isinstance(xid, str)
                                  else int(xid))
            if span is None:
                return {"error": f"xid {xid} not in the rings "
                        "(unsampled, or overwritten)"}
            return span
        limit = int(params.get("limit", 64))
        out_dir = params.get("dir")
        if out_dir:
            path = os.path.join(
                out_dir, f"trace-spans-{_clock.now_ms()}.json"
            )
            return {"path": spans.write_artifact(path, limit=limit)}
        assembled = spans.assemble_recent(limit=limit)
        return {
            "completeness": spans.completeness(assembled),
            "spans": assembled,
        }
    if action == "blackbox":
        if not blackbox.enabled() and not params.get("dir"):
            return {"error": "no black-box dir configured; pass dir="}
        return {
            "path": blackbox.dump(
                reason=params.get("reason", "operator"),
                directory=params.get("dir"),
            )
        }
    return {"error": "action must be arm|disarm|status|spans|blackbox"}


@command_mapping(
    "cluster/server/slo",
    "per-tenant SLO plane; action=local|fleet (fleet: body = JSON list "
    "of pod clusterServerStats/slo payloads)",
)
def cmd_cluster_server_slo(params, body):
    """Per-tenant latency/burn-rate surface (``sentinel_tpu.trace.slo``):

    - ``local``: this pod's snapshot — objective, per-namespace latency
      quantiles, 1m/1h burn rates, shed attribution;
    - ``fleet``: merge pod snapshots into the fleet view. The body is a
      JSON array whose items are either raw ``slo`` snapshots or whole
      ``clusterServerStats`` payloads (their ``slo`` block is used) —
      the same pull-and-merge path ``aggregate_snapshots`` established
      for per-flow metrics. Malformed pod items contribute nothing.
    """
    from sentinel_tpu.trace.slo import merge_fleet, slo_plane

    action = params.get("action", "local")
    if action == "local":
        return slo_plane().snapshot()
    if action == "fleet":
        try:
            pods = json.loads(body) if body else []
        except Exception:
            return {"error": "body must be a JSON array of pod payloads"}
        if not isinstance(pods, list):
            return {"error": "body must be a JSON array of pod payloads"}
        snaps = [
            p.get("slo", p) if isinstance(p, dict) else p for p in pods
        ]
        merged = merge_fleet(snaps)
        merged["pods"] = len(pods)
        return merged
    return {"error": "action must be local|fleet"}


@command_mapping(
    "cluster/server/snapshot",
    "token-server state snapshot; action=save|fetch|restore|status [&dir=]",
)
def cmd_cluster_server_snapshot(params, body):
    """HA state snapshot surface (``sentinel_tpu.ha.snapshot``):

    - ``save``: write an artifact to ``dir`` (or the server's configured
      snapshot directory) and return its path;
    - ``fetch``: return the encoded snapshot document inline — the warm
      standby's pull path (restore it with action=restore, body=doc);
    - ``restore``: load state from the JSON document in the body, or from
      the newest artifact in ``dir``;
    - ``status``: periodic-writer configuration and last artifact path.
    """
    from sentinel_tpu.cluster import api as cluster_api
    from sentinel_tpu.ha import snapshot as ha_snapshot

    service = cluster_api.get_embedded_server()
    if service is None or not hasattr(service, "export_state"):
        return {"error": "this machine is not a token server"}
    action = params.get("action", "status")
    if action == "fetch":
        return ha_snapshot.snapshot_to_doc(service)
    if action == "save":
        directory = params.get("dir") or _snapshot_dir_of_embedded()
        if not directory:
            return {"error": "no snapshot dir configured; pass dir="}
        return {"path": ha_snapshot.save_snapshot(service, directory)}
    if action == "restore":
        if body:
            try:
                ha_snapshot.restore_from_doc(service, json.loads(body))
            except ValueError as e:
                return {"error": str(e)}
            return "success"
        directory = params.get("dir") or _snapshot_dir_of_embedded()
        if not directory:
            return {"error": "no snapshot dir configured; pass dir= or body"}
        if not ha_snapshot.restore_latest(service, directory):
            return {"error": f"no usable snapshot in {directory}"}
        return "success"
    if action == "status":
        out = {"dir": _snapshot_dir_of_embedded()}
        with _EMBEDDED_LOCK:
            server = _EMBEDDED_SERVER["server"]
        manager = getattr(server, "_snapshots", None)
        if manager is not None:
            out["periodS"] = manager.period_s
            out["lastPath"] = manager.last_path
        return out
    return {"error": "action must be save|fetch|restore|status"}


def _snapshot_dir_of_embedded():
    with _EMBEDDED_LOCK:
        server = _EMBEDDED_SERVER["server"]
    return getattr(server, "snapshot_dir", None)


@command_mapping(
    "cluster/server/promote",
    "warm-standby control; action=promote|status",
)
def cmd_cluster_server_promote(params, body):
    """Replication role surface (``sentinel_tpu.ha.replication``):

    - ``promote``: open an unpromoted standby's front door (idempotent;
      errors if this server is not a standby);
    - ``status``: replication role + sender/applier progress counters.
    """
    with _EMBEDDED_LOCK:
        server = _EMBEDDED_SERVER["server"]
    if server is None:
        return {"error": "this machine is not a token server"}
    action = params.get("action", "status")
    if action == "promote":
        applier = getattr(server, "applier", None)
        if applier is None:
            return {"error": "this server is not a standby"}
        already = applier.promoted
        server.promote(reason=params.get("reason", "manual"))
        return {"promoted": True, "alreadyPromoted": already}
    if action == "status":
        out = {"isStandby": bool(getattr(server, "is_standby", False))}
        applier = getattr(server, "applier", None)
        if applier is not None:
            out["applier"] = applier.status()
        replicator = getattr(server, "replicator", None)
        if replicator is not None:
            out["sender"] = replicator.status()
        return out
    return {"error": "action must be promote|status"}


@command_mapping("cluster/server/metrics", "token-server per-flow metrics")
def cmd_cluster_server_metrics(params, body):
    from sentinel_tpu.cluster import api as cluster_api

    service = cluster_api._pick_service()
    snapshot = getattr(service, "metrics_snapshot", None)
    if snapshot is None:
        return {}
    return {str(k): v for k, v in snapshot().items()}


# ---------------------------------------------------------------------------
# transport-common parity: api / switch / tree + node variants
# (``ApiCommandHandler``, ``{Fetch,Modify}SwitchCommandHandler``,
# ``FetchJsonTreeCommandHandler``, ``FetchClusterNodeByIdCommandHandler``,
# ``FetchSimpleClusterNodeCommandHandler``)
# ---------------------------------------------------------------------------


@command_mapping("api", "list all supported commands")
def cmd_api(params, body):
    from sentinel_tpu.transport.command import list_commands

    return [
        {"url": f"/{name}", "desc": desc}
        for name, desc in sorted(list_commands().items())
    ]


@command_mapping("getSwitch", "global guard switch state")
def cmd_get_switch(params, body):
    from sentinel_tpu.local.sph import is_enabled

    return {"enabled": is_enabled()}


@command_mapping("setSwitch", "toggle the global guard switch; value=true|false")
def cmd_set_switch(params, body):
    from sentinel_tpu.local.sph import set_enabled as sph_set_enabled

    value = str(params.get("value", "")).lower()
    if value not in ("true", "false"):
        return {"error": "value must be true or false"}
    sph_set_enabled(value == "true")
    return "success"


@command_mapping("jsonTree", "invocation tree as JSON")
def cmd_json_tree(params, body):
    from sentinel_tpu.local import context as ctx_mod

    now = _clock.now_ms()

    def node_dict(node):
        name = getattr(node, "resource", None)
        d = {
            "id": name.name if name else "machine-root",
            "passQps": node.pass_qps(now) if hasattr(node, "pass_qps") else 0,
            "blockQps": node.block_qps(now) if hasattr(node, "block_qps") else 0,
            "averageRt": node.avg_rt(now) if hasattr(node, "avg_rt") else 0,
            "threadNum": getattr(node, "cur_thread_num", 0),
            "children": [
                node_dict(child) for child in getattr(node, "children", [])
            ],
        }
        return d

    return node_dict(ctx_mod.ROOT)


@command_mapping("clusterNodeById", "one resource's statistics; id=<resource>")
def cmd_cluster_node_by_id(params, body):
    from sentinel_tpu.local.chain import get_cluster_node

    name = params.get("id", "")
    cn = get_cluster_node(name)
    if cn is None:
        return {}
    now = _clock.now_ms()
    return {
        "resourceName": name,
        "passQps": cn.pass_qps(now),
        "blockQps": cn.block_qps(now),
        "totalQps": cn.total_qps(now),
        "averageRt": cn.avg_rt(now),
        "exceptionQps": cn.exception_qps(now),
        "threadNum": cn.cur_thread_num,
        "oneMinutePass": cn.total_pass_minute(now),
    }


@command_mapping("cnode", "plain-text per-resource statistics table")
def cmd_cnode(params, body):
    from sentinel_tpu.local.chain import cluster_node_map

    now = _clock.now_ms()
    lines = ["resource passQps blockQps totalQps rt threads"]
    for name, cn in sorted(cluster_node_map().items()):
        lines.append(
            f"{name} {cn.pass_qps(now):g} {cn.block_qps(now):g} "
            f"{cn.total_qps(now):g} {cn.avg_rt(now):g} {cn.cur_thread_num}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# cluster-server command set (``sentinel-cluster-server-default/.../command/
# handler/``): rule fetch/modify per namespace, config fetch/modify,
# namespace set, server info, per-namespace metrics
# ---------------------------------------------------------------------------


def _embedded_service():
    from sentinel_tpu.cluster import api as cluster_api

    service = cluster_api.get_embedded_server()
    if service is None:
        return None, {"error": "this machine is not a token server"}
    return service, None


def _flow_rule_to_dict(rule) -> dict:
    d = {
        "flowId": rule.flow_id,
        "count": rule.count,
        "thresholdType": int(rule.mode),
        "namespace": rule.namespace,
    }
    if int(getattr(rule, "control_behavior", 0)) != 0:
        # FlowRule's traffic-shaping knobs, dashboard field names
        d["controlBehavior"] = int(rule.control_behavior)
        d["warmUpPeriodSec"] = int(rule.warm_up_period_sec)
        d["coldFactor"] = int(rule.cold_factor)
        d["maxQueueingTimeMs"] = int(rule.max_queueing_time_ms)
    return d


def _flow_rule_from_dict(d: dict, namespace: str):
    from sentinel_tpu.engine import ClusterFlowRule
    from sentinel_tpu.engine.rules import ThresholdMode

    return ClusterFlowRule(
        flow_id=int(d["flowId"]),
        count=float(d["count"]),
        mode=ThresholdMode(int(d.get("thresholdType", 0))),
        namespace=namespace,
        control_behavior=int(d.get("controlBehavior", 0)),
        warm_up_period_sec=int(d.get("warmUpPeriodSec", 10)),
        cold_factor=int(d.get("coldFactor", 3)),
        max_queueing_time_ms=int(d.get("maxQueueingTimeMs", 500)),
    )


@command_mapping("cluster/server/flowRules", "cluster flow rules [namespace=]")
def cmd_cluster_server_flow_rules(params, body):
    service, err = _embedded_service()
    if err:
        return err
    return [
        _flow_rule_to_dict(r)
        for r in service.current_rules(params.get("namespace"))
    ]


@command_mapping(
    "cluster/server/modifyFlowRules",
    "replace one namespace's cluster flow rules; namespace=&data=[...]",
)
def cmd_cluster_server_modify_flow_rules(params, body):
    service, err = _embedded_service()
    if err:
        return err
    namespace = params.get("namespace")
    if not namespace:
        return {"error": "namespace cannot be empty"}
    data = json.loads(body or params.get("data", "[]"))
    service.load_namespace_rules(
        namespace, [_flow_rule_from_dict(d, namespace) for d in data]
    )
    return "success"


@command_mapping(
    "cluster/server/paramRules", "cluster param-flow rules [namespace=]"
)
def cmd_cluster_server_param_rules(params, body):
    service, err = _embedded_service()
    if err:
        return err
    return [
        {
            "flowId": r.flow_id,
            "count": r.count,
            "namespace": r.namespace,
            "itemThresholds": [list(t) for t in (r.item_thresholds or ())],
        }
        for r in service.current_param_rules(params.get("namespace"))
    ]


@command_mapping(
    "cluster/server/modifyParamRules",
    "replace one namespace's cluster param rules; namespace=&data=[...]",
)
def cmd_cluster_server_modify_param_rules(params, body):
    from sentinel_tpu.cluster.token_service import ClusterParamFlowRule

    service, err = _embedded_service()
    if err:
        return err
    namespace = params.get("namespace")
    if not namespace:
        return {"error": "namespace cannot be empty"}
    data = json.loads(body or params.get("data", "[]"))
    rules = [
        ClusterParamFlowRule(
            flow_id=int(d["flowId"]),
            count=float(d["count"]),
            item_thresholds=tuple(
                (int(h), float(c)) for h, c in d.get("itemThresholds", [])
            ) or None,
            namespace=namespace,
        )
        for d in data
    ]
    service.load_namespace_param_rules(namespace, rules)
    return "success"


@command_mapping("cluster/server/fetchConfig", "token-server config view")
def cmd_cluster_server_fetch_config(params, body):
    service, err = _embedded_service()
    if err:
        return err
    out = dict(service.config_snapshot())
    with _EMBEDDED_LOCK:
        server = _EMBEDDED_SERVER["server"]
    if server is not None:
        out["port"] = server.port
    return out


@command_mapping(
    "cluster/server/modifyFlowConfig",
    "modify dynamic flow config; data={maxAllowedQps}",
)
def cmd_cluster_server_modify_flow_config(params, body):
    service, err = _embedded_service()
    if err:
        return err
    data = json.loads(body or params.get("data", "{}"))
    static_keys = {"exceedCount", "maxOccupyRatio", "intervalMs",
                   "sampleCount"} & set(data)
    if static_keys:
        # these are compile-time engine geometry here (EngineConfig is baked
        # into the jitted step); changing them means re-provisioning the
        # server, unlike the reference's mutable statics — be explicit
        return {"error": "static engine config cannot change at runtime: "
                + ", ".join(sorted(static_keys))}
    if "maxAllowedQps" in data:
        service.set_max_allowed_qps(float(data["maxAllowedQps"]))
    return "success"


@command_mapping(
    "cluster/server/modifyTransportConfig",
    "move the token-server transport; data={port}",
)
def cmd_cluster_server_modify_transport_config(params, body):
    data = json.loads(body or params.get("data", "{}"))
    port = int(data.get("port", 0))
    if not port:
        return {"error": "port required"}
    with _EMBEDDED_LOCK:
        server = _EMBEDDED_SERVER["server"]
        if server is None:
            return {"error": "this machine is not a token server"}
        if server.port == port:
            return "success"
        _EMBEDDED_SERVER["server"] = None
        # class-, service-, and tuning-preserving rebind with rollback —
        # kernels stay warm either way
        _EMBEDDED_SERVER["server"] = _rebind_server_port(server, port)
    return "success"


@command_mapping(
    "cluster/server/modifyNamespaceSet", "set served namespaces; data=[...]"
)
def cmd_cluster_server_modify_namespace_set(params, body):
    service, err = _embedded_service()
    if err:
        return err
    data = json.loads(body or params.get("data", "[]"))
    service.namespace_set = set(str(ns) for ns in data)
    return "success"


@command_mapping("cluster/server/info", "token-server info (connections, config)")
def cmd_cluster_server_info(params, body):
    service, err = _embedded_service()
    if err:
        return err
    with _EMBEDDED_LOCK:
        server = _EMBEDDED_SERVER["server"]
    info = {
        "appName": SentinelConfig.get("project.name") or "sentinel-tpu",
        "namespaceSet": service.served_namespaces(),
        "flow": service.config_snapshot(),
        "embedded": server is not None,
    }
    if server is not None:
        info["port"] = server.port
        info["connection"] = [
            {"namespace": ns, "connectedCount": len(addrs),
             "clients": addrs}
            for ns, addrs in sorted(server.connections.snapshot().items())
        ]
    return info


@command_mapping(
    "cluster/server/metricList", "per-flow metrics for a namespace; namespace="
)
def cmd_cluster_server_metric_list(params, body):
    service, err = _embedded_service()
    if err:
        return err
    namespace = params.get("namespace")
    if not namespace:
        return {"error": "namespace cannot be empty"}
    flow_ids = {r.flow_id for r in service.current_rules(namespace)}
    snapshot = service.metrics_snapshot()
    return {
        str(fid): metrics
        for fid, metrics in snapshot.items()
        if fid in flow_ids
    }
