"""ASGI-embedded command center: serve the command surface from the app's
own web server instead of a dedicated thread server.

The reference ships alternative command-center transports so the control
plane can ride the application's existing HTTP stack —
``sentinel-transport-netty-http``'s ``NettyHttpCommandCenter.java:36`` runs
the handlers on the app's netty event loop, and the spring-mvc variant mounts
them as controllers. The Python-ecosystem analog of both is one thing: an
ASGI app. Mount it in the server you already run (uvicorn/hypercorn,
FastAPI/Starlette sub-app, etc.):

    from sentinel_tpu.transport.command_asgi import command_asgi_app
    app.mount("/sentinel", command_asgi_app())        # Starlette/FastAPI
    # or serve it standalone: uvicorn.run(command_asgi_app(), port=8719)

The same ``@command_mapping`` registry backs every transport, so handlers
registered by extensions appear here exactly as on the thread server
(``SimpleHttpCommandCenter``), and the dashboard talks to either
interchangeably. Handlers stay sync (they mutate rule managers guarded by
locks); they run in a worker thread via ``asyncio.to_thread`` so a slow
command (e.g. a promote that compiles kernels) never stalls the app's event
loop — the same isolation the netty variant gets from its business group.

Security stance matches ``CommandCenter``: the surface mutates rules with no
auth, so mount it where only operators can reach it (the reference binds
loopback by default for the same reason).
"""

from __future__ import annotations

import asyncio
import urllib.parse
from typing import Iterable, Tuple

from sentinel_tpu.core.httpd import MAX_BODY_BYTES
from sentinel_tpu.transport.command import _route


def command_asgi_app(max_body_bytes: int = MAX_BODY_BYTES):
    """Build the ASGI callable. Importing the default handler set happens
    here (like ``CommandCenter.start``) so a bare mount serves all 30+
    commands without extra wiring."""
    from sentinel_tpu.transport import handlers  # noqa: F401

    async def app(scope, receive, send) -> None:
        if scope["type"] == "lifespan":
            # cooperate with servers that run the lifespan protocol
            while True:
                message = await receive()
                if message["type"] == "lifespan.startup":
                    await send({"type": "lifespan.startup.complete"})
                elif message["type"] == "lifespan.shutdown":
                    await send({"type": "lifespan.shutdown.complete"})
                    return
        if scope["type"] != "http":
            raise RuntimeError(f"unsupported scope type {scope['type']!r}")
        # strip('/') (both sides) to match the thread server's routing
        # (httpd.py) — trailing-slash URLs must resolve identically on
        # every transport. Mounted sub-apps arrive with root_path already
        # removed by the framework, so no extra handling is needed.
        name = scope.get("path", "/").strip("/")
        params = {
            k: v[0]
            for k, v in urllib.parse.parse_qs(
                scope.get("query_string", b"").decode("latin-1")
            ).items()
        }
        body = bytearray()
        while True:
            message = await receive()
            if message["type"] != "http.request":
                return  # client disconnected before the body arrived
            body.extend(message.get("body", b""))
            if len(body) > max_body_bytes:
                await _respond(send, 413, b"body too large",
                               "text/plain; charset=utf-8")
                return
            if not message.get("more_body", False):
                break
        status, text, content_type = await asyncio.to_thread(
            _route, scope.get("method", "GET"), name, params,
            body.decode("utf-8", errors="replace"),
        )
        await _respond(send, status, text.encode(), content_type)

    return app


async def _respond(send, status: int, body: bytes, content_type: str) -> None:
    headers: Iterable[Tuple[bytes, bytes]] = [
        (b"content-type", content_type.encode()),
        (b"content-length", str(len(body)).encode()),
    ]
    await send({
        "type": "http.response.start",
        "status": status,
        "headers": list(headers),
    })
    await send({"type": "http.response.body", "body": body})
