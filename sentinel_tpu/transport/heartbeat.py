"""Heartbeat: periodic registration POST to the dashboard.

Analog of ``HeartbeatSender.java:35`` / ``HeartbeatSenderInitFunc.java:38-91``
/ ``SimpleHttpHeartbeatSender``: POST ``/registry/machine`` with app/ip/port/
version on an interval (``csp.sentinel.heartbeat.interval.ms``); multiple
dashboard addresses are tried in order.
"""

from __future__ import annotations

import json
import random
import socket
import threading
import urllib.request
from typing import List, Optional

import sentinel_tpu
from sentinel_tpu.core import clock as _clock
from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.core.log import record_log


class HeartbeatSender:
    def __init__(
        self,
        dashboard_addrs: Optional[List[str]] = None,
        command_port: Optional[int] = None,
        interval_ms: Optional[int] = None,
        client_ip: Optional[str] = None,
    ):
        raw = SentinelConfig.get("csp.sentinel.dashboard.server") or ""
        self.addrs = dashboard_addrs or [a for a in raw.split(",") if a]
        # csp.sentinel.heartbeat.client.ip (TransportConfig): pin the
        # advertised IP when the auto-detected one isn't routable
        self.client_ip = client_ip or SentinelConfig.get(
            "csp.sentinel.heartbeat.client.ip"
        )
        # keys keep the reference's names (TransportConfig.java:35-41)
        self.command_port = command_port or SentinelConfig.get_int(
            "csp.sentinel.api.port", 8719
        )
        self.interval_ms = interval_ms or SentinelConfig.get_int(
            "csp.sentinel.heartbeat.interval.ms", 10_000
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._consecutive_failures = 0

    def _payload(self) -> bytes:
        return json.dumps(
            {
                "app": SentinelConfig.app_name(),
                "app_type": SentinelConfig.get_int("csp.sentinel.app.type", 0),
                "hostname": socket.gethostname(),
                "ip": self.client_ip or _local_ip(),
                "port": self.command_port,
                "version": f"sentinel-tpu/{sentinel_tpu.__version__}",
                "timestamp": _clock.now_ms(),
            }
        ).encode()

    def send_once(self) -> bool:
        payload = self._payload()
        for addr in self.addrs:
            url = f"http://{addr}/registry/machine"
            try:
                req = urllib.request.Request(
                    url, data=payload,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=3) as rsp:
                    if 200 <= rsp.status < 300:
                        return True
            except Exception as e:
                record_log.debug("heartbeat to %s failed: %s", addr, e)
        return False

    def start(self) -> "HeartbeatSender":
        if not self.addrs:
            record_log.info("no dashboard configured; heartbeat disabled")
            return self
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="sentinel-heartbeat"
        )
        self._thread.start()
        return self

    def _interval_s(self) -> float:
        """Next wait. A dead dashboard is probed on an exponentially
        growing interval (doubling per consecutive failure, capped at 10×)
        with ±25% jitter so a fleet that lost its dashboard together
        doesn't re-register in one synchronized thundering herd; one
        success snaps back to the configured cadence."""
        base = self.interval_ms / 1000.0
        if self._consecutive_failures == 0:
            return base
        backoff = min(base * (2.0 ** self._consecutive_failures), base * 10.0)
        return backoff * random.uniform(0.75, 1.25)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval_s()):
            if self.send_once():
                self._consecutive_failures = 0
            else:
                self._consecutive_failures += 1

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


def _local_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"
