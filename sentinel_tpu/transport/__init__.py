"""Control plane: command center (HTTP) + heartbeat.

Analog of ``sentinel-transport`` — an embedded HTTP server exposing
``CommandHandler``-style endpoints (rule CRUD, metrics pull, node trees,
cluster mode) and a periodic heartbeat POST to the dashboard.
"""

from sentinel_tpu.transport.command import CommandCenter, command_mapping
from sentinel_tpu.transport.heartbeat import HeartbeatSender

__all__ = ["CommandCenter", "command_mapping", "HeartbeatSender"]
