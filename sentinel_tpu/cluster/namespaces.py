"""Namespace partitioning across pods — the second scaling tier.

Tier 1 (ICI): within one pod, the flow axis of the engine state shards over
the pod's chips (``DefaultTokenService(mesh=...)`` →
``parallel.sharding.make_sharded_decide``).

Tier 2 (DCN): namespaces partition across pods, mirroring the reference's
ownership model — a namespace is served by exactly ONE token server
(``ClusterFlowRuleManager.java:67`` keeps namespace → flowId sets,
``ConnectionManager.java:35`` namespace → connection group; clients are
pointed at their namespace's server by assignment config). Decisions never
cross pods, so the cross-pod (DCN) traffic is only:

- assignment changes (this module's ``NamespaceAssignment``),
- global observability (``aggregate_snapshots`` — the dashboard-facing sum
  of per-pod metric snapshots).

Counter state is ephemeral by design (sliding windows ≤ seconds; SURVEY §5
checkpoint stance), so moving a namespace = repoint rules + clients; the new
owner starts with fresh windows — the same behavior the reference exhibits
when a token server restarts or an assignment changes.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

from sentinel_tpu.core.log import record_log
from sentinel_tpu.engine.rules import ClusterFlowRule

# pods whose snapshot fetch raised during aggregate_snapshots — surfaced by
# the exporter as sentinel_assignment_snapshot_errors_total so a pod that
# silently vanishes from the dashboard sum shows up as a counter instead
_SNAPSHOT_ERRORS = 0
_SNAPSHOT_ERRORS_LOCK = threading.Lock()


def count_snapshot_error(n: int = 1) -> None:
    global _SNAPSHOT_ERRORS
    with _SNAPSHOT_ERRORS_LOCK:
        _SNAPSHOT_ERRORS += int(n)


def snapshot_error_total() -> int:
    with _SNAPSHOT_ERRORS_LOCK:
        return _SNAPSHOT_ERRORS


def reset_snapshot_errors_for_tests() -> None:
    global _SNAPSHOT_ERRORS
    with _SNAPSHOT_ERRORS_LOCK:
        _SNAPSHOT_ERRORS = 0


class NamespaceAssignment:
    """namespace → pod ownership map with a generation counter.

    The generation bumps on every change so routers can cheaply detect
    staleness (the reference pushes new assignment configs through the
    property system; here the property payload carries ``snapshot()``).
    """

    def __init__(self, initial: Optional[Mapping[str, str]] = None):
        self._lock = threading.Lock()
        self._pod_of: Dict[str, str] = dict(initial or {})
        self.generation = 0

    def assign(self, namespace: str, pod_id: str) -> None:
        with self._lock:
            if self._pod_of.get(namespace) != pod_id:
                self._pod_of[namespace] = pod_id
                self.generation += 1

    move = assign  # moving is just re-assigning; counters don't travel

    def unassign(self, namespace: str) -> None:
        with self._lock:
            if self._pod_of.pop(namespace, None) is not None:
                self.generation += 1

    def pod_of(self, namespace: str) -> Optional[str]:
        with self._lock:
            return self._pod_of.get(namespace)

    def namespaces_of(self, pod_id: str) -> List[str]:
        with self._lock:
            return sorted(
                ns for ns, p in self._pod_of.items() if p == pod_id
            )

    def snapshot(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._pod_of)


def partition_rules(
    rules: Iterable[ClusterFlowRule], assignment: NamespaceAssignment
) -> Dict[str, List[ClusterFlowRule]]:
    """Split a global rule set by owning pod (namespace → flowId set,
    ``ClusterFlowRuleManager.java:67``). Rules in unassigned namespaces are
    grouped under ``None`` so callers can surface the config error instead
    of silently dropping quota enforcement."""
    out: Dict[str, List[ClusterFlowRule]] = {}
    for rule in rules:
        out.setdefault(assignment.pod_of(rule.namespace), []).append(rule)
    return out


def flow_namespaces(rules: Iterable[ClusterFlowRule]) -> Dict[int, str]:
    """flow_id → namespace routing key (what clients use to pick a pod)."""
    return {r.flow_id: r.namespace for r in rules}


def aggregate_snapshots(
    snapshots: Iterable[Mapping[int, Mapping[str, float]]],
) -> Dict[int, Dict[str, float]]:
    """DCN-tier metric aggregation: sum per-flow metric snapshots from every
    pod into the global view the dashboard shows. Namespace ownership makes
    this a disjoint union in steady state, but a snapshot taken mid-move can
    see a flow on two pods — summing (not overwriting) keeps totals right.

    Items may be mappings or zero-arg callables fetching one (a remote pod's
    stats pull). A pod whose fetch raises — or whose payload is malformed —
    contributes NOTHING (no half-merged rows), is logged, and is counted in
    ``sentinel_assignment_snapshot_errors_total``; it must not abort the
    other pods' aggregation or silently vanish from the sum."""
    out: Dict[int, Dict[str, float]] = {}
    for i, snap in enumerate(snapshots):
        try:
            if callable(snap):
                snap = snap()
            staged: Dict[int, Dict[str, float]] = {}
            for fid, metrics in snap.items():
                slot = staged.setdefault(int(fid), {})
                for k, v in metrics.items():
                    slot[k] = slot.get(k, 0.0) + float(v)
        except Exception:
            record_log.exception(
                "pod snapshot %d failed during aggregation; skipping it", i,
            )
            count_snapshot_error()
            continue
        for fid, metrics in staged.items():
            slot = out.setdefault(fid, {})
            for k, v in metrics.items():
                slot[k] = slot.get(k, 0.0) + v
    return out
