"""Namespace partitioning across pods — the second scaling tier.

Tier 1 (ICI): within one pod, the flow axis of the engine state shards over
the pod's chips (``DefaultTokenService(mesh=...)`` →
``parallel.sharding.make_sharded_decide``).

Tier 2 (DCN): namespaces partition across pods, mirroring the reference's
ownership model — a namespace is served by exactly ONE token server
(``ClusterFlowRuleManager.java:67`` keeps namespace → flowId sets,
``ConnectionManager.java:35`` namespace → connection group; clients are
pointed at their namespace's server by assignment config). Decisions never
cross pods, so the cross-pod (DCN) traffic is only:

- assignment changes (this module's ``NamespaceAssignment``),
- global observability (``aggregate_snapshots`` — the dashboard-facing sum
  of per-pod metric snapshots).

Counter state is ephemeral by design (sliding windows ≤ seconds; SURVEY §5
checkpoint stance), so moving a namespace = repoint rules + clients; the new
owner starts with fresh windows — the same behavior the reference exhibits
when a token server restarts or an assignment changes.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

from sentinel_tpu.core.log import record_log
from sentinel_tpu.engine.rules import ClusterFlowRule

# pods whose snapshot fetch raised during aggregate_snapshots — surfaced by
# the exporter as sentinel_assignment_snapshot_errors_total so a pod that
# silently vanishes from the dashboard sum shows up as a counter instead
_SNAPSHOT_ERRORS = 0
_SNAPSHOT_ERRORS_LOCK = threading.Lock()


def count_snapshot_error(n: int = 1) -> None:
    global _SNAPSHOT_ERRORS
    with _SNAPSHOT_ERRORS_LOCK:
        _SNAPSHOT_ERRORS += int(n)


def snapshot_error_total() -> int:
    with _SNAPSHOT_ERRORS_LOCK:
        return _SNAPSHOT_ERRORS


def reset_snapshot_errors_for_tests() -> None:
    global _SNAPSHOT_ERRORS
    with _SNAPSHOT_ERRORS_LOCK:
        _SNAPSHOT_ERRORS = 0


# flow copies dropped by the mid-MOVE dedupe in aggregate_snapshots —
# surfaced as sentinel_assignment_move_dedup_total so a redirect window
# that lingers (end_redirect never called) is visible on the dashboard
_MOVE_DEDUP = 0
_MOVE_DEDUP_LOCK = threading.Lock()


def count_move_dedup(n: int = 1) -> None:
    global _MOVE_DEDUP
    with _MOVE_DEDUP_LOCK:
        _MOVE_DEDUP += int(n)


def move_dedup_total() -> int:
    with _MOVE_DEDUP_LOCK:
        return _MOVE_DEDUP


def reset_move_dedup_for_tests() -> None:
    global _MOVE_DEDUP
    with _MOVE_DEDUP_LOCK:
        _MOVE_DEDUP = 0


class NamespaceAssignment:
    """namespace → pod ownership map with a generation counter.

    The generation bumps on every change so routers can cheaply detect
    staleness (the reference pushes new assignment configs through the
    property system; here the property payload carries ``snapshot()``).
    """

    def __init__(self, initial: Optional[Mapping[str, str]] = None):
        self._lock = threading.Lock()
        self._pod_of: Dict[str, str] = dict(initial or {})
        self.generation = 0

    def assign(self, namespace: str, pod_id: str) -> None:
        with self._lock:
            if self._pod_of.get(namespace) != pod_id:
                self._pod_of[namespace] = pod_id
                self.generation += 1

    move = assign  # moving is just re-assigning; counters don't travel

    def unassign(self, namespace: str) -> None:
        with self._lock:
            if self._pod_of.pop(namespace, None) is not None:
                self.generation += 1

    def pod_of(self, namespace: str) -> Optional[str]:
        with self._lock:
            return self._pod_of.get(namespace)

    def namespaces_of(self, pod_id: str) -> List[str]:
        with self._lock:
            return sorted(
                ns for ns, p in self._pod_of.items() if p == pod_id
            )

    def snapshot(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._pod_of)


def partition_rules(
    rules: Iterable[ClusterFlowRule], assignment: NamespaceAssignment
) -> Dict[str, List[ClusterFlowRule]]:
    """Split a global rule set by owning pod (namespace → flowId set,
    ``ClusterFlowRuleManager.java:67``). Rules in unassigned namespaces are
    grouped under ``None`` so callers can surface the config error instead
    of silently dropping quota enforcement."""
    out: Dict[str, List[ClusterFlowRule]] = {}
    for rule in rules:
        out.setdefault(assignment.pod_of(rule.namespace), []).append(rule)
    return out


def flow_namespaces(rules: Iterable[ClusterFlowRule]) -> Dict[int, str]:
    """flow_id → namespace routing key (what clients use to pick a pod)."""
    return {r.flow_id: r.namespace for r in rules}


def aggregate_snapshots(
    snapshots: Iterable[Mapping[int, Mapping[str, float]]],
    global_budgets: Optional[Mapping[int, float]] = None,
) -> Dict[object, Dict[str, float]]:
    """DCN-tier metric aggregation: sum per-flow metric snapshots from every
    pod into the global view the dashboard shows.

    Namespace ownership makes this a disjoint union in steady state, but
    during a MOVE's redirect window BOTH pods report the flow: the source's
    counters froze at the begin-move device step (its snapshot rows carry a
    ``moved_epoch`` marker stamping the shard-map epoch), while the
    destination counts live traffic. Summing both double-reports the frozen
    window, so marked rows dedupe: a flow with any UNMARKED copy keeps only
    the unmarked copies; a flow seen only as marked copies (destination's
    snapshot missing from this pull) keeps the single copy with the highest
    shard-map epoch. Dropped copies are counted in
    ``sentinel_assignment_move_dedup_total``. The ``moved_epoch`` marker
    itself never reaches the output — it is routing metadata, not a metric.

    Items may be mappings or zero-arg callables fetching one (a remote pod's
    stats pull). A pod whose fetch raises — or whose payload is malformed —
    contributes NOTHING (no half-merged rows), is logged, and is counted in
    ``sentinel_assignment_snapshot_errors_total``; it must not abort the
    other pods' aggregation or silently vanish from the sum.

    ``global_budgets`` (flow_id → the coordinator's budget tokens) adds a
    ``"global"`` block for ``clusterServerStats``: fleet-wide LEASED-share
    charge summed across pods vs the global budget, per flow — the one
    number that says whether a hierarchical limit is holding."""
    # staged per-flow copies: (metrics-without-marker, moved_epoch or None)
    copies: Dict[int, List[Tuple[Dict[str, float], Optional[float]]]] = {}
    for i, snap in enumerate(snapshots):
        try:
            if callable(snap):
                snap = snap()
            staged: Dict[int, Tuple[Dict[str, float], Optional[float]]] = {}
            for fid, metrics in snap.items():
                row: Dict[str, float] = {}
                moved: Optional[float] = None
                for k, v in metrics.items():
                    if k == "moved_epoch":
                        moved = float(v)
                    else:
                        row[k] = row.get(k, 0.0) + float(v)
                staged[int(fid)] = (row, moved)
        except Exception:
            record_log.exception(
                "pod snapshot %d failed during aggregation; skipping it", i,
            )
            count_snapshot_error()
            continue
        for fid, copy in staged.items():
            copies.setdefault(fid, []).append(copy)
    out: Dict[object, Dict[str, float]] = {}
    for fid, rows in copies.items():
        unmarked = [r for r, moved in rows if moved is None]
        if unmarked:
            keep = unmarked
        else:
            # every copy is mid-move/committed-away: keep the newest-epoch
            # one (the closest thing to the authoritative frozen window)
            keep = [max(rows, key=lambda rm: rm[1])[0]]
        if len(keep) < len(rows):
            count_move_dedup(len(rows) - len(keep))
        slot = out.setdefault(fid, {})
        for row in keep:
            for k, v in row.items():
                slot[k] = slot.get(k, 0.0) + v
    if global_budgets is not None:
        glob: Dict[str, Dict[str, float]] = {}
        for fid, budget in global_budgets.items():
            leased = float(
                out.get(int(fid), {}).get("leased_tokens", 0.0)
            )
            budget = float(budget)
            glob[str(int(fid))] = {
                "budget_tokens": budget,
                "leased_tokens": leased,
                "occupancy": leased / budget if budget > 0 else 0.0,
            }
        out["global"] = glob
    return out
