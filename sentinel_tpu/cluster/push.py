"""Server-side push hub: the wire-rev-7 server→client control plane.

One :class:`PushHub` per front door. Connections register a *sink* — a
non-blocking, thread-safe "hand these bytes to this connection's reply
lane" callable (the asyncio door schedules ``writer.write`` on its loop;
the native door enqueues through the C++ plane's per-connection send,
which also covers shm ring connections) — and the hub broadcasts encoded
push frames to every live sink.

Delivery contract (docs/CLUSTER_HA.md "Push plane"):

- **at-most-once, fire-and-forget**: a sink that raises (closed socket,
  full ring) silently drops the frame and is counted in ``dropped``;
  nothing retries, nothing blocks, and no verdict write ever waits on a
  push — the sink primitives are the same non-blocking enqueues the reply
  lanes already use.
- **re-derivable**: every pushed fact has a polling fallback (lease TTL,
  breaker refusal on the wire path, shard-map publish, OVERLOAD answer),
  so a dark channel only widens staleness back to the rev-6 bounds —
  docs/ROBUSTNESS.md carries the push-on vs push-dark table.
- **disarmable**: ``enabled=False`` (the servers' ``push=`` knob) makes
  every emit a no-op; the drills run their push-dark phases through it.

Emitters stamp each frame with the server's wall clock (``stamp_ms``) so
the client-side apply can record end-to-end staleness, and with a hub-
local xid sequence the staleness probes key on.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Dict

from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.metrics.server import server_metrics as _SM

# metric/type-label names for the five push frame types
PUSH_TYPE_NAMES: Dict[int, str] = {
    int(P.MsgType.LEASE_REVOKE): "lease_revoke",
    int(P.MsgType.BREAKER_FLIP): "breaker_flip",
    int(P.MsgType.RULE_EPOCH_INVALIDATE): "rule_epoch_invalidate",
    int(P.MsgType.SHARD_MAP_PUSH): "shard_map_push",
    int(P.MsgType.BROWNOUT_ADVISORY): "brownout_advisory",
}


class PushHub:
    """Registry of per-connection push sinks + the five emitters."""

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._sinks: Dict[object, Callable[[bytes], None]] = {}
        self._xid = itertools.count(1)
        self._sent: Dict[str, int] = {}
        self._dropped = 0

    # -- sink lifecycle -----------------------------------------------------
    def attach(self, key, send_fn: Callable[[bytes], None]) -> None:
        """Register ``key``'s sink (most recent wins — a reconnect under
        the same key replaces the dead sink)."""
        with self._lock:
            self._sinks[key] = send_fn

    def detach(self, key) -> None:
        with self._lock:
            self._sinks.pop(key, None)

    def connections(self) -> int:
        with self._lock:
            return len(self._sinks)

    # -- broadcast core -----------------------------------------------------
    def _broadcast(self, frame: bytes, type_name: str) -> int:
        """Hand ``frame`` to every live sink; returns deliveries that did
        not raise. Never blocks, never raises."""
        if not self.enabled:
            return 0
        with self._lock:
            sinks = list(self._sinks.values())
        sent = 0
        dropped = 0
        for fn in sinks:
            try:
                fn(frame)
                sent += 1
            except Exception:
                dropped += 1
        if dropped:
            with self._lock:
                self._dropped += dropped
        if sent:
            with self._lock:
                self._sent[type_name] = self._sent.get(type_name, 0) + sent
            try:
                _SM().count_push_frame(type_name, sent)
            except Exception:
                pass
        return sent

    @staticmethod
    def _now_ms() -> int:
        return int(time.time() * 1000)

    # -- emitters -----------------------------------------------------------
    def push_lease_revoke(
        self, lease_id: int, flow_id: int, tokens: int = 0
    ) -> int:
        n = self._broadcast(
            P.encode_push_lease_revoke(
                next(self._xid), self._now_ms(), int(lease_id),
                int(flow_id), int(tokens),
            ),
            "lease_revoke",
        )
        if n:
            try:
                _SM().count_push_revocation()
            except Exception:
                pass
        return n

    def push_breaker_flip(
        self, flow_id: int, state: int, retry_after_ms: int = 0
    ) -> int:
        return self._broadcast(
            P.encode_push_breaker_flip(
                next(self._xid), self._now_ms(), int(flow_id), int(state),
                int(retry_after_ms),
            ),
            "breaker_flip",
        )

    def push_rule_epoch(self, epoch: int) -> int:
        return self._broadcast(
            P.encode_push_rule_epoch(
                next(self._xid), self._now_ms(), int(epoch)
            ),
            "rule_epoch_invalidate",
        )

    def push_shard_map(self, doc: bytes) -> int:
        """``doc`` is the zlib-compressed ShardMap JSON. A doc too big for
        one frame is dropped here (counted) — the polling publish path
        still carries it."""
        try:
            frame = P.encode_push_shard_map(
                next(self._xid), self._now_ms(), bytes(doc)
            )
        except ValueError:
            with self._lock:
                self._dropped += 1
            return 0
        return self._broadcast(frame, "shard_map_push")

    def push_brownout(self, level: int, retry_ms: int = 0) -> int:
        return self._broadcast(
            P.encode_push_brownout(
                next(self._xid), self._now_ms(), int(level), int(retry_ms)
            ),
            "brownout_advisory",
        )

    # -- observability ------------------------------------------------------
    def stats(self) -> dict:
        """The ``clusterServerStats`` ``push`` block's hub half."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "connections": len(self._sinks),
                "sent": dict(self._sent),
                "dropped": self._dropped,
            }
