"""Native-front-door token server: C++ epoll data plane, Python device loop.

The round-3 gap: the asyncio front door served ~1/8 of the device kernel's
ceiling — per-frame Python costs dominated. Here the whole per-frame path
(socket reads, length-prefixed framing, BATCH_FLOW/FLOW decode, verdict
frame encode, socket writes, idle reaping) lives in
``native/src/sentinel_frontdoor.cpp``; Python's serving loop is one blocking
``wait_batch`` → ``TokenService.request_batch_arrays`` → ``submit`` cycle
per DEVICE STEP, regardless of how many frames or connections fed it.
This is the netty-pipeline analog (``NettyTransportServer.java:73-101``)
taken to its TPU conclusion: the host's job is to keep the device fed.

Control-plane frames (PING handshake, PARAM_FLOW, CONCURRENT_*) and
open/close events surface through a low-rate poll thread so namespace
connection groups (AVG_LOCAL scaling) and the host-side paths stay exactly
as in the asyncio server. API-compatible with ``TokenServer`` (start/stop/
port/connections/tuning_kwargs) so ``apply_cluster_mode`` and the benches
can switch via ``native=True``.

Dispatcher concurrency: ``n_dispatchers`` threads run the wait→step→submit
cycle. The service lock serializes only device dispatch, so with 2 threads
one batch's host prep and verdict materialization overlap the other's
device step (the same overlap the asyncio server got from ``to_thread``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

import numpy as np

from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.connection import ConnectionManager
from sentinel_tpu.cluster.token_service import TokenService
from sentinel_tpu.core.log import record_log
from sentinel_tpu.engine import TokenStatus
from sentinel_tpu.metrics.profiler import ProfilerHook
from sentinel_tpu.metrics.server import server_metrics

_SM = server_metrics()


def native_available() -> bool:
    try:
        from sentinel_tpu.native import lib as native_lib

        return native_lib.available()
    except Exception:
        return False


class NativeTokenServer:
    def __init__(
        self,
        service: TokenService,
        host: str = "127.0.0.1",
        port: int = 18730,
        max_batch: int = 16384,
        n_dispatchers: int = 2,
        idle_ttl_s: Optional[float] = 600.0,
        arena_cap: int = 65536,
        profile_dir: Optional[str] = None,
        metrics_port: Optional[int] = None,
        snapshot_dir: Optional[str] = None,
        snapshot_period_s: Optional[float] = None,
    ):
        from sentinel_tpu.native.lib import Frontdoor  # raises if unbuilt

        self._Frontdoor = Frontdoor
        self.service = service
        self.host = host
        self.port = port
        self.max_batch = max_batch
        self.n_dispatchers = max(1, int(n_dispatchers))
        self.idle_ttl_s = idle_ttl_s
        self.arena_cap = arena_cap
        self._door = None
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        notify = getattr(service, "connected_count_changed", None)
        self.connections = ConnectionManager(on_count_changed=notify)
        self._addr_by_conn = {}  # (fd, gen) → address
        self._addr_lock = threading.Lock()
        # same observability surface as the asyncio front door: opt-in
        # profiler command target + optional standalone /metrics endpoint
        self.profile_dir = profile_dir
        self.profiler = ProfilerHook(default_dir=profile_dir)
        self.metrics_port = metrics_port
        self._metrics_exporter = None
        self._gauge_fns: dict = {}
        # HA state snapshots: same contract as the asyncio front door
        self.snapshot_dir = snapshot_dir or os.environ.get(
            "SENTINEL_SNAPSHOT_DIR"
        ) or None
        self.snapshot_period_s = snapshot_period_s
        self._snapshots = None

    def tuning_kwargs(self) -> dict:
        return dict(
            max_batch=self.max_batch,
            n_dispatchers=self.n_dispatchers,
            idle_ttl_s=self.idle_ttl_s,
            arena_cap=self.arena_cap,
            profile_dir=self.profile_dir,
            metrics_port=self.metrics_port,
            snapshot_dir=self.snapshot_dir,
            snapshot_period_s=self.snapshot_period_s,
        )

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._door is not None:
            return
        warmup = getattr(self.service, "warmup", None)
        if warmup is not None:
            warmup()
        if self.snapshot_dir and hasattr(self.service, "import_state"):
            from sentinel_tpu.ha.snapshot import restore_latest

            if not self.service.current_rules():  # cold service only
                restore_latest(self.service, self.snapshot_dir)
        reopen = getattr(self.service, "reopen", None)
        if reopen is not None:
            reopen()
        self._stop.clear()
        self._door = self._Frontdoor(
            self.host, self.port, arena_cap=self.arena_cap
        )
        self.port = self._door.port
        if self.idle_ttl_s:
            self._door.set_idle_ttl(int(self.idle_ttl_s * 1000))
        for i in range(self.n_dispatchers):
            t = threading.Thread(
                target=self._dispatch_loop,
                name=f"sentinel-native-dispatch-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        t = threading.Thread(
            target=self._control_loop, name="sentinel-native-control",
            daemon=True,
        )
        t.start()
        self._threads.append(t)
        if self.profile_dir:
            try:
                self.profiler.start(self.profile_dir)
            except Exception:
                record_log.exception("profiler start failed; serving anyway")
        # gauges: the native door keeps its own counters (stats()); surface
        # the in-flight depth and the namespace connection groups. The C++
        # plane owns the request queue, so queue_depth reads pending frames
        # when the door exports them, else 0.
        self._gauge_fns = {
            "queue_depth": lambda: float(
                (self.stats() or {}).get("pending_frames", 0)
            ),
            "connections": lambda: sum(
                len(addrs) for addrs in self.connections.snapshot().values()
            ),
        }
        for name, fn in self._gauge_fns.items():
            _SM.register_gauge(name, fn)
        if self.metrics_port is not None:
            from sentinel_tpu.metrics.exporter import PrometheusExporter

            self._metrics_exporter = PrometheusExporter(
                host="0.0.0.0", port=self.metrics_port
            ).start()
            self.metrics_port = self._metrics_exporter.port
        if self.snapshot_dir and hasattr(self.service, "export_state"):
            from sentinel_tpu.ha.snapshot import SnapshotManager

            self._snapshots = SnapshotManager(
                self.service, self.snapshot_dir,
                period_s=self.snapshot_period_s,
            ).start()
        record_log.info(
            "native token server listening on %s:%d (%d dispatchers)",
            self.host, self.port, self.n_dispatchers,
        )

    def stop(self) -> None:
        if self._door is None:
            return
        if self._snapshots is not None:
            self._snapshots.stop(final_save=True)
            self._snapshots = None
        if self.profiler.active:
            self.profiler.stop()
        if self._metrics_exporter is not None:
            self._metrics_exporter.stop()
            self._metrics_exporter = None
        for name, fn in self._gauge_fns.items():
            _SM.unregister_gauge(name, fn)
        self._gauge_fns = {}
        self._stop.set()
        self._door.stop()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []
        self._door = None
        # the door closed every socket without emitting CTRL_CLOSE (the
        # control thread is already down), so deregister the clients here —
        # a restart would otherwise inherit phantom connections that keep
        # deflating AVG_LOCAL per-connection budgets
        for key in list(self._addr_by_conn):
            address = self._addr_by_conn.pop(key, None)
            if address is not None:
                self.connections.remove_address(address)
        close = getattr(self.service, "close", None)
        if close is not None:
            close()

    # -- data plane ---------------------------------------------------------
    def _dispatch_loop(self) -> None:
        door = self._door
        service = self.service
        while not self._stop.is_set():
            try:
                # max_batch bounds one pull (clamped to >= one max frame);
                # the remainder stays queued for the other dispatchers
                got = door.wait_batch(timeout_ms=100, max_n=self.max_batch)
            except Exception:
                if self._stop.is_set():
                    return
                raise
            if got is None:
                continue
            ids, counts, prios, frames = got
            _SM.batch_size.record(len(ids))
            t_decide = time.perf_counter()
            try:
                # pulls larger than the engine batch size pipeline
                # internally: request_batch_arrays dispatches ALL chunk
                # steps before blocking on the first verdict (the
                # dispatch/materialize split in DefaultTokenService);
                # across threads, another dispatcher's step overlaps this
                # one's materialization (the service lock covers dispatch
                # only)
                status, remaining, wait = service.request_batch_arrays(
                    ids, counts, prios
                )
            except Exception:
                record_log.exception("device step failed; failing batch")
                n = len(ids)
                status = np.full(n, int(TokenStatus.FAIL), np.int8)
                remaining = np.zeros(n, np.int32)
                wait = np.zeros(n, np.int32)
            t_write = time.perf_counter()
            _SM.decide_ms.record((t_write - t_decide) * 1e3)
            try:
                door.submit(frames, status, remaining, wait)
            except Exception:
                if not self._stop.is_set():
                    record_log.exception("native submit failed")
            _SM.write_ms.record((time.perf_counter() - t_write) * 1e3)

    # -- control plane ------------------------------------------------------
    def _control_loop(self) -> None:
        door = self._door
        service = self.service
        while not self._stop.is_set():
            try:
                item = door.next_control()
            except Exception:
                if self._stop.is_set():
                    return
                raise
            if item is None:
                self._stop.wait(0.002)
                continue
            kind, fd, gen, payload = item
            if kind == door.CTRL_OPEN:
                address = payload.decode("latin-1")
                with self._addr_lock:
                    self._addr_by_conn[(fd, gen)] = address
                self.connections.attach_closer(
                    address,
                    lambda fd=fd, gen=gen: door.close_conn(fd, gen),
                )
                continue
            if kind == door.CTRL_CLOSE:
                with self._addr_lock:
                    address = self._addr_by_conn.pop((fd, gen), None)
                if address:
                    self.connections.remove_address(address)
                continue
            # kind == CTRL_FRAME: a non-data-plane request
            with self._addr_lock:
                address = self._addr_by_conn.get((fd, gen), f"fd{fd}")
            try:
                req = P.decode_request(payload)
            except Exception:
                record_log.warning("bad control frame; closing %s", address)
                door.close_conn(fd, gen)
                continue
            try:
                rsp = self._handle_control(req, address)
            except Exception:
                record_log.exception("%s control request failed",
                                     type(req).__name__)
                rsp = P.FlowResponse(
                    req.xid, getattr(req, "msg_type", P.MsgType.PING),
                    int(TokenStatus.FAIL),
                )
            door.send(fd, gen, P.encode_response(rsp))

    def _handle_control(self, req, address: str) -> P.FlowResponse:
        service = self.service
        if isinstance(req, P.Ping):
            count = self.connections.add(req.namespace, address)
            return P.FlowResponse(req.xid, P.MsgType.PING, 0, remaining=count)
        self.connections.touch(address)
        if req.msg_type == P.MsgType.PARAM_FLOW:
            r = service.request_params_token(
                req.flow_id, req.count, req.param_hashes
            )
            return P.FlowResponse(
                req.xid, req.msg_type, int(r.status), r.remaining, r.wait_ms
            )
        if req.msg_type == P.MsgType.CONCURRENT_ACQUIRE:
            r = service.request_concurrent_token(
                req.flow_id, req.count, req.prioritized
            )
            return P.FlowResponse(
                req.xid, req.msg_type, int(r.status), r.remaining, r.wait_ms,
                r.token_id,
            )
        if req.msg_type == P.MsgType.CONCURRENT_RELEASE:
            r = service.release_concurrent_token(req.flow_id)
            return P.FlowResponse(req.xid, req.msg_type, int(r.status))
        return P.FlowResponse(req.xid, req.msg_type, int(TokenStatus.FAIL))

    def stats(self) -> dict:
        return self._door.stats() if self._door is not None else {}
