"""Native-front-door token server: C++ epoll data plane, Python device loop.

The round-3 gap: the asyncio front door served ~1/8 of the device kernel's
ceiling — per-frame Python costs dominated. Here the whole per-frame path
(socket reads, length-prefixed framing, BATCH_FLOW/FLOW decode, verdict
frame encode, socket writes, idle reaping) lives in
``native/src/sentinel_frontdoor.cpp``; Python's serving loop is one blocking
``wait_batch`` → ``TokenService.request_batch_arrays`` → ``submit`` cycle
per DEVICE STEP, regardless of how many frames or connections fed it.
This is the netty-pipeline analog (``NettyTransportServer.java:73-101``)
taken to its TPU conclusion: the host's job is to keep the device fed.

Control-plane frames (PING handshake, PARAM_FLOW, CONCURRENT_*) and
open/close events surface through a low-rate poll thread so namespace
connection groups (AVG_LOCAL scaling) and the host-side paths stay exactly
as in the asyncio server. API-compatible with ``TokenServer`` (start/stop/
port/connections/tuning_kwargs) so ``apply_cluster_mode`` and the benches
can switch via ``native=True``.

Serving pipeline: three decoupled lanes with bounded handoff queues,
instead of one thread doing wait→step→submit in series. The **intake
lane** pulls decoded frames from the C++ door and hands copies to the
**device lane**, which drains everything queued (bounded by
``fuse_depth`` pulls of host prep), concatenates it, and issues ONE
dispatch — the token service's fusion ladder then folds full engine
frames into a single chained ``lax.scan`` device step, so the fixed
per-dispatch overhead (20–50ms/bucket in BENCH_r05) is paid once per
fused group. ``n_dispatchers`` **reply lanes** block on the async
verdicts, slice them back per pull, and submit — so host-side prep and
reply encoding overlap device time instead of serializing behind it.
Fusion depth adapts to load by construction: an idle queue yields
single-frame dispatches (no added latency), a backed-up queue yields
deep fused steps (max amortization).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from sentinel_tpu import chaos
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.connection import ConnectionManager
from sentinel_tpu.cluster.token_service import TokenService
from sentinel_tpu.core.log import record_log
from sentinel_tpu.engine import TokenStatus
from sentinel_tpu.metrics.profiler import ProfilerHook
from sentinel_tpu.metrics.server import server_metrics
from sentinel_tpu.overload import AdmissionController, BrownoutLevel
from sentinel_tpu.trace import ring as _TR
from sentinel_tpu.trace.slo import slo_plane as _slo_plane

_SM = server_metrics()
_OVERLOAD = int(TokenStatus.OVERLOAD)
_STANDBY = int(TokenStatus.STANDBY)


def native_available() -> bool:
    try:
        from sentinel_tpu.native import lib as native_lib

        return native_lib.available()
    except Exception:
        return False


class NativeTokenServer:
    def __init__(
        self,
        service: TokenService,
        host: str = "127.0.0.1",
        port: int = 18730,
        max_batch: int = 16384,
        n_dispatchers: int = 2,
        fuse_depth: int = 4,
        max_device_inflight: int = 2,
        intake_shards: int = 1,
        intake_timeout_ms: int = 20,
        idle_ttl_s: Optional[float] = 600.0,
        arena_cap: int = 65536,
        profile_dir: Optional[str] = None,
        metrics_port: Optional[int] = None,
        snapshot_dir: Optional[str] = None,
        snapshot_period_s: Optional[float] = None,
        shed_age_ms: Optional[float] = 1000.0,
        drain_timeout_s: float = 10.0,
        overload: Optional[AdmissionController] = None,
        standby_of: Optional[str] = None,
        promote_after_ms: Optional[float] = None,
        replicate_to: Optional[Sequence] = None,
        repl_interval_ms: Optional[float] = None,
        shm_dir: Optional[str] = None,
        shm_spin_us: Optional[int] = None,
        push: bool = True,
    ):
        from sentinel_tpu.native.lib import Frontdoor  # raises if unbuilt

        self._Frontdoor = Frontdoor
        # opt-in shared-memory ring door for co-located sidecar clients:
        # one extra intake lane pulls from the ring poller and drains into
        # the SAME dispatch semaphore, so the fusion ladder fuses the union
        # of TCP and shm bursts; replies scatter-encode straight into each
        # client's response ring (zero syscalls steady-state)
        self.shm_dir = shm_dir
        self.shm_spin_us = shm_spin_us
        self._shm_door = None
        if shm_dir is not None:
            from sentinel_tpu.native.lib import ShmDoor  # raises if stale

            self._ShmDoor = ShmDoor
        self.service = service
        self.host = host
        self.port = port
        self.max_batch = max_batch
        self.n_dispatchers = max(1, int(n_dispatchers))
        # SO_REUSEPORT intake sharding: N doors bound to the SAME port, the
        # kernel hash-spreads connections across them, and each door gets a
        # dedicated intake thread with its own bounded handoff queue. The
        # single device lane drains the UNION of the shard queues, so the
        # fusion ladder still sees one merged burst — sharding multiplies
        # intake pull/decode bandwidth without forking the device pipeline.
        self.intake_shards = max(1, int(intake_shards))
        # fuse_depth bounds how many queued intake pulls the device lane
        # folds into one dispatch (each pull is itself up to max_batch
        # rows) — the host-prep budget of the adaptive frame fusion
        self.fuse_depth = max(1, int(fuse_depth))
        # double-buffering bound: fused groups dispatched but not yet
        # materialized. 2 overlaps the next group's host prep (queue
        # drain, concat, shed masks, staging) with the previous group's
        # device compute; higher depths only add verdict latency, since
        # dispatch order is already the state-chain order. 1 restores
        # the serialized lane.
        self.max_device_inflight = max(1, int(max_device_inflight))
        self._device_inflight = 0
        self._device_cv = threading.Condition()
        # intake poll granularity only — the C++ door wakes the waiter the
        # moment the first frame queues, so this never delays a ready frame
        self.intake_timeout_ms = max(1, int(intake_timeout_ms))
        self.idle_ttl_s = idle_ttl_s
        self.arena_cap = arena_cap
        # the C++ door strips the wire deadline before Python sees a pull,
        # so the native lanes shed by AGE instead: a pull older than this
        # when the device lane picks it up is answered OVERLOAD without a
        # dispatch (every client budget is long gone at 1s; None disables).
        # Also the bounded-wait budget for the intake→device handoff — a
        # full dispatch queue refuses (answers OVERLOAD) after this long
        # instead of blocking the intake lane forever.
        self.shed_age_ms = shed_age_ms
        # lane join budget in stop() before _abandon flips drops on
        self.drain_timeout_s = max(0.1, float(drain_timeout_s))
        # BBR-style admission gate + brownout ladder (overload/admission.py)
        self.overload = (
            overload if overload is not None else AdmissionController()
        )
        self._door = None  # door 0 (back-compat handle; owns self.port)
        self._doors: List = []
        self._threads: List[threading.Thread] = []
        self._lane_threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._intake_stop = threading.Event()
        self._abandon = threading.Event()  # give up lane drain (dead lane)
        self._shard_qs: List[queue.Queue] = []
        self._dispatch_sem: Optional[threading.Semaphore] = None
        self._dispatch_q: Optional[queue.Queue] = None  # alias: shard 0's q
        self._reply_q: Optional[queue.Queue] = None
        self._staging = None  # StagingPool of intake decode blocks
        notify = getattr(service, "connected_count_changed", None)
        self.connections = ConnectionManager(on_count_changed=notify)
        self._addr_by_conn = {}  # (fd, gen) → address
        self._addr_lock = threading.Lock()
        # same observability surface as the asyncio front door: opt-in
        # profiler command target + optional standalone /metrics endpoint
        self.profile_dir = profile_dir
        self.profiler = ProfilerHook(default_dir=profile_dir)
        self.metrics_port = metrics_port
        self._metrics_exporter = None
        self._gauge_fns: dict = {}
        # HA state snapshots: same contract as the asyncio front door
        self.snapshot_dir = snapshot_dir or os.environ.get(
            "SENTINEL_SNAPSHOT_DIR"
        ) or None
        self.snapshot_period_s = snapshot_period_s
        self._snapshots = None
        # warm-standby replication roles: same contract as TokenServer —
        # standby_of= refuses data-plane traffic with TokenStatus.STANDBY
        # until promoted while rev-3 frames stream state in; replicate_to=
        # ships deltas out (see cluster/server.py for the full rationale)
        self.standby_of = standby_of
        self.promote_after_ms = promote_after_ms
        self.replicate_to = list(replicate_to) if replicate_to else None
        self.repl_interval_ms = repl_interval_ms
        self.applier = None
        self.replicator = None
        self._repl_sessions: dict = {}  # (fd, gen) → ReplSession
        # rev-4 namespace-move channel (cluster.rebalance): one MoveSession
        # per inbound connection, same lifecycle as _repl_sessions
        from sentinel_tpu.cluster.rebalance import MoveTarget

        self.move_target = MoveTarget(service)
        self._move_sessions: dict = {}  # (fd, gen) → MoveSession
        # rev-7 push plane (cluster.push): sinks registered per (fd, gen)
        # at CTRL_OPEN hand encoded push frames to door.send — the same
        # non-blocking C++ send queue the control replies use, which also
        # covers shm ring connections (their door routes sends onto the
        # response lane). push=False disarms every emit.
        from sentinel_tpu.cluster.push import PushHub

        self.push_hub = PushHub(enabled=push)
        attach_hub = getattr(service, "attach_push_hub", None)
        if attach_hub is not None:
            attach_hub(self.push_hub)
        self.overload.on_level_change = (
            lambda level, retry_ms: self.push_hub.push_brownout(
                level, retry_ms
            )
        )

    def tuning_kwargs(self) -> dict:
        return dict(
            max_batch=self.max_batch,
            n_dispatchers=self.n_dispatchers,
            fuse_depth=self.fuse_depth,
            max_device_inflight=self.max_device_inflight,
            intake_shards=self.intake_shards,
            intake_timeout_ms=self.intake_timeout_ms,
            idle_ttl_s=self.idle_ttl_s,
            arena_cap=self.arena_cap,
            profile_dir=self.profile_dir,
            metrics_port=self.metrics_port,
            snapshot_dir=self.snapshot_dir,
            snapshot_period_s=self.snapshot_period_s,
            shed_age_ms=self.shed_age_ms,
            drain_timeout_s=self.drain_timeout_s,
            overload=self.overload,
            standby_of=self.standby_of,
            promote_after_ms=self.promote_after_ms,
            replicate_to=self.replicate_to,
            repl_interval_ms=self.repl_interval_ms,
            shm_dir=self.shm_dir,
            shm_spin_us=self.shm_spin_us,
            push=self.push_hub.enabled,
        )

    @property
    def is_standby(self) -> bool:
        """True while this server refuses data-plane traffic (unpromoted
        warm standby)."""
        return self.applier is not None and not self.applier.promoted

    def promote(self, reason: str = "manual") -> bool:
        """Promote a standby to serving. Returns True if the server was a
        standby and is now (or already was) promoted."""
        if self.applier is None:
            return False
        self.applier.promote(reason)
        return True

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._door is not None:
            return
        warmup = getattr(self.service, "warmup", None)
        if warmup is not None:
            warmup()
        if self.snapshot_dir and hasattr(self.service, "import_state"):
            from sentinel_tpu.ha.snapshot import restore_latest

            if not self.service.current_rules():  # cold service only
                restore_latest(self.service, self.snapshot_dir)
        reopen = getattr(self.service, "reopen", None)
        if reopen is not None:
            reopen()
        if self.standby_of is not None:
            # before the listener: the first control frame a standby sees
            # may be the primary's REPL_HELLO
            from sentinel_tpu.ha.replication import StandbyApplier

            self.applier = StandbyApplier(
                self.service, promote_after_ms=self.promote_after_ms,
            ).start()
        self._stop.clear()
        self._intake_stop.clear()
        self._abandon.clear()
        # bounded handoffs: each shard's dispatch queue depth caps how far
        # its intake runs ahead of the device (their union IS the fusion
        # opportunity); reply queue depth caps device-step in-flight count.
        # The semaphore counts queued pulls across ALL shard queues so the
        # device lane blocks on one primitive instead of polling N queues.
        # the shm door (when enabled) is one more intake lane with its own
        # shard queue at index intake_shards — the device lane's union
        # drain and sentinel accounting see it as just another shard
        n_lanes = self.intake_shards + (1 if self.shm_dir is not None else 0)
        self._shard_qs = [
            queue.Queue(maxsize=max(2, 2 * self.fuse_depth))
            for _ in range(n_lanes)
        ]
        self._dispatch_q = self._shard_qs[0]
        self._dispatch_sem = threading.Semaphore(0)
        self._reply_q = queue.Queue(maxsize=max(2, 2 * self.n_dispatchers))
        # recycled intake decode blocks: the C++ arena memcpys each pull
        # straight into one of these (wait_batch_into) and the block rides
        # the pull through device prep and reply submit, then returns to
        # the pool — zero steady-state allocation on the intake path
        from sentinel_tpu.cluster.protocol import StagingPool

        self._staging = StagingPool(
            self._alloc_staging_block,
            capacity=2 * self.fuse_depth + self.n_dispatchers
            + n_lanes + 2,
        )
        # door 0 binds the requested port (possibly 0 → ephemeral); the
        # remaining shards bind the LEARNED concrete port via SO_REUSEPORT
        # (set unconditionally in sn_fd_create) so the kernel spreads
        # accepted connections across the shard listeners
        doors = [self._Frontdoor(self.host, self.port,
                                 arena_cap=self.arena_cap)]
        self.port = doors[0].port
        for _ in range(1, self.intake_shards):
            doors.append(
                self._Frontdoor(self.host, self.port,
                                arena_cap=self.arena_cap)
            )
        if self.shm_dir is not None:
            kw = {}
            if self.shm_spin_us is not None:
                kw["spin_us"] = self.shm_spin_us
            self._shm_door = self._ShmDoor(
                self.shm_dir, arena_cap=self.arena_cap, **kw
            )
            doors.append(self._shm_door)  # control loop + stats cover it
        self._doors = doors
        self._door = doors[0]
        if self.idle_ttl_s:
            for d in doors:
                d.set_idle_ttl(int(self.idle_ttl_s * 1000))
        lanes = [
            threading.Thread(
                target=self._intake_loop,
                args=(i, doors[i], self._shard_qs[i]),
                name=f"sentinel-native-intake-{i}", daemon=True,
            )
            for i in range(self.intake_shards)
        ]
        if self._shm_door is not None:
            # shard index intake_shards: its pulls/occupancy surface under
            # the per-shard intake series like any TCP shard's
            lanes.append(
                threading.Thread(
                    target=self._intake_loop,
                    args=(self.intake_shards, self._shm_door,
                          self._shard_qs[self.intake_shards]),
                    name="sentinel-native-intake-shm", daemon=True,
                )
            )
        lanes.append(
            threading.Thread(
                target=self._device_loop, name="sentinel-native-device",
                daemon=True,
            )
        )
        lanes.extend(
            threading.Thread(
                target=self._reply_loop,
                name=f"sentinel-native-reply-{i}", daemon=True,
            )
            for i in range(self.n_dispatchers)
        )
        for t in lanes:
            t.start()
        self._lane_threads = lanes
        t = threading.Thread(
            target=self._control_loop, name="sentinel-native-control",
            daemon=True,
        )
        t.start()
        self._threads.append(t)
        if self.profile_dir:
            try:
                self.profiler.start(self.profile_dir)
            except Exception:
                record_log.exception("profiler start failed; serving anyway")
        # gauges: the native door keeps its own counters (stats()); surface
        # the in-flight depth and the namespace connection groups. The C++
        # plane owns the request queue, so queue_depth reads pending frames
        # when the door exports them, else 0.
        self._gauge_fns = {
            "queue_depth": lambda: float(
                (self.stats() or {}).get("pending_frames", 0)
            ),
            "dispatch_lane_depth": lambda: float(
                sum(q.qsize() for q in self._shard_qs)
            ),
            "reply_lane_depth": lambda: float(
                self._reply_q.qsize() if self._reply_q else 0
            ),
            "device_inflight": lambda: float(self._device_inflight),
            "connections": lambda: sum(
                len(addrs) for addrs in self.connections.snapshot().values()
            ),
        }
        if self._shm_door is not None:
            def _ring_occupancy(door=self._shm_door):
                try:
                    st = door.stats()
                except Exception:
                    return 0.0
                total = st.get("shm_req_slots_total", 0)
                return st.get("shm_req_slots_used", 0) / total if total else 0.0

            self._gauge_fns["shm_ring_occupancy"] = _ring_occupancy
            # counter series (sentinel_server_shm_{polls,doorbells,
            # ring_full}_total) render from the door's relaxed atomics via
            # this provider — each independently monotonic, no snapshot
            _SM.register_shm_provider(self._shm_stats_provider)
        for name, fn in self._gauge_fns.items():
            _SM.register_gauge(name, fn)
        # hub half of the clusterServerStats `push` block (single-slot
        # provider, same contract as the asyncio door's)
        _SM.register_push_provider(self.push_hub.stats)
        if self.metrics_port is not None:
            from sentinel_tpu.metrics.exporter import PrometheusExporter

            self._metrics_exporter = PrometheusExporter(
                host="0.0.0.0", port=self.metrics_port
            ).start()
            self.metrics_port = self._metrics_exporter.port
        if self.snapshot_dir and hasattr(self.service, "export_state"):
            from sentinel_tpu.ha.snapshot import SnapshotManager

            self._snapshots = SnapshotManager(
                self.service, self.snapshot_dir,
                period_s=self.snapshot_period_s,
            ).start()
        if self.replicate_to and hasattr(self.service, "export_delta"):
            from sentinel_tpu.ha.replication import ReplicationSender

            self.replicator = ReplicationSender(
                self.service, self.replicate_to,
                interval_ms=self.repl_interval_ms,
                sender_id=f"{self.host}:{self.port}",
            ).start()
        record_log.info(
            "native token server listening on %s:%d "
            "(%d intake shards, %d dispatchers)",
            self.host, self.port, self.intake_shards, self.n_dispatchers,
        )

    def _shm_stats_provider(self) -> dict:
        door = self._shm_door
        if door is None:
            return {}
        try:
            st = door.stats()
        except Exception:
            return {}
        return {
            "polls": st.get("shm_polls", 0),
            "doorbells": st.get("shm_doorbells", 0),
            "ring_full": st.get("shm_ring_full", 0),
            "segments": st.get("shm_segments", 0),
        }

    def _alloc_staging_block(self) -> dict:
        """One intake decode block: row arrays sized for the largest pull
        (``max_batch``, clamped so a max-size frame always fits) plus frame
        metadata. ``prios`` is the raw wire byte (what the C++ arena
        holds); ``prios_bool`` is its normalized boolean row, converted in
        place per pull so downstream masking (`~`, shed_mask) sees real
        booleans whatever byte a client sent."""
        rows = max(
            min(int(self.max_batch), int(self.arena_cap)),
            P.MAX_BATCH_PER_FRAME,
        )
        # frames per pull is bounded by rows except for degenerate 0-row
        # frames; the frame capacity below also CAPS how many frames one
        # wait_batch_into may take, so a smaller array just splits a
        # pathological all-empty-frame burst across pulls
        max_f = rows + 64
        return dict(
            ids=np.empty(rows, np.int64),
            counts=np.empty(rows, np.int32),
            prios=np.empty(rows, np.uint8),
            prios_bool=np.empty(rows, bool),
            f_fd=np.empty(max_f, np.int32),
            f_gen=np.empty(max_f, np.int32),
            f_xid=np.empty(max_f, np.int32),
            f_n=np.empty(max_f, np.int32),
            f_type=np.empty(max_f, np.uint8),
        )

    def stop(self) -> None:
        if self._door is None:
            return
        if self.replicator is not None:
            self.replicator.stop()
            self.replicator = None
        if self.applier is not None:
            self.applier.stop()
            self.applier = None
        self._repl_sessions.clear()
        for sess in self._move_sessions.values():
            sess.closed()  # discard any staged (uncommitted) move state
        self._move_sessions.clear()
        if self._snapshots is not None:
            self._snapshots.stop(final_save=True)
            self._snapshots = None
        if self.profiler.active:
            self.profiler.stop()
        if self._metrics_exporter is not None:
            self._metrics_exporter.stop()
            self._metrics_exporter = None
        for name, fn in self._gauge_fns.items():
            _SM.unregister_gauge(name, fn)
        self._gauge_fns = {}
        # drain shutdown, in lane order: stop intake first so every frame
        # already pulled still gets answered, then let the sentinel flow
        # intake → device → reply before the door closes. A wedged lane
        # can't deadlock stop(): after the join timeout we flip _abandon,
        # which turns every blocking lane handoff into a drop.
        self._intake_stop.set()
        for t in self._lane_threads:
            t.join(timeout=self.drain_timeout_s)
            if t.is_alive():
                self._abandon.set()
                t.join(timeout=2)
        self._lane_threads = []
        # staging-leak audit (abandoned shutdown): a dead or abandoned lane
        # can strand pulls inside the shard/reply queues — nobody will
        # answer them, but their staging blocks must still go back to the
        # pool or the freelist never quiesces. Lanes are joined, so a
        # nowait drain here sees every stranded item.
        pool = self._staging
        if pool is not None:
            stranded = []
            for q in self._shard_qs:
                while True:
                    try:
                        item = q.get_nowait()
                    except queue.Empty:
                        break
                    if item is not self._SENTINEL:
                        stranded.append(item)
            for pull in stranded:
                n = len(pull[0])
                self.overload.note_done(n)
                _SM.count_shed("lane_abandon", n)
                pool.release(pull[6])
            if self._reply_q is not None:
                while True:
                    try:
                        item = self._reply_q.get_nowait()
                    except queue.Empty:
                        break
                    if item is self._SENTINEL:
                        continue
                    pulls, lengths, _mat = item
                    self.overload.note_done(sum(lengths))
                    _SM.count_shed("lane_abandon", sum(lengths))
                    for p in pulls:
                        pool.release(p[6])
        self._stop.set()
        for d in self._doors:
            d.stop()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []
        self._shard_qs = []
        self._dispatch_sem = None
        self._dispatch_q = None
        self._reply_q = None
        self._staging = None
        self._doors = []
        self._door = None
        self._shm_door = None
        # the door closed every socket without emitting CTRL_CLOSE (the
        # control thread is already down), so deregister the clients here —
        # a restart would otherwise inherit phantom connections that keep
        # deflating AVG_LOCAL per-connection budgets
        for key in list(self._addr_by_conn):
            address = self._addr_by_conn.pop(key, None)
            if address is not None:
                self.connections.remove_address(address)
        close = getattr(self.service, "close", None)
        if close is not None:
            close()

    # -- data plane ---------------------------------------------------------
    _SENTINEL = object()  # lane shutdown marker, flows intake→device→reply

    def _lane_put(
        self, q: queue.Queue, item, give_up_after_s: Optional[float] = None
    ) -> bool:
        """Blocking bounded-queue handoff (the lanes' backpressure). Never
        deadlocks shutdown: once ``_abandon`` is set (a lane died and its
        join timed out) the put gives up and drops instead. With
        ``give_up_after_s`` the put also refuses after that long against a
        full queue — the caller then answers OVERLOAD instead of wedging
        its lane (sentinel handoffs pass None and keep the forever
        semantics: a dropped sentinel would strand the downstream lane)."""
        deadline = (
            None if give_up_after_s is None
            else time.monotonic() + give_up_after_s
        )
        while True:
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                if self._abandon.is_set():
                    return False
                if deadline is not None and time.monotonic() >= deadline:
                    return False

    def _intake_loop(self, shard: int, door, q: queue.Queue) -> None:
        """Lane 1 (×``intake_shards``): pull decoded frames from this
        shard's C++ door straight into a recycled staging block, hand the
        block to the device lane. The door wakes ``wait_batch_into`` the
        moment the first frame queues — ``intake_timeout_ms`` is only the
        shutdown-poll granularity, never a batching stall.

        Zero-copy shape: the C++ IO thread memcpys its arena directly into
        the staging arrays (no thread-local bounce buffer, no per-pull
        ``np.array`` copies); the block travels with the pull and returns
        to the pool after the reply lane submits its verdicts. Pulls this
        lane answers itself (standby/overload refusals, chaos drops) reuse
        the block immediately — ``sn_fd_submit`` copies synchronously."""
        pool = self._staging
        if self.intake_shards > 1:
            # best-effort shard→core pinning so each intake lane's cache
            # stays hot; harmless no-op on single-core or restricted hosts
            try:
                cpus = sorted(os.sched_getaffinity(0))
                if len(cpus) > 1:
                    os.sched_setaffinity(0, {cpus[shard % len(cpus)]})
            except (AttributeError, OSError):
                pass
        block = pool.acquire()
        try:
            while not self._intake_stop.is_set():
                try:
                    # max_batch bounds one pull (clamped to >= one max
                    # frame); the remainder stays queued for the next cycle
                    got = door.wait_batch_into(
                        block, timeout_ms=self.intake_timeout_ms,
                        max_n=self.max_batch,
                    )
                except Exception:
                    if self._stop.is_set() or self._intake_stop.is_set():
                        break
                    record_log.exception(
                        "native wait_batch failed; intake %d down", shard
                    )
                    break
                if got is None:
                    continue
                n, k = got
                if chaos.ARMED:
                    chaos.maybe_sleep("lane_delay")
                    if chaos.should("frame_drop"):
                        _SM.count_shed("chaos_drop", n)
                        continue
                t0 = time.perf_counter()
                # normalize the wire prio bytes into the block's boolean
                # row in place (clients send 0/1 but the wire admits any
                # byte; masking downstream needs real booleans)
                prios = np.not_equal(
                    block["prios"][:n], 0, out=block["prios_bool"][:n]
                )
                # the one host copy this path pays: C arena → staging
                # (13B/row + 17B/frame) plus the 1B/row bool normalize
                _SM.count_copy_bytes(n * 14 + k * 17)
                # pull = (rows..., frames, age stamp, owning door, block):
                # the age stamp is the shed-by-age deadline proxy (the C++
                # door strips the wire deadline); the door routes replies
                # and refusals back to the shard that owns the connection
                pull = (
                    block["ids"][:n], block["counts"][:n], prios,
                    (block["f_fd"][:k], block["f_gen"][:k],
                     block["f_xid"][:k], block["f_n"][:k],
                     block["f_type"][:k]),
                    time.monotonic(), door, block,
                )
                if _TR.ARMED:  # flight recorder: frames entered the host
                    if door is self._shm_door:
                        _TR.record(_TR.SHM_POLL, shard=shard, aux=n)
                    _TR.record_many(
                        _TR.CLIENT_IN, pull[3][2], shard=shard, aux=n
                    )
                if self.is_standby:
                    # unpromoted warm standby: data plane is closed. Refuse
                    # the whole pull with STANDBY so the failover client
                    # walks on to the live primary (no retry hint — this is
                    # not backpressure)
                    _SM.count_shed("standby", n)
                    if _TR.ARMED:
                        _TR.record_many(
                            _TR.SHED, pull[3][2], shard=shard, aux=n
                        )
                    status = np.full(n, _STANDBY, np.int8)
                    _SM.record_verdict_batch(status, None, ())
                    try:
                        door.submit(
                            pull[3], status, np.zeros(n, np.int32),
                            np.zeros(n, np.int32),
                        )
                    except Exception:
                        if not self._stop.is_set():
                            record_log.exception(
                                "native standby submit failed"
                            )
                    continue
                _SM.batch_size.record(n)
                self.overload.note_enqueued(n)
                give_up = (
                    None if self.shed_age_ms is None
                    else self.shed_age_ms / 1000.0
                )
                if self._lane_put(q, pull, give_up_after_s=give_up):
                    self._dispatch_sem.release()
                    if _TR.ARMED:
                        _TR.record_many(
                            _TR.ENQUEUE, pull[3][2], shard=shard, aux=n
                        )
                    dt_ms = (time.perf_counter() - t0) * 1e3
                    _SM.intake_ms.record(dt_ms)
                    _SM.count_shard_pull(shard, n, dt_ms)
                    # the block now rides the pull; next cycle decodes
                    # into a fresh (usually recycled) one
                    block = pool.acquire()
                else:
                    # dispatch lane saturated past the age budget: refuse
                    # the whole pull explicitly rather than queue frames
                    # that will only expire — the clients get an immediate
                    # retry hint
                    self.overload.note_done(n)
                    _SM.count_shed("queue_full", n)
                    if _TR.ARMED:
                        _TR.record_many(
                            _TR.SHED, pull[3][2], shard=shard, aux=n
                        )
                    status = np.full(n, _OVERLOAD, np.int8)
                    wait = np.full(
                        n, self.overload.retry_hint_ms, np.int32
                    )
                    # per-tenant attribution: these rows never reach the
                    # device path, so resolve namespaces here (the SLO
                    # plane's shed accounting rides the verdict counters)
                    ns_fn = getattr(
                        self.service, "namespace_index", None
                    )
                    _SM.record_verdict_batch(
                        status,
                        *(ns_fn(pull[0]) if ns_fn is not None
                          else (None, ())),
                    )
                    try:
                        door.submit(
                            pull[3], status, np.zeros(n, np.int32), wait
                        )
                    except Exception:
                        if not self._stop.is_set():
                            record_log.exception(
                                "native overload submit failed"
                            )
        finally:
            pool.release(block)
            # sentinel handoff keeps the forever semantics; only a
            # successful put may release the semaphore (the device lane
            # trusts every release to have a queued item behind it)
            if self._lane_put(q, self._SENTINEL):
                self._dispatch_sem.release()

    # -- device pipelining ---------------------------------------------------
    def _acquire_device_permit(self) -> bool:
        """Block until a dispatch slot frees (``max_device_inflight``
        bound). Returns True when another fused group was already in
        flight — i.e. this group's host prep just ran overlapped with
        device compute that a depth-1 lane would have serialized behind.
        On abandoned shutdown the wait gives up and over-admits; the
        release path tolerates it."""
        with self._device_cv:
            while (
                self._device_inflight >= self.max_device_inflight
                and not self._abandon.is_set()
            ):
                self._device_cv.wait(timeout=0.1)
            overlapped = self._device_inflight > 0
            self._device_inflight += 1
            return overlapped

    def _release_device_permit(self) -> None:
        with self._device_cv:
            self._device_inflight = max(0, self._device_inflight - 1)
            self._device_cv.notify()

    def _tracked_dispatch(self, dispatch, ids, counts, prios):
        """Issue one device dispatch under the inflight bound.

        Returns ``(mat, release, overlapped)``: ``mat`` materializes the
        verdicts and releases the permit (exactly once, even if the
        materialize raises); ``release`` is the idempotent escape hatch
        for paths that never call ``mat`` (dispatch exception handled by
        the caller, abandoned-shutdown drop). ``overlapped`` reports
        whether the permit wait found earlier work still in flight."""
        overlapped = self._acquire_device_permit()
        done = [False]

        def release():
            if not done[0]:
                done[0] = True
                self._release_device_permit()

        try:
            inner = dispatch(ids, counts, prios)
        except Exception:
            release()
            raise

        def mat():
            try:
                return inner()
            finally:
                release()

        return mat, release, overlapped

    def _device_loop(self) -> None:
        """Lane 2: the only thread issuing device work — dispatch order IS
        state-chain order. Drains every queued pull (bounded by
        ``fuse_depth``), concatenates, and issues ONE dispatch; the token
        service's fusion ladder folds the full engine frames inside into a
        single chained scan step. Dispatch returns before the device
        finishes (async), so this lane loops back to prep the next group
        while the reply lanes block on the verdicts. Up to
        ``max_device_inflight`` fused groups may be dispatched and not yet
        materialized — the permit wait applies backpressure beyond that,
        and the overlap the pipeline wins is accounted in
        ``overlap_saved_ms_total``.

        With intake sharding the drain is the UNION of the shard queues:
        the semaphore counts queued pulls across all of them, and a
        round-robin ``get_nowait`` scan fetches the item each acquired
        permit guarantees — so a burst split across N doors by the kernel
        still fuses into one device step. Shutdown ends after every
        shard's sentinel has been consumed."""
        qs = self._shard_qs
        sem = self._dispatch_sem
        n_shards = len(qs)
        done_shards = 0
        rr = 0
        service = self.service
        dispatch = getattr(service, "dispatch_batch_arrays", None)

        def pop_next():
            # every sem permit has a queued item behind it and this lane
            # is the sole consumer, so one scan pass finds it; the spin
            # guard only matters if a lane died mid-shutdown
            nonlocal rr
            while True:
                for j in range(n_shards):
                    qi = (rr + j) % n_shards
                    try:
                        item = qs[qi].get_nowait()
                    except queue.Empty:
                        continue
                    rr = (qi + 1) % n_shards
                    return item
                if self._abandon.is_set():
                    return None

        try:
            while True:
                if not sem.acquire(timeout=0.5):
                    if self._abandon.is_set():
                        break
                    continue
                item = pop_next()
                if item is None:
                    break
                if item is self._SENTINEL:
                    done_shards += 1
                    if done_shards >= n_shards:
                        break
                    continue
                pulls = [item]
                # adaptive frame fusion: everything already queued joins
                # this dispatch. Idle queues → depth 1 (no added latency);
                # backlog → deep fused step (max amortization).
                stop_after = False
                while len(pulls) < self.fuse_depth:
                    if not sem.acquire(blocking=False):
                        break
                    nxt = pop_next()
                    if nxt is None:
                        break
                    if nxt is self._SENTINEL:
                        done_shards += 1
                        if done_shards >= n_shards:
                            stop_after = True  # all intake done; finish
                            break
                        continue
                    pulls.append(nxt)
                if len(pulls) == 1:
                    ids, counts, prios = item[0], item[1], item[2]
                else:
                    ids = np.concatenate([p[0] for p in pulls])
                    counts = np.concatenate([p[1] for p in pulls])
                    prios = np.concatenate([p[2] for p in pulls])
                    _SM.count_copy_bytes(
                        ids.nbytes + counts.nbytes + prios.nbytes
                    )
                lengths = [len(p[0]) for p in pulls]
                n_rows = len(ids)
                # deadline proxy: pulls older than shed_age_ms are answered
                # OVERLOAD without touching the device (row mask via repeat)
                shed = None
                n_deadline = 0
                if self.shed_age_ms is not None:
                    cutoff = time.monotonic() - self.shed_age_ms / 1000.0
                    expired = np.array(
                        [p[4] < cutoff for p in pulls], bool
                    )
                    if expired.any():
                        shed = np.repeat(expired, lengths)
                        n_deadline = int(shed.sum())
                level = self.overload.level()
                ns_fn = getattr(service, "namespace_index", None)
                if _TR.ARMED:  # flight recorder: fused group dispatched
                    for p in pulls:
                        _TR.record_many(
                            _TR.DISPATCH, p[3][2], aux=len(pulls)
                        )
                t0 = time.perf_counter()
                permit_rel = None
                overlapped = False
                try:
                    if level >= BrownoutLevel.DEGRADE:
                        # brownout floor: no device dispatch at all; a BDP
                        # slice gets probabilistic local answers, the rest
                        # (and every expired row) OVERLOAD
                        deg = self.overload.shed_mask(prios, level)
                        if shed is not None:
                            deg = deg | shed
                        status, remaining, wait = (
                            self.overload.degrade_verdicts(deg)
                        )
                        if n_deadline:
                            _SM.count_shed("deadline", n_deadline)
                        _SM.count_shed(
                            "degrade", int(deg.sum()) - n_deadline
                        )
                        _SM.record_verdict_batch(
                            status,
                            *(ns_fn(ids) if ns_fn is not None
                              else (None, ())),
                        )
                        mat = (  # noqa: E731
                            lambda r=(status, remaining, wait): r
                        )
                    else:
                        mask = shed
                        if level >= BrownoutLevel.SHED_LOW:
                            # tenant attribution up front so the shed is
                            # share-weighted when shares are configured
                            ns_pair = (
                                ns_fn(ids) if ns_fn is not None
                                else (None, ())
                            )
                            m = self.overload.shed_mask(
                                prios, level,
                                ns_idx=ns_pair[0], ns_names=ns_pair[1],
                            )
                            mask = m if mask is None else (mask | m)
                            if not mask.any():
                                mask = None
                        if mask is None:
                            if dispatch is not None:
                                mat, permit_rel, overlapped = (
                                    self._tracked_dispatch(
                                        dispatch, ids, counts, prios
                                    )
                                )
                            else:
                                # SPI implementations without the dispatch/
                                # materialize split run synchronously here
                                res = service.request_batch_arrays(
                                    ids, counts, prios
                                )
                                mat = lambda res=res: res  # noqa: E731
                        else:
                            if n_deadline:
                                _SM.count_shed("deadline", n_deadline)
                            n_brown = int(mask.sum()) - n_deadline
                            if n_brown > 0:
                                _SM.count_shed("brownout", n_brown)
                            keep = np.nonzero(~mask)[0]
                            if keep.size:
                                if dispatch is not None:
                                    inner, permit_rel, overlapped = (
                                        self._tracked_dispatch(
                                            dispatch, ids[keep],
                                            counts[keep], prios[keep],
                                        )
                                    )
                                else:
                                    res = service.request_batch_arrays(
                                        ids[keep], counts[keep], prios[keep]
                                    )
                                    inner = lambda res=res: res  # noqa: E731
                            else:
                                inner = None
                            hint = self.overload.retry_hint_ms
                            n_shed = n_rows - int(keep.size)
                            _SM.record_verdict_batch(
                                np.full(n_shed, _OVERLOAD, np.int8),
                                *(ns_fn(ids[mask]) if ns_fn is not None
                                  else (None, ())),
                            )

                            # scatter the dispatched slice back into full-
                            # width arrays so the reply lane's per-pull
                            # offsets stay valid
                            def mat(
                                inner=inner, keep=keep, n=n_rows, hint=hint
                            ):
                                status = np.full(n, _OVERLOAD, np.int8)
                                remaining = np.zeros(n, np.int32)
                                wait = np.full(n, hint, np.int32)
                                if inner is not None:
                                    st, rm, wt = inner()
                                    status[keep] = st
                                    remaining[keep] = rm
                                    wait[keep] = wt
                                return status, remaining, wait
                except Exception:
                    record_log.exception("device step failed; failing batch")
                    if permit_rel is not None:
                        permit_rel()
                    n = n_rows
                    mat = lambda n=n: (  # noqa: E731
                        np.full(n, int(TokenStatus.FAIL), np.int8),
                        np.zeros(n, np.int32),
                        np.zeros(n, np.int32),
                    )
                dt_ms = (time.perf_counter() - t0) * 1e3
                _SM.dispatch_ms.record(dt_ms)
                if overlapped:
                    # this group's whole dispatch arm ran while the prior
                    # group still computed — the pipelining win
                    _SM.count_overlap_saved_ms(dt_ms)
                if not self._lane_put(
                    self._reply_q, (pulls, lengths, mat)
                ):
                    # abandoned shutdown drop: nobody will materialize or
                    # answer these rows — account for them and park the
                    # staging blocks the reply lane would have returned
                    if permit_rel is not None:
                        permit_rel()
                    self.overload.note_done(n_rows)
                    _SM.count_shed("lane_abandon", n_rows)
                    if self._staging is not None:
                        for p in pulls:
                            self._staging.release(p[6])
                if stop_after:
                    break
        finally:
            # always propagate shutdown, even if this lane died — the
            # reply lanes must not block forever on an empty queue
            self._lane_put(self._reply_q, self._SENTINEL)

    def _reply_loop(self) -> None:
        """Lane 3 (×``n_dispatchers``): block on the async verdicts, slice
        them back per intake pull, submit to each pull's owning door. While
        one reply thread waits on device results the device lane keeps
        dispatching, and a second reply thread overlaps the next group's
        encode. Consecutive pulls from the same door collapse into one
        ``submit_many`` call — one outbox lock and one IO wakeup per run,
        with the C++ scatter encode grouping same-connection frames across
        pull boundaries. Once the verdicts are submitted (``sn_fd_submit``
        copies synchronously) the pulls' staging blocks go back to the
        intake pool."""
        rq = self._reply_q
        while True:
            item = rq.get()
            if item is self._SENTINEL:
                rq.put(item)  # release sibling reply lanes
                return
            pulls, lengths, mat = item
            t0 = time.perf_counter()
            try:
                status, remaining, wait = mat()
            except Exception:
                record_log.exception("materialize failed; failing batch")
                n = sum(lengths)
                status = np.full(n, int(TokenStatus.FAIL), np.int8)
                remaining = np.zeros(n, np.int32)
                wait = np.zeros(n, np.int32)
            t_write = time.perf_counter()
            _SM.decide_ms.record((t_write - t0) * 1e3)
            off = 0
            i = 0
            n_pulls = len(pulls)
            while i < n_pulls:
                door = pulls[i][5]
                frames_list = []
                span = 0
                j = i
                while j < n_pulls and pulls[j][5] is door:
                    frames_list.append(pulls[j][3])
                    span += lengths[j]
                    j += 1
                try:
                    # the C++ scatter encode carries (status, remaining,
                    # wait) only, so MOVED verdicts ship the shard-map
                    # epoch in ``remaining`` without the endpoint trailer
                    # the asyncio door appends — clients re-resolve the
                    # destination through the shard map on the epoch bump
                    door.submit_many(
                        frames_list,
                        status[off : off + span],
                        remaining[off : off + span],
                        wait[off : off + span],
                    )
                    if _TR.ARMED:  # flight recorder: replies on the wire
                        for fr in frames_list:
                            _TR.record_many(
                                _TR.REPLY_OUT, fr[2], aux=span
                            )
                except Exception:
                    if not self._stop.is_set():
                        record_log.exception("native submit failed")
                off += span
                i = j
            self.overload.note_done(off)
            _SM.write_ms.record((time.perf_counter() - t_write) * 1e3)
            pool = self._staging
            if pool is not None:
                for p in pulls:
                    pool.release(p[6])

    # -- control plane ------------------------------------------------------
    def _control_loop(self) -> None:
        # one poll thread covers every shard door: control traffic is
        # low-rate (handshakes, params, repl frames), and (fd, gen) keys
        # are globally unique across doors, so the session maps need no
        # per-door namespacing — only the REPLY must go out through the
        # door that owns the connection
        doors = list(self._doors)
        while not self._stop.is_set():
            got_any = False
            for door in doors:
                try:
                    item = door.next_control()
                except Exception:
                    if self._stop.is_set():
                        return
                    raise
                if item is None:
                    continue
                got_any = True
                self._handle_control_item(door, item)
            if not got_any:
                self._stop.wait(0.002)

    def _handle_control_item(self, door, item) -> None:
        kind, fd, gen, payload = item
        if kind == door.CTRL_OPEN:
            address = payload.decode("latin-1")
            with self._addr_lock:
                self._addr_by_conn[(fd, gen)] = address
            self.connections.attach_closer(
                address,
                lambda fd=fd, gen=gen, door=door: door.close_conn(fd, gen),
            )
            # rev-7 push sink: door.send enqueues on the C++ plane's
            # non-blocking per-connection send queue (encoded push frames
            # carry their length prefix, same as control replies)
            self.push_hub.attach(
                (fd, gen),
                lambda b, fd=fd, gen=gen, door=door: door.send(fd, gen, b),
            )
            return
        if kind == door.CTRL_CLOSE:
            self.push_hub.detach((fd, gen))
            with self._addr_lock:
                address = self._addr_by_conn.pop((fd, gen), None)
            if address:
                self.connections.remove_address(address)
            self._repl_sessions.pop((fd, gen), None)
            move_sess = self._move_sessions.pop((fd, gen), None)
            if move_sess is not None:
                # crash matrix: a source that dies mid-move never sent
                # MOVE_COMMIT, so discarding its staged state here leaves
                # the source as the sole owner
                move_sess.closed()
            return
        # kind == CTRL_FRAME: a non-data-plane request
        with self._addr_lock:
            address = self._addr_by_conn.get((fd, gen), f"fd{fd}")
        # rev-3 replication frames ride the control lane but are not
        # requests (decode_request would reject their type bytes) —
        # route them to the standby applier's per-connection session
        if len(payload) >= 5 and P.peek_type(payload) in P.REPL_TYPES:
            if self.applier is None:
                record_log.warning(
                    "repl frame on non-standby server; closing %s",
                    address,
                )
                door.close_conn(fd, gen)
                return
            sess = self._repl_sessions.get((fd, gen))
            if sess is None:
                sess = self.applier.connection()
                self._repl_sessions[(fd, gen)] = sess
            try:
                sess.handle(
                    payload,
                    lambda b, fd=fd, gen=gen, door=door: door.send(
                        fd, gen, b
                    ),
                )
            except ValueError:
                record_log.warning("torn repl stream; closing %s",
                                   address)
                self._repl_sessions.pop((fd, gen), None)
                door.close_conn(fd, gen)
            return
        # rev-4 namespace-move frames: same control-lane treatment, routed
        # to the MoveTarget's per-connection session
        if len(payload) >= 5 and P.peek_type(payload) in P.MOVE_TYPES:
            sess = self._move_sessions.get((fd, gen))
            if sess is None:
                sess = self.move_target.connection()
                self._move_sessions[(fd, gen)] = sess
            try:
                sess.handle(
                    payload,
                    lambda b, fd=fd, gen=gen, door=door: door.send(
                        fd, gen, b
                    ),
                )
            except ValueError:
                record_log.warning("torn move stream; closing %s",
                                   address)
                self._move_sessions.pop((fd, gen), None)
                sess.closed()
                door.close_conn(fd, gen)
            return
        # rev-5 lease frames ride the control lane too (one per TTL per hot
        # flow — never on the per-decision path, which is the whole point)
        if len(payload) >= 5 and P.peek_type(payload) in P.LEASE_TYPES:
            try:
                rsp_bytes = self._handle_lease(payload, address)
            except ValueError:
                record_log.warning("bad lease frame; closing %s", address)
                door.close_conn(fd, gen)
                return
            door.send(fd, gen, rsp_bytes)
            return
        # hierarchy-tier frames (pod share ops + demand reports): same
        # control-lane treatment, dispatched to the co-located coordinator
        if len(payload) >= 5 and P.peek_type(payload) in P.HIER_TYPES:
            try:
                rsp_bytes = self._handle_hier(payload, address)
            except ValueError:
                record_log.warning("bad hier frame; closing %s", address)
                door.close_conn(fd, gen)
                return
            door.send(fd, gen, rsp_bytes)
            return
        # rev-6 outcome reports: fire-and-forget (no door.send — the whole
        # point is zero extra round-trips on the lease fast path). Covers
        # both the TCP and shm doors: each routes non-data type bytes here.
        if len(payload) >= 5 and P.peek_type(payload) in P.OUTCOME_TYPES:
            try:
                oxid, ofids, orts, oexcs = P.decode_outcome_report(payload)
            except Exception:
                record_log.warning("bad outcome frame; closing %s", address)
                door.close_conn(fd, gen)
                return
            if self.is_standby:
                # outcome columns replicate from the primary; counting here
                # would double on promotion
                return
            self.service.report_outcomes(ofids, orts, oexcs, oxid)
            return
        try:
            req = P.decode_request(payload)
        except Exception:
            record_log.warning("bad control frame; closing %s", address)
            door.close_conn(fd, gen)
            return
        try:
            rsp = self._handle_control(req, address)
        except Exception:
            record_log.exception("%s control request failed",
                                 type(req).__name__)
            rsp = P.FlowResponse(
                req.xid, getattr(req, "msg_type", P.MsgType.PING),
                int(TokenStatus.FAIL),
            )
        door.send(fd, gen, P.encode_response(rsp))

    def _handle_lease(self, payload, address: str) -> bytes:
        """Wire rev 5: decode a lease request, run the service's host-side
        grant/renew/return, encode the reply. Raises ValueError on a torn
        frame (caller closes the connection — the containment contract)."""
        xid, lmt, lease_id, flow_id, used, want = (
            P.decode_lease_request(payload)
        )
        if _TR.ARMED:
            _TR.record(_TR.LEASE, xid=xid, aux=want)
        self.connections.touch(address)
        if self.is_standby:
            # proof-of-life refusal, same as the decision path: the client
            # falls back to per-request RPCs, the breaker records success
            return P.encode_lease_response(xid, lmt, _STANDBY)
        service = self.service
        if getattr(service, "lease_grant", None) is None:
            return P.encode_lease_response(
                xid, lmt, P.NOT_LEASABLE_STATUS
            )
        try:
            if lmt == P.MsgType.LEASE_GRANT:
                res = service.lease_grant(flow_id, want)
            elif lmt == P.MsgType.LEASE_RENEW:
                res = service.lease_renew(lease_id, flow_id, used, want)
            else:
                res = service.lease_return(lease_id, used)
        except Exception:
            record_log.exception("lease op failed")
            return P.encode_lease_response(
                xid, lmt, int(TokenStatus.FAIL)
            )
        return P.encode_lease_response(
            xid, lmt, int(res.status), lease_id=res.lease_id,
            tokens=res.tokens, ttl_ms=res.ttl_ms, endpoint=res.endpoint,
        )

    def _handle_hier(self, payload, address: str) -> bytes:
        """Hierarchy tier: decode a share op or demand report, run the
        co-located coordinator's ledger op, encode the (lease-layout)
        reply. Raises ValueError on a torn frame (caller closes)."""
        mtype = P.peek_type(payload)
        if mtype == int(P.MsgType.DEMAND_REPORT):
            xid, pod_id, entries = P.decode_demand_report(payload)
            hmt = P.MsgType.DEMAND_REPORT
            args = None
        else:
            xid, hmt, share_id, flow_id, used, want = (
                P.decode_lease_request(payload)
            )
            args = (share_id, flow_id, used, want)
        if _TR.ARMED:
            _TR.record(_TR.HIER, xid=xid)
        self.connections.touch(address)
        if self.is_standby:
            return P.encode_lease_response(xid, hmt, _STANDBY)
        hier = getattr(self.service, "hierarchy", None)
        if hier is None:
            # no coordinator co-located here: refuse so the agent's
            # failover walk tries the next endpoint
            return P.encode_lease_response(
                xid, hmt, P.NOT_LEASABLE_STATUS
            )
        try:
            if hmt == P.MsgType.DEMAND_REPORT:
                res = hier.handle_demand_report(pod_id, entries)
            elif hmt == P.MsgType.SHARE_GRANT:
                res = hier.share_grant(args[1], args[3])
            elif hmt == P.MsgType.SHARE_RENEW:
                res = hier.share_renew(args[0], args[1], args[2], args[3])
            else:
                res = hier.share_return(args[0], args[2])
        except Exception:
            record_log.exception("hier op failed")
            return P.encode_lease_response(
                xid, hmt, int(TokenStatus.FAIL)
            )
        return P.encode_lease_response(
            xid, hmt, int(res.status), lease_id=res.lease_id,
            tokens=res.tokens, ttl_ms=res.ttl_ms, endpoint=res.endpoint,
        )

    def _handle_control(self, req, address: str) -> P.FlowResponse:
        service = self.service
        if isinstance(req, P.Ping):
            count = self.connections.add(req.namespace, address)
            return P.FlowResponse(req.xid, P.MsgType.PING, 0, remaining=count)
        self.connections.touch(address)
        if self.is_standby:
            # control-lane verdicts get the same closed-door refusal as the
            # data plane (PING above still answers: standbys stay pingable)
            return P.FlowResponse(req.xid, req.msg_type, _STANDBY)
        if req.msg_type == P.MsgType.PARAM_FLOW:
            r = service.request_params_token(
                req.flow_id, req.count, req.param_hashes
            )
            return P.FlowResponse(
                req.xid, req.msg_type, int(r.status), r.remaining, r.wait_ms
            )
        if req.msg_type == P.MsgType.CONCURRENT_ACQUIRE:
            r = service.request_concurrent_token(
                req.flow_id, req.count, req.prioritized
            )
            return P.FlowResponse(
                req.xid, req.msg_type, int(r.status), r.remaining, r.wait_ms,
                r.token_id,
            )
        if req.msg_type == P.MsgType.CONCURRENT_RELEASE:
            r = service.release_concurrent_token(req.flow_id)
            return P.FlowResponse(req.xid, req.msg_type, int(r.status))
        return P.FlowResponse(req.xid, req.msg_type, int(TokenStatus.FAIL))

    def stats(self) -> dict:
        """Door counters, summed across the intake shards. Every summand
        is an independently monotonic relaxed atomic read without pausing
        the IO threads, so the result is NOT a consistent cross-counter
        snapshot — each key is its own monotonic series; derived deltas
        between two calls must be clamped at zero."""
        doors = list(self._doors)
        if not doors:
            return {}
        out: dict = {}
        for d in doors:
            for key, v in d.stats().items():
                out[key] = out.get(key, 0) + v
        return out
