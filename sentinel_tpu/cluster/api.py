"""Process-global cluster state + the local flow checker's cluster branch.

Analog of ``ClusterStateManager.java:38-86`` (mode CLIENT=0 / SERVER=1) and
the verdict-application half of ``FlowRuleChecker.passClusterCheck``
(``FlowRuleChecker.java:147-208``).
"""

from __future__ import annotations

import enum
import threading
from typing import Optional

from sentinel_tpu.cluster.token_service import TokenResult, TokenService
from sentinel_tpu.core import clock as _clock
from sentinel_tpu.engine import TokenStatus


class ClusterMode(enum.IntEnum):
    NOT_STARTED = -1
    CLIENT = 0
    SERVER = 1  # embedded token server


_lock = threading.RLock()
_mode = ClusterMode.NOT_STARTED
_client: Optional[TokenService] = None
_embedded: Optional[TokenService] = None


def _close_quietly(service) -> None:
    close = getattr(service, "close", None)
    if close is not None:
        try:
            close()
        except Exception:
            pass


def set_client(client: TokenService) -> None:
    global _client, _mode
    with _lock:
        prev, _client = _client, client
        _mode = ClusterMode.CLIENT
    # a replaced client holds a socket + reader thread; reassignment (e.g.
    # the dashboard re-pointing the fleet) must not leak one per swap
    if prev is not None and prev is not client:
        _close_quietly(prev)


def clear_client() -> None:
    """Drop (and close) the installed token client WITHOUT switching modes —
    the client holds a socket + reader thread, so a node promoted to SERVER
    (or switched off) must not leak one per transition."""
    global _client
    with _lock:
        prev, _client = _client, None
    if prev is not None:
        _close_quietly(prev)


def set_embedded_server(service: TokenService) -> None:
    global _embedded, _mode
    with _lock:
        _embedded = service
        _mode = ClusterMode.SERVER


def clear_embedded_server() -> None:
    """Demotion path: forget the embedded service WITHOUT switching modes —
    cluster/server/* commands must answer 'not a token server' afterwards
    instead of operating on a stopped server's service."""
    global _embedded
    with _lock:
        _embedded = None


def set_mode(mode: ClusterMode) -> None:
    global _mode
    with _lock:
        _mode = mode


def get_mode() -> ClusterMode:
    return _mode


def get_embedded_server() -> Optional[TokenService]:
    """The in-process token service when this agent runs in SERVER mode
    (``EmbeddedClusterTokenServerProvider`` analog) — the cluster/server/*
    command handlers operate on it."""
    return _embedded


def _pick_service() -> Optional[TokenService]:
    """``FlowRuleChecker.pickClusterService`` (``:176-184``)."""
    if _mode == ClusterMode.CLIENT:
        return _client
    if _mode == ClusterMode.SERVER:
        return _embedded
    return None


def reset_for_tests() -> None:
    global _mode, _client, _embedded
    with _lock:
        prev_client, _client = _client, None
        _mode = ClusterMode.NOT_STARTED
        _embedded = None
    if prev_client is not None:
        _close_quietly(prev_client)


# -- called from sentinel_tpu.local.flow ------------------------------------


def request_token(rule, acquire: int, prioritized: bool) -> Optional[TokenResult]:
    service = _pick_service()
    if service is None:
        return None
    flow_id = (rule.cluster_config or {}).get("flow_id")
    if flow_id is None:
        return None
    return service.request_token(int(flow_id), acquire, prioritized)


def apply_token_result(
    result: TokenResult, rule, context, node, acquire, prioritized, fallback
) -> bool:
    """``FlowRuleChecker.applyTokenResult`` (``:186-208``): OK → pass;
    SHOULD_WAIT → sleep the hint then pass; BLOCKED → block; anything else
    (FAIL / NO_RULE / TOO_MANY) → local fallback or pass-through."""
    if result.status == TokenStatus.OK:
        return True
    if result.status == TokenStatus.SHOULD_WAIT:
        _clock.get_clock().wait_ms(result.wait_ms)
        return True
    if result.status == TokenStatus.BLOCKED:
        return False
    return fallback(rule, context, node, acquire, prioritized)
