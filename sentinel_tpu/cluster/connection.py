"""Namespace-scoped connection groups.

Analog of ``connection/ConnectionManager.java:35`` + ``ConnectionGroup.java``:
the token server groups client connections by the namespace they declared in
their PING handshake; each group's connected count feeds the AVG_LOCAL
threshold scaling (``ClusterFlowChecker.java:43-47`` →
``rules.ns_connected`` in the device table here).

Instance-scoped rather than the reference's static map: every ``TokenServer``
owns one manager, so two embedded servers in one process (tests, multi-pod
dryruns) don't share groups.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Set

from sentinel_tpu.core import clock as _clock


class ConnectionManager:
    def __init__(
        self, on_count_changed: Optional[Callable[[str, int], None]] = None
    ):
        self._lock = threading.Lock()
        self._groups: Dict[str, Set[str]] = {}
        # address → namespaces it registered (one connection may serve
        # several namespaces; each PING adds one)
        self._by_address: Dict[str, Set[str]] = {}
        # address → last activity ms (PING or any request), for the idle
        # sweep (ScanIdleConnectionTask.java analog)
        self._last_active_ms: Dict[str, int] = {}
        # address → transport closer; the sweep CLOSES reaped connections
        # (like the reference closing the netty channel) so a client that
        # was merely quiet reconnects + re-PINGs and is counted again
        self._closers: Dict[str, Callable[[], None]] = {}
        self._on_count_changed = on_count_changed

    def attach_closer(self, address: str, closer: Callable[[], None]) -> None:
        """Register the transport-close hook for a connection (thread-safe
        callable; the server passes a loop.call_soon_threadsafe wrapper).

        Also seeds the liveness stamp: the reference tracks every accepted
        channel from its first activity, so a socket that connects but never
        PINGs must still age out of the idle sweep instead of being held
        open forever."""
        with self._lock:
            self._closers[address] = closer
            self._last_active_ms.setdefault(address, _clock.now_ms())

    def add(self, namespace: str, address: str) -> int:
        """Register; returns the group's connected count (PING response)."""
        with self._lock:
            group = self._groups.setdefault(namespace, set())
            group.add(address)
            self._by_address.setdefault(address, set()).add(namespace)
            self._last_active_ms[address] = _clock.now_ms()
            n = len(group)
        if self._on_count_changed is not None:
            self._on_count_changed(namespace, n)
        return n

    def touch(self, address: str) -> None:
        """Refresh a connection's liveness (any request counts, like the
        reference updating ``Connection.lastReadTime`` per channelRead)."""
        if address in self._last_active_ms:  # racy pre-check is fine: worst
            with self._lock:  # case a just-removed address gets a stale stamp
                if address in self._last_active_ms:
                    self._last_active_ms[address] = _clock.now_ms()

    def sweep_idle(self, ttl_ms: float) -> List[str]:
        """Close + drop connections with no PING/request inside ``ttl_ms``;
        returns the reaped addresses. ``ScanIdleConnectionTask.java`` analog:
        a wedged client must not inflate AVG_LOCAL connected counts forever
        (thresholds would stay too high). Closing the transport — not just
        deregistering — means a merely-quiet client notices, reconnects, and
        re-PINGs back into its group instead of being undercounted forever."""
        now = _clock.now_ms()
        with self._lock:
            stale = [
                addr for addr, ts in self._last_active_ms.items()
                if now - ts > ttl_ms
            ]
            closers = [self._closers.get(a) for a in stale]
        for addr, closer in zip(stale, closers):
            if closer is not None:
                try:
                    closer()
                except Exception:
                    pass
            self.remove_address(addr)
        return stale

    def remove_address(self, address: str) -> None:
        """Drop every registration of a disconnected client."""
        changed: List[tuple] = []
        with self._lock:
            self._last_active_ms.pop(address, None)
            self._closers.pop(address, None)
            for ns in self._by_address.pop(address, ()):
                group = self._groups.get(ns)
                if group is not None:
                    group.discard(address)
                    changed.append((ns, len(group)))
                    if not group:
                        del self._groups[ns]
        if self._on_count_changed is not None:
            for ns, n in changed:
                self._on_count_changed(ns, n)

    def connected_count(self, namespace: str) -> int:
        with self._lock:
            return len(self._groups.get(namespace, ()))

    def namespaces(self) -> List[str]:
        with self._lock:
            return sorted(self._groups)

    def snapshot(self) -> Dict[str, List[str]]:
        """namespace → sorted addresses (FetchClusterServerInfo shape)."""
        with self._lock:
            return {ns: sorted(g) for ns, g in self._groups.items()}


class IdleConnectionSweeper:
    """Periodic ``sweep_idle`` driver (``ScanIdleConnectionTask.java``: the
    reference schedules it at fixed rate on the server's scheduler pool).

    The period is wall-clock (daemon timer); the idle judgment itself uses
    the injectable ``core.clock`` so tests advance a ManualClock and call
    ``sweep_idle`` directly.
    """

    def __init__(self, connections: ConnectionManager, ttl_s: float = 600.0,
                 period_s: Optional[float] = None):
        self.connections = connections
        self.ttl_ms = ttl_s * 1000.0
        self.period_s = period_s if period_s is not None else max(ttl_s / 2, 0.5)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="sentinel-idle-conn-sweep", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        from sentinel_tpu.core.log import record_log

        while not self._stop.wait(self.period_s):
            reaped = self.connections.sweep_idle(self.ttl_ms)
            if reaped:
                record_log.info(
                    "idle sweep reaped %d connection(s): %s",
                    len(reaped), ", ".join(reaped),
                )
