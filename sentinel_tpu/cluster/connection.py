"""Namespace-scoped connection groups.

Analog of ``connection/ConnectionManager.java:35`` + ``ConnectionGroup.java``:
the token server groups client connections by the namespace they declared in
their PING handshake; each group's connected count feeds the AVG_LOCAL
threshold scaling (``ClusterFlowChecker.java:43-47`` →
``rules.ns_connected`` in the device table here).

Instance-scoped rather than the reference's static map: every ``TokenServer``
owns one manager, so two embedded servers in one process (tests, multi-pod
dryruns) don't share groups.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Set


class ConnectionManager:
    def __init__(
        self, on_count_changed: Optional[Callable[[str, int], None]] = None
    ):
        self._lock = threading.Lock()
        self._groups: Dict[str, Set[str]] = {}
        # address → namespaces it registered (one connection may serve
        # several namespaces; each PING adds one)
        self._by_address: Dict[str, Set[str]] = {}
        self._on_count_changed = on_count_changed

    def add(self, namespace: str, address: str) -> int:
        """Register; returns the group's connected count (PING response)."""
        with self._lock:
            group = self._groups.setdefault(namespace, set())
            group.add(address)
            self._by_address.setdefault(address, set()).add(namespace)
            n = len(group)
        if self._on_count_changed is not None:
            self._on_count_changed(namespace, n)
        return n

    def remove_address(self, address: str) -> None:
        """Drop every registration of a disconnected client."""
        changed: List[tuple] = []
        with self._lock:
            for ns in self._by_address.pop(address, ()):
                group = self._groups.get(ns)
                if group is not None:
                    group.discard(address)
                    changed.append((ns, len(group)))
                    if not group:
                        del self._groups[ns]
        if self._on_count_changed is not None:
            for ns, n in changed:
                self._on_count_changed(ns, n)

    def connected_count(self, namespace: str) -> int:
        with self._lock:
            return len(self._groups.get(namespace, ()))

    def namespaces(self) -> List[str]:
        with self._lock:
            return sorted(self._groups)

    def snapshot(self) -> Dict[str, List[str]]:
        """namespace → sorted addresses (FetchClusterServerInfo shape)."""
        with self._lock:
            return {ns: sorted(g) for ns, g in self._groups.items()}
