"""Envoy Rate Limit Service (RLS) v3 server on the TPU token path.

Analog of ``sentinel-cluster-server-envoy-rls``:

- ``EnvoyRlsRule`` / converter (``rule/EnvoySentinelRuleConverter.java``):
  domain + descriptor (key/value entries) → deterministic flow id; each
  descriptor becomes a GLOBAL-threshold cluster flow rule.
- ``shouldRateLimit`` semantics (``service/v3/SentinelEnvoyRlsServiceImpl.
  java:32-115``): check each descriptor; NO_RULE → OK (pass-through); any
  non-OK descriptor ⇒ overall OVER_LIMIT; per-descriptor status carries the
  configured limit + remaining.
- The reference compiles the envoy protos; here the two RLS messages are
  (de)coded by a hand-rolled protobuf-wire codec (they are tiny), so the
  gRPC layer needs no generated stubs — ``grpc.GenericRpcHandler`` with
  identity serializers speaks the real wire format.

The decision path is the shared ``DefaultTokenService`` — i.e. Envoy
descriptors ride the same jitted device kernel as native token clients
(the reference's ``SimpleClusterFlowChecker`` is a simplified copy of the
flow checker instead).
"""

from __future__ import annotations

import struct
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.core.hashing import stable_param_hash
from sentinel_tpu.core.log import record_log
from sentinel_tpu.engine import ClusterFlowRule, TokenStatus
from sentinel_tpu.engine.rules import ThresholdMode
from sentinel_tpu.cluster.token_service import DefaultTokenService, TokenResult
from sentinel_tpu.metrics.ha import ha_metrics
from sentinel_tpu.metrics.server import server_metrics

SEPARATOR = "|"  # EnvoySentinelRuleConverter.SEPARATOR

# RateLimitResponse.Code
CODE_UNKNOWN = 0
CODE_OK = 1
CODE_OVER_LIMIT = 2

# RateLimit.Unit
UNIT_SECOND = 1

RLS_METHOD = "/envoy.service.ratelimit.v3.RateLimitService/ShouldRateLimit"


# -- rules ------------------------------------------------------------------


@dataclass(frozen=True)
class RlsDescriptor:
    """``EnvoyRlsRule.ResourceDescriptor``: ordered key/value entries + count."""

    entries: Tuple[Tuple[str, str], ...]
    count: float


@dataclass(frozen=True)
class EnvoyRlsRule:
    """``EnvoyRlsRule.java``: one domain, many descriptors."""

    domain: str
    descriptors: Tuple[RlsDescriptor, ...]


def generate_key(domain: str, entries: Sequence[Tuple[str, str]]) -> str:
    """``EnvoySentinelRuleConverter.generateKey``: ``domain|k|v|k|v…``."""
    parts = [domain]
    for k, v in entries:
        parts.append(k)
        parts.append(v)
    return SEPARATOR.join(parts)


def generate_flow_id(key: str) -> int:
    """Deterministic positive flow id from the descriptor key.

    The reference uses Java ``String.hashCode + Integer.MAX_VALUE``
    (``EnvoySentinelRuleConverter.java:70-76``); the TPU build uses its
    process-stable blake2b hash (``core.hashing``) masked positive — same
    contract (stable across restarts and across nodes), better dispersion.
    """
    if not key:
        return -1
    return stable_param_hash(key) & 0x7FFF_FFFF_FFFF_FFFF


class EnvoyRlsRuleManager:
    """``EnvoyRlsRuleManager.java``: converts + publishes RLS rules into the
    token service; keeps the flow-id → (rule, descriptor) map for responses."""

    def __init__(self, service: DefaultTokenService, publish: bool = True):
        # publish=False: keep only the flow-id → descriptor map (the RLS
        # response metadata) without pushing flow rules into the service —
        # co-located mode, where the backing service is a remote token
        # server that owns its own rule set
        self._service = service
        self._publish = publish
        self._lock = threading.Lock()
        self._by_id: Dict[int, Tuple[str, RlsDescriptor]] = {}

    def load_rules(self, rules: Sequence[EnvoyRlsRule]) -> None:
        flow_rules: List[ClusterFlowRule] = []
        by_id: Dict[int, Tuple[str, RlsDescriptor]] = {}
        for rule in rules:
            if not rule.domain:
                record_log.warning("RLS rule with empty domain ignored")
                continue
            for desc in rule.descriptors:
                if not desc.entries or desc.count < 0:
                    record_log.warning(
                        "invalid RLS descriptor ignored: %s", desc
                    )
                    continue
                fid = generate_flow_id(generate_key(rule.domain, desc.entries))
                by_id[fid] = (rule.domain, desc)
                flow_rules.append(
                    ClusterFlowRule(
                        flow_id=fid, count=desc.count, mode=ThresholdMode.GLOBAL
                    )
                )
        with self._lock:
            self._by_id = by_id
        if self._publish:
            self._service.load_rules(flow_rules)

    def lookup(self, flow_id: int) -> Optional[Tuple[str, RlsDescriptor]]:
        with self._lock:
            return self._by_id.get(flow_id)


# -- service logic (transport-free, like the reference's unit tests) --------


@dataclass
class DescriptorStatus:
    code: int
    limit_per_unit: Optional[int] = None
    limit_remaining: int = 0


@dataclass
class RlsVerdict:
    overall_code: int
    statuses: List[DescriptorStatus]


class RlsService:
    """``shouldRateLimit`` without the transport, testable directly.

    ``failure_mode`` is Envoy's RLS failure-mode knob mirrored server-side:
    when the token service errors mid-batch (device fault, service swapped
    out under us, transport layer raising), every descriptor of the request
    resolves to the configured verdict — ``allow`` (fail-open, Envoy's
    ``failure_mode_deny=false`` default) or ``deny`` (fail-closed) — instead
    of the exception tearing down the RPC."""

    def __init__(
        self,
        service: DefaultTokenService,
        rules: EnvoyRlsRuleManager,
        failure_mode: Optional[str] = None,
    ):
        self._service = service
        self._rules = rules
        if failure_mode is None:
            failure_mode = SentinelConfig.get(
                "csp.sentinel.rls.failure.mode", "allow"
            )
        failure_mode = str(failure_mode).lower()
        if failure_mode not in ("allow", "deny"):
            raise ValueError(
                f"failure_mode must be allow|deny, got {failure_mode!r}"
            )
        self.failure_mode = failure_mode

    def _failure_verdict(self, n: int) -> RlsVerdict:
        allow = self.failure_mode == "allow"
        ha_metrics().count_fallback(
            "rls_allow" if allow else "rls_deny", max(1, n)
        )
        code = CODE_OK if allow else CODE_OVER_LIMIT
        return RlsVerdict(code, [DescriptorStatus(code) for _ in range(n)])

    def should_rate_limit(
        self,
        domain: str,
        descriptors: Sequence[Sequence[Tuple[str, str]]],
        hits_addend: int = 1,
    ) -> RlsVerdict:
        if hits_addend < 0:
            raise ValueError(
                f"acquireCount should be positive, but actual: {hits_addend}"
            )
        acquire = hits_addend or 1  # 0 means "not present" → default 1
        blocked = False
        statuses: List[DescriptorStatus] = []
        # one device step for all descriptors of the request (the reference
        # loops per descriptor; the batch is strictly cheaper)
        known = [
            (i, generate_flow_id(generate_key(domain, entries)))
            for i, entries in enumerate(descriptors)
        ]
        requests = [(fid, acquire, False) for _, fid in known]
        try:
            results = self._service.request_batch(requests)
            if len(results) != len(requests):
                raise RuntimeError(
                    f"token service returned {len(results)} results "
                    f"for {len(requests)} descriptors"
                )
        except Exception:
            # token service errored mid-batch: resolve the whole request via
            # the configured failure mode instead of raising through the RPC
            record_log.exception(
                "RLS token service error; failing %s", self.failure_mode
            )
            return self._failure_verdict(len(descriptors))
        for (i, fid), result in zip(known, results):
            entry = self._rules.lookup(fid)
            if entry is None or result.status == TokenStatus.NO_RULE_EXISTS:
                # absent rule → pass (SentinelEnvoyRlsServiceImpl.java:56-58)
                statuses.append(DescriptorStatus(CODE_OK))
                continue
            if result.status == TokenStatus.FAIL:
                # this descriptor's verdict degraded (e.g. the client-side
                # TokenClient timed out): per-descriptor failure mode, not
                # an OVER_LIMIT the rule never issued
                allow = self.failure_mode == "allow"
                ha_metrics().count_fallback(
                    "rls_allow" if allow else "rls_deny"
                )
                blocked = blocked or not allow
                statuses.append(
                    DescriptorStatus(CODE_OK if allow else CODE_OVER_LIMIT)
                )
                continue
            ok = result.status == TokenStatus.OK
            blocked = blocked or not ok
            statuses.append(
                DescriptorStatus(
                    CODE_OK if ok else CODE_OVER_LIMIT,
                    limit_per_unit=int(entry[1].count),
                    limit_remaining=max(0, result.remaining),
                )
            )
        # RLS-shaped view of the same verdicts (sentinel_server_verdicts_
        # total{namespace="rls:<domain>"}); the engine path already counted
        # each descriptor under its rule namespace
        ok_n = sum(1 for st in statuses if st.code == CODE_OK)
        server_metrics().count_rls(domain, ok_n, len(statuses) - ok_n)
        return RlsVerdict(CODE_OVER_LIMIT if blocked else CODE_OK, statuses)


def co_located_rls(
    shm_dir: str,
    timeout_ms: int = 20,
    namespace: str = "rls",
    failure_mode: Optional[str] = None,
    spin_us: Optional[int] = None,
):
    """Opt-in co-located mode: an RLS sidecar sharing a host with a
    ``NativeTokenServer(shm_dir=...)`` rides the shared-memory ring door
    instead of TCP loopback — zero syscalls per verdict batch on the
    steady state.

    Returns ``(rls, rules, client)``. The rule manager is created with
    ``publish=False``: the token server owns the flow rules (load them
    there); ``rules.load_rules(...)`` here only builds the descriptor map
    RLS responses need for limit metadata. Close the returned ``client``
    to unlink the segment.
    """
    from sentinel_tpu.cluster.shm_client import ShmTokenClient

    client = ShmTokenClient(
        shm_dir, timeout_ms=timeout_ms, namespace=namespace,
        spin_us=spin_us,
    )
    rules = EnvoyRlsRuleManager(client, publish=False)
    return RlsService(client, rules, failure_mode), rules, client


# -- protobuf wire codec (hand-rolled; messages are tiny and frozen) --------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(data: bytes, off: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = data[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7


def _field(tag: int, wire: int, payload: bytes) -> bytes:
    return _varint((tag << 3) | wire) + payload


def _ld(tag: int, payload: bytes) -> bytes:  # length-delimited
    return _field(tag, 2, _varint(len(payload)) + payload)


def _iter_fields(data: bytes):
    off = 0
    while off < len(data):
        key, off = _read_varint(data, off)
        tag, wire = key >> 3, key & 7
        if wire == 0:
            value, off = _read_varint(data, off)
        elif wire == 2:
            n, off = _read_varint(data, off)
            value = data[off : off + n]
            off += n
        elif wire == 5:
            value = struct.unpack_from("<I", data, off)[0]
            off += 4
        elif wire == 1:
            value = struct.unpack_from("<Q", data, off)[0]
            off += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield tag, wire, value


def decode_rate_limit_request(
    data: bytes,
) -> Tuple[str, List[List[Tuple[str, str]]], int]:
    """RateLimitRequest: domain=1, descriptors=2 (RateLimitDescriptor:
    entries=1 (Entry: key=1, value=2)), hits_addend=3."""
    domain = ""
    descriptors: List[List[Tuple[str, str]]] = []
    hits = 0
    for tag, _, value in _iter_fields(data):
        if tag == 1:
            domain = value.decode()
        elif tag == 2:
            entries: List[Tuple[str, str]] = []
            for dtag, _, dval in _iter_fields(value):
                if dtag == 1:
                    k = v = ""
                    for etag, _, eval_ in _iter_fields(dval):
                        if etag == 1:
                            k = eval_.decode()
                        elif etag == 2:
                            v = eval_.decode()
                    entries.append((k, v))
            descriptors.append(entries)
        elif tag == 3:
            hits = value
    return domain, descriptors, hits


def encode_rate_limit_request(
    domain: str, descriptors: Sequence[Sequence[Tuple[str, str]]],
    hits_addend: int = 0,
) -> bytes:
    out = _ld(1, domain.encode())
    for entries in descriptors:
        desc = b""
        for k, v in entries:
            desc += _ld(1, _ld(1, k.encode()) + _ld(2, v.encode()))
        out += _ld(2, desc)
    if hits_addend:
        out += _field(3, 0, _varint(hits_addend))
    return out


def encode_rate_limit_response(verdict: RlsVerdict) -> bytes:
    """RateLimitResponse: overall_code=1, statuses=2 (DescriptorStatus:
    code=1, current_limit=2 (RateLimit: requests_per_unit=1, unit=2),
    limit_remaining=3)."""
    out = b""
    if verdict.overall_code:
        out += _field(1, 0, _varint(verdict.overall_code))
    for st in verdict.statuses:
        body = b""
        if st.code:
            body += _field(1, 0, _varint(st.code))
        if st.limit_per_unit is not None:
            limit = _field(1, 0, _varint(st.limit_per_unit))
            limit += _field(2, 0, _varint(UNIT_SECOND))
            body += _ld(2, limit)
            body += _field(3, 0, _varint(st.limit_remaining))
        out += _ld(2, body)
    return out


def decode_rate_limit_response(data: bytes) -> RlsVerdict:
    overall = CODE_UNKNOWN
    statuses: List[DescriptorStatus] = []
    for tag, _, value in _iter_fields(data):
        if tag == 1:
            overall = value
        elif tag == 2:
            st = DescriptorStatus(CODE_UNKNOWN)
            for stag, _, sval in _iter_fields(value):
                if stag == 1:
                    st.code = sval
                elif stag == 2:
                    for ltag, _, lval in _iter_fields(sval):
                        if ltag == 1:
                            st.limit_per_unit = lval
                elif stag == 3:
                    st.limit_remaining = sval
            statuses.append(st)
    return RlsVerdict(overall, statuses)


# -- gRPC front door --------------------------------------------------------


class SentinelRlsGrpcServer:
    """``SentinelRlsGrpcServer.java:28`` analog: standalone gRPC server
    exposing ``ShouldRateLimit`` (gated on ``grpcio``)."""

    def __init__(self, rls: RlsService, host: str = "127.0.0.1", port: int = 10245,
                 max_workers: int = 8):
        import grpc
        from concurrent import futures

        self._grpc = grpc
        self._rls = rls
        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                if details.method != RLS_METHOD:
                    return None
                return grpc.unary_unary_rpc_method_handler(
                    outer._handle,
                    request_deserializer=bytes,
                    response_serializer=bytes,
                )

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers))
        self._server.add_generic_rpc_handlers([Handler()])
        self.port = self._server.add_insecure_port(f"{host}:{port}")

    def _handle(self, request: bytes, context) -> bytes:
        try:
            domain, descriptors, hits = decode_rate_limit_request(request)
            verdict = self._rls.should_rate_limit(domain, descriptors, hits)
        except ValueError as e:
            context.abort(self._grpc.StatusCode.INVALID_ARGUMENT, str(e))
            return b""  # pragma: no cover - abort raises
        except Exception:
            record_log.exception("RLS request failed")
            context.abort(self._grpc.StatusCode.INTERNAL, "internal error")
            return b""  # pragma: no cover - abort raises
        return encode_rate_limit_response(verdict)

    def start(self) -> None:
        warmup = getattr(self._rls._service, "warmup", None)
        if warmup is not None:
            warmup()  # compile the decision kernels before accepting traffic
        self._server.start()
        record_log.info("RLS gRPC server on port %d", self.port)

    def stop(self, grace: Optional[float] = None) -> None:
        self._server.stop(grace)
